"""Failure flight recorder: an always-on ring of the last K epochs.

A :class:`FlightRecorder` keeps per-epoch *frames* — the epoch's
completion events (``obs/critpath.py``), its time-series row
(``obs/timeseries.py``), and optionally its spans — in a
``deque(maxlen=K)`` ring (K from ``HBBFT_TPU_FLIGHT_EPOCHS``, default
8).  When a run dies — ``CrankError``, a failed verdict, or a
``crash:*`` fault — the harness (``net/scenarios.run_cell``) dumps the
ring as a *forensics bundle*: a single JSON document holding the frames
plus the reconstructed critical path of the window, attached by
``tools/soak.py`` / ``tools/scenario_matrix.py`` next to the failed
cell's replay record and read back by ``tools/trace_report.py
--forensics``.

Determinism contract (this module is in the determinism lint scope): no
wall-clock reads; bundles are pure functions of the recorded frames, so
a seeded replay reproduces them bit-identically.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Dict, List, Optional

from hbbft_tpu.obs import critpath as _critpath

#: ring size knob: how many epochs of evidence a bundle carries
FLIGHT_EPOCHS_ENV = "HBBFT_TPU_FLIGHT_EPOCHS"
DEFAULT_FLIGHT_EPOCHS = 8

REQUIRED_BUNDLE_KEYS = ("version", "kind", "reason", "frames", "critical_path")


def flight_epochs() -> int:
    raw = os.environ.get(FLIGHT_EPOCHS_ENV, "")
    try:
        k = int(raw)
    except ValueError:
        return DEFAULT_FLIGHT_EPOCHS
    return k if k > 0 else DEFAULT_FLIGHT_EPOCHS


class FlightRecorder:
    """Always-on per-epoch evidence ring; ``bundle()`` is the dump."""

    __slots__ = ("epochs", "frames", "context", "_recorded")

    def __init__(
        self, epochs: Optional[int] = None, context: Optional[Dict[str, Any]] = None
    ) -> None:
        self.epochs = epochs if epochs is not None else flight_epochs()
        self.frames: deque = deque(maxlen=max(1, self.epochs))
        self.context = context
        self._recorded = 0

    def record(
        self,
        epoch: int,
        series_row: Optional[Dict[str, Any]] = None,
        events: Any = (),
        spans: Any = (),
    ) -> None:
        """Append one epoch frame (oldest frame falls off the ring)."""
        frame: Dict[str, Any] = {"epoch": epoch, "events": list(events)}
        if series_row is not None:
            frame["series"] = series_row
        spans = list(spans)
        if spans:
            frame["spans"] = spans
        self.frames.append(frame)
        self._recorded += 1

    @property
    def recorded(self) -> int:
        return self._recorded

    def bundle(
        self,
        reason: str,
        why: Optional[Dict[str, Any]] = None,
        faults: Any = None,
        gate_hint: Optional[str] = None,
    ) -> Dict[str, Any]:
        """The forensics dump: ring frames + the window's reconstructed
        critical path (gating chain per epoch, run-window gating
        histogram, and the latest gate one-liner).  ``gate_hint`` (e.g.
        a why-stalled summary line) overrides the gate label when the
        window holds no committed epoch to attribute."""
        frames = list(self.frames)
        events = [ev for fr in frames for ev in fr.get("events", ())]
        paths = _critpath.paths_from_events(events)
        gate = paths[-1].one_liner() if paths else None
        if gate_hint and not paths:
            gate = gate_hint
        return {
            "version": 1,
            "kind": "forensics",
            "reason": reason,
            "context": self.context,
            "frames": frames,
            "critical_path": {
                "gate": gate,
                "gating": _critpath.gating_histogram(paths),
                "paths": [p.to_dict() for p in paths],
            },
            "why": why,
            "faults": list(faults) if faults else [],
        }


def write_bundle(doc: Dict[str, Any], path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=repr)
        f.write("\n")
    return path


def load_bundle(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def validate_bundle(doc: Any) -> List[str]:
    """Structural checks (``trace_report --forensics`` gate): required
    keys, monotonic frame epochs, well-formed critical path whose phase
    names stay inside the critpath registry."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return ["bundle is not a JSON object"]
    for k in REQUIRED_BUNDLE_KEYS:
        if k not in doc:
            errors.append(f"missing key {k!r}")
    if errors:
        return errors
    if doc["version"] != 1:
        errors.append(f"unknown version {doc['version']!r}")
    if doc["kind"] != "forensics":
        errors.append(f"kind is {doc['kind']!r}, not 'forensics'")
    frames = doc["frames"]
    if not isinstance(frames, list) or not frames:
        errors.append("frames must be a non-empty list")
        return errors
    prev = None
    for i, fr in enumerate(frames):
        ep = fr.get("epoch") if isinstance(fr, dict) else None
        if not isinstance(ep, int):
            errors.append(f"frame {i} has no integer epoch")
            continue
        if prev is not None and ep < prev:
            errors.append(f"frame epochs not monotonic at index {i} ({ep} < {prev})")
        prev = ep
    cp = doc["critical_path"]
    if not isinstance(cp, dict) or "gating" not in cp or "paths" not in cp:
        errors.append("critical_path must hold 'gating' and 'paths'")
        return errors
    share_sum = 0.0
    for ph in sorted(cp["gating"]):
        share = cp["gating"][ph]
        if ph not in _critpath.PHASES:
            errors.append(f"gating phase {ph!r} not in critpath.PHASES")
        if not 0.0 <= share <= 1.0001:
            errors.append(f"gating share out of range for {ph!r}: {share}")
        share_sum += share
    if cp["gating"] and not 0.99 <= share_sum <= 1.01:
        errors.append(f"gating shares sum to {share_sum:.4f}, not 1")
    for j, p in enumerate(cp["paths"]):
        if p.get("gate_phase") not in _critpath.PHASES:
            errors.append(f"path {j} gate_phase {p.get('gate_phase')!r} unknown")
    return errors


def summarize_bundle(doc: Dict[str, Any]) -> List[str]:
    """Human summary lines (``trace_report --forensics`` output)."""
    frames = doc.get("frames", [])
    epochs = [fr.get("epoch") for fr in frames if isinstance(fr.get("epoch"), int)]
    span = f"epochs {min(epochs)}..{max(epochs)}" if epochs else "no epochs"
    lines = [
        f"forensics: reason={doc.get('reason')!r} frames={len(frames)} ({span})",
    ]
    ctx = doc.get("context") or {}
    cell = ctx.get("cell") if isinstance(ctx, dict) else None
    if isinstance(cell, dict):
        axes = "x".join(
            str(cell.get(k)) for k in ("attack", "schedule", "churn", "crash", "traffic")
        )
        lines.append(f"  cell: {axes} n={cell.get('n')} seed={cell.get('seed')}")
    cp = doc.get("critical_path") or {}
    if cp.get("gate"):
        lines.append(f"  gate: {cp['gate']}")
    gating = cp.get("gating") or {}
    for ph in sorted(gating, key=lambda p: -gating[p]):
        lines.append(f"  gating {ph}: {gating[ph] * 100:.1f}%")
    why = doc.get("why") or {}
    summary = why.get("summary") if isinstance(why, dict) else None
    if summary:
        lines.append(f"  why: {summary[0]}")
    faults = doc.get("faults") or []
    kinds: Dict[str, int] = {}
    for t in faults:
        kind = t[2] if isinstance(t, (list, tuple)) and len(t) == 3 else repr(t)
        kinds[kind] = kinds.get(kind, 0) + 1
    for kind in sorted(kinds):
        lines.append(f"  fault {kind}: {kinds[kind]}")
    return lines
