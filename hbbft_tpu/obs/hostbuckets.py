"""Host-bucket attribution: a region stack that itemizes host time.

The round-5 north-star measurement (PERF.md) attributes 55% of the
N=100 epoch to one opaque "host: everything else" bucket.  This module
is the counterpart of the per-kind ``device_seconds_*`` split for the
HOST side: a lightweight stack of timed regions that partitions the
host thread's wall time inside an engine epoch into named buckets
(``utils.metrics.Counters.host_bucket_*``).

Accounting rules (single host thread, so a plain stack suffices):

* A region bills its **exclusive** time: its own wall minus the wall of
  nested child regions minus any stretch the host spent *blocked in a
  device fetch* (``counters.fetch_blocked_seconds``, billed by
  ``ops/pipeline.DispatchPipeline._resolve`` — the single sync seam).
  Blocked time is device wait, not host work; counting it would make
  the host split double-bill ``device_seconds``.
* The outermost region (:meth:`HostBuckets.epoch`) additionally bills
  the epoch's TOTAL host time (wall minus blocked) to
  ``counters.host_seconds`` and its own exclusive residue to the
  ``other`` bucket.  Because every bucket is an exclusive slice of the
  same interval, **the host_bucket_* fields sum to host_seconds
  exactly** — the invariant ``tools/trace_report.py --host-buckets``
  validates from a trace, and the residual ``other`` bucket is the
  unattributed share the <10% acceptance bar tracks.
* With a tracer attached each region also emits a retroactive span on
  the ``host`` track (``host=True``, ``bucket=<name>``) carrying its
  exclusive seconds in ``args.exclusive_s`` — span intervals nest for
  Perfetto, while the exclusive_s args reproduce the counter partition
  from the trace alone (the same by-construction agreement the device
  spans have).

Zero-cost discipline: regions are a few perf_counter calls each and are
placed at *phase* granularity (a handful per round), never per item.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Callable, Optional

#: the canonical bucket vocabulary (Counters.host_bucket_* field suffixes)
HOST_BUCKETS = (
    "encode",
    "rs_merkle",
    "assemble",
    "scatter",
    "staging",
    "dispatch",
    "other",
)


class HostBuckets:
    """Exclusive-time region stack billing ``Counters.host_bucket_*``.

    ``tracer_ref`` is a zero-arg callable returning the live tracer (the
    backend's tracer is attached after construction — same contract as
    the DispatchPipeline's).
    """

    __slots__ = ("counters", "_tracer_ref", "_stack", "_in_epoch")

    def __init__(
        self,
        counters,
        tracer_ref: Optional[Callable[[], Any]] = None,
    ) -> None:
        self.counters = counters
        self._tracer_ref = tracer_ref
        # frames: [name, t0, child_inclusive_minus_blocked, blocked_at_t0]
        self._stack: list = []
        self._in_epoch = False

    @contextmanager
    def region(self, name: str):
        """Bill this block's exclusive host time to ``host_bucket_<name>``.

        ``name`` must be one of :data:`HOST_BUCKETS` (the counter field
        must exist; an unknown name raises at exit — loudly, because a
        silently dropped bucket would break the sums-to-total invariant).
        Regions nest arbitrarily; same-name nesting is fine (the child's
        slice simply moves from the parent to itself).

        Outside an :meth:`epoch` frame a region is a NO-OP: backend
        staging blocks run from bench micro-rows or direct backend use
        too, and billing them would break the buckets-sum-to-
        ``host_seconds`` invariant the ``--host-buckets`` gate validates
        (``host_seconds`` only accrues inside epochs).
        """
        if not self._in_epoch:
            yield
            return
        c = self.counters
        frame = [name, time.perf_counter(), 0.0, c.fetch_blocked_seconds]
        self._stack.append(frame)
        try:
            yield
        finally:
            t1 = time.perf_counter()
            self._stack.pop()
            inclusive = t1 - frame[1]
            blocked = c.fetch_blocked_seconds - frame[3]
            own = max(0.0, inclusive - frame[2] - blocked)
            field = "host_bucket_" + name
            setattr(c, field, getattr(c, field) + own)
            if self._stack:
                # transfer our NON-BLOCKED inclusive wall to the parent:
                # its own blocked delta already contains ours, so passing
                # the full inclusive would double-subtract the blocked part
                self._stack[-1][2] += inclusive - blocked
            tr = self._tracer_ref() if self._tracer_ref is not None else None
            if tr is not None:
                tr.complete(
                    f"host:{name}", frame[1], t1, cat="host_bucket",
                    track="host", host=True, bucket=name,
                    exclusive_s=own,
                )

    @contextmanager
    def epoch(self):
        """Outermost region of one engine epoch (or era change): bills
        ``counters.host_seconds`` with the total (wall minus fetch-
        blocked) and the residual unattributed slice to ``other``."""
        c = self.counters
        # derive the total from the buckets themselves, not a separate
        # clock pair: the region-exit bookkeeping (setattr/span emission)
        # would otherwise skew host_seconds off the bucket sum by a few
        # microseconds per region, and the sums-to-total invariant is
        # what --host-buckets validates
        before = sum(
            getattr(c, "host_bucket_" + b) for b in HOST_BUCKETS
        )
        was_in_epoch, self._in_epoch = self._in_epoch, True
        try:
            with self.region("other"):
                yield
        finally:
            self._in_epoch = was_in_epoch
            c.host_seconds += (
                sum(getattr(c, "host_bucket_" + b) for b in HOST_BUCKETS)
                - before
            )
