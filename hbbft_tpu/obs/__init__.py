"""Observability subsystem: span tracing, Perfetto export, histograms,
and soak-run health/stall reporting.

Three cooperating pieces, all opt-in and zero-cost when absent (the same
``is None`` discipline :class:`~hbbft_tpu.utils.metrics.EventLog` uses):

* :class:`~hbbft_tpu.obs.tracer.Tracer` — hierarchical begin/end spans on
  named tracks (epoch → subset → BA instance → coin round → device
  dispatch), exported as Chrome-trace-event/Perfetto ``trace.json`` or
  JSONL, plus a registry of log-bucketed :class:`Histogram`\\ s.
* :class:`~hbbft_tpu.obs.histogram.Histogram` — log-bucketed latency /
  batch-size distributions with p50/p90/p99 summaries.
* :class:`~hbbft_tpu.obs.health.HealthReporter` — periodic heartbeat for
  soak runs and a stall detector whose :func:`~hbbft_tpu.obs.health
  .why_stalled` report names which BA instances are blocked on which coin
  rounds and which RBC instances lack Echo/Ready quorum.

Activation: ``NetBuilder.trace(Tracer())`` for the object runtime,
``ArrayHoneyBadgerNet(..., tracer=...)``/``net.tracer = ...`` for the
lockstep engine, ``--trace PATH`` / ``HBBFT_TPU_TRACE=PATH`` on
``examples/simulation.py``.
"""

from hbbft_tpu.obs.health import HealthReporter, render_why_stalled, why_stalled
from hbbft_tpu.obs.histogram import Histogram
from hbbft_tpu.obs.hostbuckets import HOST_BUCKETS, HostBuckets
from hbbft_tpu.obs.tracer import Tracer

__all__ = [
    "Tracer",
    "Histogram",
    "HealthReporter",
    "HostBuckets",
    "HOST_BUCKETS",
    "why_stalled",
    "render_why_stalled",
]
