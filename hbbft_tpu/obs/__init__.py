"""Observability subsystem: span tracing, Perfetto export, histograms,
and soak-run health/stall reporting.

Three cooperating pieces, all opt-in and zero-cost when absent (the same
``is None`` discipline :class:`~hbbft_tpu.utils.metrics.EventLog` uses):

* :class:`~hbbft_tpu.obs.tracer.Tracer` — hierarchical begin/end spans on
  named tracks (epoch → subset → BA instance → coin round → device
  dispatch), exported as Chrome-trace-event/Perfetto ``trace.json`` or
  JSONL, plus a registry of log-bucketed :class:`Histogram`\\ s.
* :class:`~hbbft_tpu.obs.histogram.Histogram` — log-bucketed latency /
  batch-size distributions with p50/p90/p99 summaries.
* :class:`~hbbft_tpu.obs.health.HealthReporter` — periodic heartbeat for
  soak runs and a stall detector whose :func:`~hbbft_tpu.obs.health
  .why_stalled` report names which BA instances are blocked on which coin
  rounds and which RBC instances lack Echo/Ready quorum.

Three more planes ride on those (PR 16 — span → series → forensics):

* :class:`~hbbft_tpu.obs.critpath.CritPathRecorder` /
  ``obs/critpath.py`` — per-epoch gating-chain reconstruction from
  protocol completion events and engine phase stamps (``epoch 12 <-
  decrypt.combine <- BA(7) coin <- RBC(7)``), with a run-level gating
  histogram.
* :class:`~hbbft_tpu.obs.timeseries.MetricsLog` — bounded per-epoch
  counter-delta/histogram/crash-state series, JSONL-exportable.
* :class:`~hbbft_tpu.obs.flight.FlightRecorder` — always-on ring of the
  last K epochs of events + series, dumped as a forensics bundle on
  failure (``HBBFT_TPU_FLIGHT_EPOCHS``).

Activation: ``NetBuilder.trace(Tracer())`` for the object runtime,
``ArrayHoneyBadgerNet(..., tracer=...)``/``net.tracer = ...`` for the
lockstep engine, ``--trace PATH`` / ``HBBFT_TPU_TRACE=PATH`` on
``examples/simulation.py``; ``net/scenarios.run_cell`` wires all three
new planes by default (``obs=False`` opts out).
"""

from hbbft_tpu.obs.critpath import (
    PHASES,
    CritPathRecorder,
    EpochCritPath,
    diff_gating,
    gating_histogram,
    paths_from_events,
)
from hbbft_tpu.obs.flight import (
    FlightRecorder,
    summarize_bundle,
    validate_bundle,
    write_bundle,
)
from hbbft_tpu.obs.health import HealthReporter, render_why_stalled, why_stalled
from hbbft_tpu.obs.histogram import Histogram
from hbbft_tpu.obs.hostbuckets import HOST_BUCKETS, HostBuckets
from hbbft_tpu.obs.timeseries import MetricsLog, snap_net
from hbbft_tpu.obs.tracer import Tracer

__all__ = [
    "Tracer",
    "Histogram",
    "HealthReporter",
    "HostBuckets",
    "HOST_BUCKETS",
    "why_stalled",
    "render_why_stalled",
    "PHASES",
    "CritPathRecorder",
    "EpochCritPath",
    "paths_from_events",
    "gating_histogram",
    "diff_gating",
    "MetricsLog",
    "snap_net",
    "FlightRecorder",
    "validate_bundle",
    "summarize_bundle",
    "write_bundle",
]
