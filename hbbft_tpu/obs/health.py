"""Soak-run health: periodic heartbeat + stall detection with a
"why-stalled" protocol report.

HoneyBadger's liveness argument is compositional: an epoch terminates iff
every accepted RBC reaches Echo/Ready quorum and every BA instance's
MMR-style agreement (Mostéfaoui et al., PODC 2014) terminates coin round
by coin round.  So when a soak run stops making progress there is always
a *nameable* culprit: a BA instance blocked on a coin round short of
threshold+1 verified shares, an RBC instance short of Echo (N−f) or
Ready (2f+1) quorum, or a ThresholdDecrypt short of f+1 shares.
:func:`why_stalled` walks the live protocol objects (through the
SenderQueue → QueueingHoneyBadger → DynamicHoneyBadger → HoneyBadger →
Subset wrapper chain) and reports exactly that, per node.

:class:`HealthReporter` is the driver-facing wrapper: call :meth:`tick`
once per crank burst / epoch with the run's monotonic progress figures;
it emits a JSON heartbeat every ``interval_s`` wall seconds (epoch,
msgs/s, device-time share, fault count, counter deltas) and — after
``stall_timeout_s`` seconds without progress — a one-shot why-stalled
report.  Wired into ``examples/simulation.py`` (``--heartbeat`` /
``--stall-timeout``) and the soak bench rows.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional


# ---------------------------------------------------------------------------
# Protocol introspection (duck-typed: no protocol imports, so obs/ stays a
# leaf package usable from net/, engine/, and tools/ alike)
# ---------------------------------------------------------------------------

#: wrapper attribute chain: SenderQueue.algo, QueueingHoneyBadger.dhb,
#: DynamicHoneyBadger.hb
_WRAPPER_ATTRS = ("algo", "dhb", "hb")


def _unwrap(algo: Any) -> Any:
    seen = set()
    while algo is not None and id(algo) not in seen:
        seen.add(id(algo))
        for attr in _WRAPPER_ATTRS:
            inner = getattr(algo, attr, None)
            if inner is not None and hasattr(inner, "handle_message"):
                algo = inner
                break
        else:
            return algo
    return algo


def _ba_status(ba: Any) -> Optional[Dict[str, Any]]:
    """Progress state of one undecided BinaryAgreement instance."""
    if ba.decision is not None:
        return None
    netinfo = ba.netinfo
    st: Dict[str, Any] = {"round": ba.round}
    if ba._coin_invoked and ba._coin_value is None:
        coin = ba._coin
        st["blocked_on"] = "coin"
        st["coin_round"] = ba.round
        st["coin_shares_verified"] = (
            len(coin._verified) if coin is not None else 0
        )
        st["coin_shares_needed"] = netinfo.public_key_set.threshold() + 1
    elif ba.sent_conf is None:
        st["blocked_on"] = "sbv"
    else:
        st["blocked_on"] = "conf"
        st["conf_received"] = ba._count_conf()
        st["conf_needed"] = netinfo.num_correct()
    return st


def _rbc_status(bc: Any) -> Optional[Dict[str, Any]]:
    """Progress state of one undelivered Broadcast (RBC) instance."""
    if bc.terminated():
        return None
    n = bc.netinfo.num_nodes()
    f = bc.netinfo.num_faulty()
    roots = {p.root_hash for p in bc.echos.values()} | set(bc.readys.values())
    echo_max = max((bc._count_echos(r) for r in roots), default=0)
    ready_max = max((bc._count_readys(r) for r in roots), default=0)
    return {
        "has_value": bc.has_value,
        "echoes": echo_max,
        "echoes_needed": n - f,
        "readys": ready_max,
        "readys_needed": 2 * f + 1,
    }


def _decrypt_status(td: Any) -> Optional[Dict[str, Any]]:
    if td.terminated():
        return None
    return {
        "ciphertext_set": td.ciphertext is not None,
        "shares_verified": len(td._verified),
        "shares_needed": td.netinfo.public_key_set.threshold() + 1,
    }


def _inspect_subset(subset: Any) -> Dict[str, Any]:
    out: Dict[str, Any] = {"ba": {}, "rbc": {}}
    for proposer, ps in subset.proposals.items():
        if ps.decision is None:
            ba = _ba_status(ps.agreement)
            if ba is not None:
                out["ba"][repr(proposer)] = ba
        if ps.value is None:
            rbc = _rbc_status(ps.broadcast)
            if rbc is not None:
                out["rbc"][repr(proposer)] = rbc
    return out


def _inspect_core(core: Any) -> Dict[str, Any]:
    """Dispatch on the duck type of an unwrapped protocol instance."""
    es = getattr(core, "_epoch_state", None)
    if es is not None and hasattr(es, "subset"):  # HoneyBadger
        out = _inspect_subset(es.subset)
        out["epoch"] = core.epoch
        dec = {
            repr(p): st
            for p, st in (
                (p, _decrypt_status(td)) for p, td in es.decrypt.items()
            )
            if st is not None
        }
        if dec:
            out["decrypt"] = dec
        return out
    if hasattr(core, "proposals"):  # Subset driven directly
        return _inspect_subset(core)
    if hasattr(core, "received_conf") and hasattr(core, "sbv"):  # BA
        ba = _ba_status(core)
        return {"ba": {"self": ba}} if ba is not None else {"ba": {}}
    if hasattr(core, "echos") and hasattr(core, "readys"):  # Broadcast
        rbc = _rbc_status(core)
        return {"rbc": {"self": rbc}} if rbc is not None else {"rbc": {}}
    return {}


def _scenario_context(net: Any) -> Optional[Dict[str, Any]]:
    """Attack/scenario/schedule identity of a VirtualNet-like runner —
    names the active adversary and the network condition so a starved
    instance reads as "partition isolates {2,3}; BA coin quorum short",
    not as an anonymous missing quorum.  Duck-typed and total: absent
    attributes simply contribute nothing."""
    ctx: Dict[str, Any] = {}
    name = getattr(net, "scenario_name", None)
    if name:
        ctx["scenario"] = name
    adv = getattr(net, "adversary", None)
    if adv is not None and type(adv).__name__ != "NullAdversary":
        describe = getattr(adv, "describe", None)
        ctx["adversary"] = (
            describe() if callable(describe) else type(adv).__name__
        )
    sched = getattr(net, "schedule", None)
    now = getattr(net, "now", 0)
    if sched is not None:
        try:
            ctx["schedule"] = sched.describe(now)
        except Exception:  # a report must never raise on a custom schedule
            ctx["schedule"] = {"name": type(sched).__name__}
        future = len(getattr(net, "_future", ()) or ())
        if future:
            ctx["future_dated_messages"] = future
    return ctx or None


def _scenario_summary(ctx: Dict[str, Any]) -> str:
    parts = []
    if "scenario" in ctx:
        parts.append(f"scenario {ctx['scenario']}")
    adv = ctx.get("adversary")
    if adv:
        parts.append(f"adversary {adv.get('name', adv)}" if isinstance(adv, dict) else f"adversary {adv}")
    sched = ctx.get("schedule")
    if isinstance(sched, dict):
        part = sched.get("partition")
        if part:
            isolates = "; ".join(
                "{" + ", ".join(map(str, g)) + "}" for g in part["isolates"]
            )
            parts.append(
                f"partition isolates {isolates} until crank {part['heals_at']}"
            )
        else:
            parts.append(f"schedule {sched.get('name')}")
    if ctx.get("future_dated_messages"):
        parts.append(f"{ctx['future_dated_messages']} messages future-dated")
    return "; ".join(parts)


def _crash_context(net: Any) -> Optional[Dict[str, Any]]:
    """Crash-axis state of a runner with a crash manager attached
    (``net.crash`` — hbbft_tpu/net/crash.py): which nodes are down since
    when, which checkpoint each would restore from, and completed
    restarts.  Duck-typed and total like the other contexts."""
    cm = getattr(net, "crash", None)
    if cm is None:
        return None
    describe = getattr(cm, "describe", None)
    if not callable(describe):
        return None
    try:
        ctx = dict(describe(getattr(net, "now", 0)))
    except Exception:
        return None
    return ctx if ctx.get("nodes") else None


def _crash_summary(ctx: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    for nid, st in sorted(ctx.get("nodes", {}).items()):
        state = st.get("state")
        if state in ("down", "restoring"):
            ck = st.get("checkpoint_epoch", [0, 0])
            lines.append(
                f"node {nid} down since crank {st.get('down_since_crank')}"
                f" / restoring from checkpoint at epoch "
                f"(era {ck[0]}, epoch {ck[1]})"
                + (
                    ""
                    if st.get("restart_pending")
                    else " — no restart scheduled"
                )
            )
        elif state == "failed":
            lines.append(
                f"node {nid} down since crank "
                f"{st.get('down_since_crank')} — recovery FAILED "
                "(crash:recovery_failed attributed)"
            )
    return lines


def _traffic_context(net: Any) -> Optional[Dict[str, Any]]:
    """Traffic-source state of a runner driven by the traffic subsystem
    (``net.traffic`` is a driver exposing ``status()`` —
    hbbft_tpu/traffic/driver.py).  Duck-typed and total like the
    scenario context: a runner without traffic contributes nothing, and
    a report must never raise on a custom driver."""
    tr = getattr(net, "traffic", None)
    if tr is None:
        return None
    status = getattr(tr, "status", None)
    if not callable(status):
        return None
    try:
        st = dict(status())
    except Exception:
        return None
    return st or None


def _traffic_summary(ctx: Dict[str, Any]) -> str:
    state = ctx.get("state", "unknown")
    src = ctx.get("source") or {}
    name = src.get("source", "traffic") if isinstance(src, dict) else str(src)
    if state == "saturated":
        line = (
            f"traffic source {name} saturated: mempool "
            f"{ctx.get('mempool_depth', '?')}/{ctx.get('capacity', '?')}, "
            f"{ctx.get('dropped', 0)} dropped, {ctx.get('evicted', 0)} evicted"
        )
    elif state == "starved":
        line = (
            f"traffic source {name} starved: mempool empty, "
            f"{ctx.get('committed', 0)} committed, nothing pending"
        )
    else:
        line = f"traffic source {name} {state}"
    ctrl = ctx.get("controller")
    if isinstance(ctrl, dict):
        # the control plane's live operating point rides the stall
        # report: current B and whether the declared SLO holds
        slo = ctrl.get("slo") or {}
        line += (
            f"; adaptive batch B={ctrl.get('batch_size')} "
            f"(p99 target {slo.get('p99_epochs')} epochs, "
            + ("SLO compliant" if ctrl.get("compliant") else "SLO VIOLATED")
            + ")"
        )
    return line


def why_stalled(net_or_nodes: Any) -> Dict[str, Any]:
    """Build the why-stalled report for a quiesced-but-unfinished run.

    Accepts a :class:`~hbbft_tpu.net.virtual_net.VirtualNet`, an
    ``examples.simulation.Simulation``, or any ``{node_id: node}`` mapping
    whose values carry the protocol under ``.algorithm``/``.algo`` (or
    are the protocol itself).  When the runner carries an adversary /
    scenario / schedule (the scenario harness), the report leads with
    that context — a starved quorum under a live partition names the
    partition, not just the shortfall.
    """
    nodes = getattr(net_or_nodes, "nodes", net_or_nodes)
    report: Dict[str, Any] = {"nodes": {}, "summary": []}
    # lead with the latest critical-path gate (net.critpath, when the
    # harness attached a CritPathRecorder): "last epoch gated by BA(3)
    # coin round 2 on node 7" orients the reader before the per-node
    # quorum shortfalls below
    cp = getattr(net_or_nodes, "critpath", None)
    gate_line = getattr(cp, "gate_line", None)
    line = gate_line() if callable(gate_line) else None
    if line:
        report["gate"] = line
        report["summary"].append(f"last {line}")
    ctx = _scenario_context(net_or_nodes)
    if ctx is not None:
        report["scenario"] = ctx
        report["summary"].append(_scenario_summary(ctx))
    cctx = _crash_context(net_or_nodes)
    if cctx is not None:
        report["crash"] = cctx
        report["summary"].extend(_crash_summary(cctx))
    tctx = _traffic_context(net_or_nodes)
    if tctx is not None:
        report["traffic"] = tctx
        report["summary"].append(_traffic_summary(tctx))
    for nid in sorted(nodes, key=repr):
        node = nodes[nid]
        algo = getattr(node, "algorithm", None)
        if algo is None:
            algo = getattr(node, "algo", node)
        state = _inspect_core(_unwrap(algo))
        pruned = {
            k: v for k, v in state.items() if v or k == "epoch"
        }
        if any(pruned.get(k) for k in ("ba", "rbc", "decrypt")):
            report["nodes"][repr(nid)] = pruned
    for nid, state in report["nodes"].items():
        for p, ba in state.get("ba", {}).items():
            if ba["blocked_on"] == "coin":
                short = ba["coin_shares_needed"] - ba["coin_shares_verified"]
                report["summary"].append(
                    f"node {nid}: BA[{p}] blocked on coin round "
                    f"{ba['coin_round']} — coin quorum short {short} shares "
                    f"({ba['coin_shares_verified']}/{ba['coin_shares_needed']}"
                    " verified)"
                )
            else:
                report["summary"].append(
                    f"node {nid}: BA[{p}] in round {ba['round']} waiting on "
                    f"{ba['blocked_on']}"
                )
        for p, rbc in state.get("rbc", {}).items():
            report["summary"].append(
                f"node {nid}: RBC[{p}] lacks quorum "
                f"(Echo {rbc['echoes']}/{rbc['echoes_needed']}, "
                f"Ready {rbc['readys']}/{rbc['readys_needed']})"
            )
        for p, td in state.get("decrypt", {}).items():
            report["summary"].append(
                f"node {nid}: decrypt[{p}] has "
                f"{td['shares_verified']}/{td['shares_needed']} shares"
            )
    return report


def render_why_stalled(report: Dict[str, Any]) -> str:
    lines = ["why-stalled report:"]
    lines.extend("  " + s for s in report["summary"])
    if not report["summary"]:
        lines.append("  no blocked protocol instances found")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Heartbeat + stall detector
# ---------------------------------------------------------------------------


def _print_sink(record: Dict[str, Any]) -> None:
    print(json.dumps(record, default=repr), flush=True)


class HealthReporter:
    """Periodic heartbeat + no-progress stall detector for soak runs.

    ``counters_fn`` returns the run's merged counter snapshot (e.g.
    ``net.metrics`` or ``backend.counters.snapshot``); heartbeats carry
    the nonzero deltas since the previous beat plus a device-time share.
    ``stall_report_fn`` (e.g. ``lambda: why_stalled(net)``) is invoked
    once per stall episode; progress re-arms the detector.
    ``gate_fn`` (e.g. ``net.critpath.gate_line`` when a critical-path
    recorder is attached) contributes the latest gating one-liner to
    every heartbeat and stall record.
    ``shard_stats_fn`` (e.g. ``backend.shard_stats`` on a MeshBackend)
    contributes the mesh scale-out health — the cumulative
    ``shard_imbalance`` ratio (max/mean per-device dispatches; 1.0 =
    balanced) and the per-device dispatch tallies — to every heartbeat,
    so a soak run surfaces a skewing placement policy the same way it
    surfaces a stalling quorum.
    """

    def __init__(
        self,
        interval_s: float = 60.0,
        stall_timeout_s: float = 0.0,
        counters_fn: Optional[Callable[[], Dict[str, float]]] = None,
        stall_report_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        sink: Callable[[Dict[str, Any]], None] = _print_sink,
        clock: Callable[[], float] = time.monotonic,
        gate_fn: Optional[Callable[[], Optional[str]]] = None,
        shard_stats_fn: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> None:
        self.interval_s = interval_s
        self.stall_timeout_s = stall_timeout_s
        self.counters_fn = counters_fn
        self.stall_report_fn = stall_report_fn
        self.gate_fn = gate_fn
        self.shard_stats_fn = shard_stats_fn
        self.sink = sink
        self.clock = clock
        t = clock()
        self._t_start = t
        self._t_beat = t
        self._t_progress = t
        self._last_progress: Any = None
        self._last_counters: Dict[str, float] = (
            dict(counters_fn()) if counters_fn else {}
        )
        self._last_msgs: Optional[float] = None
        self._seq = 0
        self.stalled = False
        self.beats: List[Dict[str, Any]] = []

    def report_quiesced(
        self, epoch: Optional[int] = None, msgs: Optional[float] = None
    ) -> Dict[str, Any]:
        """Emit a stall record UNCONDITIONALLY — for drivers that detect
        quiescence-before-completion themselves (the event loop drained
        with the run unfinished, so no further :meth:`tick` will ever
        observe the timeout).  This is exactly the quiesced-but-unfinished
        state :func:`why_stalled` introspects."""
        now = self.clock()
        record: Dict[str, Any] = {
            "stall": True,
            "quiesced": True,
            "seconds_without_progress": round(now - self._t_progress, 1),
            "epoch": epoch,
            "msgs": msgs,
        }
        self._add_gate(record)
        if self.stall_report_fn is not None:
            record["why"] = self.stall_report_fn()
        self.stalled = True
        self.sink(record)
        return record

    def _add_gate(self, record: Dict[str, Any]) -> None:
        if self.gate_fn is None:
            return
        try:
            line = self.gate_fn()
        except Exception:  # a heartbeat must never raise on a custom hook
            return
        if line:
            record["gate"] = line

    def tick(
        self,
        epoch: Optional[int] = None,
        msgs: Optional[float] = None,
        faults: Optional[int] = None,
        **extra: Any,
    ) -> Optional[Dict[str, Any]]:
        """Report progress; emits a heartbeat/stall record when due.

        Progress — for stall purposes — is the EPOCH (the run's externally
        visible output), falling back to ``msgs`` only when no epoch is
        supplied.  Counting delivered messages as progress would make the
        detector inert in a livelock: the object engine's crank loop
        delivers messages between any two ticks, so ``msgs`` always moves
        even when no epoch ever completes — exactly the state a soak run
        needs reported."""
        now = self.clock()
        progress = epoch if epoch is not None else msgs
        if progress != self._last_progress:
            self._last_progress = progress
            self._t_progress = now
            self.stalled = False
        if (
            self.stall_timeout_s
            and not self.stalled
            and now - self._t_progress >= self.stall_timeout_s
        ):
            self.stalled = True
            record: Dict[str, Any] = {
                "stall": True,
                "seconds_without_progress": round(now - self._t_progress, 1),
                "epoch": epoch,
                "msgs": msgs,
            }
            self._add_gate(record)
            if self.stall_report_fn is not None:
                record["why"] = self.stall_report_fn()
            self.sink(record)
            return record
        if now - self._t_beat < self.interval_s:
            return None
        dt = now - self._t_beat
        self._t_beat = now
        self._seq += 1
        beat: Dict[str, Any] = {
            "heartbeat": self._seq,
            "uptime_s": round(now - self._t_start, 1),
            "epoch": epoch,
            "msgs": msgs,
            "faults": faults,
        }
        if msgs is not None and self._last_msgs is not None and dt > 0:
            beat["msgs_per_s"] = round((msgs - self._last_msgs) / dt, 1)
        self._last_msgs = msgs
        if self.counters_fn is not None:
            cur = dict(self.counters_fn())
            delta = {
                k: round(cur[k] - self._last_counters.get(k, 0), 4)
                for k in cur
                if cur[k] != self._last_counters.get(k, 0)
            }
            self._last_counters = cur
            beat["counters_delta"] = delta
            if dt > 0:
                # NOTE: under pipelined dispatch (ops/pipeline.py) the
                # per-dispatch [dispatch, fetch] intervals overlap, so
                # device_share may legitimately exceed 1.0 — it reads as
                # "device dispatch wall including overlapped assembly".
                beat["device_share"] = round(
                    delta.get("device_seconds", 0.0) / dt, 4
                )
                host = delta.get("host_assembly_seconds", 0.0)
                if host:
                    beat["host_assembly_share"] = round(host / dt, 4)
                ovl = delta.get("overlap_seconds", 0.0)
                dev = delta.get("device_seconds", 0.0)
                if ovl and dev > 0:
                    beat["overlap_fraction"] = round(ovl / dev, 4)
        if self.shard_stats_fn is not None:
            try:
                st = self.shard_stats_fn()
            except Exception:  # a heartbeat must never raise on a hook
                st = None
            if st:
                beat["shard_imbalance"] = st.get("shard_imbalance")
                beat["shard_dispatches"] = st.get("shard_dispatches")
        beat.update(extra)
        self._add_gate(beat)
        self.beats.append(beat)
        self.sink(beat)
        return beat
