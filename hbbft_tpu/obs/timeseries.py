"""Per-epoch telemetry time-series (`MetricsLog`).

Every number PERF.md has recorded since round 5 is a run-end aggregate;
this module is the per-epoch series to regress against.  A
:class:`MetricsLog` is a bounded ring of per-epoch snapshot rows —
counter deltas (via :meth:`~hbbft_tpu.utils.metrics.Counters.delta`
snapshots, never a mid-run ``reset()``), histogram windows, host-bucket
splits, the controller's live batch size B, mempool depth, crash state,
and the epoch's critical-path gate — JSONL-exportable and threaded
through ``ArrayHoneyBadgerNet``/``VirtualNet`` (``metrics_log``
environment attribute), ``bench.py`` rows (``BENCH_SERIES``), and
``net/scenarios.run_cell``.

Determinism contract (this module is in the determinism lint scope): no
wall-clock reads — rows carry only caller-provided values — and, by
default, float-valued (wall-derived ``*_seconds``) counter fields are
EXCLUDED from rows so a seeded replay reproduces the series
bit-identically (``include_timing=True`` opts benches back in).
"""

from __future__ import annotations

import json
from collections import deque
from typing import Any, Dict, List, Optional


class MetricsLog:
    """Bounded per-epoch snapshot ring (``deque(maxlen=capacity)``)."""

    __slots__ = ("capacity", "include_timing", "rows", "_last", "_last_hist", "_emitted")

    def __init__(self, capacity: int = 4096, include_timing: bool = False) -> None:
        self.capacity = capacity
        self.include_timing = include_timing
        self.rows: deque = deque(maxlen=capacity)
        self._last: Dict[str, Any] = {}
        self._last_hist: Dict[str, int] = {}
        self._emitted = 0

    # -- snapshotting ------------------------------------------------------

    def snap(
        self,
        epoch: int,
        counters: Optional[Dict[str, Any]] = None,
        tracer: Any = None,
        crash: Optional[Dict[str, Any]] = None,
        controller_b: Optional[int] = None,
        mempool_depth: Optional[int] = None,
        gate: Any = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Append one epoch row.  ``counters`` is a merged
        ``Counters.snapshot()`` dict (net + backend); the row records the
        DELTA against the previous snap, so the underlying counters stay
        monotonic and run-end aggregates stay unskewed.  ``gate`` is the
        epoch's :class:`~hbbft_tpu.obs.critpath.EpochCritPath` (or an
        equivalent dict)."""
        row: Dict[str, Any] = {"epoch": epoch}
        if counters is not None:
            prev = self._last
            delta: Dict[str, Any] = {}
            buckets: Dict[str, float] = {}
            for k in sorted(counters):
                d = counters[k] - prev.get(k, 0)
                if not d:
                    continue
                if isinstance(d, float):
                    if not self.include_timing:
                        continue  # wall-derived: excluded for replay identity
                    d = round(d, 9)
                if k.startswith("host_bucket_"):
                    buckets[k[len("host_bucket_"):]] = d
                else:
                    delta[k] = d
            self._last = dict(counters)
            row["counters"] = delta
            if buckets:
                row["host_buckets"] = buckets
        if tracer is not None:
            window: Dict[str, Dict[str, float]] = {}
            summary = tracer.hist_summary()
            for name in sorted(summary):
                s = dict(summary[name])
                count = int(s.get("count", 0))
                s["window_count"] = count - self._last_hist.get(name, 0)
                self._last_hist[name] = count
                if s["window_count"]:
                    window[name] = s
            if window:
                row["hist"] = window
        if crash is not None:
            row["crash"] = crash
        if controller_b is not None:
            row["b"] = controller_b
        if mempool_depth is not None:
            row["mempool"] = mempool_depth
        if gate is not None:
            g = gate.to_dict() if hasattr(gate, "to_dict") else dict(gate)
            row["gate"] = {
                "phase": g.get("gate_phase", g.get("phase")),
                "instance": g.get("gate_instance", g.get("instance")),
                "node": g.get("gate_node", g.get("node")),
                "round": g.get("gate_round", g.get("round")),
                "cranks": g.get("cranks", 0),
            }
        if extra:
            for k in sorted(extra):
                row[k] = extra[k]
        self.rows.append(row)
        self._emitted += 1
        return row

    # -- access / export ---------------------------------------------------

    @property
    def dropped(self) -> int:
        return self._emitted - len(self.rows)

    def __len__(self) -> int:
        return len(self.rows)

    def last(self) -> Optional[Dict[str, Any]]:
        return self.rows[-1] if self.rows else None

    def rows_list(self) -> List[Dict[str, Any]]:
        return list(self.rows)

    def to_jsonl(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            for row in self.rows:
                f.write(json.dumps(row, sort_keys=True, default=repr) + "\n")


def snap_net(
    log: MetricsLog,
    net: Any,
    epoch: int,
    gate: Any = None,
    controller_b: Optional[int] = None,
    mempool_depth: Optional[int] = None,
) -> Dict[str, Any]:
    """One VirtualNet epoch row: merged counters, crash state, and the
    crank/virtual-clock context (duck-typed — any net exposing
    ``metrics()``/``cranks``/``now`` works)."""
    crash = None
    cm = getattr(net, "crash", None)
    if cm is not None:
        st = cm.stats()
        crash = {
            "crashes": st["crashes"],
            "restarts": st["restarts"],
            "down": sorted(repr(i) for i in net.down_node_ids()),
        }
    return log.snap(
        epoch,
        counters=net.metrics(),
        crash=crash,
        controller_b=controller_b,
        mempool_depth=mempool_depth,
        gate=gate,
        extra={"cranks": net.cranks, "now": net.now},
    )
