"""Epoch critical-path reconstruction (commit-latency attribution).

A HoneyBadger epoch is a DAG — N RBC instances feed N BA instances feed
per-proposer threshold-decrypts feed one batch commit — so epoch latency
is gated by one *chain* through that DAG.  This module rebuilds that
chain per epoch from two evidence sources and attributes latency to
phase x instance x node with per-contributor slack:

* **Completion events** (object runtime): the protocols stamp
  lightweight events at their output seams — RBC decode
  (``broadcast.py``), BA decision + coin reveal
  (``binary_agreement.py``), decrypt combine + batch commit
  (``honey_badger.py``) — via the module-level :func:`stamp` hook.  A
  :class:`CritPathRecorder` installed with :func:`activate` receives
  them, time-stamped with the virtual-clock/crank context the net feeds
  through :meth:`CritPathRecorder.tick`.  Zero cost when no recorder is
  active (one module-global ``is None`` check per protocol output — the
  same discipline as ``utils/metrics.EventLog``).
* **Tracer spans / phase stamps** (lockstep array engine): the engine's
  per-epoch phase wall stamps (``EpochReport.phase_seconds``) collapse
  to a path via :func:`path_from_phase_seconds`; a full Chrome trace
  collapses via ``tools/trace_report.py --critical-path``.

Determinism contract (this module is in the determinism lint scope):
no wall-clock reads — every timestamp arrives from the caller (virtual
cranks, tracer clocks) — and all dict/set iteration is sorted.
"""

from __future__ import annotations

from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

#: The closed phase vocabulary.  Every :func:`stamp` call site in the
#: protocols/engine must pass one of these literals, and each phase
#: bills exactly one tracer span category (PHASE_SPAN_CATS) — the
#: static registry guard (tests/test_phase_labels.py) pins both, so
#: critpath phase names cannot drift from the span kinds they bill.
PHASES = (
    "rbc.output",
    "ba.decide",
    "coin.reveal",
    "decrypt.combine",
    "epoch.commit",
    "crank",
    "crash:recovery",
)

#: phase -> the array-engine tracer span category it attributes
#: (engine/array_engine.py span vocabulary: cat= literals).
PHASE_SPAN_CATS = {
    "rbc.output": "rbc",
    "ba.decide": "ba",
    "coin.reveal": "coin",
    "decrypt.combine": "decrypt",
    "epoch.commit": "epoch",
    "crank": "crank",
    "crash:recovery": "crash",
}

_PHASE_SET = frozenset(PHASES)

#: engine phase-stamp key -> phase name (path_from_phase_seconds input).
_ENGINE_PHASES = {
    "rbc": "rbc.output",
    "ba": "ba.decide",
    "coin": "coin.reveal",
    "decrypt": "decrypt.combine",
    "crash:recovery": "crash:recovery",
}

# -- the module-level stamp hook -------------------------------------------

_ACTIVE: Optional["CritPathRecorder"] = None


def activate(recorder: "CritPathRecorder") -> "CritPathRecorder":
    """Install ``recorder`` as the process-wide stamp sink (single
    runtime at a time — harnesses activate around a run and deactivate
    in a ``finally``)."""
    global _ACTIVE
    _ACTIVE = recorder
    return recorder


def deactivate() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional["CritPathRecorder"]:
    return _ACTIVE


def stamp(
    phase: str,
    node: Any = None,
    instance: Optional[int] = None,
    rnd: Optional[int] = None,
    epoch: Optional[int] = None,
    value: Any = None,
) -> None:
    """Record a completion event on the active recorder (no-op when none
    is active).  Called from the protocol output seams."""
    r = _ACTIVE
    if r is not None:
        r.stamp(phase, node=node, instance=instance, rnd=rnd, epoch=epoch, value=value)


class CritPathRecorder:
    """Bounded ring of completion events with crank/virtual-clock
    context; drained per epoch by the harness (net/scenarios.run_cell)
    into flight-recorder frames."""

    __slots__ = (
        "capacity",
        "events",
        "crank",
        "now",
        "dropped",
        "last_path",
        "_recovering",
        "_emitted",
    )

    def __init__(self, capacity: int = 65536) -> None:
        self.capacity = capacity
        self.events: deque = deque(maxlen=capacity)
        self.crank = 0
        self.now = 0
        self.dropped = 0
        #: the most recent epoch's reconstructed path (the health
        #: reporter's "last epoch gated by ..." one-liner reads this)
        self.last_path: Optional["EpochCritPath"] = None
        self._recovering: List[Any] = []
        self._emitted = 0

    def tick(self, crank: int, now: int) -> None:
        """Per-crank/virtual-clock-tick context update (the net calls
        this once per crank; stamps inherit the latest tick)."""
        self.crank = crank
        self.now = now

    def stamp(
        self,
        phase: str,
        node: Any = None,
        instance: Optional[int] = None,
        rnd: Optional[int] = None,
        epoch: Optional[int] = None,
        value: Any = None,
    ) -> None:
        if phase not in _PHASE_SET:
            raise ValueError(f"unknown critpath phase {phase!r} (PHASES: {PHASES})")
        ev: Dict[str, Any] = {
            "phase": phase,
            "node": node,
            "instance": instance,
            "round": rnd,
            "epoch": epoch,
            "crank": self.crank,
            "now": self.now,
        }
        if value is not None:
            ev["value"] = value
        if self._recovering and phase != "crash:recovery":
            # WAL replay after a restart: re-derived outputs are recovery
            # work, not consensus progress — bill the pseudo-phase and
            # keep the original phase as ``via`` for forensics.
            ev["via"] = phase
            ev["phase"] = "crash:recovery"
            ev["recovering"] = self._recovering[-1]
        if len(self.events) == self.capacity:
            self.dropped += 1
        self.events.append(ev)
        self._emitted += 1

    # -- crash/WAL-replay scoping (net/crash.py _restart) ------------------

    def begin_recovery(self, node: Any) -> None:
        self._recovering.append(node)
        self.stamp("crash:recovery", node=node)

    def end_recovery(self) -> None:
        if self._recovering:
            self._recovering.pop()

    # -- draining ----------------------------------------------------------

    def take(self) -> List[Dict[str, Any]]:
        """Drain and return the buffered events (harness epoch boundary)."""
        out = list(self.events)
        self.events.clear()
        return out

    def gate_line(self) -> Optional[str]:
        p = self.last_path
        return None if p is None else p.one_liner()


# -- the reconstructed path -------------------------------------------------


def phase_label(
    phase: str, instance: Optional[int] = None, rnd: Optional[int] = None
) -> str:
    """Human vocabulary for one chain link: ``BA(7) coin round 3``."""
    inst = "*" if instance is None else str(instance)
    if phase == "rbc.output":
        return f"RBC({inst}) output"
    if phase == "ba.decide":
        return f"BA({inst}) decision" + (f" round {rnd}" if rnd is not None else "")
    if phase == "coin.reveal":
        return f"BA({inst}) coin" + (f" round {rnd}" if rnd is not None else "")
    if phase == "decrypt.combine":
        return f"decrypt.combine({inst})"
    if phase == "epoch.commit":
        return "epoch commit"
    return phase


@dataclass
class EpochCritPath:
    """One epoch's gating chain + latency attribution."""

    epoch: int
    gate_phase: str
    gate_instance: Optional[int] = None
    gate_node: Optional[str] = None  # repr'd node id (JSON-stable)
    gate_round: Optional[int] = None
    #: epoch latency in the three units the gate attributes
    cranks: int = 0
    wall_s: float = 0.0
    device_s: float = 0.0
    #: commit-first chain links: [{"phase", "instance", "node", "round",
    #: "crank", "seg_cranks"|"seg_s"}, ...] — read as
    #: ``epoch <- decrypt.combine <- BA(i) coin <- RBC(i)``
    chain: List[Dict[str, Any]] = field(default_factory=list)
    #: per-(phase, instance, node) completion + slack behind the gate
    contributors: List[Dict[str, Any]] = field(default_factory=list)

    def one_liner(self) -> str:
        label = phase_label(self.gate_phase, self.gate_instance, self.gate_round)
        where = f" on node {self.gate_node}" if self.gate_node is not None else ""
        return f"epoch {self.epoch} gated by {label}{where}"

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "EpochCritPath":
        known = {k: d[k] for k in EpochCritPath.__dataclass_fields__ if k in d}
        return EpochCritPath(**known)


def _last_event(
    window: List[Dict[str, Any]],
    phase: str,
    node: Any = None,
    instance: Optional[int] = None,
) -> Optional[Dict[str, Any]]:
    for ev in reversed(window):
        if ev.get("phase") != phase:
            continue
        if node is not None and ev.get("node") != node:
            continue
        if instance is not None and ev.get("instance") != instance:
            continue
        return ev
    return None


def _link(ev: Dict[str, Any], seg_cranks: int) -> Dict[str, Any]:
    return {
        "phase": ev.get("via") or ev.get("phase"),
        "instance": ev.get("instance"),
        "node": repr(ev.get("node")),
        "round": ev.get("round"),
        "crank": ev.get("crank", 0),
        "seg_cranks": seg_cranks,
    }


def _window_path(
    epoch: int, window: List[Dict[str, Any]], commit: Dict[str, Any]
) -> EpochCritPath:
    gate_node = commit.get("node")
    start_crank = window[0].get("crank", 0) if window else 0
    # walk the chain backwards from the slowest node's commit
    dec = _last_event(window, "decrypt.combine", node=gate_node)
    ba = _last_event(window, "ba.decide", node=gate_node)
    coin = None
    if ba is not None:
        coin = _last_event(
            window, "coin.reveal", node=gate_node, instance=ba.get("instance")
        )
    rbc = None
    if ba is not None:
        rbc = _last_event(
            window, "rbc.output", node=gate_node, instance=ba.get("instance")
        )
    if rbc is None:
        rbc = _last_event(window, "rbc.output", node=gate_node)
    temporal = [ev for ev in (rbc, coin, ba, dec, commit) if ev is not None]
    temporal.sort(key=lambda ev: ev.get("crank", 0))  # stable: ties keep order
    links: List[Dict[str, Any]] = []
    prev = start_crank
    for ev in temporal:
        c = ev.get("crank", 0)
        links.append(_link(ev, max(0, c - prev)))
        prev = max(prev, c)
    # the gating link owns the longest crank stretch (ties -> latest link)
    gate_link = links[-1] if links else _link(commit, 0)
    best = -1
    for ln in links:
        if ln["seg_cranks"] >= best:
            best = ln["seg_cranks"]
            gate_link = ln
    recov = [ev for ev in window if ev.get("phase") == "crash:recovery"]
    if recov:
        last = recov[-1]
        who = last.get("recovering", last.get("node"))
        gate_phase: str = "crash:recovery"
        gate_instance = None
        gate_round = None
        gate_node_r = repr(who)
        links.insert(0, _link(last, 0))
    else:
        gate_phase = gate_link["phase"]
        gate_instance = gate_link["instance"]
        gate_round = gate_link["round"]
        gate_node_r = gate_link["node"]
    commit_crank = commit.get("crank", 0)
    # per-contributor slack: the last completion per (phase, instance,
    # node), measured behind the commit — the critical contributor has
    # zero slack, everything that finished earlier had room to be slower
    latest: Dict[Any, Dict[str, Any]] = {}
    for ev in window:
        ph = ev.get("phase")
        if ph in ("crank", "epoch.commit"):
            continue
        key = (ph, repr(ev.get("instance")), repr(ev.get("node")))
        cur = latest.get(key)
        if cur is None or ev.get("crank", 0) >= cur.get("crank", 0):
            latest[key] = ev
    contributors = [
        {
            "phase": key[0],
            "instance": latest[key].get("instance"),
            "node": repr(latest[key].get("node")),
            "round": latest[key].get("round"),
            "crank": latest[key].get("crank", 0),
            "slack": max(0, commit_crank - latest[key].get("crank", 0)),
        }
        for key in sorted(latest, key=repr)
    ]
    contributors.sort(key=lambda c: (c["slack"], repr(c["phase"]), repr(c["node"])))
    return EpochCritPath(
        epoch=epoch,
        gate_phase=gate_phase,
        gate_instance=gate_instance,
        gate_node=gate_node_r,
        gate_round=gate_round,
        cranks=max(0, commit_crank - start_crank),
        chain=list(reversed(links)),
        contributors=contributors[:64],
    )


def paths_from_events(events: List[Dict[str, Any]]) -> List[EpochCritPath]:
    """Reconstruct per-epoch gating chains from stamped completion
    events (arrival order preserved; an epoch's window closes at its
    LAST ``epoch.commit`` — the slowest node is the gate)."""
    events = list(events)
    last_commit: Dict[int, int] = {}
    for i, ev in enumerate(events):
        if ev.get("phase") == "epoch.commit" and isinstance(ev.get("epoch"), int):
            last_commit[ev["epoch"]] = i
    paths: List[EpochCritPath] = []
    prev = -1
    for ep in sorted(last_commit):
        end = last_commit[ep]
        if end <= prev:
            continue  # interleaved late commit of an already-closed epoch
        window = events[prev + 1 : end + 1]
        paths.append(_window_path(ep, window, events[end]))
        prev = end
    return paths


def path_from_phase_seconds(
    epoch: int,
    phase_seconds: Dict[str, float],
    cranks: int = 0,
    device_s: float = 0.0,
) -> EpochCritPath:
    """The lockstep array engine's path: phase wall stamps (rbc / ba /
    coin / decrypt, EpochReport.phase_seconds) collapse to a chain whose
    gate is the longest phase.  Instances are degenerate (lockstep runs
    all N in the same wall interval), so the gate names phase only."""
    durs: Dict[str, float] = {}
    for k in sorted(phase_seconds):
        ph = _ENGINE_PHASES.get(k)
        if ph is not None:
            durs[ph] = durs.get(ph, 0.0) + phase_seconds[k]
    gate_phase = "epoch.commit"
    best = -1.0
    for ph in sorted(durs):
        if durs[ph] > best:
            best = durs[ph]
            gate_phase = ph
    chain = [
        {"phase": ph, "instance": None, "node": None, "round": None, "seg_s": round(durs[ph], 6)}
        for ph in sorted(durs, key=lambda p: -durs[p])
    ]
    return EpochCritPath(
        epoch=epoch,
        gate_phase=gate_phase,
        cranks=cranks,
        wall_s=round(sum(durs.values()), 6),
        device_s=round(device_s, 6),
        chain=chain,
    )


# -- run-level aggregation --------------------------------------------------


def gating_histogram(paths: List[EpochCritPath]) -> Dict[str, float]:
    """Run-level gating shares: fraction of epochs each phase gated
    ('BA coin rounds gate 61% of epochs')."""
    counts: Dict[str, int] = {}
    for p in paths:
        counts[p.gate_phase] = counts.get(p.gate_phase, 0) + 1
    total = sum(counts[k] for k in counts)
    if not total:
        return {}
    return {k: round(counts[k] / total, 4) for k in sorted(counts)}


def gating_from_series(rows: List[Dict[str, Any]]) -> Dict[str, float]:
    """Gating histogram straight from MetricsLog rows (their ``gate``
    field) — the series-capture form tools/trace_report.py diffs."""
    counts: Dict[str, int] = {}
    for r in rows:
        g = r.get("gate")
        if isinstance(g, dict) and g.get("phase"):
            counts[g["phase"]] = counts.get(g["phase"], 0) + 1
    total = sum(counts[k] for k in counts)
    if not total:
        return {}
    return {k: round(counts[k] / total, 4) for k in sorted(counts)}


def diff_gating(
    old: Dict[str, float], new: Dict[str, float], tol: float = 0.10
) -> List[Dict[str, Any]]:
    """Phase-share shifts beyond ``tol`` between two gating histograms
    (absolute share points; >tol is a regression-gate failure)."""
    out = []
    for ph in sorted(set(old) | set(new)):
        a, b = old.get(ph, 0.0), new.get(ph, 0.0)
        if abs(b - a) > tol:
            out.append(
                {"phase": ph, "old": round(a, 4), "new": round(b, 4), "shift": round(b - a, 4)}
            )
    return out
