from hbbft_tpu.parallel.backend import MeshBackend
from hbbft_tpu.parallel.mesh import (
    BATCH_AXIS,
    device_mesh,
    shard_batch,
    sharded_combine_g2_fn,
    sharded_product2_fn,
)

__all__ = [
    "BATCH_AXIS",
    "MeshBackend",
    "device_mesh",
    "shard_batch",
    "sharded_combine_g2_fn",
    "sharded_product2_fn",
]
