"""Mesh scale-out package.

Lazy exports (PEP 562): ``shardpipe`` is import-light by design — the
race explorer and tier-1 drive :class:`ShardedDispatchPipeline` with
MockBackend entries on no-JAX paths (tools/ci.sh budget) — so importing
``hbbft_tpu.parallel.shardpipe`` must not drag ``backend``/``mesh`` (and
therefore jax) in through this package init.
"""

import importlib

_LAZY = {
    "MeshBackend": "hbbft_tpu.parallel.backend",
    "BATCH_AXIS": "hbbft_tpu.parallel.mesh",
    "device_mesh": "hbbft_tpu.parallel.mesh",
    "shard_batch": "hbbft_tpu.parallel.mesh",
    "sharded_combine_g2_fn": "hbbft_tpu.parallel.mesh",
    "sharded_product2_fn": "hbbft_tpu.parallel.mesh",
    "ShardedDispatchPipeline": "hbbft_tpu.parallel.shardpipe",
    "placement_policy": "hbbft_tpu.parallel.shardpipe",
    "shardpipe_enabled": "hbbft_tpu.parallel.shardpipe",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
