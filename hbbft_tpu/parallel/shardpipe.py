"""Per-device pipelined shard dispatch — the mesh scale-out seam (PR 18).

The single-chip pipeline (ops/pipeline.py) keeps ONE device busy by
deferring fetches behind a bounded FIFO.  ``MeshBackend`` used to stretch
that to a mesh by sharding every dispatch's batch axis 8 ways — which
keeps all devices *synchronized*, not *busy*: each small lane-capped
chunk is split into 8 slivers (launch overhead and pad lanes eat the
win), and every chunk still runs as one collective step.

:class:`ShardedDispatchPipeline` instead gives every device its own
bounded in-flight queue and lands WHOLE chunks on distinct devices:
chunk k goes to device d_k while chunk k+1 stages on host and chunk k+2
executes elsewhere.  The dispatch layer stays single-threaded — the
parallelism is the devices' own async streams, exactly as in PR 3 —
and the single ``fetch_to_host`` sync point is preserved.

Contract (on top of the base pipeline's):

* **Deterministic placement.**  ``reserve_device()`` picks the target
  device BEFORE the launch (placement decides where the jitted call
  runs) under ``HBBFT_TPU_SHARD_PLACEMENT`` — ``round_robin`` (default)
  or ``least_loaded`` (fewest in-flight entries; ties to the lowest
  index).  Every decision is appended to :attr:`placements`, so a seeded
  replay re-derives the identical placement sequence bit-for-bit.
* **Completion order is a checked property.**  Per-device queues are
  FIFO (a device stream completes in order); CROSS-device order is the
  schedule freedom.  The default drain resolves in global submission
  order — byte-compatible with the single-queue pipeline — and the
  :attr:`choose_shard` hook hands that freedom to the race explorer
  (analysis/schedules.py), which audits that delivery callbacks really
  are slot-disjoint.  ``RaceTracker`` records a per-device-queue
  footprint on every submit/resolve, so same-device entries are ordered
  and cross-device entries surface as the racing pairs they are.
* **Kill switch.**  ``HBBFT_TPU_NO_SHARD_PIPE=1`` makes MeshBackend
  reserve nothing — every dispatch falls back to the single-queue SPMD
  path with bit-identical Batches and conserved ``device_dispatches``
  (asserted in tests/test_shard_pipe.py).
* **Per-device attribution.**  Each sharded dispatch's span lands on the
  ``device/<n>`` tracer track of its device, its [t0, t1] interval bills
  ``dev_seconds[n]`` alongside the global ``counters.device_seconds``,
  and every full drain records a ``shard_imbalance`` histogram sample
  (max/mean of the window's per-device dispatch counts; 1.0 = balanced).
  tools/trace_report.py checks that the per-device spans sum to
  ``device_seconds`` ±5%.

Import-light like the base module (no jax/numpy at module scope): the
explorer's MockBackend shard target and tier-1 run this exact class with
host-computed entries — no devices needed beyond the virtual mesh.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Callable, List, Optional

from hbbft_tpu.ops.pipeline import (
    DispatchPipeline,
    PendingDispatch,
    fetch_to_host,
    pipeline_depth,
)


def shardpipe_enabled() -> bool:
    """Kill switch for the per-device shard pipeline.  Re-read per
    placement so in-process A/Bs (``HBBFT_TPU_NO_SHARD_PIPE=1`` vs.
    default) take effect immediately."""
    return not os.environ.get("HBBFT_TPU_NO_SHARD_PIPE")


def placement_policy() -> str:
    """``HBBFT_TPU_SHARD_PLACEMENT``: ``round_robin`` (default) or
    ``least_loaded``.  Unknown values fall back to round_robin rather
    than erroring mid-epoch."""
    p = os.environ.get("HBBFT_TPU_SHARD_PLACEMENT", "round_robin")
    return p if p in ("round_robin", "least_loaded") else "round_robin"


class ShardPendingDispatch(PendingDispatch):
    """A pending dispatch that knows its device and global submit order.

    ``device`` is the reserved device index (None for unreserved entries
    riding the base single queue — sync dispatches and SPMD fallbacks);
    ``seq`` is the global submission sequence number the deterministic
    drain replays program order from."""

    __slots__ = ("device", "seq")


class ShardedDispatchPipeline(DispatchPipeline):
    """One bounded in-flight queue per device + the base single queue.

    ``n_devices`` fixes the queue fan-out.  ``reserve_device()`` must be
    called immediately before a ``submit()`` that should land whole on
    one device (the backend's ``_place`` hook does both in one breath);
    an un-reserved submit rides the base queue exactly as before.
    """

    def __init__(
        self,
        n_devices: int,
        counters=None,
        tracer_ref: Optional[Callable[[], Any]] = None,
        depth_fn: Callable[[], int] = pipeline_depth,
    ) -> None:
        super().__init__(counters, tracer_ref, depth_fn)
        self.n_devices = int(n_devices)
        self._dev_q: List[deque] = [deque() for _ in range(self.n_devices)]
        self._rr_next = 0  # round-robin cursor (submit-path only)
        self._reserved: Optional[int] = None
        self._seq = 0
        #: recorded placement decisions, in submission order — the seeded
        #: replay's bit-identity witness (tests compare A/B runs on it)
        self.placements: List[int] = []
        #: per-device tallies (NOT Counters fields — the slotted dataclass
        #: is fixed-width; these live and die with the pipeline object)
        self.dev_dispatches: List[int] = [0] * self.n_devices
        self.dev_seconds: List[float] = [0.0] * self.n_devices
        #: dispatch counts since the last imbalance sample (full drain)
        self._window_disp: List[int] = [0] * self.n_devices
        #: explorer hook: ``choose_shard(ready_device_ids) -> position``
        #: picks which nonempty device queue resolves its head next.
        #: None = global submission order (the deterministic default).
        self.choose_shard: Optional[Callable[[List[int]], int]] = None

    def __len__(self) -> int:
        return len(self._q) + sum(len(q) for q in self._dev_q)

    # -- placement -----------------------------------------------------------

    def reserve_device(self) -> int:
        """Pick (and record) the device for the NEXT submit.

        Round-robin walks a submit-path-only cursor; least-loaded reads
        the per-device queue depths.  Both are pure functions of the
        deterministic single-threaded program state at this call, so a
        seeded replay reproduces the identical placement sequence —
        :attr:`placements` is the recorded proof."""
        if placement_policy() == "least_loaded":
            # Queue depths mutate only at the deterministic program
            # points where resolves run (flush / depth trim / sync
            # drain), so the load seen here is a pure function of
            # program order; the decision is recorded in `placements`
            # and asserted replay-identical — and placement can only
            # change WHERE a chunk runs, never its slot-written value.
            d = min(range(self.n_devices), key=lambda i: (len(self._dev_q[i]), i))
        else:
            d = self._rr_next
            self._rr_next = (d + 1) % self.n_devices
        self._reserved = d
        self.placements.append(d)
        return d

    # -- submit/resolve ------------------------------------------------------

    def submit(
        self,
        launch: Callable[[], Any],
        fetch: Optional[Callable[[Any], Any]] = fetch_to_host,
        kind: str = "",
        items: int = 0,
        on_result: Optional[Callable[[Any], None]] = None,
        sync: bool = False,
    ) -> PendingDispatch:
        """Base-pipeline semantics, routed per device.

        A reserved submit enqueues on its device's bounded queue (depth
        ``depth_fn()`` per device).  ``sync=True`` / depth 0 first drains
        EVERY queue in deterministic order — the single sync point spans
        the whole mesh, exactly as the one-queue pipeline's did."""
        dev = self._reserved
        self._reserved = None
        depth = 0 if sync else self._depth_fn()
        t0 = time.perf_counter()
        raw = launch()
        t_issued = time.perf_counter()
        slot = None if depth <= 0 else self._alloc_slot()
        p = ShardPendingDispatch(
            self, raw, fetch, kind, items, slot, on_result, t0, t_issued
        )
        p.device = dev
        p.seq = self._seq
        self._seq += 1
        if dev is not None:
            self.dev_dispatches[dev] += 1
            # lint: allow[seam-race] imbalance-window tally: read only by
            # the full-drain sampler (a deterministic program point), and
            # only into a tracer histogram — never into delivered values
            self._window_disp[dev] += 1
        if self.probe is not None:
            self.probe.pipe_submit(p)
        if depth <= 0:
            # Full drain first: delivery order degenerates to program
            # order across ALL queues — byte-compatible with both the
            # pre-pipeline seam and the single-queue sync path.
            self._drain(use_hook=False)
            self._resolve(p)
            return p
        if dev is None:
            # lint: allow[seam-race] _q IS the pipeline API (see base
            # class): the bounded FIFO handoff itself, slot-disjoint
            self._q.append(p)
            while len(self._q) > depth:
                self._q.popleft().resolve()
            return p
        q = self._dev_q[dev]
        # lint: allow[seam-race] _dev_q IS the pipeline API: the base
        # class's bounded-FIFO-handoff allowance, one queue per device;
        # entries are opaque and every delivery writes only its own slots
        q.append(p)
        # Per-DEVICE launch-then-trim: each device holds up to `depth`
        # unfetched chunks, so total in-flight scales with the mesh —
        # that is the point (8 devices each depth-2 busy, not 1).
        while len(q) > depth:
            q.popleft().resolve()
        return p

    def flush(self, order: Optional[List[int]] = None) -> None:
        """Resolve everything pending.  ``order`` (a permutation of the
        base queue's pending list — the MockBackend legacy hook) applies
        to base-queue entries only; device queues then drain under
        :attr:`choose_shard` or global submission order."""
        if order is not None:
            super().flush(order=order)
        self._drain(use_hook=True)
        self._sample_imbalance()

    def _drain(self, use_hook: bool) -> None:
        """Drain all queues to empty.

        Device queues are FIFO internally (a device stream completes in
        order); the cross-device interleaving is the schedule freedom:
        ``choose_shard`` picks among the ready devices when attached,
        otherwise heads resolve in global submission order — which equals
        the single-queue FIFO order, keeping the kill-switch A/B's
        delivery order identical.  Base-queue entries (sync/SPMD) are
        merged by the same submission-order rule and are never handed to
        the hook — their order is already program-determined."""
        while True:
            heads = []
            if self._q:
                heads.append((self._q[0].seq, -1))
            for d in range(self.n_devices):
                if self._dev_q[d]:
                    heads.append((self._dev_q[d][0].seq, d))
            if not heads:
                return
            ready = [d for _, d in heads if d >= 0]
            if (
                use_hook
                and self.choose_shard is not None
                and not self._q
                and len(ready) > 1
            ):
                d = ready[self.choose_shard(list(ready))]
                self._dev_q[d].popleft().resolve()
                continue
            _, d = min(heads)
            (self._q if d < 0 else self._dev_q[d]).popleft().resolve()

    # -- base-class hooks ----------------------------------------------------

    def _track_for(self, p: PendingDispatch) -> str:
        """Sharded entries span their DEVICE's track (``device/<n>``) —
        the per-device observability axis.  Unreserved ASYNC entries
        (base-queue riders, e.g. SPMD fallbacks) get ``device/q<slot>``
        so slot numbers cannot masquerade as device indices; sync
        entries keep the classic ``device`` track."""
        d = getattr(p, "device", None)
        if d is not None:
            return f"device/{d}"
        if p.slot is None:
            return "device"
        return f"device/q{p.slot}"

    def _bill_device(self, p: PendingDispatch, dt: float) -> None:
        d = getattr(p, "device", None)
        if d is not None:
            self.dev_seconds[d] += dt

    # -- observability -------------------------------------------------------

    def _sample_imbalance(self) -> None:
        """One ``shard_imbalance`` histogram sample per full drain whose
        window dispatched anything: max/mean of the window's per-device
        dispatch counts (1.0 = perfectly balanced, n_devices = all work
        on one device)."""
        total = sum(self._window_disp)
        if not total:
            return
        tr = self._tracer_ref() if self._tracer_ref is not None else None
        if tr is not None:
            mean = total / self.n_devices
            tr.hist("shard_imbalance").record(max(self._window_disp) / mean)
        self._window_disp = [0] * self.n_devices

    def imbalance(self) -> float:
        """Cumulative max/mean per-device dispatch ratio (1.0 = balanced;
        0.0 before any sharded dispatch) — the heartbeat field."""
        total = sum(self.dev_dispatches)
        if not total:
            return 0.0
        return max(self.dev_dispatches) / (total / self.n_devices)
