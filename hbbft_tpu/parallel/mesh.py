"""Multi-chip sharding of the crypto batch dimension.

BASELINE.json config 5: at N=256 the per-epoch share-verification batch is
sharded over the chips of a v5e-8 slice; per-item pairing work is purely
data-parallel (rides each chip's VPU/MXU), while share *combination*
all-gathers partial Jacobian sums over ICI.

The batch axis is the (epoch × node × instance × share) work-item axis from
SURVEY.md §2.3 — the only scaling axis this framework has, playing the role
DP/TP/SP play in an ML stack.

Everything here works identically on a real multi-chip slice and on the
virtual 8-device CPU mesh used in CI (tests/conftest.py).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hbbft_tpu.ops import curve, pairing

BATCH_AXIS = "batch"


def device_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first n (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), axis_names=(BATCH_AXIS,))


def _batch_sharding(mesh: Mesh, leaf: jnp.ndarray) -> NamedSharding:
    """Shard the leading (batch) axis, replicate the rest."""
    spec = P(BATCH_AXIS, *([None] * (leaf.ndim - 1)))
    return NamedSharding(mesh, spec)


def shard_batch(tree: Any, mesh: Mesh) -> Any:
    """device_put every leaf with its leading axis split over the mesh.

    Accepts host numpy leaves (including rows gathered from the
    ops/staging limb-row cache) as well as committed device arrays —
    this is MeshBackend's ``_place`` hook, called at host-assembly time
    BEFORE the pipelined dispatch launches, so sharded placement
    composes with both the staging cache and the deferred-fetch queue.
    """
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            jnp.asarray(leaf), _batch_sharding(mesh, jnp.asarray(leaf))
        ),
        tree,
    )


def sharded_product2_fn(mesh: Mesh, fused=None):
    """Jitted sharded (P1,Q1,P2,Q2) → fq12 limbs of FE(ML·ML).

    Data-parallel over the mesh: XLA partitions the whole pairing graph on
    the batch axis; no cross-chip traffic until the host gathers results.
    ``fused`` routes each shard's chain onto the VMEM-resident fused
    tower kernels (ops/pairing_chain.py): pass the resolved mode for a
    cache-keyed caller, or leave None to consult the env ladder at TRACE
    time (fine for trace-once callers like the graft entry).
    """

    def wrapped(P1, Q1, P2, Q2):
        args = jax.tree_util.tree_map(
            lambda leaf: jax.lax.with_sharding_constraint(
                leaf, _batch_sharding(mesh, leaf)
            ),
            (P1, Q1, P2, Q2),
        )
        return pairing.product2_fast(*args, fused=fused)

    return jax.jit(wrapped)


def sharded_combine_g2_fn(mesh: Mesh):
    """Jitted sharded Lagrange combine: shares sharded over chips, partial
    Jacobian sums reduced across the mesh (XLA inserts the ICI collective
    for the cross-shard tree-add)."""

    def f(points, bits, negs):
        points = jax.tree_util.tree_map(
            lambda leaf: jax.lax.with_sharding_constraint(
                leaf, _batch_sharding(mesh, leaf)
            ),
            points,
        )
        return curve.linear_combine_g2(points, bits, negs)

    return jax.jit(f)
