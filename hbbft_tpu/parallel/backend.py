"""MeshBackend — the multi-chip crypto backend (ICI/DCN scaling axis).

TpuBackend resolves whole verification/combination batches in single-chip
jitted dispatches; MeshBackend is the same backend with every batch/group
axis sharded over a ``jax.sharding.Mesh`` (BASELINE config 5: "QHB N=256
sustained").  All sharded paths are data-parallel over the item/group
axis — per-item pairing work and per-item Lagrange ladders partition
across chips with no cross-chip traffic until the host gathers results.
The cross-shard Jacobian reduction (one combine whose SHARES span chips,
the literal "ICI all-gather of shares") is the separate
``parallel/mesh.sharded_combine_g2_fn`` kernel, exercised by the
multichip dryrun; protocol workloads batch many independent combines, so
the data-parallel form is the one the backend seam dispatches.

Works identically on a real multi-chip slice and on the virtual
8-device CPU mesh (tests/conftest.py) — the mesh is the only knob.

Pipelining/staging composition (PR 3): MeshBackend inherits TpuBackend's
deferred-fetch pipeline and limb-row staging cache unchanged.  The
staging cache yields HOST numpy rows; ``_place`` (the sharded
``device_put``) runs downstream of it, inside the same timed
host-assembly block, so cached staging and mesh placement compose by
construction — each pipelined chunk is already sharded before its
dispatch is launched, and the bounded in-flight queue bounds per-chip
pending buffers exactly as on one chip.

Reference analogue: none — the reference is sans-I/O and single-process
(SURVEY.md §2.3); this is the TPU-native replacement for the scaling the
reference delegates to its embedder.
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import Mesh

from hbbft_tpu.ops.backend import TpuBackend, _bucket
from hbbft_tpu.parallel.mesh import device_mesh, shard_batch


class MeshBackend(TpuBackend):
    """TpuBackend with batch axes sharded over a device mesh."""

    def __init__(self, mesh: Optional[Mesh] = None) -> None:
        super().__init__()
        self.mesh = mesh or device_mesh()
        self._n_dev = self.mesh.devices.size

    def _pad_bucket(self, n: int) -> int:
        # power-of-two bucket, widened so the sharded axis splits evenly
        # (lcm handles non-power-of-two meshes, e.g. 6 devices)
        import math

        return math.lcm(_bucket(n), self._n_dev)

    def _place(self, tree):
        return shard_batch(tree, self.mesh)

    @property
    def name(self) -> str:
        return f"MeshBackend[{self._n_dev}]"
