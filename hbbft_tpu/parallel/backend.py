"""MeshBackend — the multi-chip crypto backend (ICI/DCN scaling axis).

TpuBackend resolves whole verification/combination batches in single-chip
jitted dispatches; MeshBackend scales the same backend across a
``jax.sharding.Mesh`` (BASELINE config 5: "QHB N=256 sustained").  Since
PR 18 it does so with a PER-DEVICE PIPELINED dispatcher
(parallel/shardpipe.py): every lane-capped chunk of the pipelined chunk
streams — pairing checks, sign/decrypt/DKG ladders, Lagrange combines,
the RLC deferred first round — lands WHOLE on one device picked by a
recorded round-robin/least-loaded policy, with a bounded in-flight queue
per device.  Whole-chunk placement keeps each dispatch's lanes dense
(splitting a small chunk 8 ways burns launch overhead and pad lanes) and
keeps all devices busy concurrently instead of synchronized.

SYNC dispatches (RLC bisection rounds, single combines — control flow
needs the result immediately) still shard their batch axis SPMD over the
whole mesh: one wide collective step is exactly right when the host must
wait for it anyway.  ``HBBFT_TPU_NO_SHARD_PIPE=1`` restores the pre-PR-18
behavior everywhere — single-queue SPMD sharding for every dispatch —
with bit-identical Batches and conserved ``device_dispatches``
(tests/test_shard_pipe.py asserts the A/B).

Small-batch clamp (PR 18 satellite): ``_pad_bucket`` used to widen every
bucket to ``lcm(bucket, n_dev)`` so the sharded axis split evenly — a
singleton dispatch padded to 8 lanes of which 7 were padding.  Buckets
narrower than the mesh now stay at the single-device bucket and the
whole (sub-threshold) chunk routes to one device.

Works identically on a real multi-chip slice and on the virtual
8-device CPU mesh (tests/conftest.py) — the mesh is the only knob.

Pipelining/staging composition (PR 3): the staging cache yields HOST
numpy rows; ``_place`` (the per-device or sharded ``device_put``) runs
downstream of it, inside the same timed host-assembly block, so cached
staging and mesh placement compose by construction — each chunk is
already placed before its dispatch is launched, and the per-device
bounded queues bound per-chip pending buffers exactly as on one chip.

Reference analogue: none — the reference is sans-I/O and single-process
(SURVEY.md §2.3); this is the TPU-native replacement for the scaling the
reference delegates to its embedder.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh

from hbbft_tpu.ops.backend import TpuBackend, _bucket
from hbbft_tpu.parallel.mesh import device_mesh, shard_batch
from hbbft_tpu.parallel.shardpipe import (
    ShardedDispatchPipeline,
    shardpipe_enabled,
)


class MeshBackend(TpuBackend):
    """TpuBackend scaled across a device mesh: whole pipelined chunks on
    distinct devices (default) or batch axes sharded SPMD (sync
    dispatches, and everything under ``HBBFT_TPU_NO_SHARD_PIPE=1``)."""

    def __init__(self, mesh: Optional[Mesh] = None) -> None:
        super().__init__()
        self.mesh = mesh or device_mesh()
        self._n_dev = self.mesh.devices.size
        self._devices = list(self.mesh.devices.flat)
        # swap the inherited single-queue pipe for the per-device one
        # (same counters/tracer/probe contract; the tracer is attached
        # after construction, hence the closure)
        self._pipe = ShardedDispatchPipeline(
            self._n_dev,
            counters=self.counters,
            tracer_ref=lambda: self.tracer,
        )

    def _pad_bucket(self, n: int) -> int:
        # power-of-two bucket, widened so a SHARDED axis splits evenly
        # (lcm handles non-power-of-two meshes, e.g. 6 devices) — but a
        # bucket narrower than the mesh stays single-device-sized: a
        # singleton dispatch padded to n_dev lanes is 7/8 padding, and
        # _place routes such chunks whole to one device instead
        b = _bucket(n)
        if b < self._n_dev:
            return b
        return math.lcm(b, self._n_dev)

    def _place(self, tree, pipelined: bool = False):
        if pipelined and shardpipe_enabled():
            # whole-chunk placement: reserve the device (recorded — the
            # seeded replay re-derives the identical sequence), then
            # commit the staged inputs to it; the jitted call follows
            # its committed inputs, so chunk k runs on device d_k while
            # chunk k+1 stages on host
            d = self._pipe.reserve_device()
            return jax.device_put(tree, self._devices[d])
        leading = jax.tree_util.tree_leaves(tree)[0].shape[0]
        if leading % self._n_dev:
            # sub-threshold bucket (the _pad_bucket clamp): too narrow
            # to shard evenly — the whole chunk goes to one device
            return jax.device_put(tree, self._devices[0])
        return shard_batch(tree, self.mesh)

    def shard_stats(self) -> Dict[str, Any]:
        """Per-device dispatch tallies + cumulative imbalance (max/mean,
        1.0 = balanced) — the heartbeat/bench observability surface."""
        p = self._pipe
        return {
            "shard_devices": p.n_devices,
            "shard_dispatches": list(p.dev_dispatches),
            "shard_seconds": [round(s, 6) for s in p.dev_seconds],
            "shard_imbalance": round(p.imbalance(), 4),
            "shard_placements": len(p.placements),
        }

    @property
    def name(self) -> str:
        return f"MeshBackend[{self._n_dev}]"
