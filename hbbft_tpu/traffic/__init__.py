"""Traffic subsystem: sustained client load, per-tx latency, and the
batch-size throughput/latency curve for QueueingHoneyBadger.

Layers (each its own module, composable):

* :mod:`~hbbft_tpu.traffic.workload` — client populations (Zipf),
  arrival processes (open-loop Poisson / closed-loop fixed concurrency),
  payload-size distributions; rng-injected, replay-deterministic.
* :mod:`~hbbft_tpu.traffic.mempool` — bounded admission over
  ``TransactionQueue``: validation-first submit, capacity with
  reject/evict-oldest policies, hysteresis backpressure.
* :mod:`~hbbft_tpu.traffic.tracker` — per-transaction lifecycle
  (submit → queue → sampled → committed) feeding p50/p90/p99
  commit-latency histograms and sustained-tx/s accounting.
* :mod:`~hbbft_tpu.traffic.driver` — drives QHB-style sampling through
  ``ArrayHoneyBadgerNet`` (contribution-source + batch-listener hooks)
  and through the object protocols for small-N parity.

The ``qhb_traffic`` bench row (bench.py) sweeps batch size × arrival
rate through :class:`~hbbft_tpu.traffic.driver.ArrayTrafficDriver` and
records the throughput/latency curve as data.
"""

from hbbft_tpu.traffic.driver import ArrayTrafficDriver, ObjectTrafficDriver
from hbbft_tpu.traffic.mempool import BoundedMempool
from hbbft_tpu.traffic.tracker import TxTracker
from hbbft_tpu.traffic.workload import (
    ClosedLoopSource,
    OpenLoopSource,
    PayloadSizes,
    ZipfPopulation,
    make_tx,
)

__all__ = [
    "ArrayTrafficDriver",
    "ObjectTrafficDriver",
    "BoundedMempool",
    "TxTracker",
    "ClosedLoopSource",
    "OpenLoopSource",
    "PayloadSizes",
    "ZipfPopulation",
    "make_tx",
]
