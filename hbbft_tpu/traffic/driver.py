"""Traffic drivers: sustained client load against both runtimes.

:class:`ArrayTrafficDriver` reproduces QueueingHoneyBadger's sampling
loop at array-engine scale: per-node bounded mempools are fed by a
workload source, each lockstep epoch's contributions are random
``batch_size`` samples (the same ``TransactionQueue.choose`` math QHB
uses), and committed Batches flow back through the engine's
``batch_listeners`` fan-out hook into the lifecycle tracker and the
mempools' removal path.  This is ROADMAP item 3's measurement harness:
the batch-size knob becomes a throughput/latency *curve* (bench.py
``qhb_traffic``), with sustained tx/s and p50/p99 commit latency as
first-class outputs next to epochs/s.

:class:`ObjectTrafficDriver` drives the same source/mempool/tracker
machinery through the per-message object runtime (VirtualNet +
QueueingHoneyBadger) for small-N parity: admission happens in the same
BoundedMempool, accepted transactions are pushed into each node's real
QHB, and commits are read off ``node.outputs``.  The driver registers
itself as the net's ``traffic`` context so ``why_stalled`` names a
starved or saturated source instead of an anonymous missing quorum.

Virtual time: one epoch (array) / one submission wave (object) = one
unit; arrivals carry fractional times inside their unit, proposals are
sampled at the unit boundary, and a Batch commits one unit after it was
proposed.  All entropy comes from the injected ``rng`` (the determinism
lint family covers this package), so wall-clock rates are measured by
the CALLER around :meth:`run` — the driver itself never reads a clock.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

from hbbft_tpu.net.virtual_net import CrankError
from hbbft_tpu.traffic.mempool import BoundedMempool
from hbbft_tpu.traffic.tracker import TxTracker
from hbbft_tpu.utils import canonical


class _TrafficBase:
    """Shared admission / status plumbing of the two drivers."""

    def __init__(
        self,
        ids: List[Any],
        source,
        rng,
        batch_size: int,
        mempool_capacity: int,
        mempool_policy: str,
        fanout: str,
        tracer=None,
        health=None,
        controller=None,
        mempool_shards: int = 1,
    ) -> None:
        if fanout not in ("all", "one"):
            raise ValueError(f"unknown fanout {fanout!r}")
        self.ids = ids  # sorted by the caller (engine/net order)
        self.source = source
        self.rng = rng
        self.batch_size = batch_size
        self.fanout = fanout
        self.tracer = tracer
        self.health = health
        #: optional hbbft_tpu.control AdaptiveBatchController: observes
        #: the tracker's recent window once per epoch/wave and steers the
        #: live batch size through the ``batch_size_provider`` hook
        #: (array engine) / input-borne updates (object runtime)
        self.controller = controller
        self.mempools: List[BoundedMempool] = [
            BoundedMempool(
                mempool_capacity,
                policy=mempool_policy,
                shards=mempool_shards,
            )
            for _ in ids
        ]
        self.tracker = TxTracker(tracer.hist if tracer is not None else None)
        # sharded pools route by sha256-of-canonical: hash each arrival
        # ONCE here and share the digest across all N mempools and the
        # tracker (fanout="all" would otherwise recompute it N+1 times
        # per tx — pure waste on the million-client hot path)
        self._shard_routing = mempool_shards > 1
        self._last_wave_shed = False  # most recent wave dropped/evicted
        self.backpressure_epochs = 0
        self.committed_per_epoch: List[int] = []
        self.epochs_run = 0

    # -- admission -----------------------------------------------------------

    def _admit_wave(self, epoch: int) -> int:
        """Draw one unit's arrivals and push them through admission.
        Returns the number of transactions accepted somewhere."""
        bp = self.backpressure
        if bp:
            self.backpressure_epochs += 1
            if getattr(self.source, "name", "") == "closed_loop":
                self.tracker.on_shed(1)  # one deferred top-up wave
        arrivals = self.source.arrivals(self.rng, epoch, backpressure=bp)
        accepted = 0
        shed_before = sum(mp.dropped + mp.evicted for mp in self.mempools)
        n = len(self.ids)
        for t, tx in arrivals:
            digest = None
            if self._shard_routing:
                try:
                    digest = hashlib.sha256(canonical.encode(tx)).digest()
                except Exception:
                    digest = None  # unencodable: mempools route shard 0
            self.tracker.on_submit(tx, t, digest=digest)
            if self.fanout == "all":
                targets = range(n)
            else:
                # deterministic client->node load balancing
                targets = (tx[1] % n,)
            best = "dropped"
            victims: List[Any] = []
            for i in targets:
                outcome = self.mempools[i].submit(tx, digest=digest)
                if outcome in ("accepted", "evicted_oldest"):
                    best = "accepted"
                    self._accepted_at(i, tx)
                    if outcome == "evicted_oldest":
                        victims.append(self.mempools[i].last_evicted)
                elif outcome != "dropped" and best == "dropped":
                    best = outcome
            self.tracker.on_admission(best, tx)
            # on_rejected is an optional source hook (duck-typed, like
            # the whole workload contract — see README)
            rejected = getattr(self.source, "on_rejected", None)
            if best in ("dropped", "invalid") and rejected is not None:
                # a submission rejected everywhere will never commit:
                # release the source's concurrency slot too, or a
                # closed-loop window shrinks by every rejection forever
                rejected(1)
            # fanout="all" keeps the N mempools in lockstep, so they all
            # evict the SAME oldest entry — dedup before releasing, or a
            # closed-loop window is over-released N-fold per eviction
            # (degenerating fixed concurrency into an open loop)
            for v in dict.fromkeys(victims):
                # a victim still held by another mempool (fanout="all")
                # can still commit; one gone everywhere never will
                if v is not None and not any(v in mp for mp in self.mempools):
                    self.tracker.on_evicted(v)
                    if rejected is not None:
                        rejected(1)
            if best == "accepted":
                accepted += 1
        self._last_wave_shed = (
            sum(mp.dropped + mp.evicted for mp in self.mempools) > shed_before
        )
        if self.tracer is not None:
            self.tracer.hist("tx_arrivals_per_epoch").record(len(arrivals))
        return accepted

    def _accepted_at(self, node_idx: int, tx) -> None:
        """Hook: object driver mirrors admission into the live protocol."""

    # -- adaptive batch control ---------------------------------------------

    def _controller_obs(self, epoch: int):
        """Assemble the controller's Observation from the tracker's
        recent window + current mempool state (all virtual quantities —
        the controller never sees a wall clock)."""
        from hbbft_tpu.control.controller import Observation

        # now=epoch bounds the window to completed epochs: commits are
        # recorded at commit time (epoch+2) and would otherwise open
        # future slots that dilute the arrival-rate estimate
        rs = self.tracker.recent_summary(self.controller.window, now=epoch)
        return Observation(
            epoch=epoch,
            p99=rs["p99"],
            tx_per_epoch=rs["committed_per_epoch"],
            arrivals_per_epoch=rs["submitted_per_epoch"],
            mempool_depth=self.max_depth,
            backpressure=self.backpressure,
            validators=len(self.ids),
            arrivals_last=rs["submitted_last"],
        )

    def _record_depths(self) -> None:
        depth_hist = self.tracer.hist("mempool_depth") if self.tracer else None
        for mp in self.mempools:
            if depth_hist is not None:
                depth_hist.record(mp.depth)

    def _tick_health(self, epoch: int, msgs: Optional[float] = None) -> None:
        if self.health is None:
            return
        extra = {}
        if self.controller is not None:
            # the controller's current B and SLO compliance ride every
            # heartbeat (ISSUE 12: the control loop must be observable)
            extra["batch_size"] = self.controller.current_b
            extra["slo_compliant"] = self.controller.last_compliant
        self.health.tick(
            epoch=epoch,
            msgs=msgs,
            mempool_depth=self.max_depth,
            tx_commit_p99=round(self.tracker.commit_p99(), 3),
            tx_committed=self.tracker.committed,
            tx_dropped=self.tracker.dropped,
            **extra,
        )

    # -- introspection (why_stalled / heartbeat surface) ---------------------

    @property
    def backpressure(self) -> bool:
        return any(mp.backpressure for mp in self.mempools)

    @property
    def max_depth(self) -> int:
        return max((mp.depth for mp in self.mempools), default=0)

    def status(self) -> Dict[str, Any]:
        """Traffic-source state for the stall reporter: a quiesced run
        under this driver reads "source starved" or "source saturated",
        not an anonymous missing quorum (obs/health.py traffic context)."""
        dropped = sum(mp.dropped for mp in self.mempools)
        evicted = sum(mp.evicted for mp in self.mempools)
        depth = self.max_depth
        # state reflects RECENT conditions, not lifetime counters:
        # active backpressure or shedding in the latest admission wave
        # is saturation (the post-commit drain dipping below the
        # hysteresis watermark doesn't clear it), while a long-drained
        # run reads starved even if an early burst shed load
        if self.backpressure or self._last_wave_shed:
            state = "saturated"
        elif depth == 0 and self.tracker.pending == 0:
            state = "starved"
        else:
            state = "flowing"
        out = {
            "source": self.source.describe(),
            "state": state,
            "mempool_depth": depth,
            "capacity": self.mempools[0].capacity if self.mempools else 0,
            "dropped": dropped,
            "evicted": evicted,
            "backpressure": self.backpressure,
            "committed": self.tracker.committed,
            "pending": self.tracker.pending,
        }
        if self.controller is not None:
            out["controller"] = self.controller.describe()
        return out

    def report(self) -> Dict[str, Any]:
        out = self._report_base()
        if self.controller is not None:
            out["controller"] = {
                **self.controller.describe(),
                "b_trace": self.controller.b_trace(),
            }
        return out

    def _report_base(self) -> Dict[str, Any]:
        per_epoch = self.committed_per_epoch
        return {
            "epochs": self.epochs_run,
            "committed": self.tracker.committed,
            "committed_per_epoch": per_epoch,
            "tx_per_epoch": (
                round(self.tracker.committed / self.epochs_run, 2)
                if self.epochs_run
                else 0.0
            ),
            "backpressure_epochs": self.backpressure_epochs,
            "mempool_peak_depth": max(
                (mp.peak_depth for mp in self.mempools), default=0
            ),
            "mempool_dropped": sum(mp.dropped for mp in self.mempools),
            "mempool_evicted": sum(mp.evicted for mp in self.mempools),
            "source": self.source.describe(),
            "tracker": self.tracker.summary(),
            "status": self.status(),
        }


class ArrayTrafficDriver(_TrafficBase):
    """Client load through :class:`ArrayHoneyBadgerNet` lockstep epochs.

    Registers a ``batch_listeners`` fan-out callback and installs itself
    as the engine's ``contribution_source``, so either ``driver.run(k)``
    or the engine's own ``net.run_epochs(k)`` executes the full
    submit → sample → commit loop.
    """

    def __init__(
        self,
        net,
        source,
        rng,
        batch_size: int = 64,
        mempool_capacity: int = 1 << 16,
        mempool_policy: str = "reject",
        fanout: str = "all",
        tracer=None,
        health=None,
        controller=None,
        mempool_shards: int = 1,
    ) -> None:
        super().__init__(
            list(net.ids), source, rng, batch_size, mempool_capacity,
            mempool_policy, fanout, tracer=tracer, health=health,
            controller=controller, mempool_shards=mempool_shards,
        )
        self.net = net
        net.batch_listeners = list(net.batch_listeners) + [self._on_batches]
        net.contribution_source = self._contributions_for
        if controller is not None:
            # the engine-side hook (checkpoint-detached env attr, like
            # contribution_source): anything reading the engine sees the
            # controller's live B
            net.batch_size_provider = controller.batch_size

    # -- engine hooks --------------------------------------------------------

    def _contributions_for(self, epoch: int) -> Dict[Any, bytes]:
        """Contribution-sourcing hook: admit the epoch's arrivals, then
        sample every node's proposal (QHB's ``_try_propose`` math).

        The controller (when attached) decides B FIRST, from state
        observed through the previous epoch's commits only — so the
        decision sequence is a pure function of the seeded history and
        replay stays bit-identical."""
        if self.controller is not None:
            self.controller.decide(self._controller_obs(epoch))
        provider = getattr(self.net, "batch_size_provider", None)
        b = provider() if provider is not None else self.batch_size
        self._admit_wave(epoch)
        t_sample = float(epoch + 1)
        contribs: Dict[Any, bytes] = {}
        for i, nid in enumerate(self.ids):
            sample = self.mempools[i].choose(self.rng, b)
            self.tracker.on_sampled(sample, t_sample)
            if self.tracer is not None:
                self.tracer.hist("proposal_size").record(len(sample))
            contribs[nid] = canonical.encode(sample)
        if self.tracer is not None:
            self.tracer.hist("batch_size").record(b)
        self._record_depths()
        return contribs

    def _on_batches(self, batches: Dict[Any, Any]) -> None:
        """Batch-delivery fan-out hook: decode the committed samples,
        close tx lifecycles, and drain every mempool."""
        batch = batches[self.ids[0]]
        t_commit = float(batch.epoch + 2)
        committed: List[Any] = []
        seen: set = set()
        for nid in self.ids:
            blob = batch.contributions.get(nid)
            if not isinstance(blob, (bytes, bytearray)):
                continue
            for tx in canonical.decode(bytes(blob)):
                if tx not in seen:
                    seen.add(tx)
                    committed.append(tx)
        new = self.tracker.on_committed(committed, t_commit)
        self.source.on_committed(new)
        for mp in self.mempools:
            mp.remove_committed(committed)
        self.committed_per_epoch.append(new)
        self.epochs_run += 1
        self._tick_health(
            epoch=batch.epoch, msgs=self.net.counters.messages_delivered
        )

    def run(self, epochs: int) -> Dict[str, Any]:
        for _ in range(epochs):
            self.net.run_epoch(self._contributions_for(self.net.epoch))
        return self.report()


class ObjectTrafficDriver(_TrafficBase):
    """The same load against the per-message object runtime: VirtualNet
    nodes running real QueueingHoneyBadger.  One submission wave per
    virtual unit; cranking between waves delivers whatever the protocols
    produce.  Used for small-N parity with the array driver."""

    def __init__(
        self,
        net,
        source,
        rng,
        batch_size: int = 3,
        mempool_capacity: int = 1 << 12,
        mempool_policy: str = "reject",
        fanout: str = "all",
        tracer=None,
        health=None,
        cranks_per_wave: int = 200_000,
        controller=None,
        mempool_shards: int = 1,
    ) -> None:
        if mempool_policy == "evict_oldest":
            # admission mirrors accepted txs into each node's REAL QHB
            # queue (send_input), but an eviction from the shadow mempool
            # has no path back into the protocol — the two would diverge
            # and an "evicted" tx could still commit.  Bounded admission
            # in object mode means reject.
            raise ValueError(
                "ObjectTrafficDriver only supports mempool_policy='reject': "
                "evictions cannot be propagated into the live protocol queue"
            )
        ids = sorted(net.nodes)
        super().__init__(
            ids, source, rng, batch_size, mempool_capacity, mempool_policy,
            fanout, tracer=tracer, health=health,
            controller=controller, mempool_shards=mempool_shards,
        )
        self.net = net
        self.cranks_per_wave = cranks_per_wave
        #: last B delivered to the live protocols (input-borne — see
        #: _apply_batch_size for why object mode does NOT use the
        #: batch_size_provider hook)
        self._applied_b: Optional[int] = None
        self._seen_batches = 0  # cursor into node0's committed outputs
        net.traffic = self  # why_stalled traffic context
        # queue-dwell probe: QHB calls back with each fresh proposal
        # sample, closing the submit→sampled interval at the current
        # wave boundary (same tx_queue_latency the array driver records
        # in _contributions_for).  Byzantine nodes may run a different
        # algorithm — only instrument the ones that expose the hook.
        self._t_sample = 1.0  # wave 0's unit boundary
        for nid in ids:
            self._install_sample_hook(net.nodes[nid].algorithm)
        # crash axis (net/crash.py): a restored node comes back from a
        # snapshot, which drops the env-attr sample hook — re-install it
        crash = getattr(net, "crash", None)
        if crash is not None:
            crash.add_restart_listener(self._on_restart)

    def _install_sample_hook(self, alg) -> None:
        # the hook lives on the wrapped QHB, not a SenderQueue wrapper:
        # setting it on the wrapper would shadow nothing (QHB reads
        # self.sample_listener) AND make the wrapper unsnapshotable
        inner = getattr(alg, "algo", alg)
        if hasattr(inner, "sample_listener"):
            inner.sample_listener = self._on_sampled

    def _on_restart(self, net, node_id, algo) -> None:
        self._install_sample_hook(algo)

    def _on_sampled(self, sample: List[Any]) -> None:
        self.tracker.on_sampled(sample, self._t_sample)

    def _accepted_at(self, node_idx: int, tx) -> None:
        nid = self.ids[node_idx]
        self.net.send_input(nid, ("user", tx))

    def _apply_batch_size(self, b: int) -> None:
        """Deliver a B change as a ``("batch_size", B)`` INPUT to every
        node's live QHB rather than through the ``batch_size_provider``
        hook: inputs are WAL-logged events under the crash axis
        (net/crash.py), so a restarted node replays the exact B history
        its pre-crash self observed and the replay stays bit-identical —
        a provider would answer with TODAY'S B for yesterday's replayed
        proposals and read as ``crash:replay_divergence``.  A down
        node's update parks and applies at recovery, like votes."""
        if b == self._applied_b:
            return
        self._applied_b = b
        for nid in self.ids:
            self.net.send_input(nid, ("batch_size", b))

    def _wave(self, k: int) -> None:
        if self.controller is not None:
            self._apply_batch_size(
                self.controller.decide(self._controller_obs(k))
            )
        self._t_sample = float(k + 1)
        self._admit_wave(k)
        self._record_depths()
        target = k + 1

        def delivered(net) -> bool:
            down = (
                net.down_node_ids()
                if hasattr(net, "down_node_ids")
                else frozenset()
            )
            return all(
                len(net.nodes[nid].outputs) >= target
                for nid in self.ids
                if not net.nodes[nid].faulty and nid not in down
            )

        try:
            self.net.crank_until(delivered, max_cranks=self.cranks_per_wave)
        except CrankError:
            # a starved wave (no arrivals admitted anywhere) legitimately
            # quiesces without a batch; status() reports the starvation.
            # Anything other than a crank/quiescence trip still raises.
            if self.tracker.pending:
                raise
        self._collect(t_commit=float(k + 2))
        self.epochs_run += 1
        self._tick_health(epoch=k, msgs=self.net.messages_delivered)

    def _collect(self, t_commit: float) -> None:
        node0 = self.net.nodes[self.ids[0]]
        new_total = 0
        for b in node0.outputs[self._seen_batches:]:
            committed: List[Any] = []
            seen: set = set()
            for p in sorted(b.contributions, key=repr):
                txs = b.contributions[p]
                if not isinstance(txs, list):
                    continue
                for tx in txs:
                    if tx not in seen:
                        seen.add(tx)
                        committed.append(tx)
            new = self.tracker.on_committed(committed, t_commit)
            for mp in self.mempools:
                mp.remove_committed(committed)
            new_total += new
        self._seen_batches = len(node0.outputs)
        self.source.on_committed(new_total)
        self.committed_per_epoch.append(new_total)

    def run(self, waves: int) -> Dict[str, Any]:
        for k in range(waves):
            self._wave(k)
        return self.report()
