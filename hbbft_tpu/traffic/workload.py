"""Client-load workload generators for the traffic subsystem.

"Heavy traffic from millions of users" as *data*: a client population
(Zipf-ranked — a few hot clients dominate, a long tail trickles), an
arrival process (open-loop Poisson rate sweeps, or closed-loop fixed
concurrency), and a payload-size distribution.  Every generator draws
entropy ONLY from the rng injected per call (the determinism lint family
covers this package: same seed ⇒ bit-identical arrival schedule), so a
traffic run is replayable end to end — arrivals, sampled proposals,
Batches, and latency histograms all reproduce.

Transactions are plain canonical-codec trees (``("tx", client, seq,
payload)`` tuples): hashable for the mempool's dedup dict, and they
round-trip exactly through ``utils/canonical`` when a proposal sample is
framed into a contribution.

Time is virtual: one epoch = one unit.  Open-loop arrivals carry
fractional submit times inside their epoch (uniform order statistics,
which conditioned on the Poisson count IS the Poisson process), so
commit latency = commit_epoch − submit_time is exact in epoch units.

**Million-client scale (PR 12).**  Per-wave draws are batched: one
64-bit seed from the injected rng keys a counter-based numpy stream
(:func:`_uniforms`), client ranks come from ONE vectorized
``searchsorted`` over the precomputed Zipf CDF
(:meth:`ZipfPopulation.sample_wave`), and payload sizes draw as one
array — so a wave costs O(1) python-level rng calls and O(k log C)
total at C = 10⁶–10⁷ clients, with no per-transaction CDF bisect
(cost-flatness pinned in tests/test_traffic.py).  An optional
:class:`~hbbft_tpu.control.trace.LoadTrace` (duck-typed: ``factor`` /
``describe``) modulates the open-loop rate per epoch, making
arrival-rate swings a first-class replayable input.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

#: canonical transaction shape: ("tx", client_id, per-client seq, payload)
Tx = Tuple[str, int, int, bytes]


def make_tx(client: int, seq: int, payload: bytes) -> Tx:
    return ("tx", client, seq, payload)


def _uniforms(rng, k: int) -> np.ndarray:
    """``k`` uniforms in [0, 1) as ONE batched draw.

    Entropy is a single 64-bit seed from the injected rng keying a
    PCG64 stream, so the wave consumes O(1) python-level rng calls
    (pinned in tests) and stays bit-identical given the seed — the
    numpy bit-generator algorithms are version-stable, unlike python's
    randomized ``hash()``."""
    if k <= 0:
        return np.empty(0, dtype=np.float64)
    seed = rng.getrandbits(64)
    return np.random.Generator(np.random.PCG64(seed)).random(k)


class PayloadSizes:
    """Payload-size distribution: ``fixed`` | ``uniform`` | ``bimodal``.

    ``bimodal`` models the realistic mix (many small transfers, a thin
    stream of large blobs): ``small`` bytes with probability
    ``1 - heavy_frac``, else ``large`` bytes.
    """

    def __init__(
        self,
        kind: str = "fixed",
        size: int = 64,
        lo: int = 16,
        hi: int = 256,
        small: int = 32,
        large: int = 1024,
        heavy_frac: float = 0.05,
    ) -> None:
        if kind not in ("fixed", "uniform", "bimodal"):
            raise ValueError(f"unknown payload kind {kind!r}")
        self.kind = kind
        self.size = size
        self.lo, self.hi = lo, hi
        self.small, self.large = small, large
        self.heavy_frac = heavy_frac

    def draw(self, rng) -> int:
        if self.kind == "fixed":
            return self.size
        if self.kind == "uniform":
            return rng.randrange(self.lo, self.hi + 1)
        return self.large if rng.random() < self.heavy_frac else self.small

    def draw_wave(self, rng, k: int) -> List[int]:
        """``k`` sizes as one batched draw (entropy: one seed via
        :func:`_uniforms`; the ``fixed`` kind draws nothing at all)."""
        if self.kind == "fixed":
            return [self.size] * k
        u = _uniforms(rng, k)
        if self.kind == "uniform":
            span = self.hi - self.lo + 1
            return [self.lo + int(x) for x in (u * span)]
        return [
            self.large if x < self.heavy_frac else self.small for x in u
        ]

    def describe(self) -> dict:
        if self.kind == "fixed":
            return {"kind": "fixed", "size": self.size}
        if self.kind == "uniform":
            return {"kind": "uniform", "lo": self.lo, "hi": self.hi}
        return {
            "kind": "bimodal",
            "small": self.small,
            "large": self.large,
            "heavy_frac": self.heavy_frac,
        }


class ZipfPopulation:
    """Zipf(α)-ranked client population: client ``r`` (0-based rank) is
    drawn with weight ``1/(r+1)^alpha``.

    The CDF is precomputed ONCE as a float64 array (vectorized power +
    cumsum — ~30 ms at C = 10⁶, ~0.4 s at 10⁷), so sampling never walks
    the population: :meth:`sample` is one ``searchsorted`` (O(log C)),
    and :meth:`sample_wave` locates a whole wave's uniforms in one
    vectorized call — O(k log C) with no python-per-transaction loop,
    which is what keeps per-wave host cost flat from 10⁴ to 10⁷
    clients (asserted in tests/test_traffic.py)."""

    def __init__(self, num_clients: int, alpha: float = 1.1) -> None:
        if num_clients < 1:
            raise ValueError("num_clients must be >= 1")
        self.num_clients = num_clients
        self.alpha = alpha
        weights = np.arange(1, num_clients + 1, dtype=np.float64) ** -alpha
        self._cdf = np.cumsum(weights)
        self._total = float(self._cdf[-1])

    def _locate(self, u: np.ndarray) -> np.ndarray:
        """Ranks for uniforms scaled into [0, total) — the shared math of
        the scalar and wave paths (equivalence pinned in tests)."""
        idx = np.searchsorted(self._cdf, u, side="left")
        return np.minimum(idx, self.num_clients - 1)

    def sample(self, rng) -> int:
        return int(
            self._locate(np.float64(rng.random() * self._total))
        )

    def sample_wave(self, rng, k: int) -> List[int]:
        """``k`` client ranks as one batched draw: one seed from the
        injected rng (:func:`_uniforms`), one vectorized searchsorted
        over the CDF.  Returns plain python ints (they land in
        canonical-codec transaction tuples)."""
        u = _uniforms(rng, k) * self._total
        return self._locate(u).tolist()

    def describe(self) -> dict:
        return {"clients": self.num_clients, "alpha": self.alpha}


def _poisson(rng, lam: float) -> int:
    """Deterministic-given-rng Poisson draw.  Knuth's product method is
    exact but its ``exp(-lam)`` underflows past ~700, so large rates are
    drawn as a sum of independent chunks (Poisson is closed under
    addition) — still exact, still replayable."""
    count = 0
    while lam > 0:
        chunk = min(lam, 500.0)
        lam -= chunk
        limit = math.exp(-chunk)
        prod = rng.random()
        while prod > limit:
            count += 1
            prod *= rng.random()
    return count


class OpenLoopSource:
    """Open-loop Poisson arrivals: ``rate`` transactions per epoch
    network-wide, regardless of what the system commits (the load a
    population of independent clients actually presents).  Payload bytes
    are derived from (client, seq) — cheap and reproducible without
    burning rng draws per byte.

    ``trace`` (optional, duck-typed ``factor(epoch)`` — see
    hbbft_tpu/control/trace.py) multiplies the base rate per epoch, so
    step/spike/diurnal/10×-swing load shapes are part of the replayable
    input, not harness-side rate poking."""

    name = "open_loop"

    def __init__(
        self,
        rate: float,
        population: ZipfPopulation,
        payloads: Optional[PayloadSizes] = None,
        trace=None,
    ) -> None:
        self.rate = rate
        self.population = population
        self.payloads = payloads or PayloadSizes()
        self.trace = trace
        self._seqs: dict = {}  # client -> next seq
        self.generated = 0

    def rate_at(self, epoch: int) -> float:
        if self.trace is None:
            return self.rate
        return self.rate * self.trace.factor(epoch)

    def arrivals(self, rng, epoch: int, backpressure: bool = False) -> List[Tuple[float, Tx]]:
        """(submit_time, tx) pairs for one epoch, times ascending in
        [epoch, epoch+1).  Open-loop clients do not slow down under
        backpressure — overload shedding is the mempool's job.

        Batched: Poisson count first (exact chunked-Knuth), then ONE
        vectorized draw each for times, client ranks, and payload
        sizes; the only per-transaction python work left is the seq
        bookkeeping and tuple construction (O(k), no log C factor)."""
        count = _poisson(rng, self.rate_at(epoch))
        times = np.sort(_uniforms(rng, count)).tolist()
        clients = self.population.sample_wave(rng, count)
        sizes = self.payloads.draw_wave(rng, count)
        out: List[Tuple[float, Tx]] = []
        for t, client, size in zip(times, clients, sizes):
            seq = self._seqs.get(client, 0)
            self._seqs[client] = seq + 1
            payload = _payload_bytes(client, seq, size)
            out.append((epoch + t, make_tx(client, seq, payload)))
        self.generated += count
        return out

    def on_committed(self, n: int) -> None:  # open loop ignores completions
        pass

    def on_rejected(self, n: int) -> None:  # ...and admission rejections
        pass

    def describe(self) -> dict:
        out = {
            "source": self.name,
            "rate_per_epoch": self.rate,
            "population": self.population.describe(),
            "payloads": self.payloads.describe(),
        }
        if self.trace is not None:
            out["trace"] = self.trace.describe()
        return out


class ClosedLoopSource:
    """Closed-loop fixed concurrency: each of ``concurrency`` virtual
    clients keeps exactly one transaction in flight, submitting a
    replacement only when one commits — the classic saturation-free load
    shape.  Honors backpressure: a mempool signaling overload defers the
    top-up to the next epoch."""

    name = "closed_loop"

    def __init__(
        self,
        concurrency: int,
        population: ZipfPopulation,
        payloads: Optional[PayloadSizes] = None,
    ) -> None:
        self.concurrency = concurrency
        self.population = population
        self.payloads = payloads or PayloadSizes()
        self._seqs: dict = {}
        self.in_flight = 0
        self.generated = 0

    def arrivals(self, rng, epoch: int, backpressure: bool = False) -> List[Tuple[float, Tx]]:
        if backpressure:
            return []
        want = max(self.concurrency - self.in_flight, 0)
        times = np.sort(_uniforms(rng, want)).tolist()
        clients = self.population.sample_wave(rng, want)
        sizes = self.payloads.draw_wave(rng, want)
        out: List[Tuple[float, Tx]] = []
        for t, client, size in zip(times, clients, sizes):
            seq = self._seqs.get(client, 0)
            self._seqs[client] = seq + 1
            out.append((epoch + t, make_tx(client, seq, _payload_bytes(client, seq, size))))
        self.in_flight += len(out)
        self.generated += len(out)
        return out

    def on_committed(self, n: int) -> None:
        self.in_flight = max(0, self.in_flight - n)

    def on_rejected(self, n: int) -> None:
        """A submission rejected at admission (mempool full/invalid) will
        never commit: release its concurrency slot, or the effective
        window silently shrinks by every rejection for the rest of the
        run (with concurrency > capacity the source would stop
        generating entirely)."""
        self.in_flight = max(0, self.in_flight - n)

    def describe(self) -> dict:
        return {
            "source": self.name,
            "concurrency": self.concurrency,
            "population": self.population.describe(),
            "payloads": self.payloads.describe(),
        }


def _payload_bytes(client: int, seq: int, size: int) -> bytes:
    """Deterministic payload content of exactly ``size`` bytes."""
    stamp = client.to_bytes(8, "big") + seq.to_bytes(8, "big")
    reps = -(-size // len(stamp))
    return (stamp * reps)[:size]
