"""Bounded mempool: admission, backpressure, and eviction over
:class:`~hbbft_tpu.protocols.transaction_queue.TransactionQueue`.

The unbounded reference queue grows without limit under overload — at
"millions of users" that is an OOM, not a design.  :class:`BoundedMempool`
puts an admission layer in front:

* **validation first** — ``submit`` is a client-facing path, so every
  byte is attacker-controlled; the transaction is shape- and size-checked
  BEFORE any node state is touched (the byzantine-input lint family
  enforces this ordering for the whole package), and a bad transaction is
  an accounting outcome, never an exception;
* **capacity** — at ``capacity`` entries the pool either rejects the
  newcomer (``policy="reject"``, protecting in-flight work) or evicts the
  oldest pending entry (``policy="evict_oldest"``, favoring fresh load);
* **backpressure** — ``backpressure`` trips at ``hi_frac`` of capacity
  and clears at ``lo_frac`` (hysteresis, so the signal doesn't flap at
  the boundary); closed-loop sources honor it, open-loop sources keep
  pushing and the admission accounting shows the shed load.

Admission outcomes are strings (``accepted`` / ``duplicate`` /
``invalid`` / ``dropped`` / ``evicted_oldest``) consumed by
:class:`~hbbft_tpu.traffic.tracker.TxTracker`.
"""

from __future__ import annotations

from typing import Any, List, Optional

from hbbft_tpu.protocols.transaction_queue import RemovalAccount, TransactionQueue

#: admission outcomes (``submit`` return values)
OUTCOMES = ("accepted", "duplicate", "invalid", "dropped", "evicted_oldest")


def default_validate(tx: Any, max_payload: int) -> bool:
    """Shape check for the canonical ``("tx", client, seq, payload)``
    transaction: exact arity, typed fields, bounded payload."""
    if not isinstance(tx, tuple) or len(tx) != 4:
        return False
    tag, client, seq, payload = tx
    if tag != "tx" or not isinstance(client, int) or not isinstance(seq, int):
        return False
    if client < 0 or seq < 0:
        return False
    if not isinstance(payload, bytes) or len(payload) > max_payload:
        return False
    return True


class BoundedMempool:
    """Capacity-bounded admission wrapper around TransactionQueue."""

    def __init__(
        self,
        capacity: int,
        policy: str = "reject",
        max_payload: int = 1 << 16,
        hi_frac: float = 0.9,
        lo_frac: float = 0.7,
        validate=None,
    ) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if policy not in ("reject", "evict_oldest"):
            raise ValueError(f"unknown mempool policy {policy!r}")
        self.capacity = capacity
        self.policy = policy
        self.max_payload = max_payload
        self.hi = max(1, int(capacity * hi_frac))
        self.lo = int(capacity * lo_frac)
        self._validate = validate or (
            lambda tx: default_validate(tx, self.max_payload)
        )
        self._q = TransactionQueue()
        self._backpressure = False
        #: the tx displaced by the most recent ``evicted_oldest`` submit
        #: (None otherwise) — the driver releases its tracker lifecycle
        #: when no other mempool still holds a copy
        self.last_evicted: Optional[Any] = None
        # admission accounting (monotonic)
        self.accepted = 0
        self.duplicates = 0
        self.invalid = 0
        self.dropped = 0
        self.evicted = 0
        self.peak_depth = 0

    # -- admission (client-facing: validate before any state change) ---------

    def submit(self, tx: Any) -> str:
        ok = self._validate(tx)
        if not ok:
            self.invalid += 1
            return "invalid"
        if tx in self._q:
            self.duplicates += 1
            return "duplicate"
        outcome = "accepted"
        self.last_evicted = None
        if len(self._q) >= self.capacity:
            if self.policy == "reject":
                self.dropped += 1
                return "dropped"
            self.last_evicted = self._q.pop_oldest()
            self.evicted += 1
            outcome = "evicted_oldest"
        self._q.push(tx)
        self.accepted += 1
        depth = len(self._q)
        if depth > self.peak_depth:
            self.peak_depth = depth
        self._update_backpressure(depth)
        return outcome

    def _update_backpressure(self, depth: int) -> None:
        if self._backpressure:
            if depth <= self.lo:
                self._backpressure = False
        elif depth >= self.hi:
            self._backpressure = True

    # -- proposal / commit sides --------------------------------------------

    def choose(self, rng, amount: int) -> List[Any]:
        return self._q.choose(rng, amount)

    def remove_committed(self, txs) -> RemovalAccount:
        acct = self._q.remove_multiple(txs)
        self._update_backpressure(len(self._q))
        return acct

    # -- introspection -------------------------------------------------------

    @property
    def backpressure(self) -> bool:
        return self._backpressure

    @property
    def depth(self) -> int:
        return len(self._q)

    def __len__(self) -> int:
        return len(self._q)

    def __contains__(self, tx: Any) -> bool:
        return tx in self._q

    def status(self) -> dict:
        return {
            "depth": len(self._q),
            "capacity": self.capacity,
            "policy": self.policy,
            "backpressure": self._backpressure,
            "accepted": self.accepted,
            "duplicates": self.duplicates,
            "invalid": self.invalid,
            "dropped": self.dropped,
            "evicted": self.evicted,
            "peak_depth": self.peak_depth,
        }
