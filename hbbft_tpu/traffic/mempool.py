"""Bounded mempool: admission, backpressure, and eviction over
:class:`~hbbft_tpu.protocols.transaction_queue.TransactionQueue`.

The unbounded reference queue grows without limit under overload — at
"millions of users" that is an OOM, not a design.  :class:`BoundedMempool`
puts an admission layer in front:

* **validation first** — ``submit`` is a client-facing path, so every
  byte is attacker-controlled; the transaction is shape- and size-checked
  BEFORE any node state is touched (the byzantine-input lint family
  enforces this ordering for the whole package), and a bad transaction is
  an accounting outcome, never an exception;
* **capacity** — at ``capacity`` entries the pool either rejects the
  newcomer (``policy="reject"``, protecting in-flight work) or evicts the
  oldest pending entry (``policy="evict_oldest"``, favoring fresh load);
* **backpressure** — ``backpressure`` trips at ``hi_frac`` of capacity
  and clears at ``lo_frac`` (hysteresis, so the signal doesn't flap at
  the boundary); closed-loop sources honor it, open-loop sources keep
  pushing and the admission accounting shows the shed load.

**Sharding (PR 12).**  ``shards`` (a power of two, default 1) splits the
dedup/admission index over the transaction-digest keyspace: each tx
routes to the shard named by a sha256-of-canonical prefix (deterministic
across processes — python ``hash()`` is salted and would fork seeded
replays), so at sustained 10⁶-client load no single insertion-ordered
index absorbs every submit and the per-shard tombstone compaction cost
stays bounded by shard size, not pool size.  The capacity bound, the
hysteresis watermarks, and ``status()`` stay GLOBAL — callers see one
pool; per-outcome accounting lives on the shards and sums
(:meth:`shard_status` exposes the split).  Under ``evict_oldest`` the
displaced entry is the oldest of the newcomer's own shard (falling back
to the deepest shard when that one is empty) — FIFO per digest range,
not global FIFO.  ``shards=1`` routes nothing and consumes rng draws
exactly like the pre-shard pool, so existing seeded fingerprints are
unchanged.

Admission outcomes are strings (``accepted`` / ``duplicate`` /
``invalid`` / ``dropped`` / ``evicted_oldest``) consumed by
:class:`~hbbft_tpu.traffic.tracker.TxTracker`.
"""

from __future__ import annotations

import hashlib
from typing import Any, List, Optional

from hbbft_tpu.protocols.transaction_queue import RemovalAccount, TransactionQueue
from hbbft_tpu.utils import canonical

#: admission outcomes (``submit`` return values)
OUTCOMES = ("accepted", "duplicate", "invalid", "dropped", "evicted_oldest")


def default_validate(tx: Any, max_payload: int) -> bool:
    """Shape check for the canonical ``("tx", client, seq, payload)``
    transaction: exact arity, typed fields, bounded payload."""
    if not isinstance(tx, tuple) or len(tx) != 4:
        return False
    tag, client, seq, payload = tx
    if tag != "tx" or not isinstance(client, int) or not isinstance(seq, int):
        return False
    if client < 0 or seq < 0:
        return False
    if not isinstance(payload, bytes) or len(payload) > max_payload:
        return False
    return True


class _Shard:
    """One digest-range slice of the pool: its own queue + accounting."""

    __slots__ = (
        "q", "accepted", "duplicates", "invalid", "dropped", "evicted"
    )

    def __init__(self) -> None:
        self.q = TransactionQueue()
        self.accepted = 0
        self.duplicates = 0
        self.invalid = 0
        self.dropped = 0
        self.evicted = 0

    def status(self) -> dict:
        return {
            "depth": len(self.q),
            "accepted": self.accepted,
            "duplicates": self.duplicates,
            "invalid": self.invalid,
            "dropped": self.dropped,
            "evicted": self.evicted,
        }


class BoundedMempool:
    """Capacity-bounded admission wrapper around TransactionQueue."""

    def __init__(
        self,
        capacity: int,
        policy: str = "reject",
        max_payload: int = 1 << 16,
        hi_frac: float = 0.9,
        lo_frac: float = 0.7,
        validate=None,
        shards: int = 1,
    ) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if policy not in ("reject", "evict_oldest"):
            raise ValueError(f"unknown mempool policy {policy!r}")
        if shards < 1 or shards & (shards - 1) or shards > (1 << 16):
            raise ValueError(
                f"shards must be a power of two in [1, 65536], got {shards}"
            )
        self.capacity = capacity
        self.policy = policy
        self.max_payload = max_payload
        self.hi = max(1, int(capacity * hi_frac))
        self.lo = int(capacity * lo_frac)
        self._validate = validate or (
            lambda tx: default_validate(tx, self.max_payload)
        )
        self.shards = shards
        self._mask = shards - 1
        self._shards: List[_Shard] = [_Shard() for _ in range(shards)]
        self._depth = 0  # global live count (incremental: submit is O(1))
        self._backpressure = False
        #: the tx displaced by the most recent ``evicted_oldest`` submit
        #: (None otherwise) — the driver releases its tracker lifecycle
        #: when no other mempool still holds a copy
        self.last_evicted: Optional[Any] = None
        self.peak_depth = 0

    # -- routing -------------------------------------------------------------

    def _route(self, tx: Any, digest: Optional[bytes] = None) -> int:
        """Digest-prefix shard routing: sha256 of the canonical bytes,
        first four bytes masked down to the power-of-two shard count
        (four bytes cover every permitted shard count; two would leave
        shards beyond 2¹⁶ permanently empty).  Stable across processes
        (seeded-replay contract); an unencodable transaction routes to
        shard 0 — it is about to be accounted ``invalid`` anyway, never
        stored.  ``digest`` lets a caller that already hashed the tx
        (the driver hashes once per ARRIVAL and reuses it across all N
        node mempools and the tracker) skip the recompute."""
        if self._mask == 0:
            return 0
        if digest is None:
            try:
                digest = hashlib.sha256(canonical.encode(tx)).digest()
            except Exception:
                return 0
        return int.from_bytes(digest[:4], "big") & self._mask

    # -- admission (client-facing: validate before any state change) ---------

    def submit(self, tx: Any, digest: Optional[bytes] = None) -> str:
        ok = self._validate(tx)
        shard = self._shards[self._route(tx, digest)]
        if not ok:
            shard.invalid += 1
            return "invalid"
        if tx in shard.q:
            shard.duplicates += 1
            return "duplicate"
        outcome = "accepted"
        self.last_evicted = None
        if self._depth >= self.capacity:
            if self.policy == "reject":
                shard.dropped += 1
                return "dropped"
            victim_shard = shard if len(shard.q) else self._fullest()
            self.last_evicted = victim_shard.q.pop_oldest()
            self._depth -= 1
            victim_shard.evicted += 1
            outcome = "evicted_oldest"
        shard.q.push(tx)
        shard.accepted += 1
        self._depth += 1
        if self._depth > self.peak_depth:
            self.peak_depth = self._depth
        self._update_backpressure(self._depth)
        return outcome

    def _fullest(self) -> _Shard:
        """Deepest shard (lowest index on ties) — the evict fallback
        when the newcomer's own shard has nothing to displace."""
        best = self._shards[0]
        for sh in self._shards[1:]:
            if len(sh.q) > len(best.q):
                best = sh
        return best

    def _update_backpressure(self, depth: int) -> None:
        if self._backpressure:
            if depth <= self.lo:
                self._backpressure = False
        elif depth >= self.hi:
            self._backpressure = True

    # -- proposal / commit sides --------------------------------------------

    def choose(self, rng, amount: int) -> List[Any]:
        """Uniform random sample (without replacement) over ALL live
        entries.  Single shard delegates (rng draw order identical to
        the pre-shard pool — seeded fingerprints unchanged); sharded
        pools first split ``amount`` multivariate-hypergeometrically
        across shards (so the composite sample is exactly uniform over
        the union), then sample within each shard."""
        if self._mask == 0:
            return self._shards[0].q.choose(rng, amount)
        total = self._depth
        amount = min(amount, total)
        if amount <= 0:
            return []
        remaining = [len(sh.q) for sh in self._shards]
        counts = [0] * len(self._shards)
        left = total
        for _ in range(amount):
            r = rng.randrange(left)
            for i, rem in enumerate(remaining):
                if r < rem:
                    counts[i] += 1
                    remaining[i] -= 1
                    break
                r -= rem
            left -= 1
        out: List[Any] = []
        for i, k in enumerate(counts):
            if k:
                out.extend(self._shards[i].q.choose(rng, k))
        return out

    def remove_committed(self, txs) -> RemovalAccount:
        if self._mask == 0:
            acct = self._shards[0].q.remove_multiple(txs)
        else:
            buckets: dict = {}  # shard index -> txs routed there
            for tx in txs:
                buckets.setdefault(self._route(tx), []).append(tx)
            acct = RemovalAccount()
            for i in sorted(buckets):
                acct = acct.merged(
                    self._shards[i].q.remove_multiple(buckets[i])
                )
        self._depth -= acct.removed
        self._update_backpressure(self._depth)
        return acct

    # -- introspection -------------------------------------------------------

    @property
    def backpressure(self) -> bool:
        return self._backpressure

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def accepted(self) -> int:
        return sum(sh.accepted for sh in self._shards)

    @property
    def duplicates(self) -> int:
        return sum(sh.duplicates for sh in self._shards)

    @property
    def invalid(self) -> int:
        return sum(sh.invalid for sh in self._shards)

    @property
    def dropped(self) -> int:
        return sum(sh.dropped for sh in self._shards)

    @property
    def evicted(self) -> int:
        return sum(sh.evicted for sh in self._shards)

    def __len__(self) -> int:
        return self._depth

    def __contains__(self, tx: Any) -> bool:
        return tx in self._shards[self._route(tx)].q

    def shard_status(self) -> List[dict]:
        """Per-shard depth + outcome accounting (sums to :meth:`status`)."""
        return [sh.status() for sh in self._shards]

    def status(self) -> dict:
        return {
            "depth": self._depth,
            "capacity": self.capacity,
            "policy": self.policy,
            "backpressure": self._backpressure,
            "accepted": self.accepted,
            "duplicates": self.duplicates,
            "invalid": self.invalid,
            "dropped": self.dropped,
            "evicted": self.evicted,
            "peak_depth": self.peak_depth,
            "shards": self.shards,
        }
