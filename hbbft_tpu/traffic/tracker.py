"""Per-transaction lifecycle tracking: submit → queue → sampled → committed.

One :class:`TxTracker` observes every transaction the driver pushes at
the network and every Batch the network emits, and turns the stream into
the traffic subsystem's first-class metrics:

* ``tx_commit_latency`` histogram — submit time to commit time, in epoch
  units (p50/p90/p99 ride bench rows and heartbeats as ``tx_commit_p99``
  etc.; log-bucketed obs/histogram.py, so soak horizons stay O(1) memory
  per sample);
* ``tx_queue_latency`` histogram — submit to first sampled-into-proposal
  (the mempool-dwell component of commit latency);
* sustained committed-tx counter + drop/duplicate/shed accounting, so an
  overload run shows WHERE the offered load went (committed vs dropped at
  admission vs duplicate-submitted vs committed-elsewhere).

Commit dedup is cross-proposer: N decorrelated samples overlap, and a
transaction is committed once no matter how many proposals carried it —
``committed_duplicates`` counts the redundant copies.  A commit for a
transaction the tracker never saw submitted (possible when a driver only
tracks a subset of clients) is ``committed_unseen``, distinguishable from
the mempool's committed-elsewhere removals via
:class:`~hbbft_tpu.protocols.transaction_queue.RemovalAccount`.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Iterable, Optional

from hbbft_tpu.obs.histogram import Histogram
from hbbft_tpu.utils import canonical


def _commit_digest(tx: Any) -> bytes:
    """Compact identity for the lifetime committed-set: canonical bytes
    hashed to 16 bytes, so dedup costs O(1) memory per committed tx
    regardless of payload size (a soak at thousands of tx/s would
    otherwise retain every payload tuple forever).  sha256, not
    ``hash()`` — Python's randomized hashing would break the
    cross-process seeded-replay fingerprint contract."""
    return hashlib.sha256(canonical.encode(tx)).digest()[:16]


class TxTracker:
    """Lifecycle observer; all times are virtual (epoch units)."""

    def __init__(self, hist_factory=None) -> None:
        # hist_factory: Tracer.hist-compatible callable so a live tracer
        # owns the histograms (bench rows pick them up via hist_summary);
        # standalone use gets private Histograms.
        if hist_factory is None:
            self._own: Dict[str, Histogram] = {}

            def hist_factory(name: str) -> Histogram:
                h = self._own.get(name)
                if h is None:
                    h = self._own[name] = Histogram(name)
                return h

        self.hist = hist_factory
        self._pending: Dict[Any, float] = {}  # tx -> submit time
        self._sampled_at: Dict[Any, float] = {}  # tx -> first proposal time
        self._committed: set = set()  # _commit_digest(tx) — never raw txs
        self.submitted = 0
        self.committed = 0
        self.committed_duplicates = 0  # redundant cross-proposer copies
        self.committed_unseen = 0  # committed but never tracked as submitted
        self.dropped = 0  # rejected at admission (mempool full)
        self.duplicate_submissions = 0  # client re-submitted a known tx
        self.invalid = 0  # failed admission validation
        self.shed = 0  # backpressure-deferred by a closed-loop source

    # -- lifecycle events ----------------------------------------------------

    def on_submit(self, tx: Any, t: float) -> None:
        self.submitted += 1
        if tx not in self._pending and _commit_digest(tx) not in self._committed:
            self._pending[tx] = t

    def on_admission(self, outcome: str, tx: Any = None) -> None:
        """Aggregate one admission verdict (mempool.submit return).

        A transaction rejected everywhere (``dropped``/``invalid``) will
        never commit, so its pending entry is released immediately —
        otherwise an overload soak leaks tracker memory linearly in
        offered load and ``pending`` can never drain to the starved
        state.  (``duplicate`` means the tx is already live in a
        mempool, so its original pending entry stays.)"""
        if outcome == "dropped":
            self.dropped += 1
        elif outcome == "duplicate":
            self.duplicate_submissions += 1
        elif outcome == "invalid":
            self.invalid += 1
        if outcome in ("dropped", "invalid") and tx is not None:
            self._pending.pop(tx, None)
            self._sampled_at.pop(tx, None)

    def on_shed(self, n: int = 1) -> None:
        self.shed += n

    def on_evicted(self, tx: Any) -> None:
        """A tx evicted from its last mempool can never commit: release
        its lifecycle entries (the mempool's ``evicted`` counter owns the
        accounting), or evict-policy soaks leak tracker memory."""
        self._pending.pop(tx, None)
        self._sampled_at.pop(tx, None)

    def on_sampled(self, txs: Iterable[Any], t: float) -> None:
        """First inclusion in a proposal: close the queue-dwell interval."""
        qh = self.hist("tx_queue_latency")
        for tx in txs:
            if tx in self._sampled_at:
                continue
            sub = self._pending.get(tx)
            if sub is None:
                continue
            self._sampled_at[tx] = t
            qh.record(t - sub)

    def on_committed(self, txs: Iterable[Any], t: float) -> int:
        """Record a Batch's transactions; returns newly-committed count."""
        ch = self.hist("tx_commit_latency")
        new = 0
        for tx in txs:
            d = _commit_digest(tx)
            if d in self._committed:
                self.committed_duplicates += 1
                continue
            self._committed.add(d)
            new += 1
            sub = self._pending.pop(tx, None)
            self._sampled_at.pop(tx, None)
            if sub is None:
                self.committed_unseen += 1
            else:
                ch.record(t - sub)
        self.committed += new
        return new

    # -- summaries -----------------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._pending)

    def latency_summary(self) -> Dict[str, float]:
        return self.hist("tx_commit_latency").summary()

    def commit_p99(self) -> float:
        return self.hist("tx_commit_latency").percentile(99)

    def summary(self) -> Dict[str, Any]:
        return {
            "submitted": self.submitted,
            "committed": self.committed,
            "pending": self.pending,
            "dropped": self.dropped,
            "duplicate_submissions": self.duplicate_submissions,
            "invalid": self.invalid,
            "shed": self.shed,
            "committed_duplicates": self.committed_duplicates,
            "committed_unseen": self.committed_unseen,
            "commit_latency": self.latency_summary(),
            "queue_latency": self.hist("tx_queue_latency").summary(),
        }

    def fingerprint(self) -> Dict[str, Any]:
        """Replay-determinism digest: exact counters plus the raw commit-
        latency bucket counts (two same-seed runs must match bit for bit;
        tests/test_traffic.py seeded-replay contract)."""
        h = self.hist("tx_commit_latency")
        return {
            "submitted": self.submitted,
            "committed": self.committed,
            "dropped": self.dropped,
            "duplicates": self.duplicate_submissions,
            "latency_buckets": sorted(h.counts.items()),
        }
