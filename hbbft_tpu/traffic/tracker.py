"""Per-transaction lifecycle tracking: submit → queue → sampled → committed.

One :class:`TxTracker` observes every transaction the driver pushes at
the network and every Batch the network emits, and turns the stream into
the traffic subsystem's first-class metrics:

* ``tx_commit_latency`` histogram — submit time to commit time, in epoch
  units (p50/p90/p99 ride bench rows and heartbeats as ``tx_commit_p99``
  etc.; log-bucketed obs/histogram.py, so soak horizons stay O(1) memory
  per sample);
* ``tx_queue_latency`` histogram — submit to first sampled-into-proposal
  (the mempool-dwell component of commit latency);
* sustained committed-tx counter + drop/duplicate/shed accounting, so an
  overload run shows WHERE the offered load went (committed vs dropped at
  admission vs duplicate-submitted vs committed-elsewhere).

Commit dedup is cross-proposer: N decorrelated samples overlap, and a
transaction is committed once no matter how many proposals carried it —
``committed_duplicates`` counts the redundant copies.  A commit for a
transaction the tracker never saw submitted (possible when a driver only
tracks a subset of clients) is ``committed_unseen``, distinguishable from
the mempool's committed-elsewhere removals via
:class:`~hbbft_tpu.protocols.transaction_queue.RemovalAccount`.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Iterable, Optional

from hbbft_tpu.obs.histogram import Histogram
from hbbft_tpu.utils import canonical


def _commit_digest(tx: Any) -> bytes:
    """Compact identity for the lifetime committed-set: canonical bytes
    hashed to 16 bytes, so dedup costs O(1) memory per committed tx
    regardless of payload size (a soak at thousands of tx/s would
    otherwise retain every payload tuple forever).  sha256, not
    ``hash()`` — Python's randomized hashing would break the
    cross-process seeded-replay fingerprint contract."""
    return hashlib.sha256(canonical.encode(tx)).digest()[:16]


class TxTracker:
    """Lifecycle observer; all times are virtual (epoch units).

    Besides the cumulative histograms, the tracker keeps a bounded
    RECENT window — per-epoch commit-latency bucket counts plus
    submitted/committed tallies for the last ``recent_epochs`` epochs —
    because the adaptive batch controller (hbbft_tpu/control/) steers
    on the *live* operating point: a run-lifetime p99 would still be
    quoting the morning's quiet hours in the middle of a spike.  The
    window is O(recent_epochs × buckets) memory regardless of load and
    is NOT part of :meth:`fingerprint` (it is derived state; the
    cumulative counters already pin replay bit-identity).
    """

    def __init__(self, hist_factory=None, recent_epochs: int = 8) -> None:
        # hist_factory: Tracer.hist-compatible callable so a live tracer
        # owns the histograms (bench rows pick them up via hist_summary);
        # standalone use gets private Histograms.
        if hist_factory is None:
            self._own: Dict[str, Histogram] = {}

            def hist_factory(name: str) -> Histogram:
                h = self._own.get(name)
                if h is None:
                    h = self._own[name] = Histogram(name)
                return h

        self.hist = hist_factory
        self.recent_epochs = recent_epochs
        #: epoch -> {"submitted", "committed", "lat" bucket dict, "lat_min",
        #: "lat_max"} — trimmed to the last ``recent_epochs`` keys
        self._recent: Dict[int, Dict[str, Any]] = {}
        self._first_epoch: Optional[int] = None
        self._pending: Dict[Any, float] = {}  # tx -> submit time
        self._sampled_at: Dict[Any, float] = {}  # tx -> first proposal time
        self._committed: set = set()  # _commit_digest(tx) — never raw txs
        self.submitted = 0
        self.committed = 0
        self.committed_duplicates = 0  # redundant cross-proposer copies
        self.committed_unseen = 0  # committed but never tracked as submitted
        self.dropped = 0  # rejected at admission (mempool full)
        self.duplicate_submissions = 0  # client re-submitted a known tx
        self.invalid = 0  # failed admission validation
        self.shed = 0  # backpressure-deferred by a closed-loop source

    # -- the recent window ---------------------------------------------------

    def _epoch_slot(self, epoch: int) -> Dict[str, Any]:
        slot = self._recent.get(epoch)
        if slot is None:
            slot = self._recent[epoch] = {
                "submitted": 0,
                "committed": 0,
                "lat": {},
                "lat_min": None,
                "lat_max": None,
            }
            if self._first_epoch is None or epoch < self._first_epoch:
                self._first_epoch = epoch
            cutoff = epoch - self.recent_epochs
            for e in sorted(self._recent):
                if e <= cutoff:
                    del self._recent[e]
        return slot

    def recent_summary(
        self, window: Optional[int] = None, now: Optional[int] = None
    ) -> Dict[str, Any]:
        """Operating point over the last ``window`` epochs (default: the
        tracker's ``recent_epochs``): merged commit-latency p99 (None
        when nothing committed in the window), committed and submitted
        rates per epoch, plus ``submitted_last`` — the newest complete
        epoch's arrivals, the controller's spike-detection signal (a
        window AVERAGE dilutes a 10× swing's first epoch 4:1).

        ``now`` bounds the window to slots strictly BEFORE it AND
        anchors it at ``now - 1``.  Pass the current decision epoch:
        commits are recorded at their commit time (epoch+2), so without
        the bound a freshly-committed batch opens future slots whose
        zero ``submitted`` would dilute the arrival-rate estimate below
        the true offered load (measured: the controller mis-read a
        steady 100/epoch as ~50 and stepped B below demand) — and
        without the anchor a fully-idle tail would freeze the window at
        the last ACTIVE slot and report the pre-idle rates forever
        (pinning B high through the idle phase).  Rates divide by the
        number of epoch SLOTS in the window — silent epochs count as
        zeros, they are real time."""
        w = window or self.recent_epochs
        slots = self._recent
        if now is not None:
            slots = {e: s for e, s in self._recent.items() if e < now}
        if not slots:
            return {
                "epochs": 0,
                "p99": None,
                "committed_per_epoch": 0.0,
                "submitted_per_epoch": 0.0,
                "submitted_last": 0.0,
            }
        latest = (now - 1) if now is not None else max(slots)
        lo = max(latest - w + 1, self._first_epoch or 0)
        span = latest - lo + 1
        merged = Histogram("recent_commit_latency")
        submitted = committed = 0
        for e in range(lo, latest + 1):
            slot = slots.get(e)
            if slot is None:
                continue
            submitted += slot["submitted"]
            committed += slot["committed"]
            for b, c in sorted(slot["lat"].items()):
                merged.counts[b] = merged.counts.get(b, 0) + c
                merged.count += c
            v = slot["lat_min"]
            if v is not None and (merged.min is None or v < merged.min):
                merged.min = v
            v = slot["lat_max"]
            if v is not None and (merged.max is None or v > merged.max):
                merged.max = v
        last = slots.get(latest)
        return {
            "epochs": span,
            "p99": (
                round(merged.percentile(99), 3) if merged.count else None
            ),
            "committed_per_epoch": round(committed / span, 3),
            "submitted_per_epoch": round(submitted / span, 3),
            "submitted_last": float(last["submitted"] if last else 0),
        }

    # -- lifecycle events ----------------------------------------------------

    def on_submit(self, tx: Any, t: float, digest: bytes = None) -> None:
        """``digest`` (optional): the tx's full sha256-of-canonical, when
        the caller already computed it for shard routing — the committed-
        set key is its 16-byte prefix, so one hash serves both."""
        self.submitted += 1
        self._epoch_slot(int(t))["submitted"] += 1
        key = digest[:16] if digest is not None else _commit_digest(tx)
        if tx not in self._pending and key not in self._committed:
            self._pending[tx] = t

    def on_admission(self, outcome: str, tx: Any = None) -> None:
        """Aggregate one admission verdict (mempool.submit return).

        A transaction rejected everywhere (``dropped``/``invalid``) will
        never commit, so its pending entry is released immediately —
        otherwise an overload soak leaks tracker memory linearly in
        offered load and ``pending`` can never drain to the starved
        state.  (``duplicate`` means the tx is already live in a
        mempool, so its original pending entry stays.)"""
        if outcome == "dropped":
            self.dropped += 1
        elif outcome == "duplicate":
            self.duplicate_submissions += 1
        elif outcome == "invalid":
            self.invalid += 1
        if outcome in ("dropped", "invalid") and tx is not None:
            self._pending.pop(tx, None)
            self._sampled_at.pop(tx, None)

    def on_shed(self, n: int = 1) -> None:
        self.shed += n

    def on_evicted(self, tx: Any) -> None:
        """A tx evicted from its last mempool can never commit: release
        its lifecycle entries (the mempool's ``evicted`` counter owns the
        accounting), or evict-policy soaks leak tracker memory."""
        self._pending.pop(tx, None)
        self._sampled_at.pop(tx, None)

    def on_sampled(self, txs: Iterable[Any], t: float) -> None:
        """First inclusion in a proposal: close the queue-dwell interval."""
        qh = self.hist("tx_queue_latency")
        for tx in txs:
            if tx in self._sampled_at:
                continue
            sub = self._pending.get(tx)
            if sub is None:
                continue
            self._sampled_at[tx] = t
            qh.record(t - sub)

    def on_committed(self, txs: Iterable[Any], t: float) -> int:
        """Record a Batch's transactions; returns newly-committed count."""
        ch = self.hist("tx_commit_latency")
        slot = self._epoch_slot(int(t))
        new = 0
        for tx in txs:
            d = _commit_digest(tx)
            if d in self._committed:
                self.committed_duplicates += 1
                continue
            self._committed.add(d)
            new += 1
            sub = self._pending.pop(tx, None)
            self._sampled_at.pop(tx, None)
            if sub is None:
                self.committed_unseen += 1
            else:
                lat = t - sub
                ch.record(lat)
                b = Histogram._bucket(max(lat, 0.0))
                slot["lat"][b] = slot["lat"].get(b, 0) + 1
                if slot["lat_min"] is None or lat < slot["lat_min"]:
                    slot["lat_min"] = lat
                if slot["lat_max"] is None or lat > slot["lat_max"]:
                    slot["lat_max"] = lat
        slot["committed"] += new
        self.committed += new
        return new

    # -- summaries -----------------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._pending)

    def latency_summary(self) -> Dict[str, float]:
        return self.hist("tx_commit_latency").summary()

    def commit_p99(self) -> float:
        return self.hist("tx_commit_latency").percentile(99)

    def summary(self) -> Dict[str, Any]:
        return {
            "submitted": self.submitted,
            "committed": self.committed,
            "pending": self.pending,
            "dropped": self.dropped,
            "duplicate_submissions": self.duplicate_submissions,
            "invalid": self.invalid,
            "shed": self.shed,
            "committed_duplicates": self.committed_duplicates,
            "committed_unseen": self.committed_unseen,
            "commit_latency": self.latency_summary(),
            "queue_latency": self.hist("tx_queue_latency").summary(),
        }

    def fingerprint(self) -> Dict[str, Any]:
        """Replay-determinism digest: exact counters plus the raw commit-
        latency bucket counts (two same-seed runs must match bit for bit;
        tests/test_traffic.py seeded-replay contract)."""
        h = self.hist("tx_commit_latency")
        return {
            "submitted": self.submitted,
            "committed": self.committed,
            "dropped": self.dropped,
            "duplicates": self.duplicate_submissions,
            "latency_buckets": sorted(h.counts.items()),
        }
