"""AdaptiveBatchController: walk the B-vs-latency trade at runtime.

PR 8 measured HoneyBadgerBFT's central trade as a static grid (tx/s
grows with batch size B while p99 commit latency is paid in epochs);
this module closes the loop: observe the live operating point through
the traffic subsystem's :class:`~hbbft_tpu.traffic.tracker.TxTracker`
(recent-window p99, sustained tx/epoch, mempool depth, backpressure)
and step B along the power-of-two ladder to hold a declared
:class:`~hbbft_tpu.control.slo.SLO` under arrival-rate swings — the
same observe→adapt shape as the contamination-adaptive RLC grouping
(ops/backend.py, blst's playbook).

**Policy (AIMD-style on the ladder, hysteresis both ways).**  Per
decision epoch the controller computes a *demand* estimate — the larger
of the recent arrival rate and the backlog amortized over the SLO's
dwell budget — and compares it to the current sampling capacity
``validators × B``:

* **up** (×2, one rung) when demand exceeds ``up_frac`` of capacity,
  backpressure is active, the throughput floor is being missed with a
  live backlog, or observed p99 breaks the target after a full
  observation window at the current rung (raw p99 lags a rung change,
  so it only triggers once the window has turned over);
* **down** (÷2, one rung) only after ``hold_epochs`` *consecutive*
  eligible epochs: demand must fit comfortably (``down_frac``) inside
  the NEXT rung down's capacity and p99 must sit inside the SLO's
  declared margin.  The up threshold at rung B and the down threshold
  at rung 2B bracket a dead band, so steady load parks B on one rung
  (no oscillation — pinned in tests).

**Determinism.**  Decisions are a pure function of observed state; the
optional ``probe_jitter`` dithers the down-hysteresis length using ONLY
the injected rng (default 0: the rng is never consumed), so seeded
replay stays bit-identical and the ``HBBFT_TPU_NO_ADAPTIVE_B=1`` kill
switch (read per decision, like the adaptive-RLC and GLV switches)
reproduces the fixed-B run bit for bit.  No wall clocks, no ambient
entropy (determinism lint scope covers ``hbbft_tpu/control/``).

The controller is plain state — snapshotable via utils/snapshot (the
B trace, hysteresis counters, and rng ride a checkpoint; the *hooks*
holding it — ``batch_size_provider`` on the engine/QHB — are
environment and detach, like ``contribution_source``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.control.slo import MIN_FEASIBLE_P99, SLO

#: the batch-size ladder (ISSUE/ROADMAP: B ∈ {8..512}); power-of-two
#: rungs make one step down a true multiplicative decrease.
LADDER: Tuple[int, ...] = (8, 16, 32, 64, 128, 256, 512)


def adaptive_b_enabled() -> bool:
    """Kill switch, read per decision: ``HBBFT_TPU_NO_ADAPTIVE_B=1``
    pins B to the initial rung for the rest of the run."""
    return os.environ.get("HBBFT_TPU_NO_ADAPTIVE_B", "0") != "1"


def _effective_drain(depth: int, b: int, n: int) -> float:
    """Distinct commits per epoch from N decorrelated B-samples of a
    depth-D pool: D·(1-(1-min(B,D)/D)^N) — the fanout="all" overlap
    model (HoneyBadger proposals are independent random samples, CCS
    2016 §4.4; redundant copies commit once)."""
    if depth <= 0:
        return 0.0
    frac = min(b, depth) / depth
    return depth * (1.0 - (1.0 - frac) ** n)


@dataclass(frozen=True)
class Observation:
    """One decision epoch's view of the operating point, assembled by
    the traffic driver from tracker recent-window stats + mempool state.
    All quantities are virtual (epoch units) — no wall clocks."""

    epoch: int
    p99: Optional[float]  # recent-window commit p99 (None: no samples)
    tx_per_epoch: float  # recent committed rate
    arrivals_per_epoch: float  # recent submitted rate (window average)
    mempool_depth: int  # current max depth across mempools
    backpressure: bool
    validators: int
    #: newest complete epoch's arrivals — the spike signal (a window
    #: average dilutes a swing's first epoch by the window length)
    arrivals_last: float = 0.0

    def describe(self) -> Dict[str, Any]:
        return {
            "epoch": self.epoch,
            "p99": self.p99,
            "tx_per_epoch": round(self.tx_per_epoch, 2),
            "arrivals_per_epoch": round(self.arrivals_per_epoch, 2),
            "mempool_depth": self.mempool_depth,
            "backpressure": self.backpressure,
        }


class AdaptiveBatchController:
    """SLO-driven batch sizing over the power-of-two ladder."""

    def __init__(
        self,
        slo: SLO,
        initial_b: int = 32,
        ladder: Tuple[int, ...] = LADDER,
        rng=None,
        window: int = 4,
        hold_epochs: int = 3,
        up_frac: float = 0.9,
        down_frac: float = 0.7,
        probe_jitter: int = 0,
    ) -> None:
        if initial_b not in ladder:
            raise ValueError(f"initial_b {initial_b} not on ladder {ladder}")
        if list(ladder) != sorted(set(ladder)):
            raise ValueError("ladder must be strictly increasing")
        self.slo = slo
        self.ladder = tuple(ladder)
        self.initial_b = initial_b
        self.rng = rng
        self.window = window
        self.hold_epochs = hold_epochs
        self.up_frac = up_frac
        self.down_frac = down_frac
        self.probe_jitter = probe_jitter
        self._idx = self.ladder.index(initial_b)
        self._hold = 0  # consecutive down-eligible epochs
        self._since_change = window  # epochs at the current rung
        self._hold_needed = hold_epochs  # re-dithered after each step
        #: (epoch, B-after-decision, reason) per decide() call — the
        #: replayable B trace the bit-identity tests fingerprint
        self.decisions: List[Tuple[int, int, str]] = []
        self.steps_up = 0
        self.steps_down = 0
        self.last_obs: Optional[Observation] = None
        self.last_compliant = True

    # -- the hook surface ----------------------------------------------------

    @property
    def current_b(self) -> int:
        """Current batch size (kill switch pins the initial rung)."""
        if not adaptive_b_enabled():
            return self.initial_b
        return self.ladder[self._idx]

    def batch_size(self) -> int:
        """Zero-arg provider callable — install as an engine's or QHB's
        ``batch_size_provider`` (environment attr; snapshots drop it)."""
        return self.current_b

    # -- the control law -----------------------------------------------------

    def _dwell_budget(self) -> float:
        """Epochs of mempool dwell the SLO leaves after pipeline floor."""
        return max(1.0, self.slo.p99_epochs - MIN_FEASIBLE_P99)

    def _redither(self) -> None:
        self._hold_needed = self.hold_epochs
        if self.probe_jitter and self.rng is not None:
            self._hold_needed += self.rng.randrange(self.probe_jitter + 1)

    def decide(self, obs: Observation) -> int:
        """One decision epoch: observe, maybe step, record, return B."""
        self.last_obs = obs
        self.last_compliant = self.slo.compliant(obs.p99, obs.tx_per_epoch)
        if not adaptive_b_enabled():
            self.decisions.append((obs.epoch, self.initial_b, "killswitch"))
            return self.initial_b

        b = self.ladder[self._idx]
        cap = obs.validators * b
        budget = self._dwell_budget()
        demand = max(
            obs.arrivals_per_epoch,
            obs.arrivals_last,
            obs.mempool_depth / budget,
        )
        # Projected mempool dwell.  The drain estimate is the larger of
        # the measured recent rate (a lagging window average — right
        # after a rung change it still quotes the old B) and the
        # decorrelated-sampling model at the CURRENT rung: N independent
        # B-samples from a depth-D pool commit D·(1-(1-B/D)^N) distinct
        # txs per epoch.  Raw N·B would overestimate (samples overlap);
        # the stale average alone underestimates (measured: it read a
        # one-epoch backlog as 5 epochs of dwell and over-ramped B).
        drain = max(
            obs.tx_per_epoch,
            _effective_drain(obs.mempool_depth, b, obs.validators),
            1.0,
        )
        dwell_est = obs.mempool_depth / drain
        reason = "hold"

        pressure_up = (
            demand > self.up_frac * cap
            or dwell_est > budget
            or obs.backpressure
        )
        floor_miss = (
            self.slo.min_tx_per_epoch > 0
            and obs.tx_per_epoch < self.slo.min_tx_per_epoch
            and obs.mempool_depth > 0
        )
        # p99 is a LAGGING signal: committed txs carry dwell accrued at
        # the previous rung, so a breach only argues for a bigger B when
        # (a) the observation window has turned over since the last step
        # and (b) there is a LIVE queue to compress (mean dwell ≥ ~0.3
        # of the budget — random sampling's geometric tail turns that
        # into a p99 several times larger).  Without (b) the breach is a
        # stale ramp tail over a drained pool, where escalating B buys
        # nothing (measured: B over-ramped 128→512 and halved tx/s).
        p99_breach = (
            obs.p99 is not None
            and obs.p99 > self.slo.p99_epochs
            and self._since_change >= self.window
            and dwell_est > 0.3 * budget
        )
        down_ok = (
            self._idx > 0
            and demand
            < self.down_frac * obs.validators * self.ladder[self._idx - 1]
            # a stale elevated p99 must not pin B high once the pool has
            # drained: near-empty mempool means latency is at the
            # pipeline floor regardless of B
            and (self.slo.headroom(obs.p99) or dwell_est < 0.25)
            and not obs.backpressure
            and not floor_miss
        )

        if pressure_up or p99_breach or floor_miss:
            if self._idx + 1 < len(self.ladder):
                # pressure ramps MULTIPLE rungs at once: a 10x swing's
                # first epoch must not cost log2(10) reaction epochs of
                # backlog (each lagging epoch adds a full epoch of
                # excess dwell to the tail).  p99/floor triggers step a
                # single rung — they are lagging, already-amortized
                # signals.
                rungs = 1
                if pressure_up:
                    while (
                        self._idx + rungs + 1 < len(self.ladder)
                        and demand
                        > self.up_frac
                        * obs.validators
                        * self.ladder[self._idx + rungs]
                    ):
                        rungs += 1
                self._idx += rungs
                self.steps_up += rungs
                self._since_change = 0
                reason = (
                    "up:pressure"
                    if pressure_up
                    else ("up:floor" if floor_miss else "up:p99")
                )
                self._redither()
            else:
                reason = "hold:ceiling"
            self._hold = 0
        elif down_ok:
            self._hold += 1
            if self._hold >= self._hold_needed:
                self._idx -= 1
                self.steps_down += 1
                self._since_change = 0
                self._hold = 0
                reason = "down:slack"
                self._redither()
            else:
                reason = "hold:settling"
        else:
            self._hold = 0
        self._since_change += 1
        b = self.ladder[self._idx]
        self.decisions.append((obs.epoch, b, reason))
        return b

    # -- reporting -----------------------------------------------------------

    def b_trace(self) -> List[int]:
        """Per-decision B values — the seeded-replay fingerprint axis."""
        return [b for _, b, _ in self.decisions]

    def describe(self) -> Dict[str, Any]:
        """Status block for ``why_stalled`` / heartbeats / bench rows."""
        out: Dict[str, Any] = {
            "batch_size": self.current_b,
            "adaptive": adaptive_b_enabled(),
            "slo": self.slo.describe(),
            "compliant": self.last_compliant,
            "steps_up": self.steps_up,
            "steps_down": self.steps_down,
        }
        if self.decisions:
            out["last_reason"] = self.decisions[-1][2]
        if self.last_obs is not None:
            out["observed"] = self.last_obs.describe()
        return out
