"""Load traces: piecewise arrival-rate schedules as first-class inputs.

"Arrival-rate swings" stop being prose and become data: a
:class:`LoadTrace` maps a virtual epoch to a rate *multiplier* applied
to an open-loop source's base rate, so a bench cell's offered load is a
pure function of (trace, epoch) — replayable, diffable, and identical
across the controller arm and every fixed-B arm of the ``slo_traffic``
row.  Traces are arithmetic over the epoch index only (no entropy, no
wall clocks — the determinism lint family covers this package), so the
same seed still yields the same arrival schedule wave for wave.

Shapes (factories below; ``TRACES`` registers them by name for bench /
soak cell specs):

* ``constant`` — factor 1.0 forever (the degenerate trace; a traced
  source with this trace is bit-identical to an untraced one).
* ``step`` — low until ``at``, then high forever (capacity re-planning).
* ``spike`` — low everywhere except ``[at, at+width)`` (flash crowd).
* ``swing`` — square wave: each period is ``duty`` low then ``1-duty``
  high; ``swing10x`` is the flagship 10×-swing the SLO row runs.
* ``diurnal`` — raised-cosine day/night curve between low and high.
"""

from __future__ import annotations

import math
from typing import Dict


class LoadTrace:
    """Arrival-rate multiplier over virtual epochs.

    ``kind`` selects the arithmetic shape; ``params`` are its constants.
    Instances are plain data (snapshotable via utils/snapshot) and are
    consumed duck-typed by :class:`~hbbft_tpu.traffic.workload.
    OpenLoopSource` through ``factor(epoch)`` / ``describe()``.
    """

    def __init__(self, kind: str, **params: float) -> None:
        if kind not in ("constant", "step", "spike", "swing", "diurnal"):
            raise ValueError(f"unknown trace kind {kind!r}")
        self.kind = kind
        self.params: Dict[str, float] = dict(sorted(params.items()))

    # -- the schedule --------------------------------------------------------

    def factor(self, epoch: int) -> float:
        p = self.params
        if self.kind == "constant":
            return p.get("level", 1.0)
        if self.kind == "step":
            return p["high"] if epoch >= p["at"] else p["low"]
        if self.kind == "spike":
            lo, at, width = p["low"], p["at"], p["width"]
            return p["high"] if at <= epoch < at + width else lo
        if self.kind == "swing":
            period = p["period"]
            phase = (epoch % period) / period
            return p["low"] if phase < p["duty"] else p["high"]
        # diurnal: raised cosine, trough at epoch 0, crest at period/2
        period = p["period"]
        x = 0.5 * (1.0 - math.cos(2.0 * math.pi * (epoch % period) / period))
        return p["low"] + (p["high"] - p["low"]) * x

    def peak(self) -> float:
        """Largest factor the trace ever emits (capacity planning)."""
        if self.kind == "constant":
            return self.params.get("level", 1.0)
        return self.params["high"]

    def describe(self) -> dict:
        return {"trace": self.kind, **self.params}

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"LoadTrace({self.kind!r}, {self.params})"


# -- factories (the canonical shapes; keyword-overridable) -------------------


def constant(level: float = 1.0) -> LoadTrace:
    return LoadTrace("constant", level=level)


def step(low: float = 1.0, high: float = 4.0, at: int = 8) -> LoadTrace:
    return LoadTrace("step", low=low, high=high, at=at)


def spike(
    low: float = 1.0, high: float = 10.0, at: int = 8, width: int = 2
) -> LoadTrace:
    return LoadTrace("spike", low=low, high=high, at=at, width=width)


def swing(
    low: float = 1.0,
    high: float = 10.0,
    period: int = 12,
    duty: float = 0.5,
) -> LoadTrace:
    return LoadTrace("swing", low=low, high=high, period=period, duty=duty)


def swing10x(period: int = 12) -> LoadTrace:
    """The flagship 10×-swing: half the period at 1×, half at 10×."""
    return swing(low=1.0, high=10.0, period=period, duty=0.5)


def diurnal(low: float = 1.0, high: float = 4.0, period: int = 24) -> LoadTrace:
    return LoadTrace("diurnal", low=low, high=high, period=period)


#: name -> zero-arg factory, for bench knobs and soak cell specs
TRACES = {
    "constant": constant,
    "step": step,
    "spike": spike,
    "swing10x": swing10x,
    "diurnal": diurnal,
}


def make_trace(name: str) -> LoadTrace:
    """Build a registered trace by name (bench/soak spec surface)."""
    if name not in TRACES:
        raise ValueError(
            f"unknown trace {name!r} (have {sorted(TRACES)})"
        )
    return TRACES[name]()
