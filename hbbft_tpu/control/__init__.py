"""Control plane: SLO-driven adaptive batch sizing.

The traffic subsystem (hbbft_tpu/traffic/) made "millions of users"
measurable; this package makes the system *react*: declare a service
objective (:mod:`~hbbft_tpu.control.slo`), drive it with a replayable
arrival-rate trace (:mod:`~hbbft_tpu.control.trace`), and let the
:class:`~hbbft_tpu.control.controller.AdaptiveBatchController` walk
HoneyBadgerBFT's batch-size/latency trade at runtime through the
engine/QHB ``batch_size_provider`` hook.  The ``slo_traffic`` bench row
(bench.py) runs the controller against every fixed-B cell under the
10×-swing trace; ``HBBFT_TPU_NO_ADAPTIVE_B=1`` pins B for bit-identical
fixed-B replay.
"""

from hbbft_tpu.control.controller import (
    LADDER,
    AdaptiveBatchController,
    Observation,
    adaptive_b_enabled,
)
from hbbft_tpu.control.slo import MIN_FEASIBLE_P99, SLO
from hbbft_tpu.control.trace import TRACES, LoadTrace, make_trace, swing10x

__all__ = [
    "AdaptiveBatchController",
    "Observation",
    "LADDER",
    "adaptive_b_enabled",
    "SLO",
    "MIN_FEASIBLE_P99",
    "LoadTrace",
    "TRACES",
    "make_trace",
    "swing10x",
]
