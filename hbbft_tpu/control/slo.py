"""SLO spec: the declared service objective the control plane holds.

HoneyBadgerBFT's central knob is the batch size B — throughput grows
with B while commit latency is paid in epochs (CCS 2016 §5) — so "how
big should B be?" is only answerable against a *declared objective*.
:class:`SLO` is that declaration: a p99 commit-latency target in
**epoch units** (the traffic subsystem's virtual clock — multiply by a
row's measured seconds/epoch for wall latency), plus an optional
sustained-throughput floor in tx/epoch.  Everything the controller and
the ``slo_traffic`` bench row decide or report is phrased against this
one object, so "compliant" means the same thing in tests, heartbeats,
bench rows, and the trace_report regression gate.

Latency floor: a submitted transaction is sampled at the next epoch
boundary and commits one epoch later, so ~2 epochs is the physical
minimum — a target below ``MIN_FEASIBLE_P99`` is rejected at
construction rather than silently unachievable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

#: submit → sample (next boundary) → commit (one epoch later): no batch
#: size can beat ~2 epochs of pipeline latency.
MIN_FEASIBLE_P99 = 2.0


@dataclass(frozen=True)
class SLO:
    """Declared objective: ``p99_epochs`` commit-latency ceiling and an
    optional ``min_tx_per_epoch`` throughput floor (0 = no floor).

    ``margin`` is the compliance headroom the controller demands before
    it trades latency slack for efficiency (stepping B down): observed
    p99 must sit at or under ``margin * p99_epochs``.  It is part of the
    spec — two operators with the same ceiling but different margins
    have declared different risk appetites.
    """

    p99_epochs: float
    min_tx_per_epoch: float = 0.0
    margin: float = 0.8

    def __post_init__(self) -> None:
        if self.p99_epochs < MIN_FEASIBLE_P99:
            raise ValueError(
                f"p99 target {self.p99_epochs} below the {MIN_FEASIBLE_P99}"
                "-epoch pipeline floor (submit -> sample -> commit)"
            )
        if not 0.0 < self.margin <= 1.0:
            raise ValueError(f"margin must be in (0, 1], got {self.margin}")
        if self.min_tx_per_epoch < 0:
            raise ValueError("min_tx_per_epoch must be >= 0")

    # -- compliance ----------------------------------------------------------

    def compliant(
        self, p99: Optional[float], tx_per_epoch: Optional[float] = None
    ) -> bool:
        """Does an observed operating point meet the objective?

        ``p99=None`` (no committed samples yet) reads as compliant —
        an idle system violates nothing.  The throughput floor is only
        checked when a measurement is supplied.
        """
        if p99 is not None and p99 > self.p99_epochs:
            return False
        if (
            self.min_tx_per_epoch
            and tx_per_epoch is not None
            and tx_per_epoch < self.min_tx_per_epoch
        ):
            return False
        return True

    def headroom(self, p99: Optional[float]) -> bool:
        """Is p99 comfortably inside the target (under ``margin``×)?"""
        return p99 is None or p99 <= self.margin * self.p99_epochs

    def describe(self) -> Dict[str, Any]:
        return {
            "p99_epochs": self.p99_epochs,
            "min_tx_per_epoch": self.min_tx_per_epoch,
            "margin": self.margin,
        }
