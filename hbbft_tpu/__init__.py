"""hbbft-tpu: TPU-native Honey Badger BFT framework.

A ground-up rebuild of the capabilities of the Rust `hbbft` library
(c0gent/hbbft) with a JAX/XLA/Pallas execution backend for the
threshold-crypto inner loop.  See SURVEY.md for the reference analysis.
"""

__version__ = "0.1.0"
