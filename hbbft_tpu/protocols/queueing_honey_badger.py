"""QueueingHoneyBadger — transaction buffering in front of DynamicHoneyBadger.

Rebuild of `src/queueing_honey_badger/mod.rs` § (SURVEY.md §2.1): an
unbounded `TransactionQueue` feeds random samples of ``batch_size``
transactions into DHB epochs; committed transactions are removed, and a new
proposal is made automatically as soon as the previous epoch's batch lands
(also immediately after era changes, when the fresh HoneyBadger starts).

Messages pass through unchanged (`DhbMessage`): QHB adds no wire traffic of
its own.
"""

from __future__ import annotations

from typing import Any, List, Optional

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import Step, absorb_child_step
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.protocols.change import Change
from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch, DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.transaction_queue import RemovalAccount, TransactionQueue


class QueueingHoneyBadgerBuilder:
    """Builder mirroring the reference `QueueingHoneyBadgerBuilder` §."""

    def __init__(self, netinfo: NetworkInfo, backend: CryptoBackend, rng) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.rng = rng
        self._batch_size = 100
        self._session_id = b"qhb"
        self._encryption_schedule = EncryptionSchedule.always()
        self._queue: Optional[TransactionQueue] = None

    def batch_size(self, n: int) -> "QueueingHoneyBadgerBuilder":
        self._batch_size = n
        return self

    def session_id(self, sid: bytes) -> "QueueingHoneyBadgerBuilder":
        self._session_id = sid
        return self

    def encryption_schedule(self, s: EncryptionSchedule) -> "QueueingHoneyBadgerBuilder":
        self._encryption_schedule = s
        return self

    def queue(self, q: TransactionQueue) -> "QueueingHoneyBadgerBuilder":
        self._queue = q
        return self

    def build(self) -> "QueueingHoneyBadger":
        return QueueingHoneyBadger(
            self.netinfo,
            self.backend,
            rng=self.rng,
            batch_size=self._batch_size,
            session_id=self._session_id,
            encryption_schedule=self._encryption_schedule,
            queue=self._queue,
        )


class QueueingHoneyBadger(ConsensusProtocol):
    # class-level fallbacks: snapshots written before these attributes
    # existed restore without them (utils/snapshot.py rebuilds via
    # __new__ + setattr)
    removal_account = RemovalAccount()
    #: optional observer called with each freshly-sampled proposal —
    #: the traffic subsystem's queue-dwell probe (ObjectTrafficDriver
    #: closes the submit→sampled interval here; the array engine has an
    #: equivalent hook in its contribution source).  Environment, not
    #: state: snapshots drop it (a live bound method would otherwise
    #: make every traffic-driven node unsnapshotable) and restore falls
    #: back to the class None.
    sample_listener = None
    #: optional zero-arg -> int supplying the live batch size B (the
    #: control plane's adaptive-batch hook; checkpoint-detached like
    #: sample_listener).  When None, proposals sample ``batch_size`` —
    #: which is STATE and can also be steered by ("batch_size", B)
    #: inputs; under the crash axis (net/crash.py) input-borne updates
    #: are the correct channel, because inputs are WAL-logged and
    #: replay bit-identically while a provider would answer replayed
    #: proposals with the current B (see ObjectTrafficDriver).
    batch_size_provider = None
    _SNAPSHOT_ENV_ATTRS = ("sample_listener", "batch_size_provider")

    def __init__(
        self,
        netinfo: NetworkInfo,
        backend: CryptoBackend,
        rng,
        batch_size: int = 100,
        session_id: bytes = b"qhb",
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
        queue: Optional[TransactionQueue] = None,
    ) -> None:
        self.backend = backend
        self.rng = rng
        self.batch_size = batch_size
        self.queue = queue if queue is not None else TransactionQueue()
        #: cumulative committed-batch removal accounting: ``removed`` txs
        #: were in our queue, ``absent`` committed from other proposers'
        #: samples without ever being submitted here (the traffic
        #: tracker's committed-elsewhere signal)
        self.removal_account = RemovalAccount()
        self.dhb = DynamicHoneyBadger(
            netinfo,
            backend,
            rng=rng,
            session_id=session_id,
            encryption_schedule=encryption_schedule,
        )

    @staticmethod
    def builder(netinfo, backend, rng) -> QueueingHoneyBadgerBuilder:
        return QueueingHoneyBadgerBuilder(netinfo, backend, rng)

    @property
    def netinfo(self) -> NetworkInfo:
        return self.dhb.netinfo

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.dhb.our_id()

    def terminated(self) -> bool:
        return False

    def handle_input(self, input: Any, rng=None) -> Step:
        """("user", tx) pushes a transaction; ("change", Change) votes;
        ("batch_size", B) re-sizes future proposals (the control
        plane's input-borne channel — a plain state write, so it is
        snapshotted and WAL-replayed like any other input; deliberately
        does NOT trigger a proposal)."""
        kind, payload = input
        if kind == "user":
            return self.push_transaction(payload)
        if kind == "change":
            return self.vote_for(payload)
        if kind == "batch_size":
            self.batch_size = int(payload)
            return Step()
        raise ValueError(f"unknown input kind {kind!r}")

    def push_transaction(self, tx: Any) -> Step:
        self.queue.push(tx)
        return self._try_propose()

    def vote_for(self, change: Change) -> Step:
        step = self._wrap(self.dhb.vote_for(change))
        return step.extend(self._try_propose())

    def vote_to_add(self, node_id, pub_key) -> Step:
        step = self._wrap(self.dhb.vote_to_add(node_id, pub_key))
        return step.extend(self._try_propose())

    def vote_to_remove(self, node_id) -> Step:
        step = self._wrap(self.dhb.vote_to_remove(node_id))
        return step.extend(self._try_propose())

    def handle_message(self, sender_id: Any, message: Any, rng=None) -> Step:
        step = self._wrap(self.dhb.handle_message(sender_id, message, rng))
        return step.extend(self._try_propose())

    # -- internals -----------------------------------------------------------

    def _wrap(self, dhb_step: Step) -> Step:
        return absorb_child_step(
            dhb_step,
            wrap_msg=lambda m: m,  # QHB adds no envelope
            on_output=self._on_batch,
        )

    def _on_batch(self, batch: DhbBatch) -> Step:
        # lint: allow[determinism] queue removals commute; order irrelevant
        for contributions in batch.contributions.values():
            if isinstance(contributions, list):
                acct = self.queue.remove_multiple(contributions)
                self.removal_account = acct.merged(self.removal_account)
        step = Step.from_output(batch)
        return step.extend(self._try_propose())

    def _try_propose(self) -> Step:
        """Propose a fresh random sample if no proposal is in flight."""
        if not self.dhb.netinfo.is_validator() or self.dhb.hb.has_input:
            return Step()
        b = (
            self.batch_size
            if self.batch_size_provider is None
            # lint: allow[replay-purity] detached during replay by
            # construction: restore drops the provider and the restart
            # listener reattaches it only after the WAL loop finishes, so
            # replayed proposals fall back to the logged ("batch_size", B)
            # input channel — the replay-safe path for B
            else int(self.batch_size_provider())
        )
        sample = self.queue.choose(self.rng, b)
        if self.sample_listener is not None:
            # lint: allow[replay-purity] observer-only: the listener sees a
            # copy of the sample and its return value is ignored; a restored
            # node replays unsampled (listener falls back to None) and the
            # driver reattaches it post-replay via restart_listeners
            self.sample_listener(sample)
        return self._wrap(self.dhb.propose(sample, self.rng))
