"""Compact boolean-set helpers for BinaryAgreement.

Rebuilds `src/binary_agreement/{bool_set,bool_multimap}.rs` § (SURVEY.md
§2.1): a set over {False, True} packed into two bits, and a map from bool to
sets of node ids (who sent which value).
"""

from __future__ import annotations

from typing import Any, Iterator, Set


class BoolSet:
    """Immutable subset of {False, True}; NONE/FALSE/TRUE/BOTH."""

    __slots__ = ("bits",)

    def __init__(self, bits: int = 0) -> None:
        self.bits = bits & 3

    @staticmethod
    def none() -> "BoolSet":
        return BoolSet(0)

    @staticmethod
    def both() -> "BoolSet":
        return BoolSet(3)

    @staticmethod
    def single(b: bool) -> "BoolSet":
        return BoolSet(2 if b else 1)

    @staticmethod
    def from_iter(vals) -> "BoolSet":
        s = BoolSet(0)
        for v in vals:
            s = s.inserted(v)
        return s

    def inserted(self, b: bool) -> "BoolSet":
        return BoolSet(self.bits | (2 if b else 1))

    def union(self, other: "BoolSet") -> "BoolSet":
        return BoolSet(self.bits | other.bits)

    def contains(self, b: bool) -> bool:
        return bool(self.bits & (2 if b else 1))

    def contains_set(self, other: "BoolSet") -> bool:
        return (self.bits | other.bits) == self.bits

    def is_subset_of(self, other: "BoolSet") -> bool:
        return (self.bits & other.bits) == self.bits

    def definite(self):
        """The single value if a singleton, else None."""
        if self.bits == 1:
            return False
        if self.bits == 2:
            return True
        return None

    def __iter__(self) -> Iterator[bool]:
        if self.bits & 1:
            yield False
        if self.bits & 2:
            yield True

    def __len__(self) -> int:
        return (self.bits & 1) + ((self.bits >> 1) & 1)

    def __bool__(self) -> bool:
        return self.bits != 0

    def __eq__(self, other) -> bool:
        return isinstance(other, BoolSet) and self.bits == other.bits

    def __hash__(self) -> int:
        return hash(("BoolSet", self.bits))

    def __repr__(self) -> str:  # pragma: no cover
        return f"BoolSet({sorted(self)})"


class BoolMultimap:
    """Map bool -> set of node ids."""

    __slots__ = ("f", "t")

    def __init__(self) -> None:
        self.f: Set[Any] = set()
        self.t: Set[Any] = set()

    def __getitem__(self, b: bool) -> Set[Any]:
        return self.t if b else self.f

    def insert(self, b: bool, node_id) -> bool:
        """Insert; returns True if newly added."""
        s = self[b]
        if node_id in s:
            return False
        s.add(node_id)
        return True

    def senders(self) -> Set[Any]:
        return self.f | self.t
