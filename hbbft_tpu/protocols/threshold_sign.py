"""ThresholdSign — the common-coin primitive.

Rebuild of `src/threshold_sign/mod.rs` § (SURVEY.md §2.1): every node BLS-signs
a canonical document with its secret key share; any f+1 valid shares
Lagrange-combine into the unique master signature, whose hash is an
unbiasable random value (the coin).

TPU-first delta: incoming shares are **not** verified inline.  Each share
becomes a ``verify_sig_share`` :class:`~hbbft_tpu.core.types.CryptoWork`
item; the runtime batches all shares from a crank round into one device
pairing dispatch (the hottest loop — SURVEY.md §3.2).  A share is
"received-but-unverified" until the barrier; combination fires when f+1
*verified* shares are present, which yields the same unique signature
regardless of which subset verifies first.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import CryptoWork, Step, Target, TargetedMessage
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.keys import Signature, SignatureShare


@dataclass(frozen=True, slots=True)
class ThresholdSignMessage:
    """Wire message: one node's signature share."""

    share: SignatureShare


class ThresholdSign(ConsensusProtocol):
    """Threshold-sign a fixed document; outputs the combined `Signature`."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        backend: CryptoBackend,
        doc: Optional[bytes] = None,
    ) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.doc = doc
        self.had_input = False
        self._verified: Dict[int, SignatureShare] = {}  # node index -> share
        self._pending_senders = set()  # senders whose share is in-flight or done
        self._early = []  # (sender, share) received before the doc was set
        self.signature: Optional[Signature] = None
        self._terminated = False

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.netinfo.our_id

    def terminated(self) -> bool:
        return self._terminated

    def set_document(self, doc: bytes) -> Step:
        """Set the document to sign; drains shares that arrived early."""
        if self.doc is not None and self.doc != doc:
            raise ValueError("document already set")
        self.doc = doc
        step = Step()
        early, self._early = self._early, []
        for sender_id, message in early:
            step.extend(self.handle_message(sender_id, message))
        return step

    def handle_input(self, input: Any = None, rng=None) -> Step:
        return self.sign()

    def sign(self) -> Step:
        """Multicast our signature share and record it locally."""
        if self.doc is None:
            raise ValueError("no document to sign")
        if self.had_input:
            return Step()
        self.had_input = True
        step = Step()
        if not self.netinfo.is_validator():
            return step
        share = self.netinfo.secret_key_share.sign_share(self.doc)
        step.messages.append(TargetedMessage(Target.all(), ThresholdSignMessage(share)))
        our_idx = self.netinfo.node_index(self.netinfo.our_id)
        self._pending_senders.add(self.netinfo.our_id)
        self._verified[our_idx] = share
        step.extend(self._try_combine())
        return step

    def handle_message(self, sender_id: Any, message: ThresholdSignMessage, rng=None) -> Step:
        if self._terminated:
            return Step()
        if not isinstance(message, ThresholdSignMessage) or not isinstance(
            message.share, Signature
        ):
            return Step.from_fault(sender_id, "threshold_sign:malformed_message")
        idx = self.netinfo.node_index(sender_id)
        if idx is None:
            return Step.from_fault(sender_id, "threshold_sign:non_validator_share")
        if sender_id in self._pending_senders:
            # Duplicate share: ignore (re-sends are legal under reordering).
            return Step()
        if self.doc is None:
            # Share raced ahead of set_document: buffer, drained on set.
            self._early.append((sender_id, message))
            return Step()
        self._pending_senders.add(sender_id)
        pk_share = self.netinfo.public_key_set.public_key_share(idx)
        share = message.share

        def on_verified(valid: bool, _sender=sender_id, _idx=idx, _share=share) -> Step:
            if not valid:
                return Step.from_fault(_sender, "threshold_sign:invalid_sig_share")
            self._verified[_idx] = _share
            return self._try_combine()

        return Step().defer(
            CryptoWork("verify_sig_share", (pk_share, self.doc, share), on_verified)
        )

    # -- combination ---------------------------------------------------------

    def _try_combine(self) -> Step:
        threshold = self.netinfo.public_key_set.threshold()
        if self.signature is not None or len(self._verified) <= threshold:
            return Step()
        shares = dict(list(sorted(self._verified.items()))[: threshold + 1])
        sig = self.backend.combine_signatures(
            self.netinfo.public_key_set, shares, doc=self.doc
        )
        self.signature = sig
        self._terminated = True
        return Step.from_output(sig)
