"""Broadcast — Bracha reliable broadcast with AVID-style erasure coding.

Rebuild of `src/broadcast/{broadcast,message}.rs` § (SURVEY.md §2.1): a
designated proposer disseminates a value; every correct node outputs the same
value or none, tolerating f Byzantine nodes among N > 3f.

Protocol: the proposer Reed–Solomon-encodes the (length-prefixed) value into
N−2f data + 2f parity shards, commits with a Merkle tree, and sends each node
its shard + proof as ``Value``.  Nodes re-multicast their shard as ``Echo``;
N−f matching Echoes trigger ``Ready(root)``; f+1 Readys trigger Ready
re-multicast (amplification); 2f+1 Readys + N−2f stored Echo shards allow
reconstruction.  The reconstructed value's re-computed Merkle root must match
— otherwise the *proposer* provably equivocated and is logged.

The RS encode/decode rides the matmul-shaped GF(2⁸) codec
(hbbft_tpu/crypto/erasure.py) — on device this is an int8 matmul kernel
(BASELINE.json: "Reed–Solomon RBC as GF(2^8) matmul").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Set, Tuple

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import Step, Target, TargetedMessage
from hbbft_tpu.crypto.erasure import RSCodec, rs_codec
from hbbft_tpu.crypto.merkle import MerkleTree, Proof
from hbbft_tpu.obs import critpath as _critpath


@dataclass(frozen=True, slots=True)
class BroadcastMessage:
    """kind ∈ {"value", "echo", "ready"}; payload: Proof | Proof | root bytes."""

    kind: str
    payload: Any

    @staticmethod
    def value(proof: Proof) -> "BroadcastMessage":
        return BroadcastMessage("value", proof)

    @staticmethod
    def echo(proof: Proof) -> "BroadcastMessage":
        return BroadcastMessage("echo", proof)

    @staticmethod
    def ready(root: bytes) -> "BroadcastMessage":
        return BroadcastMessage("ready", root)


class Broadcast(ConsensusProtocol):
    """One reliable-broadcast instance for a fixed ``proposer_id``."""

    def __init__(self, netinfo: NetworkInfo, proposer_id: Any) -> None:
        self.netinfo = netinfo
        self.proposer_id = proposer_id
        n = netinfo.num_nodes()
        f = netinfo.num_faulty()
        self.data_shards = n - 2 * f
        self.parity_shards = 2 * f
        self.codec = rs_codec(self.data_shards, self.parity_shards)
        self.echo_sent = False
        self.ready_sent = False
        self.has_value = False  # got proposer's Value (or we are proposer)
        self._value_proof: Optional[Proof] = None  # the Value we accepted
        self.echos: Dict[Any, Proof] = {}
        self.readys: Dict[Any, bytes] = {}
        self.output: Optional[bytes] = None
        self._decided = False

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.netinfo.our_id

    def terminated(self) -> bool:
        return self._decided

    def handle_input(self, input: bytes, rng=None) -> Step:
        return self.broadcast(input)

    def broadcast(self, value: bytes) -> Step:
        """Proposer entry point: shard, commit, disseminate."""
        if self.netinfo.our_id != self.proposer_id:
            raise ValueError("only the proposer can broadcast")
        if self.has_value:
            return Step()
        self.has_value = True
        framed = len(value).to_bytes(4, "big") + bytes(value)
        shards = self.codec.encode(framed)
        tree = MerkleTree(shards)
        step = Step()
        for i, node_id in enumerate(self.netinfo.all_ids()):
            proof = tree.proof(i)
            if node_id == self.netinfo.our_id:
                step.extend(self._handle_value(self.netinfo.our_id, proof))
            else:
                step.messages.append(
                    TargetedMessage(Target.node(node_id), BroadcastMessage.value(proof))
                )
        return step

    def handle_message(self, sender_id: Any, message: BroadcastMessage, rng=None) -> Step:
        if not isinstance(message, BroadcastMessage):
            return Step.from_fault(sender_id, "broadcast:malformed_message")
        if message.kind == "value":
            return self._handle_value(sender_id, message.payload)
        if message.kind == "echo":
            return self._handle_echo(sender_id, message.payload)
        if message.kind == "ready":
            return self._handle_ready(sender_id, message.payload)
        return Step.from_fault(sender_id, "broadcast:unknown_kind")

    # -- phases --------------------------------------------------------------

    def _validate_proof(self, proof: Any, expect_index: Optional[int]) -> bool:
        if not isinstance(proof, Proof):
            return False
        if expect_index is not None and proof.index != expect_index:
            return False
        return proof.validate(self.netinfo.num_nodes())

    def _handle_value(self, sender_id: Any, proof: Any) -> Step:
        if sender_id != self.proposer_id:
            return Step.from_fault(sender_id, "broadcast:value_from_non_proposer")
        if self.has_value and sender_id != self.netinfo.our_id:
            # Second Value under exactly-once delivery is provable either
            # way; a *different* proof is equivocation (two commitments
            # for one instance — the EquivocatingAdversary signature),
            # split from a plain re-send exactly like Echo/Ready.
            if self._value_proof is not None and proof != self._value_proof:
                return Step.from_fault(sender_id, "broadcast:conflicting_values")
            return Step.from_fault(sender_id, "broadcast:multiple_values")
        our_idx = self.netinfo.node_index(self.netinfo.our_id)
        if not self._validate_proof(proof, our_idx):
            return Step.from_fault(self.proposer_id, "broadcast:invalid_value_proof")
        # lint: allow[byzantine-input] the sender gate above is IDENTITY
        # equality against the instance's proposer (only the proposer may
        # send Value) — strictly stronger than set membership
        self.has_value = True
        self._value_proof = proof
        return self._send_echo(proof)

    def _send_echo(self, proof: Proof) -> Step:
        if self.echo_sent:
            return Step()
        self.echo_sent = True
        step = Step()
        step.messages.append(
            TargetedMessage(Target.all(), BroadcastMessage.echo(proof))
        )
        step.extend(self._handle_echo(self.netinfo.our_id, proof))
        return step

    def _handle_echo(self, sender_id: Any, proof: Any) -> Step:
        sender_idx = self.netinfo.node_index(sender_id)
        if sender_idx is None:
            return Step.from_fault(sender_id, "broadcast:echo_from_non_validator")
        if sender_id in self.echos:
            if self.echos[sender_id] == proof:
                # Re-sent Echo: provable misbehaviour under exactly-once
                # delivery (reference `Fault::MultipleEchos`), not a drop.
                return Step.from_fault(sender_id, "broadcast:multiple_echos")
            return Step.from_fault(sender_id, "broadcast:conflicting_echo")
        # An Echo must carry the *sender's* shard (AVID dispersal).
        if not self._validate_proof(proof, sender_idx):
            return Step.from_fault(sender_id, "broadcast:invalid_echo_proof")
        self.echos[sender_id] = proof
        step = Step()
        root = proof.root_hash
        if (
            self._count_echos(root) >= self.netinfo.num_correct()
            and not self.ready_sent
        ):
            step.extend(self._send_ready(root))
        return step.extend(self._try_decode())

    def _send_ready(self, root: bytes) -> Step:
        if self.ready_sent:
            return Step()
        self.ready_sent = True
        step = Step()
        step.messages.append(
            TargetedMessage(Target.all(), BroadcastMessage.ready(root))
        )
        step.extend(self._handle_ready(self.netinfo.our_id, root))
        return step

    def _handle_ready(self, sender_id: Any, root: Any) -> Step:
        if not isinstance(root, bytes) or len(root) != 32:
            return Step.from_fault(sender_id, "broadcast:malformed_ready")
        if self.netinfo.node_index(sender_id) is None:
            return Step.from_fault(sender_id, "broadcast:ready_from_non_validator")
        if sender_id in self.readys:
            if self.readys[sender_id] == root:
                # Re-sent Ready (reference `Fault::MultipleReadys`).
                return Step.from_fault(sender_id, "broadcast:multiple_readys")
            return Step.from_fault(sender_id, "broadcast:conflicting_ready")
        self.readys[sender_id] = root
        step = Step()
        f = self.netinfo.num_faulty()
        if self._count_readys(root) > f and not self.ready_sent:
            # Ready amplification: f+1 Readys imply a correct node saw N-f Echoes.
            step.extend(self._send_ready(root))
        return step.extend(self._try_decode())

    # -- decoding ------------------------------------------------------------

    def _count_echos(self, root: bytes) -> int:
        return sum(1 for p in self.echos.values() if p.root_hash == root)

    def _count_readys(self, root: bytes) -> int:
        return sum(1 for r in self.readys.values() if r == root)

    def _try_decode(self) -> Step:
        if self._decided:
            return Step()
        f = self.netinfo.num_faulty()
        # Find a root with ≥ 2f+1 Readys and ≥ N-2f stored Echo shards.
        # (sorted: at most one root can reach a Ready quorum — conflicting
        # Readys are rejected per sender — but candidate order must still be
        # replica-independent for the fault-evidence path below.)
        candidates: Set[bytes] = {r for r in self.readys.values()}
        for root in sorted(candidates):
            if self._count_readys(root) <= 2 * f:
                continue
            proofs = {
                self.netinfo.node_index(nid): p
                for nid, p in self.echos.items()
                if p.root_hash == root
            }
            if len(proofs) < self.data_shards:
                continue
            shard_slots = [proofs.get(i) for i in range(self.netinfo.num_nodes())]
            shards = [p.value if p is not None else None for p in shard_slots]
            # A Byzantine proposer can Merkle-commit to unequal-length shards;
            # every proof then validates individually.  Mismatched lengths
            # under a ready-quorum root are proof of proposer misbehaviour.
            lengths = {len(s) for s in shards if s is not None}
            if len(lengths) != 1:
                self._decided = True
                return Step.from_fault(
                    self.proposer_id, "broadcast:inconsistent_shard_lengths"
                )
            try:
                full = self.codec.reconstruct(shards)
            except ValueError:
                self._decided = True
                return Step.from_fault(self.proposer_id, "broadcast:undecodable_shards")
            # Re-commit: the reconstructed shard vector must hash to `root`,
            # otherwise the proposer encoded inconsistently.
            tree = MerkleTree(full)
            self._decided = True
            if tree.root_hash != root:
                return Step.from_fault(self.proposer_id, "broadcast:invalid_shard_encoding")
            framed = b"".join(full[: self.data_shards])
            length = int.from_bytes(framed[:4], "big")
            if length > len(framed) - 4:
                return Step.from_fault(self.proposer_id, "broadcast:bad_length_prefix")
            self.output = framed[4 : 4 + length]
            _critpath.stamp(
                "rbc.output",
                node=self.netinfo.our_id,
                instance=self.netinfo.node_index(self.proposer_id),
            )
            return Step.from_output(self.output)
        return Step()
