"""ThresholdDecrypt — collect and combine decryption shares.

Rebuild of `src/threshold_decrypt/mod.rs` § (SURVEY.md §2.1): given a
threshold ciphertext, every validator multicasts its decryption share;
f+1 pairing-verified shares Lagrange-combine in G1 to the plaintext.

Like ThresholdSign, share verification is deferred into batched
``verify_dec_share`` work items — at N=100 this is the second half of the
O(N²)-pairings-per-epoch hot loop (SURVEY.md §3.2) that the device backend
resolves in one dispatch.  Shares arriving before the ciphertext is set are
buffered.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import CryptoWork, Step, Target, TargetedMessage
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.keys import Ciphertext, DecryptionShare


@dataclass(frozen=True, slots=True)
class ThresholdDecryptMessage:
    share: DecryptionShare


class ThresholdDecrypt(ConsensusProtocol):
    """Decrypt one ciphertext; outputs the plaintext bytes."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        backend: CryptoBackend,
        ciphertext: Optional[Ciphertext] = None,
    ) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.ciphertext: Optional[Ciphertext] = None
        self._verified: Dict[int, DecryptionShare] = {}
        self._pending_senders = set()
        self._early: List[Tuple[Any, ThresholdDecryptMessage]] = []
        self.plaintext: Optional[bytes] = None
        self._terminated = False
        self._ct_invalid = False
        self._share_sent = False
        self._decrypt_requested = False
        if ciphertext is not None:
            # Constructor form: validate synchronously (rare path; the HB
            # epoch pipeline uses set_ciphertext + deferred validation).
            if not ciphertext.verify():
                raise ValueError("invalid ciphertext")
            self.ciphertext = ciphertext

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.netinfo.our_id

    def terminated(self) -> bool:
        return self._terminated

    def set_ciphertext(self, ct: Ciphertext, pre_validated: bool = False) -> Step:
        """Install the ciphertext.  Unless ``pre_validated`` (e.g. HB already
        batch-checked it), validation is deferred to the device batch; an
        invalid ciphertext terminates the instance with no output."""
        if self.ciphertext is not None or self._ct_invalid:
            raise ValueError("ciphertext already set")
        if pre_validated:
            self.ciphertext = ct
            return self._drain_early()

        def on_valid(ok: bool) -> Step:
            if not ok:
                self._ct_invalid = True
                self._terminated = True
                return Step()
            self.ciphertext = ct
            return self._drain_early()

        return Step().defer(CryptoWork("verify_ciphertext", ct, on_valid))

    def _drain_early(self) -> Step:
        step = Step()
        if self._decrypt_requested:
            step.extend(self.start_decryption())
        early, self._early = self._early, []
        for sender_id, message in early:
            step.extend(self.handle_message(sender_id, message))
        return step

    def handle_input(self, input: Any = None, rng=None) -> Step:
        return self.start_decryption()

    def start_decryption(self) -> Step:
        """Multicast our decryption share (requires the ciphertext)."""
        if self._share_sent or self._ct_invalid:
            return Step()
        if self.ciphertext is None:
            # Validation still pending (deferred batch): fire on completion.
            self._decrypt_requested = True
            return Step()
        self._share_sent = True
        if not self.netinfo.is_validator():
            return Step()
        share = self.netinfo.secret_key_share.decrypt_share_unchecked(self.ciphertext)
        step = Step()
        step.messages.append(
            TargetedMessage(Target.all(), ThresholdDecryptMessage(share))
        )
        our_idx = self.netinfo.node_index(self.netinfo.our_id)
        self._pending_senders.add(self.netinfo.our_id)
        self._verified[our_idx] = share
        return step.extend(self._try_combine())

    def handle_message(self, sender_id: Any, message: ThresholdDecryptMessage, rng=None) -> Step:
        if self._terminated:
            return Step()
        if not isinstance(message, ThresholdDecryptMessage) or not isinstance(
            message.share, DecryptionShare
        ):
            return Step.from_fault(sender_id, "threshold_decrypt:malformed_message")
        idx = self.netinfo.node_index(sender_id)
        if idx is None:
            return Step.from_fault(sender_id, "threshold_decrypt:non_validator_share")
        if sender_id in self._pending_senders:
            return Step()
        if self.ciphertext is None:
            self._early.append((sender_id, message))
            return Step()
        self._pending_senders.add(sender_id)
        pk_share = self.netinfo.public_key_set.public_key_share(idx)
        share = message.share

        def on_verified(valid: bool, _s=sender_id, _i=idx, _sh=share) -> Step:
            if not valid:
                return Step.from_fault(_s, "threshold_decrypt:invalid_share")
            self._verified[_i] = _sh
            return self._try_combine()

        return Step().defer(
            CryptoWork(
                "verify_dec_share", (pk_share, self.ciphertext, share), on_verified
            )
        )

    # -- combination ---------------------------------------------------------

    def _try_combine(self) -> Step:
        threshold = self.netinfo.public_key_set.threshold()
        if self.plaintext is not None or len(self._verified) <= threshold:
            return Step()
        shares = dict(list(sorted(self._verified.items()))[: threshold + 1])
        self.plaintext = self.backend.combine_decryption_shares(
            self.netinfo.public_key_set, shares, self.ciphertext
        )
        self._terminated = True
        return Step.from_output(self.plaintext)
