"""BinaryAgreement — randomized asynchronous binary Byzantine consensus.

Rebuild of `src/binary_agreement/{binary_agreement,...}.rs` § (SURVEY.md
§2.1): the Mostéfaoui–Moumen–Raynal (PODC 2014) algorithm as realized in
hbbft — per round: SBV broadcast (BVal/Aux), a Conf phase, then a common
coin; decide when the singleton candidate matches the coin.  Early rounds
use a fixed coin schedule (round % 3: true, false, then a real threshold
coin — *(uncertain exact reference schedule — SURVEY.md §2.1)*), so crypto
is only paid every third round while an adaptive adversary still cannot
stall the protocol.

Decision broadcasts a ``Term(b)`` message; ``Term`` doubles as BVal+Aux+Conf
for all later rounds, and f+1 matching Terms decide immediately.

Coin shares ride the deferred-verification path (see threshold_sign.py):
the inner ThresholdSign's pairing checks surface through
:func:`~hbbft_tpu.core.types.absorb_child_step`, so one crank round's coin
shares across *all* concurrent BA instances batch into one device call.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import Step, Target, TargetedMessage, absorb_child_step
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.keys import Signature
from hbbft_tpu.obs import critpath as _critpath
from hbbft_tpu.protocols.bool_set import BoolMultimap, BoolSet
from hbbft_tpu.protocols.sbv_broadcast import SbvBroadcast, SbvMessage
from hbbft_tpu.protocols.threshold_sign import ThresholdSign, ThresholdSignMessage
from hbbft_tpu.utils.canonical import encode as canonical_encode

# Don't queue messages absurdly far in the future (memory-bound + fault evidence).
MAX_FUTURE_ROUNDS = 1000


@dataclass(frozen=True, slots=True)
class BaMessage:
    """Round-tagged BA wire message.

    kind ∈ {"sbv", "conf", "coin", "term"}; payload is the inner message
    (SbvMessage | BoolSet | ThresholdSignMessage | bool).
    """

    round: int
    kind: str
    payload: Any

    @staticmethod
    def sbv(r: int, m: SbvMessage) -> "BaMessage":
        return BaMessage(r, "sbv", m)

    @staticmethod
    def conf(r: int, vals: BoolSet) -> "BaMessage":
        return BaMessage(r, "conf", vals)

    @staticmethod
    def coin(r: int, m: ThresholdSignMessage) -> "BaMessage":
        return BaMessage(r, "coin", m)

    @staticmethod
    def term(r: int, b: bool) -> "BaMessage":
        return BaMessage(r, "term", b)


class BinaryAgreement(ConsensusProtocol):
    """One binary-consensus instance, identified by a session id.

    ``session_id`` must be globally unique per instance and identical on all
    nodes (it salts the coin document); Subset uses (subset-session,
    proposer-index).
    """

    def __init__(
        self,
        netinfo: NetworkInfo,
        backend: CryptoBackend,
        session_id: bytes,
        instance: Optional[int] = None,
    ) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.session_id = session_id
        # Proposer index when this BA sits inside a Subset (critical-path
        # attribution label); standalone BAs leave it None.
        self.instance = instance
        self.round = 0
        self.sbv = SbvBroadcast(netinfo)
        self.received_conf: Dict[Any, BoolSet] = {}
        self.sent_conf: Optional[BoolSet] = None
        self.conf_values: Optional[BoolSet] = None  # our SBV output this round
        self.estimate: Optional[bool] = None
        self.decision: Optional[bool] = None
        self.received_term = BoolMultimap()
        self._sent_term = False
        self._coin: Optional[ThresholdSign] = None
        self._coin_invoked = False
        self._coin_value: Optional[bool] = None
        self._coin_applied = False
        self._queue: Dict[int, List[Tuple[Any, BaMessage]]] = {}

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.netinfo.our_id

    def terminated(self) -> bool:
        return self.decision is not None

    def handle_input(self, input: bool, rng=None) -> Step:
        return self.propose(bool(input))

    def propose(self, value: bool) -> Step:
        if self.estimate is not None or self.decision is not None:
            return Step()
        self.estimate = value
        step = self._wrap_sbv(self.sbv.handle_input(value))
        return step.extend(self._poll())

    def handle_message(self, sender_id: Any, message: BaMessage, rng=None) -> Step:
        if not isinstance(message, BaMessage):
            return Step.from_fault(sender_id, "binary_agreement:malformed_message")
        if self.netinfo.node_index(sender_id) is None:
            return Step.from_fault(sender_id, "binary_agreement:non_validator_sender")
        if not isinstance(message.round, int):
            # Unvalidated round would TypeError in the comparisons below —
            # a remote crash vector (wire decode enforces int, but locally
            # embedded adversaries can inject arbitrary objects).
            return Step.from_fault(sender_id, "binary_agreement:malformed_round")
        if message.kind == "term":
            return self._handle_term(sender_id, message)
        if self.decision is not None:
            return Step()
        r = message.round
        if r < self.round:
            return Step()  # stale round — benign under async delivery
        if r > self.round:
            if r > self.round + MAX_FUTURE_ROUNDS:
                return Step.from_fault(sender_id, "binary_agreement:far_future_round")
            self._queue.setdefault(r, []).append((sender_id, message))
            return Step()
        return self._handle_current(sender_id, message)

    # -- current-round dispatch ---------------------------------------------

    def _handle_current(self, sender_id: Any, message: BaMessage) -> Step:
        if message.kind == "sbv":
            if not isinstance(message.payload, SbvMessage):
                return Step.from_fault(sender_id, "binary_agreement:malformed_sbv")
            step = self._wrap_sbv(self.sbv.handle_message(sender_id, message.payload))
            return step.extend(self._poll())
        if message.kind == "conf":
            return self._handle_conf(sender_id, message.payload)
        if message.kind == "coin":
            return self._handle_coin_message(sender_id, message.payload)
        return Step.from_fault(sender_id, "binary_agreement:unknown_kind")

    # -- SBV phase -----------------------------------------------------------

    def _wrap_sbv(self, sbv_step: Step) -> Step:
        r = self.round
        return absorb_child_step(
            sbv_step,
            wrap_msg=lambda m, _r=r: BaMessage.sbv(_r, m),
            on_output=self._on_sbv_output,
        )

    def _on_sbv_output(self, vals: BoolSet) -> Step:
        if self.sent_conf is not None or self.decision is not None:
            return Step()
        self.sent_conf = vals
        self.conf_values = vals
        step = Step()
        step.messages.append(
            TargetedMessage(Target.all(), BaMessage.conf(self.round, vals))
        )
        step.extend(self._handle_conf(self.netinfo.our_id, vals))
        return step

    # -- Conf phase ----------------------------------------------------------

    def _handle_conf(self, sender_id: Any, vals: Any) -> Step:
        if not isinstance(vals, BoolSet) or not vals:
            return Step.from_fault(sender_id, "binary_agreement:malformed_conf")
        if sender_id in self.received_conf:
            # A Term replay pre-fills received_conf, so a conf racing its
            # own sender's Term is legal; absent a Term, two different Conf
            # values in one round are provable equivocation.
            if (
                self.received_conf[sender_id] != vals
                and sender_id not in self.received_term.senders()
            ):
                return Step.from_fault(sender_id, "binary_agreement:conflicting_conf")
            return Step()
        self.received_conf[sender_id] = vals
        return self._poll()

    def _count_conf(self) -> int:
        bv = self.sbv.bin_values
        return sum(1 for v in self.received_conf.values() if v.is_subset_of(bv))

    def _poll(self) -> Step:
        """Re-check conf-round completion (bin_values may have grown),
        invoke the coin when ready, and apply a coin value that may have
        already combined from peers' shares before our conf round finished."""
        if (
            self.decision is not None
            or self.sent_conf is None
            or self._count_conf() < self.netinfo.num_correct()
        ):
            return Step()
        step = Step()
        if not self._coin_invoked:
            self._coin_invoked = True
            fixed = self._fixed_coin()
            if fixed is not None:
                self._coin_value = fixed
            else:
                step.extend(self._wrap_coin(self._ensure_coin().sign()))
        return step.extend(self._try_apply_coin())

    # -- Coin ----------------------------------------------------------------

    def _coin_doc(self) -> bytes:
        return canonical_encode(("ba-coin", self.session_id, self.round))

    def _fixed_coin(self) -> Optional[bool]:
        """Fixed schedule for cheap early rounds; every third round flips a
        real threshold coin."""
        m = self.round % 3
        if m == 0:
            return True
        if m == 1:
            return False
        return None

    def _ensure_coin(self) -> ThresholdSign:
        if self._coin is None:
            self._coin = ThresholdSign(self.netinfo, self.backend, doc=self._coin_doc())
        return self._coin

    def _handle_coin_message(self, sender_id: Any, msg: Any) -> Step:
        if self._fixed_coin() is not None:
            return Step.from_fault(sender_id, "binary_agreement:coin_in_fixed_round")
        if not isinstance(msg, ThresholdSignMessage):
            return Step.from_fault(sender_id, "binary_agreement:malformed_coin")
        return self._wrap_coin(self._ensure_coin().handle_message(sender_id, msg))

    def _wrap_coin(self, ts_step: Step) -> Step:
        r = self.round
        return absorb_child_step(
            ts_step,
            wrap_msg=lambda m, _r=r: BaMessage.coin(_r, m),
            on_output=lambda sig, _r=r: self._on_coin_output(_r, sig),
        )

    def _on_coin_output(self, r: int, sig: Signature) -> Step:
        if r != self.round or self._coin_value is not None:
            return Step()  # late coin from a superseded round
        # The coin may combine from f+1 peers' shares before our own
        # SBV/Conf phase completes — store it and apply at conf quorum.
        self._coin_value = sig.parity()
        _critpath.stamp(
            "coin.reveal",
            node=self.netinfo.our_id,
            instance=self.instance,
            rnd=r,
            value=self._coin_value,
        )
        return self._try_apply_coin()

    def _try_apply_coin(self) -> Step:
        if (
            self.decision is not None
            or self._coin_applied
            or self._coin_value is None
            or self.conf_values is None
            or self._count_conf() < self.netinfo.num_correct()
        ):
            return Step()
        self._coin_applied = True
        coin = self._coin_value
        definite = self.conf_values.definite()
        if definite is not None:
            if definite == coin:
                return self._decide(definite)
            next_est = definite
        else:
            next_est = coin
        return self._next_round(next_est)

    # -- Term ----------------------------------------------------------------

    def _handle_term(self, sender_id: Any, message: BaMessage) -> Step:
        b = message.payload
        if not isinstance(b, bool):
            return Step.from_fault(sender_id, "binary_agreement:malformed_term")
        if sender_id in self.received_term.senders():
            return Step.from_fault(sender_id, "binary_agreement:duplicate_term")
        self.received_term.insert(b, sender_id)
        if self.decision is not None:
            return Step()
        step = Step()
        # A Term implies BVal+Aux+Conf for the current and all later rounds.
        step.extend(self._replay_term(sender_id, b))
        if len(self.received_term[b]) > self.netinfo.num_faulty():
            # f+1 Terms(b): at least one correct node decided b.
            step.extend(self._decide(b))
        return step

    def _replay_term(self, sender_id: Any, b: bool) -> Step:
        step = self._wrap_sbv(self.sbv.handle_message(sender_id, SbvMessage.bval(b)))
        step.extend(self._wrap_sbv(self.sbv.handle_message(sender_id, SbvMessage.aux(b))))
        if sender_id not in self.received_conf:
            self.received_conf[sender_id] = BoolSet.single(b)
        return step.extend(self._poll())

    # -- round transitions ---------------------------------------------------

    def _decide(self, b: bool) -> Step:
        if self.decision is not None:
            return Step()
        self.decision = b
        _critpath.stamp(
            "ba.decide",
            node=self.netinfo.our_id,
            instance=self.instance,
            rnd=self.round,
            value=b,
        )
        step = Step.from_output(b)
        if not self._sent_term:
            self._sent_term = True
            step.messages.append(
                TargetedMessage(Target.all(), BaMessage.term(self.round, b))
            )
        return step

    def _next_round(self, estimate: bool) -> Step:
        self.round += 1
        self.sbv = SbvBroadcast(self.netinfo)
        self.received_conf = {}
        self.sent_conf = None
        self.conf_values = None
        self._coin = None
        self._coin_invoked = False
        self._coin_value = None
        self._coin_applied = False
        self.estimate = estimate
        step = self._wrap_sbv(self.sbv.handle_input(estimate))
        # Replay recorded Terms into the fresh round.
        for b in (False, True):
            for sender in sorted(self.received_term[b], key=repr):
                step.extend(self._replay_term_into_round(sender, b))
        # Drain queued messages for the new round.  Route through
        # handle_message: processing may advance the round again mid-drain,
        # turning the remaining queued messages stale.
        for sender, msg in self._queue.pop(self.round, []):
            step.extend(self.handle_message(sender, msg))
        return step.extend(self._poll())

    def _replay_term_into_round(self, sender_id: Any, b: bool) -> Step:
        step = self._wrap_sbv(self.sbv.handle_message(sender_id, SbvMessage.bval(b)))
        step.extend(self._wrap_sbv(self.sbv.handle_message(sender_id, SbvMessage.aux(b))))
        self.received_conf.setdefault(sender_id, BoolSet.single(b))
        return step
