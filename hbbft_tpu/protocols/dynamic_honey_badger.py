"""DynamicHoneyBadger — HoneyBadger with dynamic validator membership.

Rebuild of `src/dynamic_honey_badger/` § (SURVEY.md §2.1, §3.4): validators
cast signed votes for `Change`s (add/remove a validator, or switch the
encryption schedule); votes ride inside committed contributions so every
node tallies them identically.  A strict-majority winner triggers an
in-band `SyncKeyGen` among the *new* validator set, whose Part/Ack messages
also ride (signed) inside contributions; when the DKG completes, the era
ends: a fresh `NetworkInfo` (new master key, new shares) and a fresh
`HoneyBadger` start, and the batch reports ``ChangeState.complete``.

A joining node starts from a serializable `JoinPlan` as an *observer*: it
follows all traffic (combining broadcast shares without contributing),
passively receives its DKG row values from committed Acks (each Ack carries
an encrypted value slot for every member of the next era, including the
joiner), and becomes a validator when the era turns over.

All per-node signatures committed in one batch are verified through a
single batched backend call — on the device backend this joins the same
per-round dispatch as the pairing checks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import Step, absorb_child_step
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.keys import PublicKey, PublicKeySet, Signature
from hbbft_tpu.protocols.change import Change, ChangeState
from hbbft_tpu.protocols.honey_badger import (
    Batch as HbBatch,
    EncryptionSchedule,
    HoneyBadger,
)
from hbbft_tpu.protocols.sync_key_gen import (
    Ack,
    Part,
    SyncKeyGen,
    ack_from_canonical,
    ack_to_canonical,
    part_from_canonical,
    part_to_canonical,
)
from hbbft_tpu.protocols.votes import SignedVote, VoteCounter
from hbbft_tpu.utils import canonical


@dataclass(frozen=True, slots=True)
class DhbMessage:
    era: int
    payload: Any  # HbMessage


@dataclass(slots=True)
class DhbBatch:
    """One committed epoch: user contributions + membership-change state."""

    era: int
    epoch: int
    contributions: Dict[Any, Any]
    change: ChangeState

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, DhbBatch)
            and (self.era, self.epoch) == (other.era, other.epoch)
            and self.contributions == other.contributions
            and self.change == other.change
        )


@dataclass(frozen=True, slots=True)
class JoinPlan:
    """Everything a joining observer needs to follow era ``era``
    (reference `JoinPlan` §)."""

    era: int
    pub_key_set_bytes: bytes
    pub_keys: Tuple[Tuple[Any, bytes], ...]  # sorted (node_id, pk_bytes)
    encryption_schedule: EncryptionSchedule


class _KeyGenState:
    def __init__(
        self,
        change: Change,
        keygen: SyncKeyGen,
        pub_keys: Dict[Any, PublicKey],
    ) -> None:
        self.change = change
        self.keygen = keygen
        self.pub_keys = pub_keys


class DynamicHoneyBadgerBuilder:
    """Builder mirroring the reference `DynamicHoneyBadgerBuilder` §."""

    def __init__(self, netinfo: NetworkInfo, backend: CryptoBackend, rng) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.rng = rng
        self._max_future_epochs = 3
        self._encryption_schedule = EncryptionSchedule.always()
        self._session_id = b"dhb"

    def max_future_epochs(self, n: int) -> "DynamicHoneyBadgerBuilder":
        self._max_future_epochs = n
        return self

    def encryption_schedule(self, s: EncryptionSchedule) -> "DynamicHoneyBadgerBuilder":
        self._encryption_schedule = s
        return self

    def session_id(self, sid: bytes) -> "DynamicHoneyBadgerBuilder":
        self._session_id = sid
        return self

    def build(self) -> "DynamicHoneyBadger":
        return DynamicHoneyBadger(
            self.netinfo,
            self.backend,
            rng=self.rng,
            session_id=self._session_id,
            max_future_epochs=self._max_future_epochs,
            encryption_schedule=self._encryption_schedule,
        )


class DynamicHoneyBadger(ConsensusProtocol):
    def __init__(
        self,
        netinfo: NetworkInfo,
        backend: CryptoBackend,
        rng,
        session_id: bytes = b"dhb",
        max_future_epochs: int = 3,
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
        era: int = 0,
    ) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.rng = rng
        self.session_id = session_id
        self.max_future_epochs = max_future_epochs
        self.encryption_schedule = encryption_schedule
        self.era = era
        self.hb = self._new_hb()
        self.vote_counter = VoteCounter(self.era, netinfo.num_nodes())
        self._vote_num = 0
        self._pending_votes: List[SignedVote] = []
        self._pending_kg: List[Tuple[Tuple, bytes]] = []  # (msg_canonical, sig)
        self.key_gen: Optional[_KeyGenState] = None
        self._future_era: List[Tuple[Any, DhbMessage]] = []

    # -- construction helpers ------------------------------------------------

    def _new_hb(self) -> HoneyBadger:
        sid = canonical.encode(("dhb-era", self.session_id, self.era))
        return HoneyBadger(
            self.netinfo,
            self.backend,
            session_id=sid,
            max_future_epochs=self.max_future_epochs,
            encryption_schedule=self.encryption_schedule,
        )

    @staticmethod
    def builder(netinfo, backend, rng) -> DynamicHoneyBadgerBuilder:
        return DynamicHoneyBadgerBuilder(netinfo, backend, rng)

    @staticmethod
    def new_joining(
        our_id: Any,
        secret_key,
        join_plan: JoinPlan,
        backend: CryptoBackend,
        rng,
        session_id: bytes = b"dhb",
        max_future_epochs: int = 3,
    ) -> "DynamicHoneyBadger":
        """Construct an observer from a `JoinPlan` (reference §3.4)."""
        g = backend.group
        pub_keys = {
            nid: PublicKey.from_bytes(g, pkb) for nid, pkb in join_plan.pub_keys
        }
        netinfo = NetworkInfo(
            our_id=our_id,
            secret_key_share=None,
            public_key_set=PublicKeySet.from_bytes(g, join_plan.pub_key_set_bytes),
            secret_key=secret_key,
            public_keys=pub_keys,
        )
        return DynamicHoneyBadger(
            netinfo,
            backend,
            rng=rng,
            session_id=session_id,
            max_future_epochs=max_future_epochs,
            encryption_schedule=join_plan.encryption_schedule,
            era=join_plan.era,
        )

    def join_plan(self) -> JoinPlan:
        """Snapshot for an observer to join the *current* era."""
        return JoinPlan(
            era=self.era,
            pub_key_set_bytes=self.netinfo.public_key_set.to_bytes(),
            pub_keys=tuple(
                sorted(
                    (nid, pk.to_bytes())
                    for nid, pk in self.netinfo.public_key_map().items()
                )
            ),
            encryption_schedule=self.encryption_schedule,
        )

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.netinfo.our_id

    def terminated(self) -> bool:
        return False

    def handle_input(self, input: Any, rng=None) -> Step:
        """Generic input: ("user", contribution) or ("change", Change)."""
        kind, payload = input
        if kind == "user":
            return self.propose(payload, rng or self.rng)
        if kind == "change":
            return self.vote_for(payload)
        raise ValueError(f"unknown input kind {kind!r}")

    # -- voting --------------------------------------------------------------

    def vote_for(self, change: Change) -> Step:
        """Sign and queue a vote; it rides in our next contribution."""
        if not self.netinfo.is_validator():
            return Step()
        self._vote_num += 1
        vote = SignedVote(
            voter=self.netinfo.our_id,
            era=self.era,
            num=self._vote_num,
            change=change,
            sig_bytes=b"",
        )
        sig = self.netinfo.secret_key.sign(vote.signed_payload())
        vote = SignedVote(vote.voter, vote.era, vote.num, vote.change, sig.to_bytes())
        self._pending_votes.append(vote)
        return Step()

    def vote_to_add(self, node_id, pub_key: PublicKey) -> Step:
        return self.vote_for(Change.add(node_id, pub_key.to_bytes()))

    def vote_to_remove(self, node_id) -> Step:
        return self.vote_for(Change.remove(node_id))

    # -- proposing -----------------------------------------------------------

    def propose(self, contribution: Any, rng=None) -> Step:
        if not self.netinfo.is_validator():
            return Step()
        rng = rng or self.rng
        votes = [v.to_canonical() for v in self._pending_votes]
        kg = [list(item) for item in self._pending_kg]
        internal = ("icontrib", contribution, votes, kg)
        return self._wrap_hb(self.era, self.hb.propose(internal, rng))

    def handle_message(self, sender_id: Any, message: DhbMessage, rng=None) -> Step:
        if not isinstance(message, DhbMessage) or not isinstance(message.era, int):
            return Step.from_fault(sender_id, "dynamic_honey_badger:malformed_message")
        if message.era < self.era:
            return Step()  # previous era: stale but benign
        if message.era > self.era + 1:
            return Step.from_fault(sender_id, "dynamic_honey_badger:era_too_far_ahead")
        if message.era > self.era:
            # Fault next-era traffic only on *provable* non-membership:
            # with a DKG in progress the era+1 member set is known
            # (current validators ∪ key_gen.pub_keys covers a joiner that
            # finished the era first).  Before the DKG-start batch is
            # processed, era+1 membership is undetermined — buffer as
            # before rather than drop an honest early sender.
            if (
                self.key_gen is not None
                and not self.netinfo.is_node_validator(sender_id)
                and sender_id not in self.key_gen.pub_keys
            ):
                return Step.from_fault(
                    sender_id, "dynamic_honey_badger:future_era_from_non_member"
                )
            self._future_era.append((sender_id, message))
            return Step()
        return self._wrap_hb(
            self.era, self.hb.handle_message(sender_id, message.payload, rng)
        )

    # -- HB wiring -----------------------------------------------------------

    def _wrap_hb(self, era: int, hb_step: Step) -> Step:
        return absorb_child_step(
            hb_step,
            wrap_msg=lambda m, _e=era: DhbMessage(_e, m),
            on_output=lambda batch, _e=era: self._on_hb_batch(_e, batch),
        )

    def _on_hb_batch(self, era: int, hb_batch: HbBatch) -> Step:
        if era != self.era:
            return Step()  # late re-entry across an era boundary
        step = Step()
        contributions: Dict[Any, Any] = {}
        votes: List[Tuple[Any, SignedVote]] = []
        kg_msgs: List[Tuple[Any, Tuple, bytes]] = []
        order = sorted(
            hb_batch.contributions.items(),
            key=lambda kv: self.netinfo.node_index(kv[0]),
        )
        for proposer, internal in order:
            try:
                tag, user, vote_list, kg_list = internal
                if tag != "icontrib":
                    raise ValueError
            except (TypeError, ValueError):
                step.add_fault(proposer, "dynamic_honey_badger:malformed_contribution")
                continue
            if user is not None:
                contributions[proposer] = user
            try:
                for vt in vote_list:
                    votes.append((proposer, SignedVote.from_canonical(vt)))
                for item in kg_list:
                    change_canonical, msg_canonical, sig = item
                    if not isinstance(sig, bytes):
                        raise ValueError
                    kg_msgs.append((proposer, change_canonical, msg_canonical, sig))
            except (TypeError, ValueError, IndexError):
                step.add_fault(proposer, "dynamic_honey_badger:malformed_contribution")
                continue

        # One batched signature verification for everything in this batch.
        sig_items = []
        g = self.backend.group
        for proposer, vote in votes:
            pk = self.netinfo.public_key(vote.voter)
            sig_items.append(
                (pk, vote.signed_payload(), _sig_or_none(g, vote.sig_bytes))
            )
        for proposer, change_canonical, msg_canonical, sig_bytes in kg_msgs:
            pk = self.netinfo.public_key(proposer)
            payload = canonical.encode(
                ("dhb-kg", self.era, change_canonical, msg_canonical)
            )
            sig_items.append((pk, payload, _sig_or_none(g, sig_bytes)))
        valid = self._verify_signatures(sig_items)

        i = 0
        valid_votes: List[Tuple[Any, SignedVote]] = []
        valid_kg: List[Tuple[Any, Any, Tuple]] = []
        for proposer, vote in votes:
            if not valid[i]:
                step.add_fault(proposer, "dynamic_honey_badger:invalid_vote_signature")
            else:
                valid_votes.append((proposer, vote))
                self.vote_counter.add_committed_vote(vote)
            i += 1
        for proposer, change_canonical, msg_canonical, sig_bytes in kg_msgs:
            if not valid[i]:
                step.add_fault(
                    proposer, "dynamic_honey_badger:invalid_keygen_signature"
                )
            else:
                valid_kg.append((proposer, change_canonical, msg_canonical))
                step.extend(
                    self._handle_committed_kg(proposer, change_canonical, msg_canonical)
                )
            i += 1

        # Prune only against *authenticated* commits: a forged (voter, num)
        # tuple must not censor our real pending vote.
        self._prune_pending(valid_votes, valid_kg)

        # Era-transition decision (identical on every node: all inputs are
        # committed batch contents).
        change_state = ChangeState.none()
        era_completed = False
        if self.key_gen is not None and self.key_gen.keygen.is_ready():
            change_state = ChangeState.complete(self.key_gen.change)
            era_completed = True
        else:
            winner = self.vote_counter.winner()
            if winner is not None:
                if winner.kind == "schedule":
                    change_state = ChangeState.complete(winner)
                    self.encryption_schedule = winner.schedule
                    era_completed = True
                    self.key_gen = None
                elif self.key_gen is None or self.key_gen.change != winner:
                    kg_step = self._start_key_gen(winner)
                    step.extend(kg_step)
                    change_state = ChangeState.in_progress(winner)
                else:
                    change_state = ChangeState.in_progress(self.key_gen.change)
            elif self.key_gen is not None:
                change_state = ChangeState.in_progress(self.key_gen.change)

        batch = DhbBatch(
            era=self.era,
            epoch=hb_batch.epoch,
            contributions=contributions,
            change=change_state,
        )
        step.with_output(batch)
        if era_completed:
            step.extend(self._finish_era())
        return step

    def _verify_signatures(self, items) -> List[bool]:
        checked = []
        for pk, payload, sig in items:
            if pk is None or sig is None:
                checked.append(False)
            else:
                checked.append(None)  # placeholder: batch-verified below
        to_verify = [
            (pk, payload, sig)
            for (pk, payload, sig), c in zip(items, checked)
            if c is None
        ]
        results = iter(self.backend.verify_signatures(to_verify))
        return [c if c is not None else next(results) for c in checked]

    def _prune_pending(self, votes, kg_msgs) -> None:
        """Drop our queued votes/key-gen messages once they commit."""
        committed_votes = {
            (v.voter, v.era, v.num) for _, v in votes
        }
        self._pending_votes = [
            v
            for v in self._pending_votes
            if (v.voter, v.era, v.num) not in committed_votes
        ]
        committed_kg = {
            canonical.encode((c, m))
            for p, c, m in kg_msgs
            if p == self.netinfo.our_id
        }
        self._pending_kg = [
            (c, m, s)
            for c, m, s in self._pending_kg
            if canonical.encode((c, m)) not in committed_kg
        ]

    # -- key generation ------------------------------------------------------

    def _next_pub_keys(self, change: Change) -> Optional[Dict[Any, PublicKey]]:
        cur = self.netinfo.public_key_map()
        if change.kind == "add":
            try:
                pk = PublicKey.from_bytes(self.backend.group, change.pub_key_bytes)
            except (ValueError, TypeError):
                return None
            cur[change.node_id] = pk
            return cur
        if change.kind == "remove":
            if change.node_id not in cur:
                return None
            del cur[change.node_id]
            return cur
        return None

    def _start_key_gen(self, change: Change) -> Step:
        pub_keys = self._next_pub_keys(change)
        if pub_keys is None:
            self.key_gen = None
            return Step()
        threshold = (len(pub_keys) - 1) // 3
        keygen, part = SyncKeyGen.new(
            self.netinfo.our_id,
            self.netinfo.secret_key,
            pub_keys,
            threshold,
            self.rng,
            self.backend.group,
        )
        self.key_gen = _KeyGenState(change, keygen, pub_keys)
        # A previous DKG's queued messages are for a dead session.
        self._pending_kg = []
        if part is not None and self.netinfo.is_validator():
            self._queue_kg(part_to_canonical(part))
        return Step()

    def _queue_kg(self, msg_canonical: Tuple) -> None:
        change_canonical = self.key_gen.change.to_canonical()
        payload = canonical.encode(
            ("dhb-kg", self.era, change_canonical, msg_canonical)
        )
        sig = self.netinfo.secret_key.sign(payload)
        self._pending_kg.append((change_canonical, msg_canonical, sig.to_bytes()))

    def _handle_committed_kg(self, proposer: Any, change_canonical, msg_canonical) -> Step:
        if self.key_gen is None:
            return Step()  # no DKG in progress: stale key-gen traffic
        try:
            change_canonical = (
                tuple(change_canonical)
                if isinstance(change_canonical, list)
                else change_canonical
            )
        except TypeError:
            return Step.from_fault(proposer, "dynamic_honey_badger:malformed_keygen")
        if change_canonical != self.key_gen.change.to_canonical():
            # Signed for a different (superseded) DKG session: ignore.
            return Step()
        kg = self.key_gen.keygen
        try:
            msg_canonical = (
                tuple(msg_canonical)
                if isinstance(msg_canonical, list)
                else msg_canonical
            )
            tag = msg_canonical[0]
            if tag == "part":
                part = part_from_canonical(self.backend.group, msg_canonical)
                outcome = kg.handle_part(proposer, part, self.rng)
                step = Step()
                if outcome.fault:
                    step.add_fault(proposer, outcome.fault)
                if outcome.ack is not None and self.netinfo.is_validator():
                    self._queue_kg(ack_to_canonical(outcome.ack))
                return step
            if tag == "ack":
                outcome = kg.handle_ack(proposer, ack_from_canonical(msg_canonical))
                if outcome.fault:
                    return Step.from_fault(proposer, outcome.fault)
                return Step()
        except (TypeError, ValueError, IndexError):
            pass
        return Step.from_fault(proposer, "dynamic_honey_badger:malformed_keygen")

    # -- era turnover --------------------------------------------------------

    def _finish_era(self) -> Step:
        if self.key_gen is not None:
            pk_set, share = self.key_gen.keygen.generate()
            pub_keys = self.key_gen.pub_keys
        else:
            # Schedule-only change: keys carry over.
            pk_set = self.netinfo.public_key_set
            share = self.netinfo.secret_key_share
            pub_keys = self.netinfo.public_key_map()
        self.netinfo = NetworkInfo(
            our_id=self.netinfo.our_id,
            secret_key_share=share if self.netinfo.our_id in pub_keys else None,
            public_key_set=pk_set,
            secret_key=self.netinfo.secret_key,
            public_keys=pub_keys,
        )
        self.era += 1
        self.key_gen = None
        self.vote_counter = VoteCounter(self.era, self.netinfo.num_nodes())
        self._pending_votes = []
        self._pending_kg = []
        self.hb = self._new_hb()
        step = Step()
        future, self._future_era = self._future_era, []
        for sender_id, message in future:
            step.extend(self.handle_message(sender_id, message))
        return step


def _sig_or_none(group, sig_bytes) -> Optional[Signature]:
    try:
        return Signature.from_bytes(group, sig_bytes)
    except (ValueError, TypeError):
        return None
