"""Signed validator votes for dynamic membership changes.

Rebuild of `src/dynamic_honey_badger/votes.rs` § (SURVEY.md §2.1): each
validator signs `(era, num, change)` with its per-node secret key; votes
ride inside committed contributions so every node counts them in the same
order.  Only a voter's *latest* vote (highest ``num``) counts; a change wins
once more than half of the current validators' latest votes name it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.crypto.keys import Signature
from hbbft_tpu.protocols.change import Change
from hbbft_tpu.utils import canonical


@dataclass(frozen=True, slots=True)
class SignedVote:
    voter: Any
    era: int
    num: int
    change: Change
    sig_bytes: bytes

    def signed_payload(self) -> bytes:
        return canonical.encode(
            ("dhb-vote", self.era, self.num, self.change.to_canonical())
        )

    def to_canonical(self) -> Tuple:
        return (self.voter, self.era, self.num, self.change.to_canonical(), self.sig_bytes)

    @staticmethod
    def from_canonical(t) -> "SignedVote":
        voter, era, num, change_t, sig = t
        hash(voter)  # reject unhashable (list/dict) voter ids: TypeError
        if not isinstance(era, int) or not isinstance(num, int) or not isinstance(sig, bytes):
            raise ValueError("malformed vote")
        return SignedVote(voter, era, num, Change.from_canonical(change_t), sig)


class VoteCounter:
    """Tracks committed votes for one era."""

    def __init__(self, era: int, num_validators: int) -> None:
        self.era = era
        self.num_validators = num_validators
        self._latest: Dict[Any, SignedVote] = {}  # voter -> latest vote

    def add_committed_vote(self, vote: SignedVote) -> None:
        """Record an already-signature-verified committed vote."""
        if vote.era != self.era:
            return
        cur = self._latest.get(vote.voter)
        if cur is None or vote.num > cur.num:
            self._latest[vote.voter] = vote

    def tally(self) -> Dict[Tuple, int]:
        counts: Dict[Tuple, int] = {}
        # lint: allow[determinism] vote counting is commutative; winner() sorts
        for v in self._latest.values():
            key = v.change.to_canonical()
            counts[key] = counts.get(key, 0) + 1
        return counts

    def winner(self) -> Optional[Change]:
        """The change named by a strict majority of validators, if any."""
        for key, count in sorted(self.tally().items(), key=lambda kv: repr(kv)):
            if 2 * count > self.num_validators:
                return Change.from_canonical(key)
        return None
