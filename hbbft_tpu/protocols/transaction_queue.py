"""Transaction queue with random-sample proposals.

Rebuild of `src/transaction_queue.rs` § (SURVEY.md §2.1): a buffer of
pending transactions from which each epoch's proposal is a *random sample* —
randomization decorrelates the N nodes' proposals so the union (the ACS
output) covers more distinct transactions per epoch.

Sampling is O(batch_size)-ish, not O(mempool): alongside the
insertion-ordered dict the queue keeps an append-only index of keys
(``_order``) with lazy tombstones for removed entries.  ``choose`` draws
random *indices* into that list and rejects dead or repeated slots, so a
proposal over a million-entry mempool touches ~``amount`` entries instead
of materializing the whole buffer into a Python list (the pre-traffic
implementation paid O(mempool) per proposal per node per epoch).
Compaction runs when tombstones reach half the index — amortized O(1)
per removal — and the sampled-set distribution stays uniform without
replacement over the live entries (pinned in tests/test_traffic.py).
"""

from __future__ import annotations

from typing import Any, Iterable, List, NamedTuple, Optional

_MISSING = object()  # pop sentinel: a stored None tx is still "present"


class _DeadSlot:
    """Permanently-dead ``_order`` slot (its key relocated to the tail).

    A module-level class rather than a bare ``object()`` so queues
    holding relocated slots stay snapshotable: utils/snapshot.py
    auto-registers this module's classes, and identity is never tested
    against the singleton — dead slots are detected by ``k not in
    _txs``, which holds for any ``_DeadSlot`` instance a decode
    rebuilds."""


_DEAD = _DeadSlot()  # shared sentinel (hash-distinct from every real key)


class RemovalAccount(NamedTuple):
    """Outcome of :meth:`TransactionQueue.remove_multiple`.

    ``removed`` entries were present and dropped; ``absent`` entries were
    not in this queue — for a committed batch that means the transaction
    was committed from *other* nodes' proposals (or was never submitted
    here at all), which the traffic tracker accounts separately from
    local removals instead of the old silent ``pop(..., None)``.
    """

    removed: int = 0
    absent: int = 0

    def merged(self, other) -> "RemovalAccount":
        # ``other`` may be a plain 2-tuple (snapshots decode NamedTuples
        # as tuples — utils/snapshot.py)
        return RemovalAccount(self.removed + other[0], self.absent + other[1])


class TransactionQueue:
    """Default FIFO-set queue (insertion-ordered, deduplicated)."""

    def __init__(self, txs: Iterable[Any] = ()) -> None:
        self._txs: dict = {}  # insertion-ordered set
        self._order: List[Any] = []  # keys in insertion order (+ tombstones)
        self._indexed: dict = {}  # key -> its slot in _order
        self._stale = 0  # dead-slot count inside _order
        self._head = 0  # pop_oldest cursor: everything before it is dead
        for tx in txs:
            self.push(tx)

    def _ensure_index(self) -> None:
        """Rebuild the sampling index when absent — snapshots taken before
        the index existed restore via ``__new__`` + setattr
        (utils/snapshot.py) with only ``_txs`` populated."""
        if "_order" not in self.__dict__:
            self._order = list(self._txs)
            self._indexed = {k: i for i, k in enumerate(self._order)}
            self._stale = 0
            self._head = 0

    def push(self, tx: Any) -> None:
        self._ensure_index()
        k = _key(tx)
        if k not in self._txs:
            self._txs[k] = tx
            slot = self._indexed.get(k)
            if slot is None:
                self._indexed[k] = len(self._order)
                self._order.append(k)
            elif slot >= self._head:
                # a re-pushed tx whose tombstone is ahead of the pop
                # cursor keeps its original slot — a second append would
                # double its sampling weight
                self._stale -= 1
            else:
                # the tombstone sits BEHIND the pop_oldest cursor:
                # reviving it in place would hide a live entry from
                # pop_oldest (the evict_oldest mempool would then exceed
                # its capacity bound on a None pop).  Relocate to the
                # tail — the old slot dies for good (it is already
                # counted stale) and the re-push is FIFO-new.
                self._order[slot] = _DEAD
                self._indexed[k] = len(self._order)
                self._order.append(k)

    def extend(self, txs: Iterable[Any]) -> None:
        for tx in txs:
            self.push(tx)

    def __len__(self) -> int:
        return len(self._txs)

    def __contains__(self, tx: Any) -> bool:
        return _key(tx) in self._txs

    def _compact(self) -> None:
        self._order = [k for k in self._order if k in self._txs]
        self._indexed = {k: i for i, k in enumerate(self._order)}
        self._stale = 0
        self._head = 0

    def choose(self, rng, amount: int) -> List[Any]:
        """Random sample of up to ``amount`` transactions (uniform,
        without replacement, over the live entries)."""
        self._ensure_index()
        n = len(self._txs)
        if n <= amount:
            return list(self._txs.values())
        if self._stale * 2 > len(self._order):
            self._compact()  # amortized against the removals that staled it
        if amount * 3 >= n:
            # dense sample: rejection would thrash; one compacted pass is
            # ~the size of the result set anyway
            self._compact()
            keys = rng.sample(self._order, amount)
            return [self._txs[k] for k in keys]
        order = self._order
        txs = self._txs
        chosen: List[Any] = []
        taken: set = set()
        while len(chosen) < amount:
            i = rng.randrange(len(order))
            if i in taken:
                continue
            k = order[i]
            if k not in txs:
                continue  # tombstone (≤ half the index by construction)
            taken.add(i)
            chosen.append(txs[k])
        return chosen

    def pop_oldest(self) -> Optional[Any]:
        """Remove and return the oldest live transaction (None if empty) —
        the bounded mempool's evict-oldest policy.  Amortized O(1): the
        cursor advances over slots instead of shifting the list; the
        popped slot becomes a tombstone and ordinary compaction reclaims
        the prefix.  No live entry ever sits behind the cursor: a
        re-push whose tombstone is behind it relocates to the tail
        (``push``), so an empty scan really means an empty queue."""
        self._ensure_index()
        order, txs = self._order, self._txs
        while self._head < len(order):
            k = order[self._head]
            self._head += 1
            if k in txs:
                tx = txs.pop(k)
                self._stale += 1  # its slot stays behind the cursor
                if self._stale * 2 > len(order):
                    self._compact()
                return tx
        return None

    def remove_multiple(self, txs: Iterable[Any]) -> RemovalAccount:
        """Drop committed transactions; returns per-call accounting so
        callers can distinguish locally-removed from committed-elsewhere
        (``absent``: the entry was never in this queue)."""
        self._ensure_index()
        removed = absent = 0
        for tx in txs:
            if self._txs.pop(_key(tx), _MISSING) is not _MISSING:
                removed += 1
                self._stale += 1
            else:
                absent += 1
        return RemovalAccount(removed, absent)


def _key(tx: Any):
    """Hashable identity for a transaction (lists/dicts via canonical bytes)."""
    try:
        hash(tx)
        return tx
    except TypeError:
        from hbbft_tpu.utils import canonical

        return canonical.encode(tx)
