"""Transaction queue with random-sample proposals.

Rebuild of `src/transaction_queue.rs` § (SURVEY.md §2.1): a buffer of
pending transactions from which each epoch's proposal is a *random sample* —
randomization decorrelates the N nodes' proposals so the union (the ACS
output) covers more distinct transactions per epoch.
"""

from __future__ import annotations

from typing import Any, Iterable, List


class TransactionQueue:
    """Default FIFO-set queue (insertion-ordered, deduplicated)."""

    def __init__(self, txs: Iterable[Any] = ()) -> None:
        self._txs: dict = {}  # insertion-ordered set
        for tx in txs:
            self.push(tx)

    def push(self, tx: Any) -> None:
        self._txs.setdefault(_key(tx), tx)

    def extend(self, txs: Iterable[Any]) -> None:
        for tx in txs:
            self.push(tx)

    def __len__(self) -> int:
        return len(self._txs)

    def __contains__(self, tx: Any) -> bool:
        return _key(tx) in self._txs

    def choose(self, rng, amount: int) -> List[Any]:
        """Random sample of up to ``amount`` transactions."""
        items = list(self._txs.values())
        if len(items) <= amount:
            return items
        return rng.sample(items, amount)

    def remove_multiple(self, txs: Iterable[Any]) -> None:
        for tx in txs:
            self._txs.pop(_key(tx), None)


def _key(tx: Any):
    """Hashable identity for a transaction (lists/dicts via canonical bytes)."""
    try:
        hash(tx)
        return tx
    except TypeError:
        from hbbft_tpu.utils import canonical

        return canonical.encode(tx)
