"""SyncKeyGen — synchronous-round distributed key generation (no dealer).

Rebuild of `src/sync_key_gen.rs` § (SURVEY.md §2.1): Pedersen-style DKG over
symmetric bivariate polynomials.  Each proposer p commits to a random
symmetric bivariate polynomial f_p of degree t and sends node j its row
f_p(j+1, ·) encrypted; each receiver verifies its row against the public
commitment and broadcasts an Ack carrying, for every node k, the encrypted
value f_p(j+1, k+1).  By symmetry node k can cross-check each value against
the commitment and, once a part has 2t+1 Acks ("complete"), interpolate its
secret share f_p(k+1, 0) from any t+1 of them.  Summing over the first t+1
complete parts yields the master `PublicKeySet` and per-node
`SecretKeyShare`s — no party ever knows the master secret.

SyncKeyGen is *transport-agnostic* (it emits no network messages itself):
DynamicHoneyBadger commits `Part`/`Ack` messages inside batches so that all
nodes process them in the same order (SURVEY.md §3.4).  Thresholds follow
the reference: part complete at > 2t Acks, ready at > t complete parts
*(uncertain in reference — SURVEY.md marks these for verification)*.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.crypto.group import Group
from hbbft_tpu.crypto.keys import (
    Ciphertext,
    PublicKey,
    PublicKeySet,
    SecretKey,
    SecretKeyShare,
)
from hbbft_tpu.crypto.poly import BivarCommitment, BivarPoly, Commitment, Poly
from hbbft_tpu.utils import canonical


@dataclass(frozen=True, slots=True)
class Part:
    """A proposer's commitment + per-node encrypted rows."""

    commit: BivarCommitment
    rows: Tuple[bytes, ...]  # rows[j] encrypts Poly f(j+1, ·) to node j

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Part)
            and self.commit == other.commit
            and self.rows == other.rows
        )


@dataclass(frozen=True, slots=True)
class Ack:
    """An acker's per-node encrypted values for one proposer's part."""

    proposer_idx: int
    values: Tuple[bytes, ...]  # values[k] encrypts f(acker+1, k+1) to node k


def part_to_canonical(part: Part) -> Tuple:
    """Stable tuple form for signing / wire transport inside contributions."""
    return ("part", part.commit.to_bytes(), list(part.rows))


def part_from_canonical(group: Group, t) -> Part:
    tag, commit_bytes, rows = t
    if tag != "part":
        raise ValueError("not a part")
    return Part(BivarCommitment.from_bytes(group, commit_bytes), tuple(rows))


def ack_to_canonical(ack: Ack) -> Tuple:
    return ("ack", ack.proposer_idx, list(ack.values))


def ack_from_canonical(t) -> Ack:
    tag, proposer_idx, values = t
    if tag != "ack":
        raise ValueError("not an ack")
    return Ack(proposer_idx, tuple(values))


@dataclass(slots=True)
class PartOutcome:
    ack: Optional[Ack] = None
    fault: Optional[str] = None


@dataclass(slots=True)
class AckOutcome:
    fault: Optional[str] = None


class _ProposalState:
    #: lazy Commitment to f(·, our_idx+1); CLASS-level default so snapshots
    #: taken before this cache existed restore cleanly (snapshot.py rebuilds
    #: via __new__ + setattr of saved attributes only)
    our_col = None

    def __init__(self, commit: BivarCommitment) -> None:
        self.commit = commit
        self.acks: set = set()  # acker indices
        self.values: Dict[int, int] = {}  # acker_idx -> our decrypted value

    def is_complete(self, threshold: int) -> bool:
        return len(self.acks) > 2 * threshold


class SyncKeyGen:
    """One node's view of a running DKG session.

    Construct with :meth:`new` (mirrors the reference's
    ``SyncKeyGen::new → (SyncKeyGen, Option<Part>)``).
    """

    def __init__(
        self,
        our_id: Any,
        secret_key: SecretKey,
        pub_keys: Dict[Any, PublicKey],
        threshold: int,
        group: Group,
    ) -> None:
        self.our_id = our_id
        self.secret_key = secret_key
        self.pub_keys = dict(pub_keys)
        self.threshold = threshold
        self.G = group
        self.ids: List[Any] = sorted(pub_keys.keys())
        self.index: Dict[Any, int] = {n: i for i, n in enumerate(self.ids)}
        self.parts: Dict[int, _ProposalState] = {}
        self._early_acks: Dict[int, List[Tuple[Any, Ack]]] = {}

    @staticmethod
    def new(
        our_id: Any,
        secret_key: SecretKey,
        pub_keys: Dict[Any, PublicKey],
        threshold: int,
        rng,
        group: Group,
    ) -> Tuple["SyncKeyGen", Optional[Part]]:
        kg = SyncKeyGen(our_id, secret_key, pub_keys, threshold, group)
        if our_id not in kg.index:
            return kg, None  # observers don't propose
        bivar = BivarPoly.random(group, threshold, rng)
        commit = bivar.commitment()
        rows = []
        for j, node in enumerate(kg.ids):
            row = bivar.row(j + 1)
            payload = canonical.encode([c for c in row.coeffs])
            rows.append(pub_keys[node].encrypt(payload, rng).to_bytes())
        return kg, Part(commit, tuple(rows))

    # -- our index helpers ---------------------------------------------------

    def our_idx(self) -> Optional[int]:
        return self.index.get(self.our_id)

    def is_node_ready(self, proposer_id: Any) -> bool:
        idx = self.index.get(proposer_id)
        return idx is not None and idx in self.parts and self.parts[idx].is_complete(
            self.threshold
        )

    def count_complete(self) -> int:
        return sum(1 for ps in self.parts.values() if ps.is_complete(self.threshold))

    def is_ready(self) -> bool:
        return self.count_complete() > self.threshold

    # -- Part ----------------------------------------------------------------

    def handle_part(self, sender_id: Any, part: Part, rng) -> PartOutcome:
        sender_idx = self.index.get(sender_id)
        if sender_idx is None:
            return PartOutcome(fault="sync_key_gen:part_from_non_member")
        if not isinstance(part, Part) or not isinstance(part.commit, BivarCommitment):
            return PartOutcome(fault="sync_key_gen:malformed_part")
        if sender_idx in self.parts:
            if self.parts[sender_idx].commit == part.commit:
                return PartOutcome()  # duplicate
            return PartOutcome(fault="sync_key_gen:multiple_parts")
        if part.commit.degree() != self.threshold or len(part.rows) != len(self.ids):
            return PartOutcome(fault="sync_key_gen:invalid_part_degree")
        state = _ProposalState(part.commit)
        self.parts[sender_idx] = state
        # Drain acks that raced ahead of this part.
        for acker_id, ack in self._early_acks.pop(sender_idx, []):
            self._apply_ack(acker_id, ack)

        our_idx = self.our_idx()
        if our_idx is None:
            return PartOutcome()  # observer: record the commitment only
        # Decrypt and verify our row.
        try:
            ct = Ciphertext.from_bytes(self.G, part.rows[our_idx])
            payload = self.secret_key.decrypt(ct)
            coeffs = canonical.decode(payload) if payload is not None else None
            if not isinstance(coeffs, list) or not all(
                isinstance(c, int) for c in coeffs
            ):
                raise ValueError
            row = Poly(self.G, coeffs)
        except (ValueError, IndexError, TypeError):
            return PartOutcome(fault="sync_key_gen:invalid_row_encryption")
        if row.degree() != self.threshold or row.commitment() != part.commit.row(
            our_idx + 1
        ):
            return PartOutcome(fault="sync_key_gen:row_commitment_mismatch")
        # Build our Ack: encrypt row(k+1) to each node k.
        values = []
        for k, node in enumerate(self.ids):
            v = row.evaluate(k + 1)
            values.append(
                self.pub_keys[node].encrypt(canonical.encode(v), rng).to_bytes()
            )
        return PartOutcome(ack=Ack(sender_idx, tuple(values)))

    # -- Ack -----------------------------------------------------------------

    def handle_ack(self, sender_id: Any, ack: Ack) -> AckOutcome:
        acker_idx = self.index.get(sender_id)
        if acker_idx is None:
            return AckOutcome(fault="sync_key_gen:ack_from_non_member")
        if (
            not isinstance(ack, Ack)
            or not isinstance(ack.proposer_idx, int)
            or not 0 <= ack.proposer_idx < len(self.ids)
            or len(ack.values) != len(self.ids)
        ):
            return AckOutcome(fault="sync_key_gen:malformed_ack")
        if ack.proposer_idx not in self.parts:
            # The part may be committed later in the same batch: buffer.
            self._early_acks.setdefault(ack.proposer_idx, []).append(
                (sender_id, ack)
            )
            return AckOutcome()
        return self._apply_ack(sender_id, ack)

    def _apply_ack(self, sender_id: Any, ack: Ack) -> AckOutcome:
        acker_idx = self.index[sender_id]
        state = self.parts[ack.proposer_idx]
        if acker_idx in state.acks:
            return AckOutcome()  # duplicate
        our_idx = self.our_idx()
        if our_idx is not None:
            try:
                ct = Ciphertext.from_bytes(self.G, ack.values[our_idx])
                payload = self.secret_key.decrypt(ct)
                v = canonical.decode(payload) if payload is not None else None
                if not isinstance(v, int):
                    raise ValueError
            except (ValueError, IndexError, TypeError):
                return AckOutcome(fault="sync_key_gen:invalid_ack_encryption")
            # Cross-check against the commitment:
            # f_p(acker+1, our+1) · G1 == commit(acker+1, our+1).
            # The receiver coordinate is fixed for every ack of this part,
            # so the column commitment is computed once and each ack costs
            # one univariate evaluation (t+1 ops, not (t+1)²).
            if state.our_col is None:
                state.our_col = state.commit.col(our_idx + 1)
            expect = state.our_col.evaluate(acker_idx + 1)
            if self.G.g1_mul(v, self.G.g1()) != expect:
                return AckOutcome(fault="sync_key_gen:ack_value_mismatch")
            state.values[acker_idx] = v
        state.acks.add(acker_idx)
        return AckOutcome()

    # -- output --------------------------------------------------------------

    def generate(self) -> Tuple[PublicKeySet, Optional[SecretKeyShare]]:
        """Produce the master public key set and (for members) our share.

        Uses the first t+1 *complete* parts in proposer-index order — the
        same deterministic choice on every node.
        """
        if not self.is_ready():
            raise ValueError("key generation not complete")
        complete = sorted(
            idx
            for idx, ps in self.parts.items()
            if ps.is_complete(self.threshold)
        )[: self.threshold + 1]
        # Master commitment: Σ_p commit_p.row(0).
        master_commit: Optional[Commitment] = None
        for idx in complete:
            row0 = self.parts[idx].commit.row(0)
            master_commit = row0 if master_commit is None else master_commit.add(row0)
        pk_set = PublicKeySet(master_commit)

        our_idx = self.our_idx()
        if our_idx is None:
            return pk_set, None
        from hbbft_tpu.crypto.field import interpolate_at_zero

        share_val = 0
        for idx in complete:
            ps = self.parts[idx]
            pts = sorted(ps.values.items())[: self.threshold + 1]
            if len(pts) <= self.threshold:
                raise ValueError(
                    f"not enough verified ack values for part {idx}"
                )
            share_val = (
                share_val
                + interpolate_at_zero([(a + 1, v) for a, v in pts], self.G.r)
            ) % self.G.r
        return pk_set, SecretKeyShare(self.G, share_val)
