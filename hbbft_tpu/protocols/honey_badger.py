"""HoneyBadger — the core atomic-broadcast epoch loop.

Rebuild of `src/honey_badger/` § (SURVEY.md §2.1): each epoch, every node
threshold-encrypts its contribution, the nodes run ACS (Subset) over the
ciphertexts, then threshold-decrypt the accepted ones; the epoch's output is
a `Batch` mapping proposer → contribution.  Encrypting *before* agreement
and decrypting *after* is what defeats transaction censorship — the
adversary commits to the subset before seeing any plaintext.

TPU-first deltas:
* Ciphertext validity checks and decryption-share verifications are deferred
  device work (O(N²) pairings/epoch at N=100 — SURVEY.md §3.2); HoneyBadger
  owns ciphertext-validity policy and only hands *pre-validated* ciphertexts
  to ThresholdDecrypt.
* `EncryptionSchedule` (Always / Never / EveryNth / TickTock) mirrors the
  reference's knob for trading censorship resistance against crypto load.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import CryptoWork, Step, absorb_child_step
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.crypto.keys import Ciphertext, CryptoError
from hbbft_tpu.obs import critpath as _critpath
from hbbft_tpu.protocols.subset import Subset, SubsetOutput
from hbbft_tpu.protocols.threshold_decrypt import (
    ThresholdDecrypt,
    ThresholdDecryptMessage,
)
from hbbft_tpu.utils import canonical


# ---------------------------------------------------------------------------
# Encryption schedule (reference `EncryptionSchedule` §, uncertain vintage)
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class EncryptionSchedule:
    """When to threshold-encrypt contributions.

    kind ∈ {"always", "never", "every_nth", "tick_tock"}; ``every_nth``
    encrypts epochs ≡ 0 (mod n); ``tick_tock(on, off)`` encrypts ``on``
    epochs then skips ``off``.
    """

    kind: str = "always"
    n: int = 1
    m: int = 0

    @staticmethod
    def always() -> "EncryptionSchedule":
        return EncryptionSchedule("always")

    @staticmethod
    def never() -> "EncryptionSchedule":
        return EncryptionSchedule("never")

    @staticmethod
    def every_nth(n: int) -> "EncryptionSchedule":
        return EncryptionSchedule("every_nth", n=n)

    @staticmethod
    def tick_tock(on: int, off: int) -> "EncryptionSchedule":
        return EncryptionSchedule("tick_tock", n=on, m=off)

    def encrypt_in_epoch(self, epoch: int) -> bool:
        if self.kind == "always":
            return True
        if self.kind == "never":
            return False
        if self.kind == "every_nth":
            return epoch % max(self.n, 1) == 0
        period = max(self.n + self.m, 1)
        return epoch % period < self.n


# ---------------------------------------------------------------------------
# Batch — one epoch's agreed output (reference `Batch` §)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Batch:
    epoch: int
    contributions: Dict[Any, Any]

    def iter_all(self) -> List[Tuple[Any, Any]]:
        return sorted(self.contributions.items(), key=lambda kv: repr(kv[0]))

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Batch)
            and self.epoch == other.epoch
            and self.contributions == other.contributions
        )


@dataclass(frozen=True, slots=True)
class HbMessage:
    """kind ∈ {"subset", "dec_share"}; epoch-tagged envelope."""

    epoch: int
    kind: str
    proposer: Any  # only for dec_share
    payload: Any

    @staticmethod
    def subset(epoch: int, msg) -> "HbMessage":
        return HbMessage(epoch, "subset", None, msg)

    @staticmethod
    def dec_share(epoch: int, proposer, msg) -> "HbMessage":
        return HbMessage(epoch, "dec_share", proposer, msg)


class _EpochState:
    """Per-epoch Subset + per-proposer ThresholdDecrypt map
    (reference `epoch_state.rs` §)."""

    def __init__(self, subset: Subset, encrypted: bool) -> None:
        self.subset = subset
        self.encrypted = encrypted
        self.decrypt: Dict[Any, ThresholdDecrypt] = {}
        self.accepted: Dict[Any, bytes] = {}  # proposer -> raw subset payload
        self.decrypted: Dict[Any, Any] = {}  # proposer -> contribution
        self.skipped: set = set()  # proposers with invalid payloads
        self.subset_done = False
        self.batch_emitted = False


class HoneyBadgerBuilder:
    """Builder mirroring the reference `HoneyBadgerBuilder` §."""

    def __init__(self, netinfo: NetworkInfo, backend: CryptoBackend) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self._max_future_epochs = 3
        self._encryption_schedule = EncryptionSchedule.always()
        self._session_id = b"hb"

    def max_future_epochs(self, n: int) -> "HoneyBadgerBuilder":
        self._max_future_epochs = n
        return self

    def encryption_schedule(self, s: EncryptionSchedule) -> "HoneyBadgerBuilder":
        self._encryption_schedule = s
        return self

    def session_id(self, sid: bytes) -> "HoneyBadgerBuilder":
        self._session_id = sid
        return self

    def build(self) -> "HoneyBadger":
        return HoneyBadger(
            self.netinfo,
            self.backend,
            session_id=self._session_id,
            max_future_epochs=self._max_future_epochs,
            encryption_schedule=self._encryption_schedule,
        )


class HoneyBadger(ConsensusProtocol):
    """Epochs of threshold-encrypted contributions; outputs `Batch`es."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        backend: CryptoBackend,
        session_id: bytes = b"hb",
        max_future_epochs: int = 3,
        encryption_schedule: EncryptionSchedule = EncryptionSchedule.always(),
    ) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.session_id = session_id
        self.max_future_epochs = max_future_epochs
        self.encryption_schedule = encryption_schedule
        self.epoch = 0
        self.has_input = False  # proposed in the *current* epoch
        self._epoch_state = self._new_epoch_state(0)
        self._future: Dict[int, List[Tuple[Any, HbMessage]]] = {}

    @staticmethod
    def builder(netinfo, backend) -> HoneyBadgerBuilder:
        return HoneyBadgerBuilder(netinfo, backend)

    def _new_epoch_state(self, epoch: int) -> _EpochState:
        sid = canonical.encode(("hb-subset", self.session_id, epoch))
        return _EpochState(
            Subset(self.netinfo, self.backend, session_id=sid),
            encrypted=self.encryption_schedule.encrypt_in_epoch(epoch),
        )

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.netinfo.our_id

    def terminated(self) -> bool:
        return False  # runs forever; the embedder stops driving it

    def handle_input(self, input: Any, rng=None) -> Step:
        return self.propose(input, rng)

    def propose(self, contribution: Any, rng) -> Step:
        """Propose our contribution for the current epoch."""
        if self.has_input:
            return Step()
        self.has_input = True
        if not self.netinfo.is_validator():
            return Step()
        payload = canonical.encode(contribution)
        if self._epoch_state.encrypted:
            if rng is None:
                raise ValueError("encrypting proposal requires an rng")
            ct = self.netinfo.public_key_set.encrypt(payload, rng)
            payload = ct.to_bytes()
        return self._wrap_subset(
            self.epoch, self._epoch_state.subset.propose(payload)
        )

    def handle_message(self, sender_id: Any, message: HbMessage, rng=None) -> Step:
        if not isinstance(message, HbMessage) or not isinstance(message.epoch, int):
            return Step.from_fault(sender_id, "honey_badger:malformed_message")
        e = message.epoch
        if e < self.epoch:
            return Step()  # obsolete epoch
        if e > self.epoch + self.max_future_epochs:
            return Step.from_fault(sender_id, "honey_badger:epoch_too_far_ahead")
        if e > self.epoch:
            if not self.netinfo.is_node_validator(sender_id):
                # Only validators may grow the future-epoch buffer: anyone
                # else could inflate it without bound (memory DoS).
                return Step.from_fault(
                    sender_id, "honey_badger:future_epoch_from_non_validator"
                )
            self._future.setdefault(e, []).append((sender_id, message))
            return Step()
        return self._handle_current(sender_id, message)

    def _handle_current(self, sender_id: Any, message: HbMessage) -> Step:
        es = self._epoch_state
        if message.kind == "subset":
            return self._wrap_subset(
                self.epoch, es.subset.handle_message(sender_id, message.payload)
            )
        if message.kind == "dec_share":
            if not es.encrypted:
                return Step.from_fault(
                    sender_id, "honey_badger:dec_share_in_plaintext_epoch"
                )
            if not self.netinfo.is_node_validator(message.proposer):
                # Unknown proposer id: would otherwise grow unbounded
                # ThresholdDecrypt state within the epoch.
                return Step.from_fault(
                    sender_id, "honey_badger:dec_share_unknown_proposer"
                )
            td = self._get_decrypt(message.proposer)
            return self._wrap_decrypt(
                self.epoch,
                message.proposer,
                td.handle_message(sender_id, message.payload),
            )
        return Step.from_fault(sender_id, "honey_badger:unknown_kind")

    # -- subset wiring -------------------------------------------------------

    def _wrap_subset(self, epoch: int, child_step: Step) -> Step:
        return absorb_child_step(
            child_step,
            wrap_msg=lambda m, _e=epoch: HbMessage.subset(_e, m),
            on_output=lambda out, _e=epoch: self._on_subset_output(_e, out),
        )

    def _on_subset_output(self, epoch: int, out: SubsetOutput) -> Step:
        if epoch != self.epoch:
            return Step()  # late re-entry from a completed epoch
        es = self._epoch_state
        if out.kind == "done":
            es.subset_done = True
            return self._try_emit_batch()
        proposer, payload = out.proposer, out.value
        es.accepted[proposer] = payload
        if not es.encrypted:
            return self._on_plaintext(epoch, proposer, payload)
        # Parse + validate the ciphertext, then decrypt.
        try:
            ct = Ciphertext.from_bytes(self.backend.group, payload)
        except (CryptoError, ValueError, IndexError):
            return self._skip_proposer(proposer, "honey_badger:unparseable_ciphertext")

        def on_valid(ok: bool, _e=epoch, _p=proposer, _ct=ct) -> Step:
            if _e != self.epoch:
                return Step()
            if not ok:
                return self._skip_proposer(_p, "honey_badger:invalid_ciphertext")
            td = self._get_decrypt(_p)
            step = self._wrap_decrypt(_e, _p, td.set_ciphertext(_ct, pre_validated=True))
            return step.extend(self._wrap_decrypt(_e, _p, td.start_decryption()))

        return Step().defer(CryptoWork("verify_ciphertext", ct, on_valid))

    def _on_plaintext(self, epoch: int, proposer: Any, payload: bytes) -> Step:
        es = self._epoch_state
        try:
            contribution = canonical.decode(payload)
        except (ValueError, IndexError):
            return self._skip_proposer(proposer, "honey_badger:invalid_contribution")
        es.decrypted[proposer] = contribution
        return self._try_emit_batch()

    def _skip_proposer(self, proposer: Any, fault_kind: str) -> Step:
        self._epoch_state.skipped.add(proposer)
        step = Step.from_fault(proposer, fault_kind)
        return step.extend(self._try_emit_batch())

    # -- decryption wiring ---------------------------------------------------

    def _get_decrypt(self, proposer: Any) -> ThresholdDecrypt:
        es = self._epoch_state
        if proposer not in es.decrypt:
            es.decrypt[proposer] = ThresholdDecrypt(self.netinfo, self.backend)
        return es.decrypt[proposer]

    def _wrap_decrypt(self, epoch: int, proposer: Any, child_step: Step) -> Step:
        return absorb_child_step(
            child_step,
            wrap_msg=lambda m, _e=epoch, _p=proposer: HbMessage.dec_share(_e, _p, m),
            on_output=lambda pt, _e=epoch, _p=proposer: self._on_decrypted(_e, _p, pt),
        )

    def _on_decrypted(self, epoch: int, proposer: Any, plaintext: bytes) -> Step:
        if epoch != self.epoch:
            return Step()
        es = self._epoch_state
        try:
            contribution = canonical.decode(plaintext)
        except (ValueError, IndexError):
            return self._skip_proposer(proposer, "honey_badger:invalid_contribution")
        es.decrypted[proposer] = contribution
        _critpath.stamp(
            "decrypt.combine",
            node=self.netinfo.our_id,
            instance=self.netinfo.node_index(proposer),
            epoch=epoch,
        )
        return self._try_emit_batch()

    # -- epoch completion ----------------------------------------------------

    def _try_emit_batch(self) -> Step:
        es = self._epoch_state
        if es.batch_emitted or not es.subset_done:
            return Step()
        pending = [
            p
            for p in es.accepted
            if p not in es.decrypted and p not in es.skipped
        ]
        if pending:
            return Step()
        es.batch_emitted = True
        _critpath.stamp("epoch.commit", node=self.netinfo.our_id, epoch=self.epoch)
        batch = Batch(epoch=self.epoch, contributions=dict(es.decrypted))
        step = Step.from_output(batch)
        return step.extend(self._advance_epoch())

    def _advance_epoch(self) -> Step:
        self.epoch += 1
        self.has_input = False
        self._epoch_state = self._new_epoch_state(self.epoch)
        step = Step()
        for sender_id, message in self._future.pop(self.epoch, []):
            step.extend(self.handle_message(sender_id, message))
        return step
