"""Subset — Asynchronous Common Subset (ACS).

Rebuild of `src/subset/{subset,proposal_state}.rs` § (SURVEY.md §2.1): N
parallel Broadcast instances (one per proposer) + N BinaryAgreement instances
decide which proposals make it into the common subset.  All correct nodes
output the same set of ≥ N−f contributions.

Rules (HoneyBadgerBFT paper / reference):
* our input → our Broadcast.
* Broadcast_p delivers → input ``true`` to BA_p (if it has no input yet).
* once N−f BAs have decided ``true`` → input ``false`` to every BA without
  input yet.
* emit ``SubsetOutput.contribution(p, value)`` for every p with BA_p = true
  as soon as both the decision and the broadcast value are known; emit
  ``SubsetOutput.done()`` when all BAs have decided and every accepted
  broadcast has delivered.

This is pure composition — all crypto lives in the children and surfaces
through the shared deferred-work path, so one epoch's N broadcasts + N
agreements batch their device work together (the inter-instance parallelism
of SURVEY.md §2.3).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import Step, absorb_child_step
from hbbft_tpu.crypto.backend import CryptoBackend
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.utils.canonical import encode as canonical_encode


@dataclass(frozen=True, slots=True)
class SubsetMessage:
    """kind ∈ {"broadcast", "agreement"}; routed to the child for ``proposer``."""

    proposer: Any
    kind: str
    payload: Any


@dataclass(frozen=True, slots=True)
class SubsetOutput:
    """Either one accepted contribution or the final Done marker."""

    kind: str  # "contribution" | "done"
    proposer: Any = None
    value: Optional[bytes] = None

    @staticmethod
    def contribution(proposer, value: bytes) -> "SubsetOutput":
        return SubsetOutput("contribution", proposer, value)

    @staticmethod
    def done() -> "SubsetOutput":
        return SubsetOutput("done")


class _ProposalState:
    """Per-proposer pair of child instances + delivery bookkeeping
    (reference `proposal_state.rs` §)."""

    def __init__(self, broadcast: Broadcast, agreement: BinaryAgreement) -> None:
        self.broadcast = broadcast
        self.agreement = agreement
        self.value: Optional[bytes] = None
        self.decision: Optional[bool] = None
        self.ba_has_input = False
        self.emitted = False


class Subset(ConsensusProtocol):
    def __init__(
        self,
        netinfo: NetworkInfo,
        backend: CryptoBackend,
        session_id: bytes,
    ) -> None:
        self.netinfo = netinfo
        self.backend = backend
        self.session_id = session_id
        self.proposals: Dict[Any, _ProposalState] = {}
        for p in netinfo.all_ids():
            ba_session = canonical_encode(
                ("subset-ba", session_id, netinfo.node_index(p))
            )
            self.proposals[p] = _ProposalState(
                Broadcast(netinfo, proposer_id=p),
                BinaryAgreement(
                    netinfo,
                    backend,
                    session_id=ba_session,
                    instance=netinfo.node_index(p),
                ),
            )
        self._false_inputs_sent = False
        self._done = False

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.netinfo.our_id

    def terminated(self) -> bool:
        return self._done

    def count_accepted(self) -> int:
        return sum(1 for ps in self.proposals.values() if ps.decision is True)

    def handle_input(self, input: bytes, rng=None) -> Step:
        return self.propose(input)

    def propose(self, value: bytes) -> Step:
        ps = self.proposals[self.netinfo.our_id]
        return self._wrap_broadcast(
            self.netinfo.our_id, ps.broadcast.broadcast(bytes(value))
        )

    def handle_message(self, sender_id: Any, message: SubsetMessage, rng=None) -> Step:
        if not isinstance(message, SubsetMessage):
            return Step.from_fault(sender_id, "subset:malformed_message")
        ps = self.proposals.get(message.proposer)
        if ps is None:
            return Step.from_fault(sender_id, "subset:unknown_proposer")
        if message.kind == "broadcast":
            return self._wrap_broadcast(
                message.proposer, ps.broadcast.handle_message(sender_id, message.payload)
            )
        if message.kind == "agreement":
            return self._wrap_agreement(
                message.proposer, ps.agreement.handle_message(sender_id, message.payload)
            )
        return Step.from_fault(sender_id, "subset:unknown_kind")

    # -- child wiring --------------------------------------------------------

    def _wrap_broadcast(self, proposer, child_step: Step) -> Step:
        return absorb_child_step(
            child_step,
            wrap_msg=lambda m, _p=proposer: SubsetMessage(_p, "broadcast", m),
            on_output=lambda value, _p=proposer: self._on_broadcast_output(_p, value),
        )

    def _wrap_agreement(self, proposer, child_step: Step) -> Step:
        return absorb_child_step(
            child_step,
            wrap_msg=lambda m, _p=proposer: SubsetMessage(_p, "agreement", m),
            on_output=lambda decision, _p=proposer: self._on_ba_output(_p, decision),
        )

    def _on_broadcast_output(self, proposer, value: bytes) -> Step:
        ps = self.proposals[proposer]
        if ps.value is not None:
            return Step()
        ps.value = value
        step = Step()
        if not ps.ba_has_input and ps.decision is None:
            ps.ba_has_input = True
            step.extend(self._wrap_agreement(proposer, ps.agreement.propose(True)))
        return step.extend(self._progress())

    def _on_ba_output(self, proposer, decision: bool) -> Step:
        ps = self.proposals[proposer]
        if ps.decision is not None:
            return Step()
        ps.decision = decision
        step = Step()
        if (
            not self._false_inputs_sent
            and self.count_accepted() >= self.netinfo.num_correct()
        ):
            # Quorum of accepted proposals: vote false everywhere else so the
            # epoch terminates.
            self._false_inputs_sent = True
            for p in self.netinfo.all_ids():
                other = self.proposals[p]
                if not other.ba_has_input and other.decision is None:
                    other.ba_has_input = True
                    step.extend(
                        self._wrap_agreement(p, other.agreement.propose(False))
                    )
        return step.extend(self._progress())

    # -- output --------------------------------------------------------------

    def _progress(self) -> Step:
        if self._done:
            return Step()
        step = Step()
        for p in self.netinfo.all_ids():
            ps = self.proposals[p]
            if ps.decision is True and ps.value is not None and not ps.emitted:
                ps.emitted = True
                step.output.append(SubsetOutput.contribution(p, ps.value))
        all_decided = all(ps.decision is not None for ps in self.proposals.values())
        all_delivered = all(
            ps.value is not None
            for ps in self.proposals.values()
            if ps.decision is True
        )
        if all_decided and all_delivered:
            self._done = True
            step.output.append(SubsetOutput.done())
        return step
