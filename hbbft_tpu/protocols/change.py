"""Validator-set change requests and their lifecycle state.

Rebuild of `src/dynamic_honey_badger/change.rs` § (SURVEY.md §2.1):
`Change` is what validators vote on — add a node (with its public key),
remove a node, or alter the encryption schedule.  `ChangeState` is what a
`Batch` reports: no change pending, a winning change whose DKG is in
progress, or a change that completed (the era just restarted).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

from hbbft_tpu.protocols.honey_badger import EncryptionSchedule


@dataclass(frozen=True, slots=True)
class Change:
    """kind ∈ {"add", "remove", "schedule"}."""

    kind: str
    node_id: Any = None
    pub_key_bytes: Optional[bytes] = None  # for "add"
    schedule: Optional[EncryptionSchedule] = None  # for "schedule"

    @staticmethod
    def add(node_id, pub_key_bytes: bytes) -> "Change":
        return Change("add", node_id=node_id, pub_key_bytes=pub_key_bytes)

    @staticmethod
    def remove(node_id) -> "Change":
        return Change("remove", node_id=node_id)

    @staticmethod
    def set_schedule(schedule: EncryptionSchedule) -> "Change":
        return Change("schedule", schedule=schedule)

    def to_canonical(self) -> Tuple:
        """Stable tuple used in vote signatures and wire encoding."""
        if self.kind == "schedule":
            s = self.schedule
            return ("schedule", s.kind, s.n, s.m)
        return (self.kind, self.node_id, self.pub_key_bytes)

    @staticmethod
    def from_canonical(t) -> "Change":
        if not isinstance(t, tuple) or not t:
            raise ValueError("malformed change")
        if t[0] == "schedule":
            _, kind, n, m = t
            if kind not in ("always", "never", "every_nth", "tick_tock") or not (
                isinstance(n, int) and isinstance(m, int)
            ):
                raise ValueError("malformed schedule change")
            return Change.set_schedule(EncryptionSchedule(kind, n, m))
        if t[0] in ("add", "remove"):
            node_id = t[1]
            hash(node_id)  # reject unhashable node ids: TypeError
            if t[0] == "add":
                if not isinstance(t[2], bytes):
                    raise ValueError("add change requires a public key")
                return Change.add(node_id, t[2])
            return Change.remove(node_id)
        raise ValueError(f"unknown change kind {t[0]!r}")


@dataclass(frozen=True, slots=True)
class ChangeState:
    """kind ∈ {"none", "in_progress", "complete"}."""

    kind: str
    change: Optional[Change] = None

    @staticmethod
    def none() -> "ChangeState":
        return ChangeState("none")

    @staticmethod
    def in_progress(change: Change) -> "ChangeState":
        return ChangeState("in_progress", change)

    @staticmethod
    def complete(change: Change) -> "ChangeState":
        return ChangeState("complete", change)
