"""Synchronized Binary-Value broadcast — BinaryAgreement's inner gadget.

Rebuild of `src/binary_agreement/sbv_broadcast.rs` § (SURVEY.md §2.1),
implementing the BV-broadcast + AUX phase of Mostéfaoui–Moumen–Raynal
(PODC 2014):

* ``BVal(b)``: on input b, multicast BVal(b).  On receiving BVal(b) from f+1
  distinct nodes, multicast BVal(b) too (if not already).  On 2f+1 distinct
  BVal(b), add b to ``bin_values``.
* ``Aux(b)``: on ``bin_values`` becoming non-empty, multicast Aux(b) for the
  first such b.  Output fires once ≥ N−f nodes sent Aux values that are all
  in ``bin_values``: the output is the set of those values.

Pure counting logic — no crypto.  One instance per BA round.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.types import Step, Target, TargetedMessage
from hbbft_tpu.protocols.bool_set import BoolMultimap, BoolSet


@dataclass(frozen=True, slots=True)
class SbvMessage:
    kind: str  # "bval" | "aux"
    value: bool

    @staticmethod
    def bval(b: bool) -> "SbvMessage":
        return SbvMessage("bval", b)

    @staticmethod
    def aux(b: bool) -> "SbvMessage":
        return SbvMessage("aux", b)


class SbvBroadcast:
    """One round's synchronized binary-value broadcast state machine."""

    def __init__(self, netinfo: NetworkInfo) -> None:
        self.netinfo = netinfo
        self.received_bval = BoolMultimap()
        self.sent_bval = BoolSet.none()
        # Aux is one-per-sender: keyed by sender so a Byzantine node cannot
        # count twice toward the N-f quorum by sending both values.
        self.received_aux: dict = {}
        self.sent_aux = False
        self.bin_values = BoolSet.none()
        self.output: Optional[BoolSet] = None

    def handle_input(self, b: bool) -> Step:
        return self.send_bval(b)

    def handle_message(self, sender_id: Any, msg: SbvMessage) -> Step:
        if self.netinfo.node_index(sender_id) is None:
            # Non-validators (observers) must not count toward quorums.
            return Step.from_fault(sender_id, "sbv:non_validator_sender")
        if msg.kind == "bval":
            return self._handle_bval(sender_id, msg.value)
        if msg.kind == "aux":
            return self._handle_aux(sender_id, msg.value)
        return Step.from_fault(sender_id, "sbv:malformed_message")

    # -- BVal ----------------------------------------------------------------

    def send_bval(self, b: bool) -> Step:
        if self.sent_bval.contains(b):
            return Step()
        self.sent_bval = self.sent_bval.inserted(b)
        step = Step()
        step.messages.append(TargetedMessage(Target.all(), SbvMessage.bval(b)))
        # Count our own BVal as received.
        step.extend(self._handle_bval(self.netinfo.our_id, b))
        return step

    def _handle_bval(self, sender_id: Any, b: bool) -> Step:
        # Duplicates are ignored silently: re-delivery is legal under
        # reordering, and BA's Term replay may race the sender's own BVal.
        if not self.received_bval.insert(b, sender_id):
            return Step()
        step = Step()
        count = len(self.received_bval[b])
        f = self.netinfo.num_faulty()
        if count == 2 * f + 1:
            # b is now in bin_values.
            self.bin_values = self.bin_values.inserted(b)
            if not self.sent_aux:
                self.sent_aux = True
                step.messages.append(TargetedMessage(Target.all(), SbvMessage.aux(b)))
                step.extend(self._handle_aux(self.netinfo.our_id, b))
            else:
                step.extend(self._try_output())
        elif count == f + 1:
            step.extend(self.send_bval(b))
        return step

    # -- Aux -----------------------------------------------------------------

    def _handle_aux(self, sender_id: Any, b: bool) -> Step:
        if sender_id in self.received_aux:
            return Step()  # only the first Aux per sender counts
        self.received_aux[sender_id] = b
        return self._try_output()

    def _try_output(self) -> Step:
        if self.output is not None or not self.bin_values:
            return Step()
        # Count distinct Aux senders whose value is in bin_values.
        vals = BoolSet.none()
        count = 0
        # lint: allow[determinism] BoolSet union and counting are commutative
        for sender, b in self.received_aux.items():
            if self.bin_values.contains(b):
                vals = vals.inserted(b)
                count += 1
        if count < self.netinfo.num_correct():
            return Step()
        self.output = vals
        return Step.from_output(vals)
