"""SenderQueue — epoch-aware outgoing-message buffering.

Rebuild of `src/sender_queue/` § (SURVEY.md §2.1): wraps DynamicHoneyBadger
or QueueingHoneyBadger and holds back outgoing messages addressed to peers
that have not yet reached the message's (era, epoch) — peers announce
progress with ``EpochStarted``.  This keeps a fast node from flooding a slow
peer with traffic the peer would buffer or drop (the reference's
`max_future_epochs` contract), and cleanly drops obsolete traffic to peers
that already moved past an era.

The wrapper turns ``Target.all``/``all_except`` into per-peer sends (it must
make a per-recipient decision), so it needs the peer list: validators are
taken from the wrapped algorithm's NetworkInfo; observers can be registered
with :meth:`add_peer`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.core.protocol import ConsensusProtocol
from hbbft_tpu.core.types import (
    CryptoWork,
    Step,
    Target,
    TargetedMessage,
    absorb_child_step,
)
from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage
from hbbft_tpu.protocols.honey_badger import HbMessage


@dataclass(frozen=True, slots=True)
class SqMessage:
    """kind ∈ {"epoch_started", "algo"}."""

    kind: str
    payload: Any

    @staticmethod
    def epoch_started(era: int, epoch: int) -> "SqMessage":
        return SqMessage("epoch_started", (era, epoch))

    @staticmethod
    def algo(inner: Any) -> "SqMessage":
        return SqMessage("algo", inner)


def _default_our_epoch(algo) -> Tuple[int, int]:
    dhb = getattr(algo, "dhb", algo)
    if hasattr(dhb, "hb"):
        return (dhb.era, dhb.hb.epoch)
    return (0, dhb.epoch)  # bare HoneyBadger: single implicit era


def _default_msg_epoch(msg: Any) -> Tuple[int, int]:
    if isinstance(msg, DhbMessage):
        inner = msg.payload
        epoch = inner.epoch if isinstance(inner, HbMessage) else 0
        return (msg.era, epoch)
    if isinstance(msg, HbMessage):
        return (0, msg.epoch)
    return (0, 0)


class SenderQueue(ConsensusProtocol):
    def __init__(
        self,
        algo: ConsensusProtocol,
        max_future_epochs: int = 3,
        our_epoch_fn: Callable[[Any], Tuple[int, int]] = _default_our_epoch,
        msg_epoch_fn: Callable[[Any], Tuple[int, int]] = _default_msg_epoch,
        extra_peers: Tuple[Any, ...] = (),
    ) -> None:
        self.algo = algo
        self.max_future_epochs = max_future_epochs
        # lint: allow[hook-detachment] epoch extractors are protocol
        # structure, not environment: both default to module-level
        # functions, which the snapshot encoder serializes by name — a
        # restored queue must keep the same epoch extraction to stay
        # bit-identical under replay (env-dropping them would change
        # gating decisions mid-WAL)
        self.our_epoch_fn = our_epoch_fn
        # lint: allow[hook-detachment] same serialized-by-name contract as
        # our_epoch_fn above: module-level function, replay-significant
        self.msg_epoch_fn = msg_epoch_fn
        self._extra_peers = set(extra_peers)
        self.peer_epochs: Dict[Any, Tuple[int, int]] = {}
        self._outgoing: Dict[Any, List[Any]] = {}  # peer -> buffered inner msgs
        self._last_announced: Optional[Tuple[int, int]] = None
        # peers() memo: invalidated when the era's NetworkInfo object is
        # replaced or a peer set grows (both sets only ever grow).
        self._peers_cache: Optional[List[Any]] = None
        self._peers_netinfo: Any = None
        self._peers_sizes: Tuple[int, int] = (-1, -1)

    # -- peers ---------------------------------------------------------------

    def peers(self) -> List[Any]:
        netinfo = getattr(self.algo, "netinfo", None)
        sizes = (len(self._extra_peers), len(self.peer_epochs))
        if (
            self._peers_cache is not None
            and self._peers_netinfo is netinfo
            and self._peers_sizes == sizes
        ):
            return self._peers_cache
        ids = set(netinfo.all_ids()) if netinfo is not None else set()
        ids |= self._extra_peers
        ids |= set(self.peer_epochs)
        ids.discard(self.our_id())
        self._peers_cache = sorted(ids, key=repr)
        self._peers_netinfo = netinfo  # strong ref: no id-reuse staleness
        self._peers_sizes = sizes
        return self._peers_cache

    def add_peer(self, node_id) -> None:
        """Register an observer so it receives algorithm traffic."""
        self._extra_peers.add(node_id)

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self):
        return self.algo.our_id()

    def terminated(self) -> bool:
        return self.algo.terminated()

    def handle_input(self, input: Any, rng=None) -> Step:
        return self._post(self.algo.handle_input(input, rng=rng))

    def handle_message(self, sender_id: Any, message: SqMessage, rng=None) -> Step:
        if not isinstance(message, SqMessage):
            return Step.from_fault(sender_id, "sender_queue:malformed_message")
        if message.kind == "epoch_started":
            return self._on_epoch_started(sender_id, message.payload)
        if message.kind == "algo":
            return self._post(
                self.algo.handle_message(sender_id, message.payload, rng=rng)
            )
        return Step.from_fault(sender_id, "sender_queue:unknown_kind")

    def __getattr__(self, name):
        # Delegate protocol-specific entry points (propose, vote_for,
        # push_transaction, ...) through the queueing wrapper.
        inner = getattr(self.algo, name)
        if callable(inner):

            def call(*args, **kwargs):
                result = inner(*args, **kwargs)
                return self._post(result) if isinstance(result, Step) else result

            return call
        return inner

    # -- epoch tracking ------------------------------------------------------

    def _on_epoch_started(self, sender_id: Any, payload: Any) -> Step:
        if (
            not isinstance(payload, tuple)
            or len(payload) != 2
            or not all(isinstance(x, int) for x in payload)
        ):
            return Step.from_fault(sender_id, "sender_queue:malformed_epoch")
        cur = self.peer_epochs.get(sender_id)
        if cur is not None and payload <= cur:
            return Step()
        self.peer_epochs[sender_id] = payload
        return self._flush_peer(sender_id)

    def _flush_peer(self, peer) -> Step:
        buffered = self._outgoing.get(peer, [])
        if not buffered:
            return Step()
        keep: List[Any] = []
        step = Step()
        for msg in buffered:
            status = self._classify(peer, msg)
            if status == "send":
                step.messages.append(TargetedMessage(Target.node(peer), SqMessage.algo(msg)))
            elif status == "premature":
                keep.append(msg)
            # obsolete: drop
        self._outgoing[peer] = keep
        return step

    def _classify(self, peer, msg, era_epoch=None) -> str:
        """The single epoch-gating predicate (both the hot `_post` loop and
        buffered replay route through here).  ``era_epoch`` lets callers
        pass a precomputed ``msg_epoch_fn(msg)`` to avoid re-extracting it
        once per peer."""
        peer_epoch = self.peer_epochs.get(peer)
        if peer_epoch is None:
            # Unknown progress: optimistic send (the peer buffers future
            # epochs itself, same as an un-wrapped network).
            return "send"
        era, epoch = (
            era_epoch if era_epoch is not None else self.msg_epoch_fn(msg)
        )
        p_era, p_epoch = peer_epoch
        if era < p_era or (era == p_era and epoch < p_epoch):
            return "obsolete"
        if era > p_era or epoch > p_epoch + self.max_future_epochs:
            return "premature"
        return "send"

    # -- outgoing interception ----------------------------------------------

    def _post(self, inner_step: Step) -> Step:
        routed = Step(output=list(inner_step.output))
        routed.fault_log.extend(inner_step.fault_log)
        # Deferred-crypto follow-up steps must re-enter through _post so
        # their messages get epoch-routed too.
        for w in inner_step.work:
            routed.work.append(
                CryptoWork(
                    kind=w.kind,
                    payload=w.payload,
                    on_result=lambda res, _cb=w.on_result: self._post(_cb(res)),
                    owner=w.owner,
                )
            )
        # Inline per-peer routing (the N·messages hot loop): the envelope is
        # built once per message (frozen — shared across peers) and the
        # message's epoch is extracted lazily, once, not once per peer.
        msgs = routed.messages
        peers = self.peers()
        our = self.our_id()
        peer_epochs = self.peer_epochs
        for tm in inner_step.messages:
            m = tm.message
            envelope = SqMessage.algo(m)
            era_epoch = None
            for peer in tm.target.recipients(peers, our_id=our):
                if era_epoch is None and peer_epochs.get(peer) is not None:
                    era_epoch = self.msg_epoch_fn(m)
                status = self._classify(peer, m, era_epoch)
                if status == "send":
                    msgs.append(TargetedMessage(Target.node(peer), envelope))
                elif status == "premature":
                    self._outgoing.setdefault(peer, []).append(m)
                # obsolete: drop
        return routed.extend(self._maybe_announce())

    def _route(self, tm: TargetedMessage) -> Step:
        """Route one targeted message (the unit-testable single-message
        form of the inlined loop in :meth:`_post`)."""
        step = Step()
        for peer in tm.target.recipients(self.peers(), our_id=self.our_id()):
            status = self._classify(peer, tm.message)
            if status == "send":
                step.messages.append(
                    TargetedMessage(Target.node(peer), SqMessage.algo(tm.message))
                )
            elif status == "premature":
                self._outgoing.setdefault(peer, []).append(tm.message)
        return step

    def _maybe_announce(self) -> Step:
        cur = self.our_epoch_fn(self.algo)
        if cur == self._last_announced:
            return Step()
        self._last_announced = cur
        return Step.from_msg(Target.all(), SqMessage.epoch_started(*cur))
