"""Core state-machine contracts.

TPU-native rebuild of the reference's trait core (hbbft `src/traits.rs`,
`src/lib.rs` §, unverified — see SURVEY.md provenance note): the universal
sans-I/O contract every protocol speaks.  A protocol is a deterministic state
machine; feeding it input or a message yields a :class:`Step` carrying outputs,
outgoing targeted messages, and a fault log.  No I/O, no threads, no clocks.

Design deltas vs the reference (deliberate, TPU-first):

* ``Step`` may also carry *deferred crypto work items* (``CryptoWork``) so the
  runtime can batch BLS pairing checks / Lagrange combines across every node
  and protocol instance into one device dispatch per crank round, instead of
  verifying each share synchronously inside ``handle_message``.  The reference
  verifies inline; on TPU per-share dispatch would be ruinous (SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, Hashable, Iterable, Optional, TypeVar

from hbbft_tpu.core.fault_log import Fault, FaultLog

NodeId = TypeVar("NodeId", bound=Hashable)
M = TypeVar("M")  # message payload type


# ---------------------------------------------------------------------------
# Target — who an outgoing message is addressed to.
# Mirrors hbbft `Target::{All, Nodes, AllExcept, Node}` (src/traits.rs §).
# ---------------------------------------------------------------------------


@dataclass(frozen=True, slots=True)
class Target(Generic[NodeId]):
    """Routing directive for an outgoing message.

    Exactly one of the four forms:

    * ``Target.all()``            — every other node.
    * ``Target.node(id)``         — a single node.
    * ``Target.nodes(ids)``       — an explicit set of nodes.
    * ``Target.all_except(ids)``  — everyone except the given set.
    """

    kind: str  # "all" | "node" | "nodes" | "all_except"
    ids: frozenset = frozenset()

    @staticmethod
    def all() -> "Target":
        return _TARGET_ALL

    @staticmethod
    def node(node_id) -> "Target":
        # Memoized: Target.node(peer) is built once per message *delivery*
        # (hot in SenderQueue routing); targets are frozen so sharing is safe.
        try:
            return _node_target(node_id)
        except TypeError:  # unhashable id — cannot memoize
            return Target("node", frozenset([node_id]))

    @staticmethod
    def nodes(node_ids: Iterable) -> "Target":
        return Target("nodes", frozenset(node_ids))

    @staticmethod
    def all_except(node_ids: Iterable) -> "Target":
        return Target("all_except", frozenset(node_ids))

    def recipients(self, all_ids: Iterable, our_id=None) -> list:
        """Expand to the concrete recipient list: members of ``all_ids``
        only, always excluding ``our_id`` (uniform across all four kinds)."""
        if self.kind == "all":
            return [n for n in all_ids if n != our_id]
        if self.kind in ("node", "nodes"):
            return [n for n in all_ids if n in self.ids and n != our_id]
        return [n for n in all_ids if n not in self.ids and n != our_id]

    def contains(self, node_id, our_id=None) -> bool:
        if self.kind == "all":
            return node_id != our_id
        if self.kind in ("node", "nodes"):
            return node_id in self.ids
        return node_id not in self.ids and node_id != our_id


_TARGET_ALL = Target("all")


@functools.lru_cache(maxsize=4096)
def _node_target(node_id) -> "Target":
    return Target("node", frozenset([node_id]))


@dataclass(frozen=True, slots=True)
class TargetedMessage(Generic[M, NodeId]):
    """An outgoing message with its routing target (hbbft `TargetedMessage` §)."""

    target: Target
    message: Any

    def map(self, f: Callable[[Any], Any]) -> "TargetedMessage":
        return TargetedMessage(self.target, f(self.message))


@dataclass(frozen=True, slots=True)
class SourcedMessage(Generic[M, NodeId]):
    """An inbound message tagged with its sender (hbbft `SourcedMessage` §)."""

    sender: Any
    message: Any


# ---------------------------------------------------------------------------
# Deferred crypto work items (TPU-first addition; no reference equivalent).
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class CryptoWork:
    """A crypto check/combine deferred to the round-barrier device batch.

    ``kind`` selects the batched kernel (e.g. ``"verify_sig_share"``,
    ``"verify_dec_share"``).  ``payload`` is kernel-specific.  ``on_result``
    re-enters the protocol state machine with the boolean/array result and
    returns a follow-up :class:`Step` (possibly with more work).
    """

    kind: str
    payload: Any
    on_result: Callable[[Any], "Step"]
    owner: Any = None  # node id; stamped by the runtime when the Step surfaces


# ---------------------------------------------------------------------------
# Step — the universal return value of every state-machine transition.
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Step(Generic[NodeId]):
    """Result of one state-machine transition (hbbft `Step` §).

    ``output``    — values this protocol has irrevocably decided/delivered.
    ``messages``  — outgoing :class:`TargetedMessage`\\ s for the embedder.
    ``fault_log`` — evidence of provably faulty peer behaviour.
    ``work``      — deferred device crypto (TPU-first extension).
    """

    output: list = field(default_factory=list)
    messages: list = field(default_factory=list)
    fault_log: FaultLog = field(default_factory=FaultLog)
    work: list = field(default_factory=list)

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_output(*outputs) -> "Step":
        return Step(output=list(outputs))

    @staticmethod
    def from_msg(target: Target, message) -> "Step":
        return Step(messages=[TargetedMessage(target, message)])

    @staticmethod
    def from_fault(node_id, kind: str) -> "Step":
        return Step(fault_log=FaultLog([Fault(node_id, kind)]))

    # -- combinators --------------------------------------------------------

    def extend(self, other: "Step") -> "Step":
        """Absorb ``other`` into ``self`` (hbbft `Step::extend` §)."""
        if not other:
            return self  # most child steps are empty; skip 4 list ops
        self.output.extend(other.output)
        self.messages.extend(other.messages)
        self.fault_log.extend(other.fault_log)
        self.work.extend(other.work)
        return self

    def join(self, other: "Step") -> "Step":
        return self.extend(other)

    def extend_with(self, other: "Step", f: Callable[[Any], Any]) -> "Step":
        """Absorb ``other``, mapping its messages through ``f``.

        This is how nested protocols wrap inner messages into their own
        envelope (hbbft `Step::extend_with`/`map` §).
        """
        self.output.extend(other.output)
        self.messages.extend(tm.map(f) for tm in other.messages)
        self.fault_log.extend(other.fault_log)
        self.work.extend(other.work)
        return self

    def map_messages(self, f: Callable[[Any], Any]) -> "Step":
        return Step(
            output=list(self.output),
            messages=[tm.map(f) for tm in self.messages],
            fault_log=FaultLog(list(self.fault_log.entries)),
            work=list(self.work),
        )

    def with_output(self, *outputs) -> "Step":
        self.output.extend(outputs)
        return self

    def add_fault(self, node_id, kind: str) -> "Step":
        self.fault_log.append(Fault(node_id, kind))
        return self

    def defer(self, work: CryptoWork) -> "Step":
        self.work.append(work)
        return self

    def __bool__(self) -> bool:
        # Hot (hundreds of thousands of calls per simulated epoch): read
        # fault_log.entries directly to skip a FaultLog.__bool__ dispatch.
        return bool(
            self.messages or self.output or self.work or self.fault_log.entries
        )


def absorb_child_step(
    child_step: "Step",
    wrap_msg: Callable[[Any], Any],
    on_output: Callable[[Any], "Step"],
) -> "Step":
    """Lift a sub-protocol's Step into its parent's message/output space.

    The reference does this with `Step::extend_with`/`map` per nesting level
    (QHB ⊃ DHB ⊃ HB ⊃ Subset ⊃ {Broadcast | BA ⊃ Coin} — SURVEY.md §1).
    The TPU twist: deferred :class:`CryptoWork` callbacks inside the child
    step are *re-wrapped recursively*, so when the runtime resolves a batched
    pairing check the follow-up step re-enters through every parent layer —
    outputs keep triggering parent logic and messages keep getting enveloped.

    ``wrap_msg``  — child message -> parent message envelope.
    ``on_output`` — child output -> parent Step (parent's reaction).
    """
    if not child_step:
        return Step()
    step = Step(messages=[tm.map(wrap_msg) for tm in child_step.messages])
    step.fault_log.extend(child_step.fault_log)
    for work in child_step.work:
        step.work.append(
            CryptoWork(
                kind=work.kind,
                payload=work.payload,
                on_result=(
                    lambda res, _cb=work.on_result: absorb_child_step(
                        _cb(res), wrap_msg, on_output
                    )
                ),
                owner=work.owner,
            )
        )
    for out in child_step.output:
        step.extend(on_output(out))
    return step


# The reference's `Epoched` trait (SURVEY.md §2.1) has no class here: epoch
# extraction is structural — SenderQueue reads the epoch coordinate off
# message dataclasses directly (sender_queue._default_msg_epoch), which is
# the idiomatic-Python equivalent of the trait bound.
