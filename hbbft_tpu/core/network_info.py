"""Static per-era view of the validator set (hbbft `src/network_info.rs` §).

Holds the sorted validator ids, this node's threshold-crypto key material, and
the per-node public keys used for signing votes/key-gen messages.  Immutable
for the duration of an era; `DynamicHoneyBadger` swaps in a fresh instance on
era change.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence


class NetworkInfo:
    """Validator-set metadata + our key shares for one era.

    Parameters mirror the reference constructor
    ``NetworkInfo::new(our_id, secret_key_share, public_key_set, secret_key,
    public_keys)`` (src/network_info.rs §, unverified).
    """

    def __init__(
        self,
        our_id,
        secret_key_share,
        public_key_set,
        secret_key,
        public_keys: Dict[Any, Any],
    ) -> None:
        self._our_id = our_id
        self._secret_key_share = secret_key_share
        self._public_key_set = public_key_set
        self._secret_key = secret_key
        self._public_keys = dict(public_keys)
        self._ids: List = sorted(self._public_keys.keys())
        self._index = {n: i for i, n in enumerate(self._ids)}
        self._is_validator = our_id in self._index
        if self._is_validator and secret_key_share is None:
            raise ValueError("validator NetworkInfo requires a secret key share")

    # -- identity -----------------------------------------------------------

    @property
    def our_id(self):
        return self._our_id

    def is_our_id(self, node_id) -> bool:
        return node_id == self._our_id

    def is_validator(self) -> bool:
        return self._is_validator

    def is_node_validator(self, node_id) -> bool:
        return node_id in self._index

    # -- membership ---------------------------------------------------------

    def all_ids(self) -> List:
        return list(self._ids)

    def other_ids(self) -> List:
        return [n for n in self._ids if n != self._our_id]

    def num_nodes(self) -> int:
        return len(self._ids)

    def num_faulty(self) -> int:
        """Max tolerated Byzantine nodes: f = ⌊(N−1)/3⌋."""
        return (len(self._ids) - 1) // 3

    def num_correct(self) -> int:
        return len(self._ids) - self.num_faulty()

    def node_index(self, node_id) -> Optional[int]:
        return self._index.get(node_id)

    def node_id(self, index: int):
        return self._ids[index]

    # -- keys ---------------------------------------------------------------

    @property
    def secret_key_share(self):
        return self._secret_key_share

    @property
    def secret_key(self):
        return self._secret_key

    @property
    def public_key_set(self):
        return self._public_key_set

    def public_key_share(self, node_id):
        idx = self.node_index(node_id)
        if idx is None:
            return None
        return self._public_key_set.public_key_share(idx)

    def public_key(self, node_id):
        return self._public_keys.get(node_id)

    def public_key_map(self) -> Dict[Any, Any]:
        return dict(self._public_keys)

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"NetworkInfo(our_id={self._our_id!r}, N={self.num_nodes()},"
            f" f={self.num_faulty()}, validator={self._is_validator})"
        )

    @staticmethod
    def generate_map(ids: Sequence, rng, backend) -> Dict[Any, "NetworkInfo"]:
        """Trusted-dealer key setup for tests/benchmarks.

        Builds a full ``{id: NetworkInfo}`` map with a fresh master key set of
        threshold f = ⌊(N−1)/3⌋ (mirrors the reference test utilities §).
        ``backend`` is a :class:`~hbbft_tpu.crypto.backend.CryptoBackend`.
        """
        ids = sorted(ids)
        n = len(ids)
        f = (n - 1) // 3
        sk_set = backend.generate_key_set(threshold=f, rng=rng)
        pk_set = sk_set.public_keys()
        secret_keys = {node: backend.generate_secret_key(rng) for node in ids}
        public_keys = {node: sk.public_key() for node, sk in secret_keys.items()}
        return {
            node: NetworkInfo(
                our_id=node,
                secret_key_share=sk_set.secret_key_share(i),
                public_key_set=pk_set,
                secret_key=secret_keys[node],
                public_keys=public_keys,
            )
            for i, node in enumerate(ids)
        }
