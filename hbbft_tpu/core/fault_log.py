"""Fault evidence log (hbbft `src/fault_log.rs` §, unverified — SURVEY.md).

Every protocol records *provable* misbehaviour by peers — an invalid Merkle
proof, a second conflicting ``Value``, a decryption share that fails its
pairing check — as a :class:`Fault` with a machine-readable kind string.  The
log rides on every :class:`~hbbft_tpu.core.types.Step` and is the framework's
failure-detection subsystem (SURVEY.md §5).

Fault kinds are plain strings namespaced by protocol (``"broadcast:
invalid_proof"``) rather than per-module enums: the set is open (new protocols
add kinds freely) and strings serialize canonically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterator, List


@dataclass(frozen=True, slots=True)
class Fault:
    """A single piece of evidence that ``node_id`` misbehaved."""

    node_id: Any
    kind: str

    def __str__(self) -> str:  # pragma: no cover - debugging aid
        return f"Fault({self.node_id!r}, {self.kind})"


@dataclass(slots=True)
class FaultLog:
    """An append-only list of :class:`Fault` entries."""

    entries: List[Fault] = field(default_factory=list)

    @staticmethod
    def init(node_id, kind: str) -> "FaultLog":
        return FaultLog([Fault(node_id, kind)])

    def append(self, fault: Fault) -> None:
        self.entries.append(fault)

    def report(self, node_id, kind: str) -> None:
        self.entries.append(Fault(node_id, kind))

    def extend(self, other: "FaultLog") -> None:
        self.entries.extend(other.entries)

    def kinds_for(self, node_id) -> List[str]:
        return [f.kind for f in self.entries if f.node_id == node_id]

    def __iter__(self) -> Iterator[Fault]:
        return iter(self.entries)

    def __len__(self) -> int:
        return len(self.entries)

    def __bool__(self) -> bool:
        return bool(self.entries)
