"""Fault evidence log (hbbft `src/fault_log.rs` §, unverified — SURVEY.md).

Every protocol records *provable* misbehaviour by peers — an invalid Merkle
proof, a second conflicting ``Value``, a decryption share that fails its
pairing check — as a :class:`Fault` with a machine-readable kind string.  The
log rides on every :class:`~hbbft_tpu.core.types.Step` and is the framework's
failure-detection subsystem (SURVEY.md §5).

Fault kinds are plain strings namespaced by protocol (``"broadcast:
invalid_proof"``) rather than per-module enums: the set is open (new protocols
add kinds freely) and strings serialize canonically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, FrozenSet, Iterator, List

#: The fault-kind registry: every provable-misbehaviour kind a protocol can
#: record, keyed by namespace prefix.  This is the single enumeration that
#: (a) the handler-exhaustiveness lint rule cross-references against the
#: ``Step.from_fault`` literals in each protocol module (an unregistered
#: kind — or a registered kind no protocol emits — breaks lint), (b) the
#: scenario matrix (net/scenarios.py) draws its expected-fault assertions
#: from, and (c) tests/test_scenarios.py walks so attack-vs-fault drift
#: breaks lint and tests together.  MUST stay a pure literal: the lint rule
#: reads it via ``ast.literal_eval`` without importing this module.
FAULT_KINDS = {
    "binary_agreement": (
        "coin_in_fixed_round",
        "conflicting_conf",
        "duplicate_term",
        "far_future_round",
        "malformed_coin",
        "malformed_conf",
        "malformed_message",
        "malformed_round",
        "malformed_sbv",
        "malformed_term",
        "non_validator_sender",
        "unknown_kind",
    ),
    # crash/restart axis (net/crash.py): recovery failures are attributed
    # evidence against the crashed node — a cell whose restart could not
    # complete fails its verdict visibly instead of crashing the harness
    "crash": (
        "checkpoint_failed",
        "recovery_failed",
        "replay_divergence",
    ),
    "broadcast": (
        "bad_length_prefix",
        "conflicting_echo",
        "conflicting_ready",
        "conflicting_values",
        "echo_from_non_validator",
        "inconsistent_shard_lengths",
        "invalid_echo_proof",
        "invalid_shard_encoding",
        "invalid_value_proof",
        "malformed_message",
        "malformed_ready",
        "multiple_echos",
        "multiple_readys",
        "multiple_values",
        "ready_from_non_validator",
        "undecodable_shards",
        "unknown_kind",
        "value_from_non_proposer",
    ),
    "dynamic_honey_badger": (
        "era_too_far_ahead",
        "future_era_from_non_member",
        "invalid_keygen_signature",
        "invalid_vote_signature",
        "malformed_contribution",
        "malformed_keygen",
        "malformed_message",
    ),
    "honey_badger": (
        "dec_share_in_plaintext_epoch",
        "dec_share_unknown_proposer",
        "epoch_too_far_ahead",
        "future_epoch_from_non_validator",
        "invalid_ciphertext",
        "invalid_contribution",
        "malformed_message",
        "unknown_kind",
        "unparseable_ciphertext",
    ),
    "sbv": (
        "malformed_message",
        "non_validator_sender",
    ),
    "sender_queue": (
        "malformed_epoch",
        "malformed_message",
        "unknown_kind",
    ),
    "subset": (
        "malformed_message",
        "unknown_kind",
        "unknown_proposer",
    ),
    "sync_key_gen": (
        "ack_from_non_member",
        "ack_value_mismatch",
        "invalid_ack_encryption",
        "invalid_part_degree",
        "invalid_row_encryption",
        "malformed_ack",
        "malformed_part",
        "multiple_parts",
        "part_from_non_member",
        "row_commitment_mismatch",
    ),
    "threshold_decrypt": (
        "invalid_share",
        "malformed_message",
        "non_validator_share",
    ),
    "threshold_sign": (
        "invalid_sig_share",
        "malformed_message",
        "non_validator_share",
    ),
}


def all_fault_kinds() -> FrozenSet[str]:
    """Every registered kind as its full ``"prefix:name"`` wire string."""
    return frozenset(
        f"{prefix}:{name}" for prefix, names in FAULT_KINDS.items() for name in names
    )


@dataclass(frozen=True, slots=True)
class Fault:
    """A single piece of evidence that ``node_id`` misbehaved."""

    node_id: Any
    kind: str

    def __str__(self) -> str:  # pragma: no cover - debugging aid
        return f"Fault({self.node_id!r}, {self.kind})"


@dataclass(slots=True)
class FaultLog:
    """An append-only list of :class:`Fault` entries."""

    entries: List[Fault] = field(default_factory=list)

    @staticmethod
    def init(node_id, kind: str) -> "FaultLog":
        return FaultLog([Fault(node_id, kind)])

    def append(self, fault: Fault) -> None:
        self.entries.append(fault)

    def report(self, node_id, kind: str) -> None:
        self.entries.append(Fault(node_id, kind))

    def extend(self, other: "FaultLog") -> None:
        self.entries.extend(other.entries)

    def kinds_for(self, node_id) -> List[str]:
        return [f.kind for f in self.entries if f.node_id == node_id]

    def __iter__(self) -> Iterator[Fault]:
        return iter(self.entries)

    def __len__(self) -> int:
        return len(self.entries)

    def __bool__(self) -> bool:
        return bool(self.entries)
