"""The universal consensus-protocol interface.

TPU-native analogue of hbbft's `DistAlgorithm`/`ConsensusProtocol` trait
(src/traits.rs §, unverified — SURVEY.md): every protocol is a deterministic
state machine with two entry points (`handle_input`, `handle_message`) that
each return a :class:`~hbbft_tpu.core.types.Step`.
"""

from __future__ import annotations

import abc
from typing import Any

from hbbft_tpu.core.types import Step


class ConsensusProtocol(abc.ABC):
    """Deterministic sans-I/O consensus state machine.

    Concrete protocols also expose protocol-specific typed entry points
    (e.g. ``Broadcast.broadcast(value)``); ``handle_input`` is the generic
    form used by the harness.
    """

    @abc.abstractmethod
    def handle_input(self, input: Any, rng=None) -> Step:
        """Feed a local input (proposal/contribution) into the machine."""

    @abc.abstractmethod
    def handle_message(self, sender_id: Any, message: Any, rng=None) -> Step:
        """Feed a message received from ``sender_id`` into the machine."""

    @abc.abstractmethod
    def terminated(self) -> bool:
        """True once the machine will never produce further output."""

    @abc.abstractmethod
    def our_id(self) -> Any:
        """This node's id."""
