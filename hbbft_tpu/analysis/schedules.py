"""Schedule-space race explorer: the dynamic half of PR 9.

HoneyBadgerBFT's safety claim is *scheduler-independence* (Miller et
al., CCS 2016), and since PR 3 this repo deliberately executes its own
host/device work asynchronously: the bounded dispatch pipeline resolves
chunks out of order, the deferred-verify seam lets round r+1 assemble
while round r's checks are in flight, and the traffic hooks observe
mempool state between epochs.  Those seams were guarded only by a
couple of seeded orders in tests.  This module makes order-independence
a *checked* property: it drives the MockBackend ``pipeline_chunk``
machinery and the VirtualNet crank loop through every non-equivalent
resolution/crank schedule at small N and asserts the run fingerprint —
Batch sha256, fault log, integer counters, ``device_dispatches`` — is
bit-identical across all of them.

Machinery:

* :class:`ScheduleController` — a replayable decision trace.  Every
  nondeterministic point (which pending chunk resolves next, which
  queued message cranks next) asks ``choose(n)``; a recorded trace
  replays the exact schedule in a fresh process, which is what
  ``tools/race_explorer.py --replay`` does.
* :class:`RaceTracker` — vector-clock happens-before instrumentation.
  ``DispatchPipeline`` reports submit/resolve events, VirtualNet
  reports crank events with causal (enqueue) edges; footprints are
  object-granular (all chunks of one batch conflict, deliveries to one
  node conflict).  The tracker yields the dependence relation that
  powers both the DPOR reduction and the divergence report.
* :func:`explore` — stateless DFS over decision prefixes with two
  reductions: *canonical-trace dedup* (Foata normal form of the event
  sequence under the dependence relation — two schedules with the same
  normal form are Mazurkiewicz-equivalent and counted once) and a
  *DPOR-style swap prune* (an alternative whose event is independent of
  everything between the taken event and its own execution would yield
  an equivalent trace and is not enqueued).
* On divergence the failing choice trace is ddmin-minimized and written
  as a JSON counterexample that replays deterministically.

Targets live at the bottom (honest pipeline / traffic / virtualnet
runs) next to the seeded mutants from :mod:`analysis.mutations` — the
detector-sensitivity fixtures pinned by tests/test_race_explorer.py.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

from hbbft_tpu.crypto.backend import MockBackend

# ---------------------------------------------------------------------------
# Decision traces
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ChoicePoint:
    """One recorded decision: its label, arity, and the stable keys of
    the candidates (index-aligned with the choice values)."""

    label: str
    arity: int
    candidates: Tuple[str, ...]
    taken: int


class ScheduleController:
    """A replayable schedule: preset choices consumed in order, 0 (the
    default order) beyond the preset's end.  Arity-1 decisions are not
    recorded — they carry no information and keeping them out makes the
    trace a dense encoding of the *actual* schedule freedom."""

    def __init__(self, choices: Sequence[int] = ()) -> None:
        self.preset = list(choices)
        self.trace: List[int] = []
        self.points: List[ChoicePoint] = []

    def choose(
        self,
        n: int,
        label: str = "",
        candidates: Optional[Sequence[str]] = None,
    ) -> int:
        if n <= 1:
            return 0
        i = len(self.trace)
        c = self.preset[i] % n if i < len(self.preset) else 0
        self.trace.append(c)
        cands = tuple(candidates) if candidates is not None else tuple(
            str(j) for j in range(n)
        )
        self.points.append(ChoicePoint(label, n, cands, c))
        return c

    def permutation(
        self, k: int, label: str = "", keys: Optional[Sequence[str]] = None
    ) -> List[int]:
        """Pick an order of ``k`` items via k-1 shrinking choices
        (selection order); all-zero choices give the identity order."""
        remaining = list(range(k))
        out: List[int] = []
        while remaining:
            cands = [keys[i] if keys else str(i) for i in remaining]
            c = self.choose(len(remaining), label, candidates=cands)
            out.append(remaining.pop(c))
        return out


# ---------------------------------------------------------------------------
# Happens-before instrumentation
# ---------------------------------------------------------------------------


@dataclass
class Event:
    """One scheduled action with its vector clock and footprint."""

    index: int
    key: str
    task: str
    kind: str  # "submit" | "resolve" | "crank"
    writes: FrozenSet[Tuple[str, Any]]
    reads: FrozenSet[Tuple[str, Any]]
    causes: Tuple[int, ...]  # indices of events that enabled this one
    clock: Dict[str, int] = field(default_factory=dict)


def _footprints_conflict(a: Event, b: Event) -> bool:
    return bool(
        (a.writes & (b.writes | b.reads)) or (b.writes & a.reads)
    )


def events_dependent(a: Event, b: Event) -> bool:
    """Dependence for trace equivalence: same task, a causal edge, or an
    object-granular footprint conflict."""
    if a.task == b.task:
        return True
    if a.index in b.causes or b.index in a.causes:
        return True
    return _footprints_conflict(a, b)


def clocks_concurrent(a: Event, b: Event) -> bool:
    """Neither vector clock dominates: the two events are causally
    unordered (a race candidate when their footprints also conflict)."""

    def leq(x: Dict[str, int], y: Dict[str, int]) -> bool:
        return all(y.get(k, 0) >= v for k, v in x.items())

    return not leq(a.clock, b.clock) and not leq(b.clock, a.clock)


class RaceTracker:
    """Event recorder shared by the pipeline probe and the net probe.

    Vector clocks advance per task and join along causal edges (submit→
    resolve, enqueue→crank); footprint conflicts are deliberately NOT
    join points — a conflicting pair with concurrent clocks is exactly
    the schedule-sensitive state the explorer exists to audit."""

    def __init__(self) -> None:
        self.events: List[Event] = []
        self._task_clocks: Dict[str, Dict[str, int]] = {}
        # pipeline bookkeeping
        self._pending: Dict[int, int] = {}  # id(PendingDispatch) -> event idx
        # net bookkeeping
        self._msg_seq: Dict[Tuple[Any, Any, str], int] = {}
        self._current_crank: Optional[int] = None

    # -- core ----------------------------------------------------------------

    def record(
        self,
        key: str,
        task: str,
        kind: str,
        writes: Sequence[Tuple[str, Any]] = (),
        reads: Sequence[Tuple[str, Any]] = (),
        causes: Sequence[int] = (),
    ) -> Event:
        clock = dict(self._task_clocks.get(task, {}))
        for ci in causes:
            for t, v in self.events[ci].clock.items():
                if clock.get(t, 0) < v:
                    clock[t] = v
        clock[task] = clock.get(task, 0) + 1
        ev = Event(
            index=len(self.events),
            key=key,
            task=task,
            kind=kind,
            writes=frozenset(writes),
            reads=frozenset(reads),
            causes=tuple(causes),
            clock=clock,
        )
        self.events.append(ev)
        self._task_clocks[task] = clock
        return ev

    # -- DispatchPipeline probe API ------------------------------------------

    def pipe_submit(self, p) -> None:
        kind = p.kind or f"anon{len(self.events)}"
        batch = kind.split(".", 1)[0]
        # per-device-queue footprint (sharded pipeline, PR 18): a submit
        # APPENDS to its device's queue, a resolve POPS it — same-device
        # entries are thereby ordered (a device stream completes FIFO)
        # while cross-device entries stay concurrent, which is exactly
        # the schedule freedom the shard choose() axis explores
        dev = getattr(p, "device", None)
        ev = self.record(
            f"submit:{kind}", "main", "submit",
            writes=(("devq", dev),) if dev is not None else (),
            reads=(),
        )
        self._pending[id(p)] = ev.index
        # batch identity for the resolve's footprint
        ev.reads = frozenset({("batch", batch)})

    def pipe_resolve(self, p) -> None:
        kind = p.kind or "anon"
        batch = kind.split(".", 1)[0]
        cause = self._pending.pop(id(p), None)
        # object-granular: every chunk of one batch writes "the
        # batch's result object" — deliberately coarser than the
        # disjoint slot ranges, the way a static footprint would be
        writes = [("batch", batch)]
        dev = getattr(p, "device", None)
        if dev is not None:
            writes.append(("devq", dev))
        self.record(
            f"resolve:{kind}",
            f"chunk:{kind}",
            "resolve",
            writes=tuple(writes),
            causes=(cause,) if cause is not None else (),
        )

    # -- VirtualNet probe API ------------------------------------------------

    def tag_message(self, msg) -> str:
        """Assign a stable content-based key at enqueue time, recording
        the enqueuing crank event as the message's cause."""
        kind = type(msg.payload).__name__
        sig = (repr(msg.sender), repr(msg.to), kind)
        n = self._msg_seq.get(sig, 0)
        self._msg_seq[sig] = n + 1
        key = f"{msg.sender}->{msg.to}:{kind}#{n}"
        msg._race_key = key
        msg._race_cause = self._current_crank
        return key

    def begin_crank(self, msg) -> None:
        key = getattr(msg, "_race_key", None)
        if key is None:
            key = self.tag_message(msg)
        cause = getattr(msg, "_race_cause", None)
        ev = self.record(
            f"crank:{key}",
            f"node:{msg.to}",
            "crank",
            writes=(("node", repr(msg.to)),),
            causes=(cause,) if cause is not None else (),
        )
        self._current_crank = ev.index

    def end_crank(self) -> None:
        self._current_crank = None

    # -- analysis ------------------------------------------------------------

    def canonical_form(self) -> str:
        """Foata normal form of the executed trace under the dependence
        relation: each event's level is one past the highest level of a
        dependent predecessor, and the form is the multiset of keys per
        level.  Two schedules with equal forms are equivalent (one can
        be transformed into the other by swapping adjacent independent
        events)."""
        levels: List[int] = []
        level_of: List[int] = []
        recent: List[Event] = []
        for ev in self.events:
            lvl = 0
            for prior_idx, prior in enumerate(recent):
                if events_dependent(ev, prior):
                    lvl = max(lvl, level_of[prior_idx] + 1)
            recent.append(ev)
            level_of.append(lvl)
            levels.append(lvl)
        buckets: Dict[int, List[str]] = {}
        for ev, lvl in zip(self.events, levels):
            buckets.setdefault(lvl, []).append(ev.key)
        h = hashlib.sha256()
        for lvl in sorted(buckets):
            h.update(str(lvl).encode())
            for k in sorted(buckets[lvl]):
                h.update(k.encode())
            h.update(b"|")
        return h.hexdigest()

    def racing_pairs(self, limit: int = 8) -> List[Tuple[str, str]]:
        """Footprint-conflicting event pairs whose vector clocks are
        concurrent — the state whose final value the schedule decides."""
        out: List[Tuple[str, str]] = []
        evs = self.events
        for i in range(len(evs)):
            for j in range(i + 1, len(evs)):
                a, b = evs[i], evs[j]
                if a.task == b.task:
                    continue
                if _footprints_conflict(a, b) and clocks_concurrent(a, b):
                    out.append((a.key, b.key))
                    if len(out) >= limit:
                        return out
        return out


# ---------------------------------------------------------------------------
# Fingerprints
# ---------------------------------------------------------------------------


def stable_repr(obj: Any) -> str:
    """Deterministic, insertion-order-free repr for hashing."""
    if isinstance(obj, dict):
        items = sorted(obj.items(), key=lambda kv: repr(kv[0]))
        return "{" + ",".join(
            f"{stable_repr(k)}:{stable_repr(v)}" for k, v in items
        ) + "}"
    if isinstance(obj, (list, tuple)):
        body = ",".join(stable_repr(x) for x in obj)
        return ("[" if isinstance(obj, list) else "(") + body + (
            "]" if isinstance(obj, list) else ")"
        )
    if isinstance(obj, (set, frozenset)):
        return "{" + ",".join(sorted(stable_repr(x) for x in obj)) + "}"
    if hasattr(obj, "contributions") and hasattr(obj, "epoch"):  # Batch
        return (
            f"Batch(epoch={obj.epoch},"
            f"contributions={stable_repr(obj.contributions)})"
        )
    return repr(obj)


def sha(obj: Any) -> str:
    return hashlib.sha256(stable_repr(obj).encode()).hexdigest()


def counters_fingerprint(*counter_objs) -> Dict[str, int]:
    """Integer counters only — wall-clock attribution (``*_seconds``)
    legitimately varies run to run and is excluded."""
    out: Dict[str, int] = {}
    for c in counter_objs:
        for k, v in c.snapshot().items():
            if isinstance(v, bool) or not isinstance(v, int):
                continue
            out[k] = out.get(k, 0) + v
    return out


@dataclass
class RunResult:
    """One executed schedule: fingerprint parts + analysis artifacts."""

    parts: Dict[str, Any]
    trace: List[int]
    points: List[ChoicePoint]
    canonical: str
    events: List[Event]

    @property
    def fingerprint(self) -> str:
        return hashlib.sha256(
            json.dumps(self.parts, sort_keys=True, default=repr).encode()
        ).hexdigest()


def first_divergence(ref: RunResult, div: RunResult) -> Dict[str, Any]:
    """The first position where the two executed event sequences differ
    — the minimized counterexample's replayable anchor."""
    rk = [e.key for e in ref.events]
    dk = [e.key for e in div.events]
    for i, (a, b) in enumerate(zip(rk, dk)):
        if a != b:
            return {"index": i, "reference": a, "divergent": b}
    if len(rk) != len(dk):
        i = min(len(rk), len(dk))
        return {
            "index": i,
            "reference": rk[i] if i < len(rk) else None,
            "divergent": dk[i] if i < len(dk) else None,
        }
    return {"index": None, "reference": None, "divergent": None}


# ---------------------------------------------------------------------------
# Targets
# ---------------------------------------------------------------------------


def _engine_parts(net, batches_list, error: Optional[BaseException],
                  extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    parts: Dict[str, Any] = {
        "batches_sha": sha(batches_list),
        "faults": [],
        "counters": counters_fingerprint(net.counters, net.backend.counters),
        "device_dispatches": net.backend.counters.device_dispatches,
        "error": repr(error) if error is not None else "",
    }
    if extra:
        parts["extra"] = extra
    return parts


def run_pipeline_target(
    controller: ScheduleController,
    tracker: RaceTracker,
    n: int,
    seed: int,
    backend_factory: Optional[Callable[[], Any]] = None,
    epochs: int = 2,
    coin_rounds: int = 1,
) -> RunResult:
    """Honest lockstep epochs with the MockBackend simulated-async
    pipeline under explorer control: every flush's resolution order is a
    schedule decision.  Exercises the PR-3 chunk pipeline AND the PR-5
    deferred-verify seam (the engine's ``verify_*_deferred`` resolvers
    ride the same flush)."""
    from hbbft_tpu.crypto.backend import MockBackend
    from hbbft_tpu.engine.array_engine import ArrayHoneyBadgerNet

    backend = (backend_factory or MockBackend)()
    # chunk so the dec/sig verify batches split ~4 ways at this N
    items = n * n * max(1, n - 1)
    backend.pipeline_chunk = max(1, items // 4)
    backend._pipe.probe = tracker
    backend.resolve_order = lambda k: controller.permutation(
        k, "resolve", keys=[p.kind for p in backend._pipe._q]
    )
    net = ArrayHoneyBadgerNet(
        range(n), backend=backend, seed=seed, coin_rounds=coin_rounds
    )
    error: Optional[BaseException] = None
    batches: List[Any] = []
    try:
        batches = net.run_epochs(epochs)
    except Exception as e:  # divergence shows up as a raised invariant
        error = e
    extra = backend.race_extra() if hasattr(backend, "race_extra") else None
    parts = _engine_parts(net, batches, error, extra)
    return RunResult(
        parts, list(controller.trace), list(controller.points),
        tracker.canonical_form(), tracker.events,
    )


def run_traffic_target(
    controller: ScheduleController,
    tracker: RaceTracker,
    n: int,
    seed: int,
    backend_factory: Optional[Callable[[], Any]] = None,
    chunk_listener_factory: Optional[Callable[[Any], Callable]] = None,
    epochs: int = 3,
) -> RunResult:
    """The traffic-hook seam: an ArrayTrafficDriver sources contributions
    and commits batches through the engine hooks while the pipeline
    resolves chunks in explorer-chosen orders.  ``chunk_listener_factory
    (driver) -> callback`` attaches a per-chunk-resolution listener (the
    seeded mid-epoch mempool mutation rides this)."""
    import random

    from hbbft_tpu.crypto.backend import MockBackend
    from hbbft_tpu.engine.array_engine import ArrayHoneyBadgerNet
    from hbbft_tpu.traffic.driver import ArrayTrafficDriver
    from hbbft_tpu.traffic.workload import ClosedLoopSource, ZipfPopulation

    backend = (backend_factory or MockBackend)()
    items = n * n * max(1, n - 1)
    backend.pipeline_chunk = max(1, items // 4)
    backend._pipe.probe = tracker
    backend.resolve_order = lambda k: controller.permutation(
        k, "resolve", keys=[p.kind for p in backend._pipe._q]
    )
    net = ArrayHoneyBadgerNet(range(n), backend=backend, seed=seed)
    src = ClosedLoopSource(4 * n, ZipfPopulation(16 * n, 1.1))
    driver = ArrayTrafficDriver(
        net, src, random.Random(seed + 1), batch_size=8,
        mempool_capacity=1 << 10,
    )
    if chunk_listener_factory is not None:
        backend.chunk_listeners = (chunk_listener_factory(driver),)
    error: Optional[BaseException] = None
    batches: List[Any] = []
    try:
        batches = net.run_epochs(epochs)
    except Exception as e:
        error = e
    extra: Dict[str, Any] = {"traffic": driver.tracker.fingerprint()}
    if hasattr(backend, "race_extra"):
        extra.update(backend.race_extra())
    parts = _engine_parts(net, batches, error, extra)
    return RunResult(
        parts, list(controller.trace), list(controller.points),
        tracker.canonical_form(), tracker.events,
    )


def run_virtualnet_target(
    controller: ScheduleController,
    tracker: RaceTracker,
    n: int,
    seed: int,
    wrap: Optional[Callable[[Any], Any]] = None,
) -> RunResult:
    """Message-delivery-order exploration: Broadcast over VirtualNet with
    the controlled scheduler choosing which queued message cranks next.
    This is where the DPOR swap-prune earns its keep — deliveries to
    different nodes without a causal edge commute."""
    from hbbft_tpu.crypto.backend import MockBackend
    from hbbft_tpu.net.virtual_net import NetBuilder

    from hbbft_tpu.protocols.broadcast import Broadcast

    payload = b"race explorer payload " * 4

    def construct(ni, be):
        alg = Broadcast(ni, proposer_id=0)
        return wrap(alg) if wrap is not None else alg

    net = (
        NetBuilder(range(n))
        .backend(MockBackend())
        .using(construct)
        .scheduler("controlled")
        .crank_limit(200_000)
        .build(seed=seed)
    )
    net.race_probe = tracker

    def chooser(vnet) -> int:
        keys = [
            getattr(m, "_race_key", None) or tracker.tag_message(m)
            for m in vnet.queue
        ]
        return controller.choose(len(vnet.queue), "crank", candidates=keys)

    net.crank_chooser = chooser
    error: Optional[BaseException] = None
    try:
        net.send_input(0, payload)
        net.crank_to_quiescence()
    except Exception as e:
        error = e
    outputs = {
        repr(nid): list(net.nodes[nid].outputs) for nid in sorted(net.nodes)
    }
    faults = sorted(
        f"{repr(fault.node_id)}:{fault.kind}"
        for nid in net.nodes
        for fault in net.nodes[nid].faults_observed
    )
    parts = {
        "batches_sha": sha(outputs),
        "faults": faults,
        "counters": counters_fingerprint(net.counters, net.backend.counters),
        "device_dispatches": net.backend.counters.device_dispatches,
        "error": repr(error) if error is not None else "",
    }
    return RunResult(
        parts, list(controller.trace), list(controller.points),
        tracker.canonical_form(), tracker.events,
    )


class ShardedMockBackend(MockBackend):
    """MockBackend whose simulated-async chunks ride the PER-DEVICE
    sharded pipeline (parallel/shardpipe.py) — the tier-1/no-JAX stand-in
    for MeshBackend's whole-chunk-per-device dispatch.  Each chunk
    reserves a (recorded) device before submitting; ``finish()`` drains
    the device queues under the pipe's ``choose_shard`` hook, which the
    shard explorer target wires to the controller's choose() axis.  The
    default hook resolves the LAST ready device first — deterministic
    cross-device out-of-order, per-device FIFO — so plain tier-1 use
    exercises shard reordering without a controller."""

    #: virtual device count: >1 so cross-device order exists, small so
    #: the explorer's choice arity stays tractable at smoke budgets
    n_devices = 4

    def __init__(self) -> None:
        super().__init__()
        from hbbft_tpu.parallel.shardpipe import ShardedDispatchPipeline

        self._pipe = ShardedDispatchPipeline(
            self.n_devices, counters=None, tracer_ref=None,
            depth_fn=lambda: 1 << 30,
        )
        self._pipe.choose_shard = lambda ready: len(ready) - 1

    def _piped_submit(self, items, compute):
        # base body + a device reservation per chunk (the shard seam)
        step = self.pipeline_chunk or len(items) or 1
        out = [None] * len(items)
        b = self._batch_seq
        self._batch_seq += 1
        for ci, lo in enumerate(range(0, len(items), step)):
            chunk = items[lo : lo + step]

            def deliver(res, lo=lo):
                out[lo : lo + len(res)] = res
                for cb in self.chunk_listeners:
                    cb(lo, res)

            self._pipe.reserve_device()
            self._pipe.submit(
                lambda chunk=chunk: compute(chunk), fetch=None,
                kind=f"b{b}.c{ci}", items=len(chunk),
                on_result=deliver,
            )

        def finish():
            self._pipe.flush()
            return out

        return out, finish


def run_shard_target(
    controller: ScheduleController,
    tracker: RaceTracker,
    n: int,
    seed: int,
    backend_factory: Optional[Callable[[], Any]] = None,
    epochs: int = 2,
    coin_rounds: int = 1,
) -> RunResult:
    """The cross-shard completion-order seam (PR 18): honest lockstep
    epochs with chunks landing on per-device queues, the explorer
    choosing which device's head resolves next at every drain step.
    Placement (recorded) and per-device dispatch tallies ride the
    fingerprint — they are submit-path program state, so any schedule
    leaking into them is itself a divergence."""
    from hbbft_tpu.engine.array_engine import ArrayHoneyBadgerNet

    backend = (backend_factory or ShardedMockBackend)()
    items = n * n * max(1, n - 1)
    backend.pipeline_chunk = max(1, items // 4)
    backend._pipe.probe = tracker
    backend._pipe.choose_shard = lambda ready: controller.choose(
        len(ready), "shard", candidates=[f"dev{d}" for d in ready]
    )
    net = ArrayHoneyBadgerNet(
        range(n), backend=backend, seed=seed, coin_rounds=coin_rounds
    )
    error: Optional[BaseException] = None
    batches: List[Any] = []
    try:
        batches = net.run_epochs(epochs)
    except Exception as e:  # divergence shows up as a raised invariant
        error = e
    extra: Dict[str, Any] = {
        "dev_dispatches": list(backend._pipe.dev_dispatches),
        "placements_sha": sha(backend._pipe.placements),
    }
    if hasattr(backend, "race_extra"):
        extra.update(backend.race_extra())
    parts = _engine_parts(net, batches, error, extra)
    return RunResult(
        parts, list(controller.trace), list(controller.points),
        tracker.canonical_form(), tracker.events,
    )


def _mutant_target(name: str):
    from hbbft_tpu.analysis import mutations

    return mutations.target_runner(name)


#: name -> runner(controller, tracker, n, seed) -> RunResult
def target_runner(name: str):
    honest = {
        "pipeline": run_pipeline_target,
        "traffic": run_traffic_target,
        "virtualnet": run_virtualnet_target,
        "shard": run_shard_target,
    }
    if name in honest:
        return honest[name]
    if name.startswith("mutant:"):
        return _mutant_target(name.split(":", 1)[1])
    raise KeyError(f"unknown explorer target {name!r}")


TARGET_NAMES = ("pipeline", "traffic", "virtualnet", "shard")

#: (target, n, max_runs) triples of the tier-1 smoke sweep — small but
#: covering all four seams; ~1 s on one CPU core
SMOKE_PLAN = (
    ("pipeline", 4, 40),
    ("traffic", 4, 25),
    ("virtualnet", 4, 40),
    ("shard", 4, 40),
)

#: the slow full sweep (tests/test_race_explorer.py slow arm + PERF.md
#: round 10): ≥1000 non-equivalent schedules across the seams at
#: N ∈ {4, 7} — the CLI's --full and the acceptance-bar test share this
#: single definition so they cannot drift apart
FULL_PLAN = (
    ("pipeline", 4, 450),
    ("pipeline", 7, 200),
    ("traffic", 4, 200),
    ("traffic", 7, 100),
    ("virtualnet", 4, 250),
    ("virtualnet", 7, 150),
    ("shard", 4, 250),
    ("shard", 7, 100),
)


# ---------------------------------------------------------------------------
# Exploration
# ---------------------------------------------------------------------------


def run_schedule(target: str, n: int, seed: int, choices: Sequence[int]) -> RunResult:
    """Execute one schedule of ``target`` deterministically."""
    controller = ScheduleController(choices)
    tracker = RaceTracker()
    return target_runner(target)(controller, tracker, n, seed)


def _swap_prunable(run: RunResult, point_idx: int, alt: int) -> bool:
    """DPOR-style check: would taking ``alt`` at ``point_idx`` provably
    yield an equivalent trace?  True when the alternative's event is
    independent of every event between the taken event and its own
    execution in the observed run (the swap commutes all the way)."""
    pt = run.points[point_idx]
    taken_key = pt.candidates[pt.taken]
    alt_key = pt.candidates[alt]
    prefix = "crank:" if pt.label == "crank" else "resolve:"
    by_key = {e.key: e for e in run.events}
    taken_ev = by_key.get(prefix + taken_key)
    alt_ev = by_key.get(prefix + alt_key)
    if taken_ev is None or alt_ev is None:
        return False
    if alt_ev.index <= taken_ev.index:
        return False
    for ev in run.events[taken_ev.index : alt_ev.index]:
        if events_dependent(alt_ev, ev):
            return False
    return True


@dataclass
class Exploration:
    """Outcome of one :func:`explore` sweep."""

    target: str
    n: int
    seed: int
    runs: int = 0
    classes: int = 0
    pruned: int = 0
    revisits: int = 0
    reference: Optional[RunResult] = None
    divergence: Optional[Dict[str, Any]] = None

    @property
    def ok(self) -> bool:
        return self.divergence is None

    def summary(self) -> Dict[str, Any]:
        out = {
            "target": self.target,
            "n": self.n,
            "seed": self.seed,
            "runs": self.runs,
            "non_equivalent_schedules": self.classes,
            "dpor_pruned": self.pruned,
            "equivalent_revisits": self.revisits,
            "ok": self.ok,
        }
        if self.divergence is not None:
            out["divergence"] = self.divergence
        return out


def minimize_divergence(
    target: str, n: int, seed: int, choices: List[int], ref_parts: Dict
) -> List[int]:
    """ddmin-lite: zero out choices and strip the tail while the run
    still diverges from the reference fingerprint."""

    def diverges(c: List[int]) -> bool:
        return run_schedule(target, n, seed, c).parts != ref_parts

    best = list(choices)
    for i in range(len(best)):
        if best[i] == 0:
            continue
        trial = list(best)
        trial[i] = 0
        if diverges(trial):
            best = trial
    while best and best[-1] == 0:
        best.pop()
    return best


def explore(
    target: str,
    n: int,
    seed: int = 0,
    max_runs: int = 200,
    stop_on_divergence: bool = True,
) -> Exploration:
    """Stateless DFS over the schedule space with DPOR reduction.

    Runs the default schedule first (the reference fingerprint), then
    systematically flips one decision at a time, exploring each new
    prefix's subtree.  Every executed run's fingerprint is compared to
    the reference; the first mismatch is minimized into a replayable
    counterexample recorded on the returned :class:`Exploration`."""
    out = Exploration(target=target, n=n, seed=seed)
    ref = run_schedule(target, n, seed, [])
    out.reference = ref
    out.runs = 1
    seen_classes = {ref.canonical}

    # DFS stack of (prefix, run-to-derive-children-from or None)
    stack: List[Tuple[List[int], Optional[RunResult], int]] = [([], ref, 0)]
    while stack and out.runs < max_runs:
        prefix, run, floor = stack.pop()
        if run is None:
            run = run_schedule(target, n, seed, prefix)
            out.runs += 1
            if run.canonical in seen_classes:
                out.revisits += 1
            seen_classes.add(run.canonical)
            if run.parts != ref.parts:
                mini = minimize_divergence(
                    target, n, seed, list(run.trace), ref.parts
                )
                div_run = run_schedule(target, n, seed, mini)
                out.divergence = {
                    "choices": mini,
                    "reference_parts": ref.parts,
                    "divergent_parts": div_run.parts,
                    "first_divergence": first_divergence(ref, div_run),
                    "racing": RaceTracker.racing_pairs(
                        _tracker_of(div_run)
                    ) if div_run.events else [],
                }
                if stop_on_divergence:
                    break
        # derive children: flip each not-yet-branched decision (bounded:
        # the frontier stops growing once it could never be drained
        # within max_runs)
        for i in range(len(run.points) - 1, floor - 1, -1):
            if len(stack) + out.runs > max_runs * 4:
                break
            pt = run.points[i]
            for alt in range(1, pt.arity):
                if alt == pt.taken:
                    continue
                if _swap_prunable(run, i, alt):
                    out.pruned += 1
                    continue
                child = list(run.trace[:i]) + [alt]
                stack.append((child, None, i + 1))
                if len(stack) + out.runs > max_runs * 4:
                    break
    out.classes = len(seen_classes)
    return out


def _tracker_of(run: RunResult) -> RaceTracker:
    t = RaceTracker()
    t.events = run.events
    return t


# ---------------------------------------------------------------------------
# Counterexample files
# ---------------------------------------------------------------------------


def write_counterexample(path, exploration: Exploration) -> None:
    div = exploration.divergence
    if div is None:
        raise ValueError("exploration found no divergence")
    doc = {
        "version": 1,
        "target": exploration.target,
        "n": exploration.n,
        "seed": exploration.seed,
        "choices": div["choices"],
        "reference_parts": div["reference_parts"],
        "divergent_parts": div["divergent_parts"],
        "first_divergence": div["first_divergence"],
        "racing": div.get("racing", []),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=repr)
        f.write("\n")


def replay_counterexample(path) -> Dict[str, Any]:
    """Re-execute a counterexample file's reference and divergent
    schedules; report whether the recorded divergence reproduced
    exactly (same fingerprint pair, same first-divergent event)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    target, n, seed = doc["target"], doc["n"], doc["seed"]
    ref = run_schedule(target, n, seed, [])
    div = run_schedule(target, n, seed, doc["choices"])
    got_first = first_divergence(ref, div)
    reproduced = (
        json.loads(json.dumps(ref.parts, sort_keys=True, default=repr))
        == doc["reference_parts"]
        and json.loads(json.dumps(div.parts, sort_keys=True, default=repr))
        == doc["divergent_parts"]
        and got_first == doc["first_divergence"]
    )
    return {
        "reproduced": reproduced,
        "reference_parts": ref.parts,
        "divergent_parts": div.parts,
        "first_divergence": got_first,
        "recorded_first_divergence": doc["first_divergence"],
        "diverged": ref.parts != div.parts,
    }
