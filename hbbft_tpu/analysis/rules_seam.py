"""seam-race: state crossing the submit/resolve boundary must be blessed.

Scope: the pipelined dispatch layer (``ops/pipeline.py``,
``ops/backend.py``) and the array engine (``engine/``) — the code PR 3/5
made asynchronous.  A *submit-path* context runs between issuing a
dispatch and requesting its fetch (batch assembly, group sizing, chunk
staging); a *resolve-path* context runs when a deferred fetch delivers
(``on_result`` callbacks, returned resolvers, ``flush``/``_resolve``).
Under the bounded in-flight queue those two interleave in an order the
schedule controls, so any ``self`` attribute written on one side and
read on the other is schedule-sensitive state: its value at read time
depends on which pending dispatches have resolved.

The rule flags every such crossing.  Legal crossings carry a
``# lint: allow[seam-race] <why order cannot change observable results>``
suppression at the anchor line — making the seam inventory explicit and
reviewed (the dynamic explorer in ``analysis/schedules.py`` is the
matching runtime check).  Everything else must either ride the pipeline
API (the value travels inside the ``PendingDispatch``/``on_result``
plumbing, not ambient ``self`` state) or be write-once before submit
(assigned only in ``__init__``).

Classification is per class, name- and callgraph-based:

* submit seeds — methods named ``submit*``/``_submit*``/``dispatch*``/
  ``_dispatch*``/``*_deferred`` or whose body calls ``<x>.submit(...)``.
* resolve seeds — methods named ``resolve``/``_resolve``/``flush``/
  ``finish``/``fetch*``/``_fetch*`` or calling ``<x>.resolve()``/
  ``<x>.flush()``; nested functions/lambdas passed as ``on_result=`` or
  ``fetch=`` callbacks, named ``deliver``/``resume``/``resolve``/
  ``finish``, or returned from a submit-seeded function (deferred
  resolvers).
* tags flow caller→callee through same-class ``self.meth()`` calls to a
  fixpoint (a helper invoked while submitting is submit-path code); a
  context reachable from both sides contributes its accesses to both.

One finding per (class, attribute root): anchored at the earliest
offending access, naming a representative context on each side.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set, Tuple

from hbbft_tpu.analysis.dataflow import (
    Access,
    ClassSummary,
    FunctionSummary,
    paths_conflict,
    summarize_module,
)
from hbbft_tpu.analysis.engine import Finding, ModuleSource, Rule, register

#: the crash axis (net/crash.py) has the same two-sided shape: the LIVE
#: side (crank hooks logging the WAL/sent record, checkpointing) and the
#: RECOVERY side (_restart replaying against that record).  Live-side
#: methods seed "submit", recovery-side methods seed "resolve", so state
#: crossing checkpoint→replay is inventoried exactly like pipeline state
#: crossing submit→resolve.
SUBMIT_NAME = re.compile(
    r"(^|_)(submit|dispatch)|_deferred$|^(on_(deliver|send|input|enqueue)|_?checkpoint)"
)
RESOLVE_NAME = re.compile(r"^(resolve|_resolve|flush|finish|_?fetch|_restart|_replay)")
#: nested-callable names that identify a delivery/resolver closure
RESOLVER_NESTED = ("deliver", "resume", "resolve", "finish")
#: call kwargs that hand a closure to the pipeline as a resolve callback
CALLBACK_KWARGS = ("on_result", "fetch")


class _Context:
    """One function body (method or nested closure) with its seam tags."""

    __slots__ = ("summary", "tags", "owner_method", "parent", "is_resolver",
                 "is_returned")

    def __init__(self, summary: FunctionSummary, owner_method: str) -> None:
        self.summary = summary
        self.tags: Set[str] = set()
        self.owner_method = owner_method  # class-method name it lives under
        self.parent: Optional["_Context"] = None
        self.is_resolver = False  # callback/resolver closure
        self.is_returned = False  # returned from its enclosing function


def _seed_method(s: FunctionSummary) -> Set[str]:
    tags: Set[str] = set()
    if SUBMIT_NAME.search(s.name):
        tags.add("submit")
    if RESOLVE_NAME.search(s.name):
        tags.add("resolve")
    for site in s.calls:
        if site.name == "submit" and not site.on_self:
            tags.add("submit")
        elif site.name in ("resolve", "flush") and not site.on_self:
            tags.add("resolve")
    return tags


def _collect_contexts(cls: ClassSummary) -> List[_Context]:
    """Methods + (recursively) their nested closures, tags seeded."""
    out: List[_Context] = []

    def add_nested(parent: _Context, s: FunctionSummary) -> None:
        # only closures handed to the pipeline as DELIVERY callbacks
        # (on_result=/fetch= kwargs) are resolvers; a closure passed
        # POSITIONALLY — to a staging helper or as submit()'s launch
        # thunk — runs at submit time
        callback_names = {
            nm
            for (callee, slot, nm) in s.callbacks
            if slot in CALLBACK_KWARGS
        }
        for name, nested in s.nested.items():
            ctx = _Context(nested, parent.owner_method)
            ctx.parent = parent
            ctx.is_returned = name in s.returned_callables
            ctx.is_resolver = (
                name in callback_names or nested.name in RESOLVER_NESTED
            )
            if ctx.is_resolver:
                ctx.tags.add("resolve")
            ctx.tags |= _seed_method(nested)
            out.append(ctx)
            add_nested(ctx, nested)

    for mname, s in cls.methods.items():
        if mname == "__init__":
            continue  # construction is the write-once baseline
        ctx = _Context(s, mname)
        ctx.tags |= _seed_method(s)
        out.append(ctx)
        add_nested(ctx, s)
    return out


def _propagate(cls: ClassSummary, contexts: List[_Context]) -> None:
    """Tag flow to a fixpoint: caller→callee through same-class
    ``self.meth()`` calls, enclosing→nested for inline helpers, and
    resolver promotion for closures returned by a submit-tagged function
    (a deferred resolver)."""
    by_method: Dict[str, List[_Context]] = {}
    for ctx in contexts:
        if ctx.parent is None:
            by_method.setdefault(ctx.summary.name, []).append(ctx)
    changed = True
    while changed:
        changed = False

        def grow(ctx: _Context, tags: Set[str]) -> None:
            nonlocal changed
            new = tags - ctx.tags
            if new:
                ctx.tags |= new
                changed = True

        for ctx in contexts:
            if ctx.parent is not None:
                if ctx.is_returned and "submit" in ctx.parent.tags:
                    if not ctx.is_resolver:
                        ctx.is_resolver = True
                        grow(ctx, {"resolve"})
                if not ctx.is_resolver:
                    # inline helper: runs in the enclosing context
                    grow(ctx, ctx.parent.tags)
            if not ctx.tags:
                continue
            for site in ctx.summary.calls:
                if not site.on_self:
                    continue
                for callee in by_method.get(site.name, ()):
                    grow(callee, ctx.tags)


@register
class SeamRaceRule(Rule):
    rule_id = "seam-race"
    scope = (
        "hbbft_tpu/ops/pipeline.py",
        "hbbft_tpu/ops/backend.py",
        "hbbft_tpu/engine/",
        "hbbft_tpu/net/crash.py",
        # the mesh backend seam (ROADMAP item 1): cross-shard submit /
        # resolve ordering must hold before the pjit scale-out lands
        "hbbft_tpu/parallel/",
        # the control loop's hook crossing (PR 12): the traffic drivers'
        # admission/sampling methods call mempool ``submit`` (submit-
        # seeded), and any future deferred/resolver context added to the
        # tracker→controller→engine path gets inventoried here — state
        # shared between those sides must ride the hook APIs
        # (batch_size_provider / Observation), not ambient self attrs
        "hbbft_tpu/traffic/driver.py",
        "hbbft_tpu/control/",
        # PR 19: the device erasure/hash plane — its delivery callbacks
        # (rs_enc/rs_dec/merkle dispatch kinds) must keep state in
        # closure locals, never ambient self attrs
        "hbbft_tpu/ops/gf256.py",
        "hbbft_tpu/ops/sha256.py",
        # PR 20: the fused tower chain rides the same dispatch seam —
        # any future module-level mutable routing state (caches, mode
        # latches) shared with delivery callbacks gets inventoried here
        "hbbft_tpu/ops/tower_fused.py",
        "hbbft_tpu/ops/pairing_chain.py",
    )

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        findings: List[Finding] = []
        summary = summarize_module(mod)
        for cls in summary.classes.values():
            findings.extend(self._check_class(mod, cls))
        return findings

    def _check_class(self, mod: ModuleSource, cls: ClassSummary) -> List[Finding]:
        contexts = _collect_contexts(cls)
        _propagate(cls, contexts)
        method_names = set(cls.methods)

        # accesses per seam side: (path, line, col, context qualname)
        sides: Dict[str, Dict[str, List[Tuple[Access, str]]]] = {
            "submit": {"read": [], "write": []},
            "resolve": {"read": [], "write": []},
        }
        for ctx in contexts:
            for tag in ctx.tags:
                for acc in ctx.summary.reads:
                    if acc.root in method_names:
                        continue  # bound-method reference, not state
                    sides[tag]["read"].append((acc, ctx.summary.qualname))
                for acc in ctx.summary.writes:
                    sides[tag]["write"].append((acc, ctx.summary.qualname))

        findings: List[Finding] = []
        seen_roots: Set[str] = set()
        # deterministic: iterate submit-side accesses in source order
        ordered = sorted(
            [(a, q, "write") for a, q in sides["submit"]["write"]]
            + [(a, q, "read") for a, q in sides["submit"]["read"]],
            key=lambda t: (t[0].line, t[0].col, t[0].path),
        )
        for acc, qual, kind in ordered:
            if acc.root in seen_roots:
                continue
            other_kind = "read" if kind == "write" else "write"
            # a partner in the SAME context is a sync point's own
            # sequential access pattern, not a seam crossing — require
            # the two sides to live in different function bodies
            partners = sorted(
                (
                    (b, bq)
                    for b, bq in sides["resolve"][other_kind]
                    if bq != qual and paths_conflict(acc.path, b.path)
                ),
                key=lambda t: (t[0].line, t[0].col),
            )
            if not partners:
                continue
            partner, partner_qual = partners[0]
            seen_roots.add(acc.root)
            if kind == "write":
                msg = (
                    f"self.{acc.root} is written on the submit path "
                    f"({qual}) and read on the resolve path ({partner_qual})"
                )
            else:
                msg = (
                    f"self.{acc.root} is read on the submit path ({qual}) "
                    f"and written on the resolve path ({partner_qual})"
                )
            findings.append(
                Finding(
                    self.rule_id,
                    mod.path,
                    acc.line,
                    acc.col,
                    msg
                    + "; seam-crossing state must ride the pipeline API "
                    "(on_result/PendingDispatch) or be write-once before "
                    "submit",
                )
            )
        return findings


def seam_contexts_for_testing(
    mod: ModuleSource, class_name: str
) -> Dict[str, Set[str]]:
    """Expose the per-context tag classification (tests + docs)."""
    summary = summarize_module(mod)
    cls = summary.classes[class_name]
    contexts = _collect_contexts(cls)
    _propagate(cls, contexts)
    return {c.summary.qualname: set(c.tags) for c in contexts}
