"""Snapshot/WAL-replay soundness rules (the static twin of
``crash:replay_divergence``).

Three rule families over the :mod:`hbbft_tpu.analysis.stateinv`
inventory:

``snapshot-coverage``
    On every class in a ``_STATE_MODULES`` module: an attribute assigned
    a statically-unserializable callable (lambda, nested def, bound
    method) must be declared in ``_SNAPSHOT_ENV_ATTRS`` — ``save_node``
    rejects callables in state, so an undeclared one is a checkpoint
    crash waiting for the first snapshot.  Conversely every declared env
    attr must be *real* (defined, written, or read somewhere in the
    class) and must have a class-body default — restore drops env attrs
    and falls back to the class attribute, so a declaration without a
    default is a latent ``AttributeError`` on the restored object.

``replay-purity``
    Code reachable from the WAL replay path (``net/crash.py``
    ``_restart``/``_replay*`` seeds, propagated caller→callee to
    fixpoint like seam-race) must not: read a checkpoint-detached env
    attr without a None/truthiness guard (a restored node sees the class
    default, not the pre-crash value), invoke a detached hook at all
    (tracer, ``batch_listeners``, ``batch_size_provider``, probes —
    hooks are environment and must not steer replay), draw entropy
    outside the logged rng stream, or read wall clocks.  Every finding
    names its reach chain back to the seed.

``hook-detachment``
    An attribute that receives an externally-supplied callable (the
    value flows from a method parameter) *and* is invoked as a hook must
    be env-declared, or it rides into snapshots and ``save_node`` dies.
    Module-level functions are exempt at the encoder (serialized by
    name), so a justified exception carries a reasoned suppression.

Scope: ``snapshot-coverage``/``hook-detachment`` run exactly over the
``_STATE_MODULES`` registry (parsed statically from
``utils/snapshot.py``).  ``replay-purity`` propagates through the wider
deterministic core (protocols/net/core/traffic/control/engine/utils plus
the replay-adjacent obs trio) but deliberately not ``crypto/``/``ops/``
(backend compute has its own determinism contract) nor ``analysis/``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from hbbft_tpu.analysis.dataflow import (
    MUTATING_METHODS,
    ClassSummary,
    FunctionSummary,
)
from hbbft_tpu.analysis.engine import Finding, LintProject, Rule, register
from hbbft_tpu.analysis.stateinv import (
    ClassInventory,
    inventory_module,
    module_summary,
    parse_env_attrs,
    state_module_paths,
)

# ---------------------------------------------------------------------------
# snapshot-coverage
# ---------------------------------------------------------------------------


@register
class SnapshotCoverageRule(Rule):
    """Callable-valued state must be env-declared; env declarations must
    be real and defaulted."""

    rule_id = "snapshot-coverage"

    def check_project(self, project: LintProject) -> List[Finding]:
        out: List[Finding] = []
        for path in state_module_paths(project):
            mod = project.module(path)
            if mod is None:
                continue
            for inv in inventory_module(mod):
                out.extend(self._check_class(inv))
        return out

    def _check_class(self, inv: ClassInventory) -> List[Finding]:
        out: List[Finding] = []
        for attr in sorted(inv.attrs):
            if attr in inv.env_attrs:
                continue
            for w in inv.attrs[attr].writes:
                kind = w.callable_kind
                if kind is None:
                    continue
                out.append(
                    Finding(
                        self.rule_id,
                        inv.path,
                        w.line,
                        w.col,
                        f"self.{attr} on state class {inv.name} is assigned "
                        f"a {kind} ({w.context}) but is not declared in "
                        f"_SNAPSHOT_ENV_ATTRS; save_node rejects callables "
                        f"in state — declare it environment or store "
                        f"serializable state",
                    )
                )
                break  # one finding per attr: the minimal repro site
        for attr in inv.env_attrs:
            line = inv.env_line or inv.lineno
            if not inv.is_real(attr):
                out.append(
                    Finding(
                        self.rule_id,
                        inv.path,
                        line,
                        0,
                        f"_SNAPSHOT_ENV_ATTRS on {inv.name} declares "
                        f"{attr!r} but the class never defines, writes, or "
                        f"reads it; remove the dead declaration",
                    )
                )
            elif attr not in inv.class_defaults:
                out.append(
                    Finding(
                        self.rule_id,
                        inv.path,
                        line,
                        0,
                        f"env attr {attr!r} on {inv.name} has no class-body "
                        f"default; restore drops env attrs and falls back "
                        f"to the class attribute, so a restored instance "
                        f"would raise AttributeError",
                    )
                )
        return out


# ---------------------------------------------------------------------------
# hook-detachment
# ---------------------------------------------------------------------------


@register
class HookDetachmentRule(Rule):
    """Externally-supplied, invoked callables must be env-declared."""

    rule_id = "hook-detachment"

    def check_project(self, project: LintProject) -> List[Finding]:
        out: List[Finding] = []
        for path in state_module_paths(project):
            mod = project.module(path)
            if mod is None:
                continue
            for inv in inventory_module(mod):
                out.extend(self._check_class(inv))
        return out

    def _check_class(self, inv: ClassInventory) -> List[Finding]:
        out: List[Finding] = []
        for attr in sorted(inv.hook_calls):
            if attr in inv.env_attrs or attr in inv.method_names:
                continue
            rec = inv.attrs.get(attr)
            if rec is None:
                continue
            site = next(
                (w for w in rec.writes if w.value == "param"), None
            )
            if site is None:
                continue
            out.append(
                Finding(
                    self.rule_id,
                    inv.path,
                    site.line,
                    site.col,
                    f"self.{attr} on state class {inv.name} receives an "
                    f"externally-supplied value ({site.context} parameter "
                    f"{', '.join(site.params)}) and is invoked as a hook; "
                    f"declare it in _SNAPSHOT_ENV_ATTRS so snapshots "
                    f"detach it (module-level functions serialize by name "
                    f"and may be suppressed with a reason)",
                )
            )
        return out


# ---------------------------------------------------------------------------
# replay-purity
# ---------------------------------------------------------------------------

#: methods in net/crash.py that start a WAL replay
REPLAY_SEED = re.compile(r"^(_restart|_replay\w*)$")
SEED_PATH_SUFFIX = "net/crash.py"

#: modules the reach propagation walks (posix path prefixes)
REACH_SCOPE: Tuple[str, ...] = (
    "hbbft_tpu/protocols/",
    "hbbft_tpu/net/",
    "hbbft_tpu/core/",
    "hbbft_tpu/traffic/",
    "hbbft_tpu/control/",
    "hbbft_tpu/engine/",
    "hbbft_tpu/utils/",
    # replay-adjacent observability: the critpath recorder runs inside
    # the recovery window, so its code rides the purity contract
    "hbbft_tpu/obs/critpath.py",
    "hbbft_tpu/obs/timeseries.py",
    "hbbft_tpu/obs/flight.py",
)

#: callee names never resolved across classes — builtin container /
#: string verbs and ubiquitous tiny helpers whose name-based resolution
#: would wire the whole package together
SKIP_CALL_NAMES: frozenset = MUTATING_METHODS | frozenset(
    {
        "get", "items", "keys", "values", "copy", "join", "split",
        "startswith", "endswith", "strip", "encode", "format",
        "index", "count", "isoformat", "hexdigest", "to_bytes",
        "from_bytes", "bit_length", "most_common", "popleft",
        "appendleft", "read", "write", "flush", "close", "len",
        "repr", "str", "int", "bytes", "sorted", "min", "max",
        "isinstance", "hasattr", "getattr", "setattr", "tuple",
        "list", "dict", "set", "frozenset", "range", "enumerate",
        "zip", "map", "filter", "any", "all", "sum", "abs", "round",
        "print", "super", "type", "id", "hash", "iter", "next",
        "__class__",
    }
)

ENTROPY_PREFIXES = (
    "random.", "np.random.", "numpy.random.", "secrets.",
)
ENTROPY_EXACT = frozenset({"os.urandom", "random", "uuid.uuid4"})
WALLCLOCK_PREFIXES = ("time.",)
WALLCLOCK_EXACT = frozenset(
    {
        "datetime.now", "datetime.utcnow", "datetime.today",
        "datetime.datetime.now", "datetime.datetime.utcnow",
        "date.today", "datetime.date.today",
    }
)


class _Ctx:
    """One function body (method, module function, or nested closure) in
    the reach graph."""

    __slots__ = (
        "path", "cls", "summary", "env", "reached", "via", "children"
    )

    def __init__(
        self,
        path: str,
        cls: Optional[ClassSummary],
        summary: FunctionSummary,
        env: Tuple[str, ...],
    ) -> None:
        self.path = path
        self.cls = cls
        self.summary = summary
        self.env = env
        self.reached = False
        self.via: Optional["_Ctx"] = None
        self.children: List["_Ctx"] = []

    @property
    def qualname(self) -> str:
        return self.summary.qualname

    def chain(self) -> List[str]:
        out, cur = [], self
        while cur is not None and len(out) < 16:
            out.append(cur.qualname)
            cur = cur.via
        return list(reversed(out))


def _local_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk ``fn`` without descending into nested function bodies (those
    are their own contexts with their own guards)."""
    body = [fn.body] if isinstance(fn, ast.Lambda) else list(fn.body)
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _self_root(node: ast.AST) -> Optional[str]:
    chain = node
    while isinstance(chain, ast.Attribute):
        inner = chain.value
        if isinstance(inner, ast.Name) and inner.id == "self":
            return chain.attr
        chain = inner
    return None


def _guarded_env_attrs(fn: ast.AST, env: Tuple[str, ...]) -> Set[str]:
    """Env attrs whose value is tested (``if self.x is not None``, plain
    truthiness, ``self.x and ...``) anywhere in ``fn``'s own body: reads
    of those attrs in this function are guard-aware and allowed."""
    guards: Set[str] = set()
    tests: List[ast.AST] = []
    for node in _local_walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            tests.append(node.test)
        elif isinstance(node, ast.IfExp):
            tests.append(node.test)
        elif isinstance(node, ast.Assert):
            tests.append(node.test)
        elif isinstance(node, (ast.BoolOp, ast.Compare)):
            tests.append(node)
        elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            tests.append(node.operand)
    for t in tests:
        for sub in ast.walk(t):
            root = _self_root(sub)
            if root is not None and root in env:
                guards.add(root)
    return guards


def _env_invocations(
    fn: ast.AST, env: Tuple[str, ...]
) -> Dict[str, int]:
    """Env attrs *invoked* in ``fn``: direct calls ``self.x(...)``,
    method calls ``self.x.m(...)``, element-wise ``for f in self.x``
    loops that call the loop variable."""
    out: Dict[str, int] = {}

    def note(attr: str, line: int) -> None:
        if attr not in out or line < out[attr]:
            out[attr] = line

    for node in _local_walk(fn):
        if isinstance(node, ast.Call):
            root = _self_root(node.func)
            if root is not None and root in env:
                note(root, node.lineno)
        elif isinstance(node, ast.For):
            root = _self_root(node.iter)
            if (
                root is not None
                and root in env
                and isinstance(node.target, ast.Name)
            ):
                loopvar = node.target.id
                for inner in ast.walk(node):
                    if (
                        isinstance(inner, ast.Call)
                        and isinstance(inner.func, ast.Name)
                        and inner.func.id == loopvar
                    ):
                        note(root, node.iter.lineno)
                        break
    return out


@register
class ReplayPurityRule(Rule):
    """WAL replay must be a closed function of checkpoint + WAL + logged
    rng: no detached-hook effects, no ambient entropy, no wall clocks."""

    rule_id = "replay-purity"

    def check_project(self, project: LintProject) -> List[Finding]:
        ctxs = self._build_contexts(project)
        self._propagate(ctxs)
        out: List[Finding] = []
        for ctx in ctxs:
            if ctx.reached:
                out.extend(self._check_ctx(ctx))
        return out

    # -- context graph ----------------------------------------------------

    def _build_contexts(self, project: LintProject) -> List[_Ctx]:
        ctxs: List[_Ctx] = []
        for path in sorted(project.modules):
            if not path.startswith(REACH_SCOPE):
                continue
            mod = project.modules[path]
            if getattr(mod, "syntax_error", None) is not None:
                continue
            summary = module_summary(mod)
            for cls in sorted(
                summary.classes.values(), key=lambda c: c.node.lineno
            ):
                env, _ = parse_env_attrs(cls.node)
                for key in sorted(cls.methods):
                    self._add_ctx(
                        ctxs, path, cls, cls.methods[key], env
                    )
            for name in sorted(summary.functions):
                self._add_ctx(
                    ctxs, path, None, summary.functions[name], ()
                )
        return ctxs

    def _add_ctx(
        self,
        ctxs: List[_Ctx],
        path: str,
        cls: Optional[ClassSummary],
        summary: FunctionSummary,
        env: Tuple[str, ...],
    ) -> _Ctx:
        ctx = _Ctx(path, cls, summary, env)
        ctxs.append(ctx)
        for key in sorted(summary.nested):
            ctx.children.append(
                self._add_ctx(ctxs, path, cls, summary.nested[key], env)
            )
        return ctx

    def _propagate(self, ctxs: List[_Ctx]) -> None:
        by_name: Dict[str, List[_Ctx]] = {}
        by_class: Dict[Tuple[str, str, str], List[_Ctx]] = {}
        for ctx in ctxs:
            by_name.setdefault(ctx.summary.name, []).append(ctx)
            if ctx.cls is not None:
                key = (ctx.path, ctx.cls.name, ctx.summary.name)
                by_class.setdefault(key, []).append(ctx)

        work: List[_Ctx] = []

        def reach(ctx: _Ctx, via: Optional[_Ctx]) -> None:
            if ctx.reached:
                return
            ctx.reached = True
            ctx.via = via
            work.append(ctx)

        for ctx in ctxs:
            if (
                ctx.cls is not None
                and ctx.path.endswith(SEED_PATH_SUFFIX)
                and REPLAY_SEED.match(ctx.summary.name)
            ):
                reach(ctx, None)
        while work:
            ctx = work.pop()
            for child in ctx.children:
                reach(child, ctx)
            for site in ctx.summary.calls:
                if site.on_self and ctx.cls is not None:
                    for tgt in by_class.get(
                        (ctx.path, ctx.cls.name, site.name), []
                    ):
                        reach(tgt, ctx)
                    continue
                if site.name in SKIP_CALL_NAMES or site.name.startswith(
                    "__"
                ):
                    continue
                for tgt in by_name.get(site.name, []):
                    reach(tgt, ctx)

    # -- checks ------------------------------------------------------------

    def _via(self, ctx: _Ctx) -> str:
        chain = ctx.chain()
        if len(chain) > 4:
            chain = chain[:2] + ["…"] + chain[-1:]
        return "reached via " + " → ".join(chain)

    def _check_ctx(self, ctx: _Ctx) -> List[Finding]:
        out: List[Finding] = []
        fn = ctx.summary.node
        via = self._via(ctx)
        if ctx.env:
            invoked = _env_invocations(fn, ctx.env)
            for attr in sorted(invoked):
                out.append(
                    Finding(
                        self.rule_id,
                        ctx.path,
                        invoked[attr],
                        0,
                        f"replay path invokes checkpoint-detached hook "
                        f"self.{attr} in {ctx.qualname} ({via}); detached "
                        f"hooks must not steer WAL replay — route the "
                        f"effect through logged state or suppress with "
                        f"the replay-safety argument",
                    )
                )
            guarded = _guarded_env_attrs(fn, ctx.env)
            flagged: Set[str] = set(invoked)
            for r in ctx.summary.reads:
                attr = r.root
                if (
                    attr not in ctx.env
                    or attr in guarded
                    or attr in flagged
                ):
                    continue
                flagged.add(attr)
                out.append(
                    Finding(
                        self.rule_id,
                        ctx.path,
                        r.line,
                        r.col,
                        f"replay-path read of checkpoint-detached env attr "
                        f"self.{attr} in {ctx.qualname} ({via}); a restored "
                        f"node sees the class default — guard the read or "
                        f"carry the value in snapshotted state",
                    )
                )
        seen_dotted: Set[str] = set()
        for site in ctx.summary.calls:
            dotted = site.dotted
            if dotted is None or dotted in seen_dotted:
                continue
            if dotted.startswith("self.") or dotted.startswith("cls."):
                continue
            if dotted.startswith(ENTROPY_PREFIXES) or dotted in ENTROPY_EXACT:
                seen_dotted.add(dotted)
                out.append(
                    Finding(
                        self.rule_id,
                        ctx.path,
                        site.line,
                        site.col,
                        f"replay-path entropy outside the logged rng "
                        f"stream: {dotted}() in {ctx.qualname} ({via}); "
                        f"replay must draw from the WAL-logged rng only",
                    )
                )
            elif (
                dotted.startswith(WALLCLOCK_PREFIXES)
                or dotted in WALLCLOCK_EXACT
            ):
                seen_dotted.add(dotted)
                out.append(
                    Finding(
                        self.rule_id,
                        ctx.path,
                        site.line,
                        site.col,
                        f"replay-path wall-clock read: {dotted}() in "
                        f"{ctx.qualname} ({via}); replay timing must be "
                        f"virtual-clock only",
                    )
                )
        return out


def replay_reach_for_testing(
    project: LintProject,
) -> Dict[str, Tuple[str, ...]]:
    """qualname -> reach chain for every reached context (test hook)."""
    rule = ReplayPurityRule()
    ctxs = rule._build_contexts(project)
    rule._propagate(ctxs)
    return {
        f"{c.path}:{c.qualname}": tuple(c.chain())
        for c in ctxs
        if c.reached
    }
