"""Per-class mutable-state inventory for the snapshot/WAL-replay rules.

PR 11 made crash recovery "restore a checkpoint, then bit-identical WAL
replay"; :mod:`hbbft_tpu.utils.snapshot` enforces the *dynamic* half of
that contract (callables are rejected at encode, ``_SNAPSHOT_ENV_ATTRS``
drops environment hooks).  This module is the *static* half's substrate:
a pure-AST inventory of every ``self.x`` write site in a class —

* classified **init-only** vs **runtime-mutated** (a write is init-only
  when it happens in ``__init__`` or a helper reachable *only* from
  ``__init__``; writes inside nested closures are always runtime, since
  a closure built in ``__init__`` may run much later);
* classified by **value shape**: lambda / nested def / bound method
  (statically unserializable), parameter-sourced (an externally supplied
  object — the hook-detachment signal), or plain;
* cross-referenced with the class's ``_SNAPSHOT_ENV_ATTRS`` declaration
  and its class-body defaults (a restored instance falls back to the
  class attribute for every env attr, so a declaration without a default
  is a latent ``AttributeError``);
* annotated with **hook-call** sites: attributes invoked directly
  (``self.x(...)``) or element-wise (``for f in self.x: ... f(...)``).

Everything is built on :mod:`hbbft_tpu.analysis.dataflow` def-use
summaries (so one-level aliases like ``c = self.counters`` resolve), plus
a small value-expression walk of our own — the dataflow summaries do not
retain assignment right-hand sides.

The ``_STATE_MODULES`` registry itself is read *statically* from
``hbbft_tpu/utils/snapshot.py`` (from the lint project when the file is
loaded, from disk otherwise), so the linter keeps its no-import
guarantee and unit tests with synthetic module sets still resolve the
real registry.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from hbbft_tpu.analysis.dataflow import (
    ClassSummary,
    FunctionSummary,
    ModuleSummary,
    summarize_module,
)
from hbbft_tpu.analysis.engine import LintProject, ModuleSource

#: repo-relative path of the snapshot class registry
STATE_REGISTRY_PATH = "hbbft_tpu/utils/snapshot.py"

#: class attribute naming checkpoint-detached environment attrs
ENV_DECL = "_SNAPSHOT_ENV_ATTRS"


# ---------------------------------------------------------------------------
# Registry / declaration parsing
# ---------------------------------------------------------------------------


def state_module_paths(project: LintProject) -> Tuple[str, ...]:
    """Repo-relative paths of every ``_STATE_MODULES`` module, parsed
    statically from the snapshot registry (never imported)."""
    mod = project.module(STATE_REGISTRY_PATH)
    if mod is not None:
        tree = mod.tree
    else:
        p = project.repo_root / STATE_REGISTRY_PATH
        if not p.exists():
            return ()
        tree = ast.parse(p.read_text(encoding="utf-8"))
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "_STATE_MODULES"
            for t in node.targets
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            out = []
            for el in node.value.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.append(el.value.replace(".", "/") + ".py")
            return tuple(out)
    return ()


def parse_env_attrs(cls_node: ast.ClassDef) -> Tuple[Tuple[str, ...], Optional[int]]:
    """``(names, line)`` of the class-body ``_SNAPSHOT_ENV_ATTRS``
    declaration, or ``((), None)`` when the class has none."""
    for item in cls_node.body:
        if not isinstance(item, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == ENV_DECL for t in item.targets
        ):
            continue
        if isinstance(item.value, (ast.Tuple, ast.List)):
            names = tuple(
                el.value
                for el in item.value.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            )
            return names, item.lineno
        return (), item.lineno
    return (), None


def class_body_defaults(cls_node: ast.ClassDef) -> Set[str]:
    """Names bound at class-body level (plain and annotated assignments
    with a value — i.e. real defaults, not bare annotations)."""
    out: Set[str] = set()
    for item in cls_node.body:
        if isinstance(item, ast.Assign):
            for t in item.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(item, ast.AnnAssign) and item.value is not None:
            if isinstance(item.target, ast.Name):
                out.add(item.target.id)
    return out


# ---------------------------------------------------------------------------
# Inventory data model
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class WriteSite:
    """One ``self.x`` (or aliased) write."""

    line: int
    col: int
    context: str  # qualname of the writing function
    in_init: bool  # on the __init__-only call path
    value: str  # "lambda" | "def" | "bound-method" | "param" | "plain"
    params: Tuple[str, ...] = ()  # parameter names feeding a "param" write

    @property
    def callable_kind(self) -> Optional[str]:
        """Human word for a statically-unserializable value, else None."""
        return {
            "lambda": "lambda",
            "def": "nested function",
            "bound-method": "bound method",
        }.get(self.value)


@dataclass(slots=True)
class AttrRecord:
    """Every write/read of one attribute root within a class."""

    name: str
    writes: List[WriteSite] = field(default_factory=list)
    read_lines: List[int] = field(default_factory=list)

    @property
    def init_only(self) -> bool:
        return bool(self.writes) and all(w.in_init for w in self.writes)

    @property
    def runtime_writes(self) -> List[WriteSite]:
        return [w for w in self.writes if not w.in_init]


@dataclass(slots=True)
class ClassInventory:
    """The full mutable-state picture of one class."""

    name: str
    path: str
    lineno: int
    env_attrs: Tuple[str, ...]
    env_line: Optional[int]
    class_defaults: Set[str]
    method_names: Set[str]
    attrs: Dict[str, AttrRecord]
    #: attr -> line of the first direct (``self.x(...)``) or element-wise
    #: (``for f in self.x: ... f(...)``) invocation
    hook_calls: Dict[str, int]

    def is_real(self, attr: str) -> bool:
        """Does ``attr`` exist anywhere in the class — as a default, a
        write, a read, or a hook call?"""
        rec = self.attrs.get(attr)
        return (
            attr in self.class_defaults
            or attr in self.hook_calls
            or (rec is not None and bool(rec.writes or rec.read_lines))
        )


# ---------------------------------------------------------------------------
# init-path computation
# ---------------------------------------------------------------------------


def init_path_methods(cls: ClassSummary) -> Set[str]:
    """Method names executed only during construction: ``__init__`` plus
    every helper whose callers are all already on the init path.  A
    method with no same-class callers is an entry point (runtime)."""
    callers: Dict[str, Set[str]] = {}
    for key, m in cls.methods.items():
        for site in m.calls:
            if site.on_self:
                callers.setdefault(site.name, set()).add(m.name)
    init: Set[str] = set()
    if "__init__" in cls.methods:
        init.add("__init__")
    changed = True
    while changed:
        changed = False
        for key, m in cls.methods.items():
            nm = m.name
            if nm in init:
                continue
            who = callers.get(nm)
            if who and who <= init:
                init.add(nm)
                changed = True
    return init


# ---------------------------------------------------------------------------
# Value-expression classification
# ---------------------------------------------------------------------------


def _self_attr_root(node: ast.AST) -> Optional[Tuple[str, int, int]]:
    """``(root, line, col)`` when ``node`` is an attribute chain rooted at
    ``self`` (``self.x``, ``self.x.y``...)."""
    chain = node
    while isinstance(chain, ast.Attribute):
        inner = chain.value
        if isinstance(inner, ast.Name) and inner.id == "self":
            return chain.attr, node.lineno, node.col_offset
        chain = inner
    return None


def _classify_value(
    value: ast.AST,
    nested_defs: Set[str],
    method_names: Set[str],
    params: Set[str],
) -> Tuple[str, Tuple[str, ...]]:
    """Shape of an assignment RHS: see :class:`WriteSite`."""
    if isinstance(value, ast.Lambda):
        return "lambda", ()
    if isinstance(value, ast.Name) and value.id in nested_defs:
        return "def", ()
    if (
        isinstance(value, ast.Attribute)
        and isinstance(value.value, ast.Name)
        and value.value.id == "self"
        and value.attr in method_names
    ):
        return "bound-method", ()
    hit = tuple(
        sorted(
            {
                n.id
                for n in ast.walk(value)
                if isinstance(n, ast.Name) and n.id in params
            }
        )
    )
    if hit:
        return "param", hit
    return "plain", ()


def _scan_method(
    method_node: ast.AST, method_names: Set[str]
) -> Tuple[
    Dict[Tuple[int, int], Tuple[str, Tuple[str, ...]]], Dict[str, int]
]:
    """One walk of ``method_node`` collecting both value shapes and hook
    calls (the walk is the cost; four separate passes doubled lint wall).

    Returns ``(value_kinds, hook_calls)``: value_kinds maps the (line,
    col) of each direct ``self.x`` assignment target to the RHS shape
    (coordinates are the target Attribute node's, matching the dataflow
    write Access for the same site); hook_calls maps attr roots invoked
    directly (``self.x(...)``, x not a method) or element-wise (``for f
    in self.x: ... f(...)``) to the first such line.
    """
    params: Set[str] = set()
    nested: Set[str] = set()
    assigns: List[Tuple[ast.AST, ast.AST]] = []  # (target, value)
    hook_calls: Dict[str, int] = {}

    def note(attr: str, line: int) -> None:
        if attr not in hook_calls or line < hook_calls[attr]:
            hook_calls[attr] = line

    for node in ast.walk(method_node):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            a = node.args
            for arg in (
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            ):
                params.add(arg.arg)
            if a.vararg:
                params.add(a.vararg.arg)
            if a.kwarg:
                params.add(a.kwarg.arg)
            if node is not method_node and not isinstance(node, ast.Lambda):
                nested.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                assigns.append((t, node.value))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                assigns.append((node.target, node.value))
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and f.attr not in method_names
            ):
                note(f.attr, node.lineno)
        elif isinstance(node, ast.For):
            hit = _self_attr_root(node.iter)
            if hit is None or not isinstance(node.target, ast.Name):
                continue
            loopvar = node.target.id
            for inner in ast.walk(node):
                if (
                    isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Name)
                    and inner.func.id == loopvar
                ):
                    note(hit[0], node.iter.lineno)
                    break
    params.discard("self")
    kinds: Dict[Tuple[int, int], Tuple[str, Tuple[str, ...]]] = {}
    for t, value in assigns:
        hit = _self_attr_root(t)
        if hit is None:
            continue
        _, line, col = hit
        kinds[(line, col)] = _classify_value(
            value, nested, method_names, params
        )
    return kinds, hook_calls


# ---------------------------------------------------------------------------
# Per-class / per-module inventory
# ---------------------------------------------------------------------------


def inventory_class(
    mod: ModuleSource, cls: ClassSummary
) -> ClassInventory:
    """Build the full inventory of one class from its dataflow summary."""
    env_attrs, env_line = parse_env_attrs(cls.node)
    method_names = {m.name for m in cls.methods.values()}
    init_path = init_path_methods(cls)
    inv = ClassInventory(
        name=cls.name,
        path=mod.path,
        lineno=cls.node.lineno,
        env_attrs=env_attrs,
        env_line=env_line,
        class_defaults=class_body_defaults(cls.node),
        method_names=method_names,
        attrs={},
        hook_calls={},
    )

    def rec(attr: str) -> AttrRecord:
        r = inv.attrs.get(attr)
        if r is None:
            r = inv.attrs[attr] = AttrRecord(name=attr)
        return r

    def collect(
        summary: FunctionSummary,
        kinds: Dict[Tuple[int, int], Tuple[str, Tuple[str, ...]]],
        in_init: bool,
    ) -> None:
        for w in summary.writes:
            value, params = kinds.get((w.line, w.col), ("plain", ()))
            rec(w.root).writes.append(
                WriteSite(
                    line=w.line,
                    col=w.col,
                    context=summary.qualname,
                    in_init=in_init,
                    value=value,
                    params=params,
                )
            )
        for r in summary.reads:
            rec(r.root).read_lines.append(r.line)
        # Closures share self but run at call time, not def time: their
        # writes are runtime-mutated even when defined under __init__.
        for sub in summary.nested.values():
            collect(sub, kinds, in_init=False)

    for key, m in cls.methods.items():
        kinds, hooks = _scan_method(m.node, method_names)
        collect(m, kinds, in_init=m.name in init_path)
        for attr, line in hooks.items():
            if attr not in inv.hook_calls or line < inv.hook_calls[attr]:
                inv.hook_calls[attr] = line
    for r in inv.attrs.values():
        r.writes.sort(key=lambda w: (w.line, w.col))
        r.read_lines.sort()
    return inv


def module_summary(mod: ModuleSource) -> ModuleSummary:
    """Dataflow summary of ``mod`` (memoized inside ``summarize_module``
    on the ModuleSource, so every rule in one lint run pays the walk
    once)."""
    return summarize_module(mod)


def inventory_module(mod: ModuleSource) -> List[ClassInventory]:
    """Inventories of every class in ``mod``, in source order.  Memoized
    on the ModuleSource: coverage and hook-detachment share one scope."""
    cached = getattr(mod, "_stateinv_inventory", None)
    if cached is not None:
        return cached
    summary = module_summary(mod)
    out = [
        inventory_class(mod, cls)
        for cls in sorted(
            summary.classes.values(), key=lambda c: c.node.lineno
        )
    ]
    mod._stateinv_inventory = out  # type: ignore[attr-defined]
    return out
