"""Byzantine-input discipline: remote input faults, never raises.

Scope: ``hbbft_tpu/protocols/`` plus the adversary/scenario harness
(``hbbft_tpu/net/adversary.py``, ``hbbft_tpu/net/scenarios.py``).  A
remote peer controls every byte that reaches a
``handle_*(self, sender_id, ...)`` entry point.  Two contracts:

* **No raising on remote input.** A malformed message is *evidence*
  (``Step.from_fault`` / ``PartOutcome(fault=...)``), not an exception —
  an uncaught exception from one crafted message is a remote crash of the
  replica (the cheapest possible Byzantine attack).  Any ``raise`` inside
  a remote-input handler is flagged; programming-error asserts belong in
  internal helpers, not on the network boundary.

* **Membership before state writes.** A handler must check the sender
  against the validator set (``node_index``/``is_node_validator``/
  ``in``-membership) before mutating ``self`` state, otherwise any
  non-member can grow per-sender maps or future-message queues without
  bound (memory DoS) or influence quorum counts.

The membership check is interprocedural ONE call level deep (PR 9):
when a handler passes its sender parameter into a same-class helper
before any membership check, the helper body is scanned with the
argument mapped onto its parameter — a helper that itself checks
membership (or runs a ``*valid*``-named validation call on the sender)
*credits* the handler, and a helper that writes ``self`` state without
either is flagged at its write site, attributed through the calling
handler.  Helpers that are themselves remote handlers are scanned
independently, not re-entered.  Remote handlers are methods named
``handle_*`` whose parameter list includes ``sender_id`` or ``sender``
— matching ``ConsensusProtocol.handle_message`` and the SyncKeyGen
``handle_part``/``handle_ack`` family; ``handle_input`` (local input,
trusted embedder) is deliberately out of scope.

In the net/ harness scope the same discipline applies to the adversary
hook surface (``tamper`` / ``pre_crank`` / ``on_send``): a tamper hook
sees every message shape the protocols can emit — including shapes a
*different* adversary already mangled — so it must pass unknown payloads
through rather than raise (an attack harness that crashes on malformed
state can't compose into the scenario matrix), and it must not
dereference into ``msg.payload`` internals without an ``isinstance``
guard (the structural analogue of the sender-membership check).

In the traffic scope (``hbbft_tpu/traffic/``) the client-facing submit
surface (``submit*`` methods) carries the analogous contract: a client
controls every byte of a submitted transaction, so the method must call
a validation helper (a ``*valid*``-named callable — the mempool's
shape/size check) BEFORE the first ``self`` state write, and a bad
transaction is an admission outcome, never an escaping raise.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from hbbft_tpu.analysis.engine import Finding, ModuleSource, Rule, register

_SENDER_PARAMS = ("sender_id", "sender")
_MEMBERSHIP_CALLS = ("node_index", "is_node_validator", "is_validator", "senders")
_MUTATING_METHODS = (
    "append",
    "add",
    "insert",
    "extend",
    "setdefault",
    "update",
    "pop",
    "popitem",
    "clear",
    "remove",
    "discard",
    "push",
)


def _sender_param(fn: ast.FunctionDef) -> Optional[str]:
    names = [a.arg for a in fn.args.args]
    for p in _SENDER_PARAMS:
        if p in names:
            return p
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_state_write(node: ast.AST) -> bool:
    """Does this statement/expression mutate ``self`` state?"""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) and _root_name(t) == "self":
                return True
        return False
    if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        call = node.value
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _MUTATING_METHODS
            and _root_name(call.func.value) == "self"
        ):
            return True
    return False


def _mentions_membership_check(node: ast.AST, sender: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            # The check must be about the *sender*: `self.netinfo.
            # is_validator()` (our own membership) does not qualify.
            arg_names = {a.id for a in sub.args if isinstance(a, ast.Name)}
            if sub.func.attr in _MEMBERSHIP_CALLS and sender in arg_names:
                return True
            # index-map lookup idiom: `self.index.get(sender_id)`
            if sub.func.attr == "get" and sender in arg_names:
                return True
        if isinstance(sub, ast.Compare):
            if any(isinstance(op, (ast.In, ast.NotIn)) for op in sub.ops):
                if isinstance(sub.left, ast.Name) and sub.left.id == sender:
                    return True
    return False


#: adversary/scenario/crash hook surface checked in the net/ scope —
#: the crash axis's crank hooks (net/crash.py) carry the same contract:
#: a recovery failure becomes an attributed fault, never an exception
#: out of the crank loop
_HOOK_NAMES = (
    "tamper",
    "pre_crank",
    "on_send",
    "on_crank",
    "on_idle",
    "on_deliver",
    "on_input",
    "on_enqueue",
    "after_crank",
)
_NET_SCOPE = (
    "hbbft_tpu/net/adversary.py",
    "hbbft_tpu/net/scenarios.py",
    "hbbft_tpu/net/crash.py",
)
#: client-facing admission surface checked in the traffic scope
_TRAFFIC_SCOPE = "hbbft_tpu/traffic/"


def _is_validation_call(node: ast.AST) -> bool:
    """A call whose target name contains ``valid`` (``self._validate``,
    ``default_validate``, …) — the admission-layer shape check."""
    if not isinstance(node, ast.Call):
        return False
    fname = None
    if isinstance(node.func, ast.Name):
        fname = node.func.id
    elif isinstance(node.func, ast.Attribute):
        fname = node.func.attr
    return fname is not None and "valid" in fname.lower()


@register
class ByzantineInputRule(Rule):
    rule_id = "byzantine-input"
    scope = ("hbbft_tpu/protocols/",) + _NET_SCOPE + (_TRAFFIC_SCOPE,)

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        findings: List[Finding] = []
        in_net_scope = mod.path in _NET_SCOPE
        in_traffic_scope = mod.path.startswith(_TRAFFIC_SCOPE)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = {
                f.name: f for f in node.body if isinstance(f, ast.FunctionDef)
            }
            seen_helper_writes: set = set()
            for fn in node.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                if in_net_scope and fn.name in _HOOK_NAMES:
                    findings.extend(self._check_hook(mod, node.name, fn))
                    continue
                if in_traffic_scope and fn.name.startswith("submit"):
                    findings.extend(self._check_submit(mod, node.name, fn))
                    continue
                if not fn.name.startswith("handle_") or fn.name == "handle_input":
                    continue
                sender = _sender_param(fn)
                if sender is None:
                    continue
                findings.extend(
                    self._check_handler(
                        mod, node.name, fn, sender, methods, seen_helper_writes
                    )
                )
        return findings

    def _check_submit(
        self, mod: ModuleSource, cls: str, fn: ast.FunctionDef
    ) -> List[Finding]:
        """Client-facing admission contract: validate before the first
        self-state write, and never raise on a submitted transaction."""
        findings: List[Finding] = []
        for sub in self._escaping_raises(fn):
            findings.append(
                Finding(
                    self.rule_id,
                    mod.path,
                    sub.lineno,
                    sub.col_offset,
                    f"{cls}.{fn.name} raises on client input; return an "
                    "admission outcome instead",
                )
            )
        validated = False
        for stmt in self._linear_statements(fn):
            if not validated and any(
                _is_validation_call(sub) for sub in ast.walk(stmt)
            ):
                validated = True
            if _is_state_write(stmt) and not validated:
                findings.append(
                    Finding(
                        self.rule_id,
                        mod.path,
                        stmt.lineno,
                        stmt.col_offset,
                        f"{cls}.{fn.name} writes state before validating "
                        "the submitted transaction",
                    )
                )
                break
        return findings

    def _check_hook(
        self, mod: ModuleSource, cls: str, fn: ast.FunctionDef
    ) -> List[Finding]:
        """Adversary-hook contract: never raise (malformed or foreign
        message shapes pass through), and don't reach past ``.payload``
        into message internals without an isinstance guard somewhere in
        the hook (tamper surgery must be type-checked)."""
        findings: List[Finding] = []
        for sub in self._escaping_raises(fn):
            findings.append(
                Finding(
                    self.rule_id,
                    mod.path,
                    sub.lineno,
                    sub.col_offset,
                    f"{cls}.{fn.name} raises inside an adversary hook; "
                    "pass unrecognized messages through instead",
                )
            )
        has_guard = any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id in ("isinstance", "locate_inner", "classify_inner")
            for sub in ast.walk(fn)
        )
        if not has_guard:
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Attribute)
                    and sub.value.attr == "payload"
                ):
                    findings.append(
                        Finding(
                            self.rule_id,
                            mod.path,
                            sub.lineno,
                            sub.col_offset,
                            f"{cls}.{fn.name} dereferences .payload internals "
                            "without an isinstance/locate_inner guard",
                        )
                    )
                    break
        return findings

    def _check_handler(
        self,
        mod: ModuleSource,
        cls: str,
        fn: ast.FunctionDef,
        sender: str,
        methods: Optional[dict] = None,
        seen_helper_writes: Optional[set] = None,
    ) -> List[Finding]:
        if seen_helper_writes is None:
            seen_helper_writes = set()
        findings: List[Finding] = []
        for sub in self._escaping_raises(fn):
            findings.append(
                Finding(
                    self.rule_id,
                    mod.path,
                    sub.lineno,
                    sub.col_offset,
                    f"{cls}.{fn.name} raises on remote input; "
                    "return a FaultLog entry instead",
                )
            )

        # Statement-ordered scan: first self-state write must be preceded
        # by a sender-membership check somewhere earlier in the body.
        # Interprocedural (one level): a pre-check delegation that passes
        # the sender into a same-class helper is followed — a helper that
        # itself checks membership credits the handler; one that writes
        # self state without a check is flagged at its write site.
        checked = False
        for stmt in self._linear_statements(fn):
            if not checked and _mentions_membership_check(stmt, sender):
                checked = True
            if not checked and methods is not None:
                verdict = self._follow_delegations(
                    mod, cls, fn, stmt, sender, methods,
                    seen_helper_writes, findings,
                )
                if verdict:
                    checked = True
            if _is_state_write(stmt) and not checked:
                findings.append(
                    Finding(
                        self.rule_id,
                        mod.path,
                        stmt.lineno,
                        stmt.col_offset,
                        f"{cls}.{fn.name} writes state before checking "
                        f"{sender} membership",
                    )
                )
                break
        return findings

    def _follow_delegations(
        self, mod, cls, fn, stmt, sender, methods, seen, findings
    ) -> bool:
        """Scan ``stmt`` for same-class calls forwarding ``sender``; check
        each target helper one level deep.  Returns True when some helper
        performs the membership check (credits the caller)."""
        credited = False
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if not (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
            ):
                continue
            helper = methods.get(func.attr)
            if helper is None or helper is fn:
                continue
            if helper.name.startswith("handle_") and _sender_param(helper):
                continue  # a remote handler itself: scanned independently
            mapped = self._mapped_param(sub, helper, sender)
            if mapped is None:
                continue
            # Statement-ordered, like the handler scan itself: a helper
            # write BEFORE the helper's check is still unguarded — the
            # check must dominate the write on the linear path.
            h_checked = False
            for h_stmt in self._linear_statements(helper):
                if not h_checked and (
                    _mentions_membership_check(h_stmt, mapped)
                    or self._validates_name(h_stmt, mapped)
                ):
                    h_checked = True
                if _is_state_write(h_stmt) and not h_checked:
                    key = (helper.name, h_stmt.lineno)
                    if key not in seen:
                        seen.add(key)
                        findings.append(
                            Finding(
                                self.rule_id,
                                mod.path,
                                h_stmt.lineno,
                                h_stmt.col_offset,
                                f"{cls}.{helper.name} writes state on "
                                f"sender-controlled input without checking "
                                f"{mapped} membership (reached from "
                                f"{cls}.{fn.name} before its own check)",
                            )
                        )
                    break
            if h_checked:
                credited = True
        return credited

    @staticmethod
    def _mapped_param(call: ast.Call, helper: ast.FunctionDef, sender: str):
        """The helper parameter that receives the caller's ``sender``
        argument, or None when the sender is not forwarded."""
        params = [a.arg for a in helper.args.args]
        if params and params[0] == "self":
            params = params[1:]
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Name) and a.id == sender and i < len(params):
                return params[i]
        for kw in call.keywords:
            if (
                isinstance(kw.value, ast.Name)
                and kw.value.id == sender
                and kw.arg in params
            ):
                return kw.arg
        return None

    @staticmethod
    def _validates_name(stmt: ast.AST, name: str) -> bool:
        """A ``*valid*``-named call receiving ``name`` — the dominating
        validation call the interprocedural contract accepts."""
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call) or not _is_validation_call(sub):
                continue
            arg_names = {a.id for a in sub.args if isinstance(a, ast.Name)}
            arg_names |= {
                kw.value.id
                for kw in sub.keywords
                if isinstance(kw.value, ast.Name)
            }
            if name in arg_names:
                return True
        return False

    @classmethod
    def _escaping_raises(cls, node: ast.AST, in_try: bool = False):
        """Raise nodes not enclosed by a ``try`` with except handlers —
        the ``raise``-then-convert-to-fault idiom inside a local try/except
        (sync_key_gen validation) is legal; an escaping raise is not."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Raise):
                if not in_try:
                    yield child
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested scope: only called, not part of this body
            if isinstance(child, ast.Try) and child.handlers:
                for grand in child.body + child.orelse:
                    yield from cls._escaping_raises(grand, in_try=True)
                # except/finally bodies propagate outward
                for handler in child.handlers:
                    for grand in handler.body:
                        yield from cls._escaping_raises(grand, in_try=in_try)
                for grand in child.finalbody:
                    yield from cls._escaping_raises(grand, in_try=in_try)
            else:
                yield from cls._escaping_raises(child, in_try=in_try)

    @staticmethod
    def _linear_statements(fn: ast.FunctionDef):
        """Statements in source order, descending into control flow."""
        stack = list(reversed(fn.body))
        while stack:
            stmt = stack.pop()
            yield stmt
            for field in ("body", "orelse", "finalbody"):
                for child in reversed(getattr(stmt, field, [])):
                    stack.append(child)
            for handler in getattr(stmt, "handlers", []):
                for child in reversed(handler.body):
                    stack.append(child)
