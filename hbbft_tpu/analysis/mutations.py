"""Seeded order-dependent bugs: the explorer's sensitivity fixtures.

A race detector that silently stops detecting is worse than none, so
PR 9 pins three *mutants* — deliberately broken backends/listeners whose
bug only manifests under particular schedules — and tests assert the
explorer catches each with a minimized, replayable counterexample
(tests/test_race_explorer.py).  Each mutant is the realistic shape of a
bug the seam discipline exists to prevent:

* ``accum`` — chunk deliveries EXTEND a shared accumulator in completion
  order instead of writing their disjoint slots (the PR-3 contract
  violated).  Shares come back permuted under any non-FIFO resolution,
  the engine combines the wrong share for an index, and the epoch's
  decrypt-equality invariant trips — but ONLY on non-default schedules.
* ``counter`` — the submit path of the next batch reads state the
  previous batch's delivery callbacks wrote (which chunk resolved LAST)
  — the adaptive-RLC shape with the observation window read at the
  wrong time.  Verdicts stay correct; the schedule leaks into a
  fingerprinted probe counter.  This is also the source shape the
  static ``seam-race`` rule catches (tests/test_lint.py runs the rule
  over this very module).
* ``listener`` — a chunk-resolution listener submits transactions into
  the live mempools MID-EPOCH, so the next epoch's contribution
  sampling depends on the resolution order (the traffic-hook seam
  violated).  Batches themselves diverge.
* ``shard`` (PR 18) — decrypt chunks ride the PER-DEVICE pipeline but
  scatter their results through a cursor advanced in RESOLUTION order
  instead of writing at their submission offsets.  Per-device queues
  are FIFO, so the bug is invisible until chunks on DIFFERENT devices
  resolve out of submission order — exactly the freedom the shard
  explorer target schedules over.  The cursor is also the minimal
  submit-write/resolve-read crossing the static ``seam-race`` rule
  flags (tests/test_lint.py maps this module into the rule's scope).

These classes are exercised only by the explorer and the lint tests —
nothing in the production paths imports them.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.analysis.schedules import ShardedMockBackend


class AccumulatingResolveBackend(MockBackend):
    """Seeded bug 1: delivery extends a shared list in resolution order.

    ``decrypt_shares_batch`` rides the chunk pipeline with an
    ``out.extend`` delivery — correct only when chunks resolve FIFO.
    """

    def decrypt_shares_batch(self, items):
        out: List[Any] = []
        step = self.pipeline_chunk or len(items) or 1
        b = self._batch_seq
        self._batch_seq += 1
        for ci, lo in enumerate(range(0, len(items), step)):
            chunk = items[lo : lo + step]
            self._pipe.submit(
                lambda chunk=chunk: [
                    sk.decrypt_share_unchecked(ct) for sk, ct in chunk
                ],
                fetch=None,
                kind=f"b{b}.c{ci}",
                items=len(chunk),
                on_result=out.extend,  # BUG: completion order, not slots
            )
        self._pipe.flush(order=self._resolution_order())
        return out


class SubmitReadsResolveBackend(MockBackend):
    """Seeded bug 2: a submit-path read of resolve-path state.

    Delivery callbacks record which chunk resolved last; the NEXT
    batch's submit path folds that into a probe counter — so the probe's
    final value encodes the chosen resolution permutations.  The
    verdicts stay correct (slot writes are untouched); the fingerprint's
    ``extra`` channel exposes the leak, exactly like a group-sizing or
    batching decision would leak into dispatch structure.
    """

    def __init__(self) -> None:
        super().__init__()
        self._last_resolved_lo = 0  # resolve-path state
        self._probe_acc = 0

    def _piped_submit(self, items: Sequence, compute: Callable[[Sequence], List]):
        # BUG (seam-race shape): submit path reads _last_resolved_lo,
        # which the previous batch's delivery callbacks wrote
        self._probe_acc = (self._probe_acc * 31 + self._last_resolved_lo) & (
            (1 << 30) - 1
        )
        out, finish = super()._piped_submit(items, compute)
        return out, finish

    # record resolve-order state from the delivery side
    @property
    def chunk_listeners(self):  # type: ignore[override]
        def deliver(lo, res):
            # BUG (seam-race shape): resolve-path write of state the
            # submit path above reads
            self._last_resolved_lo = lo

        return (deliver,) + tuple(self.__dict__.get("_extra_listeners", ()))

    @chunk_listeners.setter
    def chunk_listeners(self, value):
        self.__dict__["_extra_listeners"] = tuple(value)

    def race_extra(self) -> Dict[str, int]:
        return {"probe_acc": self._probe_acc}


class ShardOrderScatterBackend(ShardedMockBackend):
    """Seeded bug 4: result scatter keyed by resolution order.

    ``decrypt_shares_batch`` submits each chunk to its reserved device
    but delivers through a shared cursor that advances as chunks
    RESOLVE — so a chunk's results land at whatever offset the schedule
    put the cursor at, not at the chunk's submission offset.  Correct
    whenever cross-device resolution happens to equal submission order;
    any other interleaving permutes the shares and trips the epoch's
    decrypt-equality invariant.
    """

    def decrypt_shares_batch(self, items):
        out: List[Any] = [None] * len(items)
        step = self.pipeline_chunk or len(items) or 1
        b = self._batch_seq
        self._batch_seq += 1
        # BUG (seam-race shape): submit-path write of the cursor the
        # resolve-path deliveries below read and advance
        self._scatter_cursor = 0
        for ci, lo in enumerate(range(0, len(items), step)):
            chunk = items[lo : lo + step]

            def deliver(res):
                # BUG: scatter keyed by resolution order, not by the
                # chunk's submission offset
                out[self._scatter_cursor : self._scatter_cursor + len(res)] = res
                self._scatter_cursor += len(res)

            self._pipe.reserve_device()
            self._pipe.submit(
                lambda chunk=chunk: [
                    sk.decrypt_share_unchecked(ct) for sk, ct in chunk
                ],
                fetch=None,
                kind=f"b{b}.c{ci}",
                items=len(chunk),
                on_result=deliver,
            )
        self._pipe.flush()
        return out


def mid_epoch_mempool_listener(driver) -> Callable:
    """Seeded bug 3: a listener mutating mempool state mid-epoch.

    On every chunk resolution it pushes a transaction tagged with the
    chunk's offset into the driver's mempools — so mempool insertion
    order (and therefore the next epoch's sampled contributions) depends
    on the resolution schedule."""
    seq = [0]

    def on_chunk(lo, res):
        seq[0] += 1
        # well-formed canonical tx so admission ACCEPTS it — the bug is
        # the timing, not the shape (client id encodes the chunk offset)
        tx = ("tx", 1_000_000 + lo, seq[0], b"inflight")
        for mp in driver.mempools:
            mp.submit(tx)  # BUG: admission outside the epoch boundary

    return on_chunk


def target_runner(name: str):
    """Explorer runners for the seeded mutants (analysis/schedules.py
    ``target_runner("mutant:<name>")``)."""
    from hbbft_tpu.analysis import schedules

    if name == "accum":

        def run_accum(controller, tracker, n, seed):
            return schedules.run_pipeline_target(
                controller, tracker, n, seed,
                backend_factory=AccumulatingResolveBackend,
            )

        return run_accum
    if name == "counter":

        def run_counter(controller, tracker, n, seed):
            return schedules.run_pipeline_target(
                controller, tracker, n, seed,
                backend_factory=SubmitReadsResolveBackend,
            )

        return run_counter
    if name == "listener":

        def run_listener(controller, tracker, n, seed):
            return schedules.run_traffic_target(
                controller, tracker, n, seed,
                chunk_listener_factory=mid_epoch_mempool_listener,
            )

        return run_listener
    if name == "shard":

        def run_shard(controller, tracker, n, seed):
            return schedules.run_shard_target(
                controller, tracker, n, seed,
                backend_factory=ShardOrderScatterBackend,
            )

        return run_shard
    raise KeyError(f"unknown mutant {name!r}")


MUTANT_NAMES = ("accum", "counter", "listener", "shard")


# ---------------------------------------------------------------------------
# Snapshot/WAL-replay mutants (PR 17)
# ---------------------------------------------------------------------------
#
# The same sensitivity doctrine for the snapshot rule family
# (analysis/rules_snapshot.py): three deliberately broken state classes,
# each the minimal shape of a durability bug the rules exist to catch.
# tests/test_lint.py lints this module's source *as if it lived at a
# ``_STATE_MODULES`` path* (hbbft_tpu/net/crash.py) and pins one finding
# per mutant.  Nothing imports these classes at runtime.


class UndeclaredCallableStateNode:
    """Snapshot mutant ``coverage``: a runtime write stores a callable in
    an attribute that is not declared in ``_SNAPSHOT_ENV_ATTRS`` — the
    first ``save_node`` after this write dies with ``SnapshotError:
    callable in state``.  (``tracer`` is declared, ``_notify`` is the
    drift.)"""

    tracer = None
    _SNAPSHOT_ENV_ATTRS = ("tracer",)

    def __init__(self) -> None:
        self.seen = 0

    def on_deliver(self, sender: Any, payload: Any) -> None:
        self.seen += 1
        self._notify = lambda: payload  # BUG: callable state, undeclared


class ReplayHookNode:
    """Snapshot mutant ``replay-hook``: the WAL replay loop invokes a
    checkpoint-detached hook.  On a restored node ``batch_listeners`` is
    the class default ``()`` while the pre-crash instance had live
    listeners — replay diverges (or silently skips effects) depending on
    environment attachment."""

    batch_listeners = ()
    _SNAPSHOT_ENV_ATTRS = ("batch_listeners",)

    def __init__(self) -> None:
        self.log: List[Any] = []

    def _replay(self, wal: Sequence[Any]) -> None:
        for entry in wal:
            self._apply(entry)

    def _apply(self, entry: Any) -> None:
        self.log.append(entry)
        for cb in self.batch_listeners:
            cb(entry)  # BUG: detached hook steered by WAL replay


class ReplayEnvReadNode:
    """Snapshot mutant ``replay-read``: the replay path reads a
    checkpoint-detached env attr without a guard.  The live instance
    carries a metrics sink; the restored instance replays with the class
    default ``None`` — ``AttributeError`` at best, divergent state at
    worst."""

    metrics_log = None
    _SNAPSHOT_ENV_ATTRS = ("metrics_log",)

    def __init__(self) -> None:
        self.rows: List[Any] = []

    def _restart(self, wal: Sequence[Any]) -> None:
        for entry in wal:
            # BUG: unguarded env read on the replay path
            self.rows.append((entry, self.metrics_log))


SNAPSHOT_MUTANT_NAMES = ("coverage", "replay-hook", "replay-read")
