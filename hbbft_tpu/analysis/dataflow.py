"""Pure-AST def-use + call-graph summaries for the seam rules.

The dynamic half of PR 9 (analysis/schedules.py) *executes* schedules;
this module is the static half's substrate: per-function summaries of
which ``self`` attributes a method reads and writes, which callables it
invokes (with argument mapping, so a sender-controlled parameter can be
tracked one call level down), and which nested functions/lambdas it
hands off as callbacks or returns as resolvers.  Everything is plain
``ast`` work on one module at a time — no imports of the code under
analysis, same contract as the rest of ``hbbft_tpu/analysis``.

Attribute paths are rooted at ``self`` and recorded as dotted strings
(``"counters.pairing_checks"`` for ``self.counters.pairing_checks``,
via one level of local-alias resolution: ``c = self.counters; c.x += 1``
is a write to ``counters.x``).  A *write* is an assignment/aug-assignment
whose target is such a path, a mutating method call on it
(``self.q.append(...)``), or passing it as the mutated first argument of
the known in-place helpers (``heapq.heappush(self.q, ...)``).  Reads are
all other Load-context accesses; ``self.meth(...)`` where ``meth`` is a
function defined on the same class is recorded as a call site instead.

Path conflict is prefix-aware: a write to ``counters.x`` conflicts with
a read of ``counters`` (the whole object was observed) and vice versa.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from hbbft_tpu.analysis.engine import ModuleSource

#: method names that mutate their receiver in place
MUTATING_METHODS = frozenset(
    (
        "append", "add", "insert", "extend", "setdefault", "update",
        "pop", "popitem", "clear", "remove", "discard", "push",
        "appendleft", "popleft", "sort", "reverse",
    )
)
#: free functions whose FIRST argument is mutated in place
MUTATING_FIRST_ARG = frozenset(
    ("heapq.heappush", "heapq.heappop", "heapq.heapify", "random.shuffle")
)


@dataclass(frozen=True)
class Access:
    """One read or write of a ``self``-rooted attribute path."""

    path: str  # dotted, without the "self." prefix
    line: int
    col: int
    kind: str  # "read" | "write"

    @property
    def root(self) -> str:
        return self.path.split(".", 1)[0]


def paths_conflict(a: str, b: str) -> bool:
    """Prefix-aware overlap: ``counters`` vs ``counters.x`` conflict."""
    return a == b or a.startswith(b + ".") or b.startswith(a + ".")


@dataclass
class CallSite:
    """One call made from a function body."""

    name: str  # simple callee name ("submit" for self._pipe.submit)
    dotted: Optional[str]  # full dotted form when resolvable
    on_self: bool  # self.<name>(...) — same-class method candidate
    line: int
    col: int
    node: ast.Call
    #: positional argument expressions that are bare names, by position
    name_args: Dict[int, str] = field(default_factory=dict)
    #: keyword argument expressions that are bare names, by kwarg
    name_kwargs: Dict[str, str] = field(default_factory=dict)

    def param_for_name(
        self, callee_params: Sequence[str], value_name: str
    ) -> Optional[str]:
        """Which of ``callee_params`` receives the caller's ``value_name``?
        ``callee_params`` excludes ``self`` for bound-method calls."""
        for pos, nm in self.name_args.items():
            if nm == value_name and pos < len(callee_params):
                return callee_params[pos]
        for kw, nm in self.name_kwargs.items():
            if nm == value_name and kw in callee_params:
                return kw
        return None


@dataclass
class FunctionSummary:
    """Def-use summary of one function (or nested function / lambda)."""

    name: str
    qualname: str
    node: ast.AST  # FunctionDef or Lambda
    params: List[str]
    reads: List[Access] = field(default_factory=list)
    writes: List[Access] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    #: nested defs/lambdas declared in this body, by name ("<lambda:LINE>")
    nested: Dict[str, "FunctionSummary"] = field(default_factory=dict)
    #: names of nested callables given away as callback arguments, keyed
    #: by the kwarg (or "#<pos>") they were passed under, with the call's
    #: callee name — e.g. ("submit", "on_result") -> "deliver"
    callbacks: List[Tuple[str, str, str]] = field(default_factory=list)
    #: names of nested callables (or "<lambda:LINE>") that are returned
    returned_callables: List[str] = field(default_factory=list)

    def writes_to(self, path: str) -> List[Access]:
        return [a for a in self.writes if paths_conflict(a.path, path)]

    def reads_of(self, path: str) -> List[Access]:
        return [a for a in self.reads if paths_conflict(a.path, path)]


@dataclass
class ClassSummary:
    name: str
    node: ast.ClassDef
    methods: Dict[str, FunctionSummary] = field(default_factory=dict)
    bases: List[str] = field(default_factory=list)


@dataclass
class ModuleSummary:
    path: str
    classes: Dict[str, ClassSummary] = field(default_factory=dict)
    functions: Dict[str, FunctionSummary] = field(default_factory=dict)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _self_path(
    node: ast.AST, aliases: Dict[str, str], self_name: str = "self"
) -> Optional[str]:
    """Dotted path rooted at self (via up to one local alias), else None.
    Subscripts collapse onto their base path (``self.q[i]`` -> ``q``)."""
    parts: List[str] = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    if node.id == self_name:
        pass
    elif node.id in aliases:
        parts.append(aliases[node.id])
    else:
        return None
    return ".".join(reversed(parts)) if parts else None


class _FunctionScanner:
    """Collect one function's accesses/calls WITHOUT descending into
    nested function bodies (those get their own summaries)."""

    def __init__(self, fn: ast.AST, qualname: str) -> None:
        self.fn = fn
        if isinstance(fn, ast.Lambda):
            name = qualname.rsplit(".", 1)[-1]
            params = [a.arg for a in fn.args.args]
            body: List[ast.AST] = [fn.body]
        else:
            name = fn.name
            params = [a.arg for a in fn.args.args]
            body = list(fn.body)
        self.summary = FunctionSummary(
            name=name, qualname=qualname, node=fn, params=params
        )
        #: local -> self-attr aliases (``c = self.counters``)
        self.aliases: Dict[str, str] = {}
        self._alias_sources: set = set()
        self._scan_aliases(body)
        write_nodes = set()
        #: attribute nodes that are the FUNC of a call — the final attr is
        #: a method lookup, not a state read (the receiver read is
        #: recorded separately), so the plain read pass skips them
        self._func_nodes: set = set()
        for stmt in self._walk_local(body):
            self._collect_writes(stmt, write_nodes)
        for stmt in self._walk_local(body):
            self._collect_reads_calls(stmt, write_nodes)

    def _walk_local(self, body: Iterable[ast.AST]):
        """ast.walk, but stopping at nested function/lambda boundaries
        (including nested defs that sit directly in ``body``)."""
        stack = [
            n
            for n in body
            if not isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
        ]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                stack.append(child)

    def _scan_aliases(self, body: Iterable[ast.AST]) -> None:
        for node in self._walk_local(body):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            path = _self_path(node.value, {})
            if path is not None:
                self.aliases[t.id] = path
                # the aliasing assignment itself is not a state read —
                # the read materializes where the alias is USED
                for sub in ast.walk(node.value):
                    self._alias_sources.add(id(sub))

    def _access(self, node: ast.AST, kind: str) -> Optional[Access]:
        path = _self_path(node, self.aliases)
        if path is None:
            return None
        return Access(
            path, getattr(node, "lineno", 0), getattr(node, "col_offset", 0), kind
        )

    def _collect_writes(self, node: ast.AST, write_nodes: set) -> None:
        s = self.summary
        def record(el: ast.AST) -> None:
            acc = self._access(el, "write")
            if acc is not None:
                s.writes.append(acc)
                # the whole target chain is part of the write, not reads
                for sub in ast.walk(el):
                    write_nodes.add(id(sub))

        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                for el in self._unpack(t):
                    # a bare Name target is a local REBINDING, never a
                    # state write, even when the name aliases self state
                    if not isinstance(el, ast.Name):
                        record(el)
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATING_METHODS
            ):
                record(node.func.value)
            dotted = _dotted(node.func)
            if dotted in MUTATING_FIRST_ARG and node.args:
                record(node.args[0])

    @staticmethod
    def _unpack(target: ast.AST) -> Iterable[ast.AST]:
        if isinstance(target, (ast.Tuple, ast.List)):
            return target.elts
        return (target,)

    def _collect_reads_calls(self, node: ast.AST, write_nodes: set) -> None:
        s = self.summary
        if isinstance(node, ast.Call):
            func = node.func
            name = None
            on_self = False
            if isinstance(func, ast.Attribute):
                name = func.attr
                on_self = (
                    isinstance(func.value, ast.Name) and func.value.id == "self"
                )
            elif isinstance(func, ast.Name):
                name = func.id
            if name is not None:
                site = CallSite(
                    name=name,
                    dotted=_dotted(func),
                    on_self=on_self,
                    line=node.lineno,
                    col=node.col_offset,
                    node=node,
                )
                for i, a in enumerate(node.args):
                    if isinstance(a, ast.Name):
                        site.name_args[i] = a.id
                for kw in node.keywords:
                    if kw.arg is not None and isinstance(kw.value, ast.Name):
                        site.name_kwargs[kw.arg] = kw.value.id
                s.calls.append(site)
            if isinstance(func, ast.Attribute):
                # `self._q.append(x)`: the `.append` lookup is not a state
                # read; record the RECEIVER (`self._q`) as the read —
                # unless this very node is already the write of a
                # mutating call (then the write subsumes it).
                self._func_nodes.add(id(func))
                if not on_self and id(func.value) not in write_nodes:
                    acc = self._access(func.value, "read")
                    if acc is not None:
                        s.reads.append(acc)
        elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            if (
                id(node) in write_nodes
                or id(node) in self._func_nodes
                or id(node) in self._alias_sources
            ):
                return
            # Only record the OUTERMOST attribute of a chain: walking
            # will also visit `self.a` inside `self.a.b`, which would
            # double-count.  Detect by checking the parent isn't an
            # Attribute — ast doesn't give parents, so approximate by
            # recording all and deduping on position+prefix below.
            acc = self._access(node, "read")
            if acc is not None:
                s.reads.append(acc)
        elif isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Name):
                s.returned_callables.append(node.value.id)
            elif isinstance(node.value, ast.Lambda):
                s.returned_callables.append(f"<lambda:{node.value.lineno}>")
            elif isinstance(node.value, ast.Tuple):
                for el in node.value.elts:
                    if isinstance(el, ast.Name):
                        s.returned_callables.append(el.id)


def _dedup_reads(reads: List[Access]) -> List[Access]:
    """Drop inner-chain duplicates: for reads at the same line/col keep
    only the longest path (``self.a.b`` visits record both ``a.b`` at the
    Attribute node and ``a`` at its child position)."""
    best: Dict[Tuple[int, int, str], Access] = {}
    for a in reads:
        key = (a.line, a.col, a.root)
        cur = best.get(key)
        if cur is None or len(a.path) > len(cur.path):
            best[key] = a
    return sorted(best.values(), key=lambda a: (a.line, a.col, a.path))


def summarize_function(
    fn: ast.AST, qualname: str
) -> FunctionSummary:
    """Summary of ``fn`` plus recursive summaries of its nested defs."""
    scanner = _FunctionScanner(fn, qualname)
    s = scanner.summary
    s.reads = _dedup_reads(s.reads)
    body = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
    for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self_contains(fn, child):
                    s.nested[child.name] = summarize_function(
                        child, f"{qualname}.{child.name}"
                    )
            elif isinstance(child, ast.Lambda):
                key = f"<lambda:{child.lineno}>"
                s.nested[key] = summarize_function(child, f"{qualname}.{key}")
    # which nested callables are handed to calls as callbacks
    for site in s.calls:
        for pos, nm in site.name_args.items():
            if nm in s.nested:
                s.callbacks.append((site.name, f"#{pos}", nm))
        for kw, nm in site.name_kwargs.items():
            if nm in s.nested:
                s.callbacks.append((site.name, kw, nm))
    return s


def self_contains(outer: ast.AST, inner: ast.AST) -> bool:
    """Is ``inner`` nested DIRECTLY under ``outer`` (not via another
    function)?  Prevents double-summarizing grandchildren."""
    body = [outer.body] if isinstance(outer, ast.Lambda) else outer.body
    stack = list(body)
    while stack:
        node = stack.pop()
        if node is inner:
            return True
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue  # another function's body: its nested defs are ITS
        stack.extend(ast.iter_child_nodes(node))
    return False


def summarize_module(mod: ModuleSource) -> ModuleSummary:
    # Memoized on the (immutable) ModuleSource: seam-race and the three
    # snapshot rules all summarize overlapping scopes in one lint run,
    # and the walk dominates lint wall time.
    cached = getattr(mod, "_dataflow_summary", None)
    if cached is not None:
        return cached
    out = ModuleSummary(path=mod.path)
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef):
            cls = ClassSummary(
                name=node.name,
                node=node,
                bases=[b for b in map(_dotted, node.bases) if b is not None],
            )
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    key = item.name
                    if key in cls.methods:
                        # property getter/setter pairs share a name;
                        # keep both bodies under distinct keys
                        key = f"{item.name}@{item.lineno}"
                    cls.methods[key] = summarize_function(
                        item, f"{node.name}.{item.name}"
                    )
            out.classes[node.name] = cls
        elif isinstance(node, ast.FunctionDef):
            out.functions[node.name] = summarize_function(node, node.name)
    try:
        mod._dataflow_summary = out  # type: ignore[attr-defined]
    except AttributeError:
        pass  # slotted test double: caching is best-effort
    return out


def resolve_self_call(
    cls: ClassSummary, site: CallSite
) -> Optional[FunctionSummary]:
    """The same-class method a ``self.meth(...)`` site targets, if any."""
    if not site.on_self:
        return None
    return cls.methods.get(site.name)
