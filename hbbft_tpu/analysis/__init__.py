"""Protocol-invariant static analysis (the lint rule engine).

HoneyBadgerBFT's safety argument assumes every replica runs the same
deterministic state machine (Miller et al., CCS 2016; the BA subprotocol
additionally needs identical per-round behaviour across correct nodes —
Mostéfaoui–Moumen–Raynal, PODC 2014).  The reference implementation gets
much of that from Rust's type system; Python silently permits the
nondeterminism (unordered set/dict iteration on message paths, wall-clock
reads, ambient ``random``) and the unchecked-input crashes that would
violate it.  This package makes those invariants machine-checked:

* :mod:`engine`               — rule registry, findings, ``# lint:
  allow[rule-id] reason`` suppressions, checked-in baseline.
* :mod:`rules_determinism`    — no clocks/ambient randomness/unordered
  iteration in ``protocols/`` and ``core/``.
* :mod:`rules_exhaustiveness` — wire-registry message variants vs each
  protocol's ``handle_message`` dispatch.
* :mod:`rules_byzantine`      — remote input must become ``FaultLog``
  entries, never exceptions; membership checks before state writes.
* :mod:`rules_tracer`         — no host syncs inside jitted functions in
  ``engine/`` and ``ops/``; hashable static args.

Run via ``tools/lint.py``; gated in tier-1 by ``tests/test_lint.py``.
"""

from hbbft_tpu.analysis.engine import (
    Baseline,
    Finding,
    LintProject,
    ModuleSource,
    Rule,
    all_rules,
    run_lint,
)

__all__ = [
    "Baseline",
    "Finding",
    "LintProject",
    "ModuleSource",
    "Rule",
    "all_rules",
    "run_lint",
]
