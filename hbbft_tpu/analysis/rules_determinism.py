"""Determinism rule: protocol state machines must be replay-identical.

Scope: ``hbbft_tpu/protocols/`` and ``hbbft_tpu/core/`` — the sans-I/O
state machines whose transitions must be byte-identical on every correct
replica (CCS 2016 safety argument; core/protocol.py docstring contract).

Forbidden:

* ``import time`` / ``from time import ...`` and any ``time.*`` use —
  wall-clock reads fork replicas.
* ``import random`` / ``from random import ...`` — ambient module-level
  randomness.  Explicit ``rng`` parameters threaded by the embedder are
  fine (and are the codebase convention).
* ``os.urandom(...)`` — ambient entropy.
* ``id(...)`` — CPython object addresses; any ordering or keying derived
  from them differs across replicas.
* iteration over ``set``-typed values or ``dict.values()``/``.items()``
  without a ``sorted(...)`` wrapper, unless the iteration feeds a
  commutative reducer (``sum``/``any``/``all``/``min``/``max``/``len``)
  or rebuilds an unordered container (``set``/``frozenset``/``dict`` and
  their comprehensions).  Python dicts iterate in *insertion* order, and
  on message paths insertion order is message-arrival order — which an
  asynchronous network does not replicate across nodes.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from hbbft_tpu.analysis.engine import Finding, ModuleSource, Rule, register

#: callables whose result does not depend on argument iteration order
_COMMUTATIVE_SINKS = {"sum", "any", "all", "min", "max", "len", "set", "frozenset", "dict", "sorted"}

_BANNED_MODULE_IMPORTS = {"time", "random"}


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def _is_sorted_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _call_name(node) == "sorted"


def _is_values_or_items(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("values", "items")
        and not node.args
        and not node.keywords
    )


class _SetTypeTracker(ast.NodeVisitor):
    """Collect names/attributes statically known to hold built-in sets.

    Tracked: ``x = set()`` / set literals / set comprehensions /
    annotations ``x: set`` / ``x: Set[...]`` — on locals and on ``self``
    attributes anywhere in the module.
    """

    def __init__(self) -> None:
        self.set_names: Set[str] = set()  # bare locals and "self.attr" keys

    @staticmethod
    def _target_key(target: ast.AST) -> Optional[str]:
        if isinstance(target, ast.Name):
            return target.id
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            return f"self.{target.attr}"
        return None

    @staticmethod
    def _is_set_expr(value: Optional[ast.AST]) -> bool:
        if value is None:
            return False
        if isinstance(value, (ast.Set, ast.SetComp)):
            return True
        if isinstance(value, ast.Call) and _call_name(value) in ("set", "frozenset"):
            return True
        return False

    @staticmethod
    def _is_set_annotation(ann: Optional[ast.AST]) -> bool:
        if ann is None:
            return False
        base = ann.value if isinstance(ann, ast.Subscript) else ann
        if isinstance(base, ast.Name):
            return base.id in ("set", "frozenset", "Set", "FrozenSet")
        if isinstance(base, ast.Attribute):
            return base.attr in ("Set", "FrozenSet")
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._is_set_expr(node.value):
            for t in node.targets:
                key = self._target_key(t)
                if key:
                    self.set_names.add(key)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if self._is_set_annotation(node.annotation) or self._is_set_expr(node.value):
            key = self._target_key(node.target)
            if key:
                self.set_names.add(key)
        self.generic_visit(node)


@register
class DeterminismRule(Rule):
    rule_id = "determinism"
    # The adversary/scenario harness is in scope (seeded-replay contract:
    # same seed ⇒ identical fault log and batch digests, so attacks and
    # schedules must draw entropy only from net.rng); the VirtualNet
    # runtime itself is not (it OWNS the seeded rng and legitimately
    # reads wall time for tracer spans).  The traffic subsystem is in
    # scope with the same contract: generators, mempools, and drivers
    # draw entropy only from the injected rng and never read wall clocks
    # (same seed ⇒ identical arrival schedule, sampled proposals,
    # Batches, and latency histograms — wall-rate timing belongs to the
    # CALLER, bench.py).  The control plane (hbbft_tpu/control/) rides
    # the same contract: batch-size decisions are a pure function of
    # observed virtual-time state + the injected rng, so a seeded
    # replay reproduces the exact B trace (and the kill-switch A/B
    # stays bit-identical).
    # The critpath/timeseries/flight observability trio (PR 13) rides
    # the same contract: stamps carry caller-provided crank/virtual-time
    # context, series rows and forensics bundles are pure functions of
    # the recorded evidence (seeded replay ⇒ bit-identical artifacts).
    # tracer.py and health.py stay OUT of scope — they legitimately read
    # wall clocks (spans, heartbeats).
    scope = (
        "hbbft_tpu/protocols/",
        "hbbft_tpu/core/",
        "hbbft_tpu/net/adversary.py",
        "hbbft_tpu/net/scenarios.py",
        "hbbft_tpu/net/crash.py",
        "hbbft_tpu/traffic/",
        "hbbft_tpu/control/",
        "hbbft_tpu/obs/critpath.py",
        "hbbft_tpu/obs/timeseries.py",
        "hbbft_tpu/obs/flight.py",
    )

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        findings: List[Finding] = []
        tracker = _SetTypeTracker()
        tracker.visit(mod.tree)

        def emit(node: ast.AST, message: str) -> None:
            findings.append(
                Finding(
                    self.rule_id,
                    mod.path,
                    getattr(node, "lineno", 1),
                    getattr(node, "col_offset", 0),
                    message,
                )
            )

        def expr_key(node: ast.AST) -> Optional[str]:
            if isinstance(node, ast.Name):
                return node.id
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return f"self.{node.attr}"
            return None

        def iter_expr_nondeterministic(it: ast.AST) -> Optional[str]:
            """Why iterating ``it`` is order-nondeterministic, or None."""
            if _is_sorted_call(it):
                return None
            if _is_values_or_items(it):
                method = it.func.attr  # type: ignore[union-attr]
                return f"iteration over unsorted dict .{method}()"
            if isinstance(it, (ast.Set, ast.SetComp)):
                return "iteration over a set literal"
            key = expr_key(it)
            if key is not None and key in tracker.set_names:
                return f"iteration over set-typed {key!r}"
            return None

        def enumerate_nondeterministic(it: ast.AST) -> Optional[str]:
            """``enumerate(<unordered>)`` bakes arrival order into indices —
            nondeterministic even when the result feeds an unordered sink."""
            if (
                isinstance(it, ast.Call)
                and _call_name(it) == "enumerate"
                and it.args
            ):
                why = iter_expr_nondeterministic(it.args[0])
                if why is not None:
                    return f"enumerate over nondeterministic order ({why})"
            return None

        # Comprehension nodes whose iteration order cannot leak: the whole
        # comprehension/genexp feeds a commutative reducer or rebuilds an
        # unordered container.
        safe_comps: Set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _call_name(node) in _COMMUTATIVE_SINKS:
                for arg in node.args:
                    if isinstance(arg, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
                        safe_comps.add(id(arg))
            if isinstance(node, (ast.SetComp, ast.DictComp)):
                safe_comps.add(id(node))

        for node in ast.walk(mod.tree):
            # -- banned imports / calls -----------------------------------
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    if root in _BANNED_MODULE_IMPORTS:
                        emit(node, f"import of nondeterministic module {root!r}")
            elif isinstance(node, ast.ImportFrom):
                root = (node.module or "").split(".")[0]
                if root in _BANNED_MODULE_IMPORTS:
                    emit(node, f"import from nondeterministic module {root!r}")
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Name
                ):
                    base, attr = node.func.value.id, node.func.attr
                    if base == "time":
                        emit(node, f"wall-clock call time.{attr}()")
                    elif base == "os" and attr == "urandom":
                        emit(node, "ambient entropy via os.urandom()")
                    elif base == "random":
                        emit(node, f"ambient randomness via random.{attr}()")
                elif _call_name(node) == "id":
                    emit(node, "id() yields address-derived (nondeterministic) values")

            # -- unordered iteration --------------------------------------
            if isinstance(node, (ast.For, ast.AsyncFor)):
                why = enumerate_nondeterministic(node.iter) or iter_expr_nondeterministic(
                    node.iter
                )
                if why is not None:
                    emit(node, f"{why} in a for loop; wrap in sorted(...)")
            elif isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp, ast.DictComp)):
                for comp in node.generators:
                    # enumerate leaks order through value expressions, so it
                    # is flagged even inside set/dict/commutative sinks.
                    why = enumerate_nondeterministic(comp.iter)
                    if why is None and id(node) not in safe_comps:
                        why = iter_expr_nondeterministic(comp.iter)
                    if why is not None:
                        emit(node, f"{why} in a comprehension; wrap in sorted(...)")
        return findings


@register
class GlvTableOrderRule(Rule):
    """Determinism-family guard for the GLV/GLS joint-table build.

    The 16-entry joint tables in ``ops/curve.py`` (``_joint_table*``)
    define the gather layout of every endomorphism ladder: entry idx must
    mean the SAME window combination in every process, or replayed runs
    and the ``HBBFT_TPU_NO_GLV`` A/B stop being bit-identical.  The build
    must therefore iterate window indices in a fixed arithmetic order —
    every ``for`` loop and comprehension inside a ``_joint_table*``
    function is required to iterate a literal ``range(...)`` (sets,
    dicts, ``.values()``/``.items()`` and arbitrary iterables are all
    rejected, not merely the provably-unordered ones: the table layout
    is load-bearing enough to pin the idiom, not just the semantics).
    The rule also fails when NO ``_joint_table*`` function exists, so a
    rename or deletion cannot silently retire the guard.
    """

    rule_id = "glv-table-order"
    scope = ("hbbft_tpu/ops/curve.py",)

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        findings: List[Finding] = []

        def emit(node: ast.AST, message: str) -> None:
            findings.append(
                Finding(
                    self.rule_id,
                    mod.path,
                    getattr(node, "lineno", 1),
                    getattr(node, "col_offset", 0),
                    message,
                )
            )

        def is_range_call(it: ast.AST) -> bool:
            return (
                isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "range"
            )

        fns = [
            n
            for n in ast.walk(mod.tree)
            if isinstance(n, ast.FunctionDef) and n.name.startswith("_joint_table")
        ]
        if not fns:
            emit(
                mod.tree,
                "no _joint_table* function found: the joint-table build "
                "(and its fixed-order guard) is missing from ops/curve.py",
            )
        for fn in fns:
            for node in ast.walk(fn):
                iters: List[ast.AST] = []
                if isinstance(node, ast.For):
                    iters.append(node.iter)
                elif isinstance(
                    node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
                ):
                    iters.extend(c.iter for c in node.generators)
                for it in iters:
                    if not is_range_call(it):
                        emit(
                            it,
                            f"table precomputation in {fn.name}() must "
                            "iterate window indices via range(...); found a "
                            "non-range iterable",
                        )
        return findings
