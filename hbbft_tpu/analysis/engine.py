"""AST lint engine: findings, suppressions, baseline, rule registry.

Pure stdlib (``ast`` + ``re`` + ``json``) — the linter never imports the
code under analysis, so a full run costs parse time only (<10s on CPU; no
JAX import) and cannot be affected by import-time side effects.

Suppression syntax (same line as the finding, or a comment-only line
immediately above it)::

    self._counts[k] += 1  # lint: allow[determinism] counting is commutative

A suppression must carry a reason; a bare ``# lint: allow[rule]`` is not
honoured and is itself reported (rule id ``lint-allow``).

The baseline file grandfathers known findings: each entry is the multiset
key ``(rule, path, message)`` with a count, so moving a grandfathered
finding within its file does not trip CI but adding a new instance does.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(r"#\s*lint:\s*allow\[([A-Za-z0-9_,\- ]+)\]\s*(.*)")

#: rule id used for meta-findings about malformed suppressions
ALLOW_RULE_ID = "lint-allow"
#: rule id for suppressions that no longer suppress anything
STALE_RULE_ID = "stale-suppression"


@dataclass(frozen=True, slots=True)
class Finding:
    """One lint finding, anchored to a source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str

    def sort_key(self) -> Tuple:
        return (self.path, self.line, self.col, self.rule, self.message)

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class ModuleSource:
    """One parsed module: source text, AST, and suppression table."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        #: line -> set of rule ids allowed on that line ("*" = all)
        self.allowed: Dict[int, set] = {}
        #: (line, rule-list) of suppressions missing a reason
        self.bare_allows: List[Tuple[int, str]] = []
        #: honoured allow comments: (comment line, target line, rules) —
        #: the stale-suppression pass checks each actually fired
        self.allow_sites: List[Tuple[int, int, frozenset]] = []
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        # Real COMMENT tokens only (tokenize): allow-syntax quoted inside a
        # docstring or string literal must not create phantom suppressions.
        if "allow[" not in self.text:
            return  # fast path: tokenizing dominates project load time
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # surfaced separately as a syntax finding
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            line = tok.start[0]
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            if not reason:
                self.bare_allows.append((line, ",".join(sorted(rules))))
                continue  # not honoured without a reason
            target = line
            code = self.lines[line - 1][: tok.start[1]].strip()
            if not code:
                # Comment-only line: applies to the next SOURCE line —
                # skipping CONTINUATION COMMENT lines only, so a
                # multi-line justification comment still binds to the
                # code it precedes.  A blank line ends the binding (the
                # allow then suppresses nothing and is reported stale)
                # — skipping blanks would let a dead allow silently
                # capture the next code block.
                target = line + 1
                while target <= len(self.lines):
                    nxt = self.lines[target - 1].strip()
                    if not nxt.startswith("#"):
                        break
                    target += 1
            self.allowed.setdefault(target, set()).update(rules)
            self.allow_sites.append((line, target, frozenset(rules)))

    def is_suppressed(self, rule: str, line: int) -> bool:
        # (no wildcard form: SUPPRESS_RE only admits rule-id characters,
        # so every suppression names the rules it blankets)
        rules = self.allowed.get(line)
        return rules is not None and rule in rules


class LintProject:
    """All modules under analysis, keyed by repo-relative posix path."""

    def __init__(self, repo_root: Path, modules: Dict[str, ModuleSource]) -> None:
        self.repo_root = repo_root
        self.modules = modules

    @staticmethod
    def load(repo_root: Path, paths: Iterable[Path]) -> "LintProject":
        modules: Dict[str, ModuleSource] = {}
        for p in sorted(paths):
            rel = p.resolve().relative_to(repo_root.resolve()).as_posix()
            text = p.read_text(encoding="utf-8")
            try:
                modules[rel] = ModuleSource(rel, text)
            except SyntaxError as e:
                # Surfaced as a finding rather than crashing the run.
                broken = ModuleSource.__new__(ModuleSource)
                broken.path = rel
                broken.text = text
                broken.lines = text.splitlines()
                broken.tree = ast.Module(body=[], type_ignores=[])
                broken.allowed = {}
                broken.bare_allows = []
                broken.allow_sites = []
                broken.syntax_error = e  # type: ignore[attr-defined]
                modules[rel] = broken
        return LintProject(repo_root, modules)

    def module(self, path: str) -> Optional[ModuleSource]:
        return self.modules.get(path)


class Rule:
    """Base class: subclasses set ``rule_id``/``scope`` and override one of
    ``check_module`` (per-file) or ``check_project`` (cross-file)."""

    rule_id: str = ""
    #: path prefixes this rule applies to (posix, repo-relative)
    scope: Tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        return not self.scope or any(path.startswith(pfx) for pfx in self.scope)

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        return []

    def check_project(self, project: LintProject) -> List[Finding]:
        out: List[Finding] = []
        for path in sorted(project.modules):
            if self.applies_to(path):
                out.extend(self.check_module(project.modules[path]))
        return out


_REGISTRY: Dict[str, Callable[[], Rule]] = {}


def register(rule_cls):
    """Class decorator adding a rule to the global registry."""
    if not rule_cls.rule_id:
        raise ValueError(f"{rule_cls.__name__} has no rule_id")
    if rule_cls.rule_id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule_cls.rule_id!r}")
    _REGISTRY[rule_cls.rule_id] = rule_cls
    return rule_cls


def all_rules() -> List[Rule]:
    """Instantiate every registered rule (importing the rule modules)."""
    # Imports are deferred so `engine` has no circular dependency on rules.
    from hbbft_tpu.analysis import (  # noqa: F401
        rules_byzantine,
        rules_determinism,
        rules_exhaustiveness,
        rules_seam,
        rules_snapshot,
        rules_tracer,
    )

    return [_REGISTRY[rid]() for rid in sorted(_REGISTRY)]


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


class Baseline:
    """Multiset of grandfathered findings keyed by (rule, path, message)."""

    def __init__(self, counts: Optional[Dict[Tuple[str, str, str], int]] = None) -> None:
        self.counts: Dict[Tuple[str, str, str], int] = dict(counts or {})

    @staticmethod
    def from_findings(findings: Iterable[Finding]) -> "Baseline":
        b = Baseline()
        for f in findings:
            k = f.baseline_key()
            b.counts[k] = b.counts.get(k, 0) + 1
        return b

    @staticmethod
    def load(path: Path) -> "Baseline":
        if not path.exists():
            return Baseline()
        data = json.loads(path.read_text(encoding="utf-8"))
        counts: Dict[Tuple[str, str, str], int] = {}
        for entry in data.get("findings", []):
            key = (entry["rule"], entry["path"], entry["message"])
            counts[key] = int(entry.get("count", 1))
        return Baseline(counts)

    def save(self, path: Path) -> None:
        entries = [
            {"rule": rule, "path": p, "message": msg, "count": n}
            for (rule, p, msg), n in sorted(self.counts.items())
        ]
        path.write_text(
            json.dumps({"version": 1, "findings": entries}, indent=2) + "\n",
            encoding="utf-8",
        )

    def new_findings(self, findings: Sequence[Finding]) -> List[Finding]:
        """Findings beyond the grandfathered counts (deterministic: for each
        key the *first* ``count`` occurrences in sorted order are absorbed)."""
        remaining = dict(self.counts)
        out: List[Finding] = []
        for f in sorted(findings, key=Finding.sort_key):
            k = f.baseline_key()
            if remaining.get(k, 0) > 0:
                remaining[k] -= 1
            else:
                out.append(f)
        return out


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def iter_python_files(root: Path) -> List[Path]:
    return sorted(p for p in root.rglob("*.py") if "__pycache__" not in p.parts)


def run_lint(
    repo_root: Path,
    paths: Optional[Iterable[Path]] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> List[Finding]:
    """Run ``rules`` over ``paths`` (default: all of hbbft_tpu/).

    Returns unsuppressed findings in deterministic sorted order.
    Suppressions without a reason surface as ``lint-allow`` findings.
    """
    if paths is None:
        paths = iter_python_files(repo_root / "hbbft_tpu")
    project = LintProject.load(repo_root, paths)
    full_rule_set = rules is None
    if full_rule_set:
        rules = all_rules()

    findings: List[Finding] = []
    for path, mod in project.modules.items():
        err = getattr(mod, "syntax_error", None)
        if err is not None:
            findings.append(
                Finding("syntax", path, err.lineno or 1, 0, f"syntax error: {err.msg}")
            )
        for line, rules_txt in mod.bare_allows:
            findings.append(
                Finding(
                    ALLOW_RULE_ID,
                    path,
                    line,
                    0,
                    f"suppression allow[{rules_txt}] has no reason; not honoured",
                )
            )
    #: (path, line, rule) triples where a suppression actually fired —
    #: rule-keyed so a dead allow cannot hide behind a DIFFERENT rule's
    #: live allow on the same line
    used_allows: set = set()
    for rule in rules:
        for f in rule.check_project(project):
            mod = project.module(f.path)
            if mod is not None and mod.is_suppressed(f.rule, f.line):
                used_allows.add((f.path, f.line, f.rule))
                continue
            findings.append(f)
    # Stale suppressions: an honoured allow comment that suppressed
    # nothing in this run is itself a finding — dead suppressions
    # otherwise silently blanket future findings on their line.  Only
    # meaningful when the full rule set ran (a subset run can't tell
    # dead from not-exercised).  (A partial FILE run — --diff or an
    # explicit list — can still transiently report one when the matching
    # finding needs cross-file context; the gate and --baseline always
    # run the full set.)
    if not full_rule_set:
        return sorted(findings, key=Finding.sort_key)
    def _fired(path: str, target: int, rules_txt: frozenset) -> bool:
        return any((path, target, r) in used_allows for r in rules_txt)

    #: candidates: allow sites that suppressed nothing
    stale = [
        (path, mod, comment_line, target, rules_txt)
        for path, mod in project.modules.items()
        for comment_line, target, rules_txt in mod.allow_sites
        if not _fired(path, target, rules_txt)
    ]
    #: (path, target) -> allow[stale-suppression] site lines binding there
    stale_sites: Dict[Tuple[str, int], List[int]] = {}
    for path, _mod, comment_line, target, rules_txt in stale:
        if STALE_RULE_ID in rules_txt:
            stale_sites.setdefault((path, target), []).append(comment_line)
    #: candidates whose stale finding is deliberately allowed, and the
    #: escape-hatch sites that did the allowing (those are live, not
    #: stale themselves — the hatch must converge)
    suppressed: set = set()
    protectors: set = set()
    for path, mod, comment_line, target, rules_txt in stale:
        if STALE_RULE_ID in rules_txt:
            continue
        if mod.is_suppressed(STALE_RULE_ID, comment_line):
            # inline dead allow: the hatch binds to its code line
            suppressed.add((path, comment_line))
            for s in stale_sites.get((path, comment_line), ()):
                protectors.add((path, s))
            continue
        # comment-only dead allow: the hatch comment above it skips the
        # dead comment line and binds to the SAME code line — treat a
        # co-targeting allow[stale-suppression] as this allow's hatch
        others = [
            s
            for s in stale_sites.get((path, target), ())
            if s != comment_line
        ]
        if others:
            suppressed.add((path, comment_line))
            protectors.update((path, s) for s in others)
    for path, mod, comment_line, target, rules_txt in stale:
        if (path, comment_line) in suppressed:
            continue  # its stale finding is deliberately allowed
        if STALE_RULE_ID in rules_txt and (path, comment_line) in protectors:
            continue  # this hatch silenced a kept dead allow: live
        findings.append(
            Finding(
                STALE_RULE_ID,
                path,
                comment_line,
                0,
                f"suppression allow[{','.join(sorted(rules_txt))}] "
                "matches no finding; remove it",
            )
        )
    return sorted(findings, key=Finding.sort_key)
