"""AST lint engine: findings, suppressions, baseline, rule registry.

Pure stdlib (``ast`` + ``re`` + ``json``) — the linter never imports the
code under analysis, so a full run costs parse time only (<10s on CPU; no
JAX import) and cannot be affected by import-time side effects.

Suppression syntax (same line as the finding, or a comment-only line
immediately above it)::

    self._counts[k] += 1  # lint: allow[determinism] counting is commutative

A suppression must carry a reason; a bare ``# lint: allow[rule]`` is not
honoured and is itself reported (rule id ``lint-allow``).

The baseline file grandfathers known findings: each entry is the multiset
key ``(rule, path, message)`` with a count, so moving a grandfathered
finding within its file does not trip CI but adding a new instance does.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(r"#\s*lint:\s*allow\[([A-Za-z0-9_,\- ]+)\]\s*(.*)")

#: rule id used for meta-findings about malformed suppressions
ALLOW_RULE_ID = "lint-allow"


@dataclass(frozen=True, slots=True)
class Finding:
    """One lint finding, anchored to a source location."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str

    def sort_key(self) -> Tuple:
        return (self.path, self.line, self.col, self.rule, self.message)

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


class ModuleSource:
    """One parsed module: source text, AST, and suppression table."""

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        #: line -> set of rule ids allowed on that line ("*" = all)
        self.allowed: Dict[int, set] = {}
        #: (line, rule-list) of suppressions missing a reason
        self.bare_allows: List[Tuple[int, str]] = []
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        # Real COMMENT tokens only (tokenize): allow-syntax quoted inside a
        # docstring or string literal must not create phantom suppressions.
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # surfaced separately as a syntax finding
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            line = tok.start[0]
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            if not reason:
                self.bare_allows.append((line, ",".join(sorted(rules))))
                continue  # not honoured without a reason
            target = line
            code = self.lines[line - 1][: tok.start[1]].strip()
            if not code:
                # Comment-only line: applies to the next source line.
                target = line + 1
            self.allowed.setdefault(target, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self.allowed.get(line)
        return rules is not None and (rule in rules or "*" in rules)


class LintProject:
    """All modules under analysis, keyed by repo-relative posix path."""

    def __init__(self, repo_root: Path, modules: Dict[str, ModuleSource]) -> None:
        self.repo_root = repo_root
        self.modules = modules

    @staticmethod
    def load(repo_root: Path, paths: Iterable[Path]) -> "LintProject":
        modules: Dict[str, ModuleSource] = {}
        for p in sorted(paths):
            rel = p.resolve().relative_to(repo_root.resolve()).as_posix()
            text = p.read_text(encoding="utf-8")
            try:
                modules[rel] = ModuleSource(rel, text)
            except SyntaxError as e:
                # Surfaced as a finding rather than crashing the run.
                broken = ModuleSource.__new__(ModuleSource)
                broken.path = rel
                broken.text = text
                broken.lines = text.splitlines()
                broken.tree = ast.Module(body=[], type_ignores=[])
                broken.allowed = {}
                broken.bare_allows = []
                broken.syntax_error = e  # type: ignore[attr-defined]
                modules[rel] = broken
        return LintProject(repo_root, modules)

    def module(self, path: str) -> Optional[ModuleSource]:
        return self.modules.get(path)


class Rule:
    """Base class: subclasses set ``rule_id``/``scope`` and override one of
    ``check_module`` (per-file) or ``check_project`` (cross-file)."""

    rule_id: str = ""
    #: path prefixes this rule applies to (posix, repo-relative)
    scope: Tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        return not self.scope or any(path.startswith(pfx) for pfx in self.scope)

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        return []

    def check_project(self, project: LintProject) -> List[Finding]:
        out: List[Finding] = []
        for path in sorted(project.modules):
            if self.applies_to(path):
                out.extend(self.check_module(project.modules[path]))
        return out


_REGISTRY: Dict[str, Callable[[], Rule]] = {}


def register(rule_cls):
    """Class decorator adding a rule to the global registry."""
    if not rule_cls.rule_id:
        raise ValueError(f"{rule_cls.__name__} has no rule_id")
    if rule_cls.rule_id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule_cls.rule_id!r}")
    _REGISTRY[rule_cls.rule_id] = rule_cls
    return rule_cls


def all_rules() -> List[Rule]:
    """Instantiate every registered rule (importing the rule modules)."""
    # Imports are deferred so `engine` has no circular dependency on rules.
    from hbbft_tpu.analysis import (  # noqa: F401
        rules_byzantine,
        rules_determinism,
        rules_exhaustiveness,
        rules_tracer,
    )

    return [_REGISTRY[rid]() for rid in sorted(_REGISTRY)]


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


class Baseline:
    """Multiset of grandfathered findings keyed by (rule, path, message)."""

    def __init__(self, counts: Optional[Dict[Tuple[str, str, str], int]] = None) -> None:
        self.counts: Dict[Tuple[str, str, str], int] = dict(counts or {})

    @staticmethod
    def from_findings(findings: Iterable[Finding]) -> "Baseline":
        b = Baseline()
        for f in findings:
            k = f.baseline_key()
            b.counts[k] = b.counts.get(k, 0) + 1
        return b

    @staticmethod
    def load(path: Path) -> "Baseline":
        if not path.exists():
            return Baseline()
        data = json.loads(path.read_text(encoding="utf-8"))
        counts: Dict[Tuple[str, str, str], int] = {}
        for entry in data.get("findings", []):
            key = (entry["rule"], entry["path"], entry["message"])
            counts[key] = int(entry.get("count", 1))
        return Baseline(counts)

    def save(self, path: Path) -> None:
        entries = [
            {"rule": rule, "path": p, "message": msg, "count": n}
            for (rule, p, msg), n in sorted(self.counts.items())
        ]
        path.write_text(
            json.dumps({"version": 1, "findings": entries}, indent=2) + "\n",
            encoding="utf-8",
        )

    def new_findings(self, findings: Sequence[Finding]) -> List[Finding]:
        """Findings beyond the grandfathered counts (deterministic: for each
        key the *first* ``count`` occurrences in sorted order are absorbed)."""
        remaining = dict(self.counts)
        out: List[Finding] = []
        for f in sorted(findings, key=Finding.sort_key):
            k = f.baseline_key()
            if remaining.get(k, 0) > 0:
                remaining[k] -= 1
            else:
                out.append(f)
        return out


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def iter_python_files(root: Path) -> List[Path]:
    return sorted(p for p in root.rglob("*.py") if "__pycache__" not in p.parts)


def run_lint(
    repo_root: Path,
    paths: Optional[Iterable[Path]] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> List[Finding]:
    """Run ``rules`` over ``paths`` (default: all of hbbft_tpu/).

    Returns unsuppressed findings in deterministic sorted order.
    Suppressions without a reason surface as ``lint-allow`` findings.
    """
    if paths is None:
        paths = iter_python_files(repo_root / "hbbft_tpu")
    project = LintProject.load(repo_root, paths)
    if rules is None:
        rules = all_rules()

    findings: List[Finding] = []
    for path, mod in project.modules.items():
        err = getattr(mod, "syntax_error", None)
        if err is not None:
            findings.append(
                Finding("syntax", path, err.lineno or 1, 0, f"syntax error: {err.msg}")
            )
        for line, rules_txt in mod.bare_allows:
            findings.append(
                Finding(
                    ALLOW_RULE_ID,
                    path,
                    line,
                    0,
                    f"suppression allow[{rules_txt}] has no reason; not honoured",
                )
            )
    for rule in rules:
        for f in rule.check_project(project):
            mod = project.module(f.path)
            if mod is not None and mod.is_suppressed(f.rule, f.line):
                continue
            findings.append(f)
    return sorted(findings, key=Finding.sort_key)
