"""Handler-exhaustiveness rule: wire variants ↔ ``handle_message`` dispatch.

The wire codec (``hbbft_tpu/utils/wire.py``) declares the full message
hierarchy in ``WIRE_VARIANTS``.  Every variant a peer can legally put on
the wire must be dispatched somewhere in the owning protocol's handler
class, and every kind string the handler dispatches on must exist on the
wire — otherwise one of two drift bugs has happened:

* **unhandled variant** — the codec decodes it, the protocol silently
  mis-files it (usually into an ``unknown_kind`` fault against an honest
  peer, which is itself a safety hazard: correct nodes must never accuse
  each other).
* **orphaned kind** — the handler dispatches on a kind the codec can
  never deliver; dead code that hides a missing wire registration.

Convention this rule relies on (documented here, checked by the tests):
handler classes compare the *message parameter*, named ``message`` or
``msg``, via ``message.kind == "..."`` or ``message.kind in (...)``.
Comparisons on other receivers (e.g. ``out.kind`` for protocol outputs)
are deliberately ignored.

The rule also drift-checks ``WIRE_VARIANTS`` against the codec itself:
every registered class must appear in an ``isinstance`` test in
``_to_tree``, and every registered tag/kind must occur as a string
literal in the module.

The same discipline covers fault kinds: ``core/fault_log.py`` declares
the full ``FAULT_KINDS`` registry, and this rule cross-checks it both
ways — every ``"prefix:name"`` literal a protocol module emits must be
registered, every registered kind must still be emitted by its protocol
module, and every fault kind the scenario harness (net/scenarios.py)
*expects* an attack to plant must exist in the registry.  Adding a fault
kind (or an attack expectation) without updating the registry breaks
lint and the scenario tests together — by design.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from hbbft_tpu.analysis.engine import Finding, LintProject, ModuleSource, Rule, register

WIRE_PATH = "hbbft_tpu/utils/wire.py"
FAULT_LOG_PATH = "hbbft_tpu/core/fault_log.py"
SCENARIOS_PATH = "hbbft_tpu/net/scenarios.py"

#: the canonical shape of a namespaced fault kind ("broadcast:multiple_echos")
FAULT_KIND_RE = re.compile(r"^[a-z][a-z_0-9]*:[a-z][a-z_0-9]*$")

#: fault-kind namespace prefix -> protocol module that emits it (the
#: unused-kind direction of the cross-check is gated per prefix on its
#: module being loaded, so --diff partial runs stay quiet)
FAULT_PREFIX_MODULES: Dict[str, str] = {
    "binary_agreement": "hbbft_tpu/protocols/binary_agreement.py",
    "broadcast": "hbbft_tpu/protocols/broadcast.py",
    # the crash/restart axis emits outside protocols/ — the emitted-kind
    # scan below covers every owner module, wherever it lives
    "crash": "hbbft_tpu/net/crash.py",
    "dynamic_honey_badger": "hbbft_tpu/protocols/dynamic_honey_badger.py",
    "honey_badger": "hbbft_tpu/protocols/honey_badger.py",
    "sbv": "hbbft_tpu/protocols/sbv_broadcast.py",
    "sender_queue": "hbbft_tpu/protocols/sender_queue.py",
    "subset": "hbbft_tpu/protocols/subset.py",
    "sync_key_gen": "hbbft_tpu/protocols/sync_key_gen.py",
    "threshold_decrypt": "hbbft_tpu/protocols/threshold_decrypt.py",
    "threshold_sign": "hbbft_tpu/protocols/threshold_sign.py",
}

#: message class -> (module path, handler class) owning its dispatch
HANDLERS: Dict[str, Tuple[str, str]] = {
    "SbvMessage": ("hbbft_tpu/protocols/sbv_broadcast.py", "SbvBroadcast"),
    "BroadcastMessage": ("hbbft_tpu/protocols/broadcast.py", "Broadcast"),
    "BaMessage": ("hbbft_tpu/protocols/binary_agreement.py", "BinaryAgreement"),
    "SubsetMessage": ("hbbft_tpu/protocols/subset.py", "Subset"),
    "HbMessage": ("hbbft_tpu/protocols/honey_badger.py", "HoneyBadger"),
    "SqMessage": ("hbbft_tpu/protocols/sender_queue.py", "SenderQueue"),
}

_MSG_PARAM_NAMES = ("message", "msg")


def _load_wire_variants(tree: ast.AST) -> Optional[Dict[str, Tuple[str, Tuple[str, ...]]]]:
    """Extract the WIRE_VARIANTS literal from wire.py's AST (no import)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "WIRE_VARIANTS":
                    try:
                        value = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        return None
                    return {
                        cls: (tag, tuple(kinds))
                        for cls, (tag, kinds) in value.items()
                    }
    return None


def _kind_literals_for_class(tree: ast.AST, class_name: str) -> Tuple[Set[str], int]:
    """Kind strings compared against ``message.kind``/``msg.kind`` inside
    ``class_name``, plus the class's definition line."""
    kinds: Set[str] = set()
    class_line = 1
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            class_line = node.lineno
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Compare):
                    continue
                left = sub.left
                if not (
                    isinstance(left, ast.Attribute)
                    and left.attr == "kind"
                    and isinstance(left.value, ast.Name)
                    and left.value.id in _MSG_PARAM_NAMES
                ):
                    continue
                for op, comparator in zip(sub.ops, sub.comparators):
                    if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(
                        comparator, ast.Constant
                    ):
                        if isinstance(comparator.value, str):
                            kinds.add(comparator.value)
                    elif isinstance(op, (ast.In, ast.NotIn)) and isinstance(
                        comparator, (ast.Tuple, ast.List, ast.Set)
                    ):
                        for elt in comparator.elts:
                            if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, str
                            ):
                                kinds.add(elt.value)
    return kinds, class_line


def _isinstance_classes(tree: ast.AST, func_name: str) -> Set[str]:
    """Class names tested via isinstance(...) inside function ``func_name``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "isinstance"
                    and len(sub.args) == 2
                ):
                    cls = sub.args[1]
                    if isinstance(cls, ast.Name):
                        out.add(cls.id)
    return out


def _load_fault_kinds(tree: ast.AST) -> Optional[Dict[str, Tuple[str, ...]]]:
    """Extract the FAULT_KINDS literal from fault_log.py's AST (no import)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "FAULT_KINDS":
                    try:
                        value = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        return None
                    return {
                        prefix: tuple(names) for prefix, names in value.items()
                    }
    return None


def _fault_kind_literals(mod: ModuleSource) -> Dict[str, int]:
    """Every ``prefix:name``-shaped string constant in the module -> its
    first line number (docstrings can't match the shape: a full kind
    string has no spaces)."""
    out: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and FAULT_KIND_RE.match(node.value)
        ):
            out.setdefault(node.value, node.lineno)
    return out


@register
class HandlerExhaustivenessRule(Rule):
    rule_id = "handler-exhaustiveness"
    scope = ("hbbft_tpu/",)

    def check_project(self, project: LintProject) -> List[Finding]:
        findings = self._check_fault_kinds(project)
        wire = project.module(WIRE_PATH)
        if wire is None:
            return findings  # partial run (--diff) without wire.py: skip
        variants = _load_wire_variants(wire.tree)
        if variants is None:
            return [
                Finding(
                    self.rule_id,
                    WIRE_PATH,
                    1,
                    0,
                    "WIRE_VARIANTS registry missing or not a literal",
                )
            ]

        # -- registry ↔ codec drift ---------------------------------------
        codec_classes = _isinstance_classes(wire.tree, "_to_tree")
        # String literals outside the registry itself — the registry's own
        # entries must not satisfy their own presence check.
        registry_nodes = set()
        for node in ast.walk(wire.tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "WIRE_VARIANTS"
                for t in node.targets
            ):
                registry_nodes = {id(sub) for sub in ast.walk(node)}
        wire_strings = {
            n.value
            for n in ast.walk(wire.tree)
            if isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and id(n) not in registry_nodes
        }
        for cls, (tag, kinds) in sorted(variants.items()):
            if cls not in codec_classes:
                findings.append(
                    Finding(
                        self.rule_id,
                        WIRE_PATH,
                        1,
                        0,
                        f"registered class {cls} is never encoded by _to_tree",
                    )
                )
            for lit in (tag, *kinds):
                if lit not in wire_strings:
                    findings.append(
                        Finding(
                            self.rule_id,
                            WIRE_PATH,
                            1,
                            0,
                            f"registered variant {cls}:{lit!r} does not appear in the wire codec",
                        )
                    )
        for cls in sorted(codec_classes - set(variants)):
            if cls in ("WireError",):
                continue
            findings.append(
                Finding(
                    self.rule_id,
                    WIRE_PATH,
                    1,
                    0,
                    f"class {cls} encoded by _to_tree but missing from WIRE_VARIANTS",
                )
            )

        # -- registry ↔ handler dispatch -----------------------------------
        for cls, (path, handler_cls) in sorted(HANDLERS.items()):
            reg = variants.get(cls)
            if reg is None:
                findings.append(
                    Finding(
                        self.rule_id,
                        WIRE_PATH,
                        1,
                        0,
                        f"handler mapping for {cls} has no WIRE_VARIANTS entry",
                    )
                )
                continue
            _tag, kinds = reg
            if not kinds:
                continue  # single-variant message: nothing to dispatch on
            mod = project.module(path)
            if mod is None:
                continue  # partial run without the handler module
            handled, class_line = _kind_literals_for_class(mod.tree, handler_cls)
            for k in sorted(set(kinds) - handled):
                findings.append(
                    Finding(
                        self.rule_id,
                        path,
                        class_line,
                        0,
                        f"{handler_cls} does not dispatch wire variant {cls}:{k!r}",
                    )
                )
            for k in sorted(handled - set(kinds)):
                findings.append(
                    Finding(
                        self.rule_id,
                        path,
                        class_line,
                        0,
                        f"{handler_cls} dispatches {cls}:{k!r} which no wire variant delivers",
                    )
                )
        return findings

    def _check_fault_kinds(self, project: LintProject) -> List[Finding]:
        """FAULT_KINDS registry ↔ emitted fault-kind literals, both ways,
        plus the scenario harness's attack expectations."""
        findings: List[Finding] = []
        fault_log = project.module(FAULT_LOG_PATH)
        if fault_log is None:
            return findings  # partial run without the registry: skip
        registry = _load_fault_kinds(fault_log.tree)
        if registry is None:
            return [
                Finding(
                    self.rule_id,
                    FAULT_LOG_PATH,
                    1,
                    0,
                    "FAULT_KINDS registry missing or not a literal",
                )
            ]
        registered: Set[str] = {
            f"{prefix}:{name}"
            for prefix, names in sorted(registry.items())
            for name in names
        }

        # every emitted literal must be registered
        emitted: Dict[str, Set[str]] = {}  # kind -> modules emitting it
        emitter_paths = set(FAULT_PREFIX_MODULES.values())
        for path in sorted(project.modules):
            if (
                not path.startswith("hbbft_tpu/protocols/")
                and path not in emitter_paths
            ):
                continue
            mod = project.modules[path]
            for kind, line in sorted(_fault_kind_literals(mod).items()):
                emitted.setdefault(kind, set()).add(path)
                if kind not in registered:
                    findings.append(
                        Finding(
                            self.rule_id,
                            path,
                            line,
                            0,
                            f"fault kind {kind!r} is not registered in "
                            "core/fault_log.FAULT_KINDS",
                        )
                    )

        # every registered kind must still be emitted by its module
        for prefix, names in sorted(registry.items()):
            owner = FAULT_PREFIX_MODULES.get(prefix)
            if owner is None:
                findings.append(
                    Finding(
                        self.rule_id,
                        FAULT_LOG_PATH,
                        1,
                        0,
                        f"fault-kind prefix {prefix!r} has no owning module "
                        "in FAULT_PREFIX_MODULES",
                    )
                )
                continue
            if project.module(owner) is None:
                continue  # partial run without the emitter: skip
            for name in sorted(names):
                kind = f"{prefix}:{name}"
                if kind not in emitted:
                    findings.append(
                        Finding(
                            self.rule_id,
                            FAULT_LOG_PATH,
                            1,
                            0,
                            f"registered fault kind {kind!r} is emitted by "
                            "no protocol module",
                        )
                    )

        # scenario expectations must be registered kinds
        scenarios = project.module(SCENARIOS_PATH)
        if scenarios is not None:
            for kind, line in sorted(_fault_kind_literals(scenarios).items()):
                if kind not in registered:
                    findings.append(
                        Finding(
                            self.rule_id,
                            SCENARIOS_PATH,
                            line,
                            0,
                            f"scenario expects unregistered fault kind {kind!r}",
                        )
                    )
        return findings
