"""Handler-exhaustiveness rule: wire variants ↔ ``handle_message`` dispatch.

The wire codec (``hbbft_tpu/utils/wire.py``) declares the full message
hierarchy in ``WIRE_VARIANTS``.  Every variant a peer can legally put on
the wire must be dispatched somewhere in the owning protocol's handler
class, and every kind string the handler dispatches on must exist on the
wire — otherwise one of two drift bugs has happened:

* **unhandled variant** — the codec decodes it, the protocol silently
  mis-files it (usually into an ``unknown_kind`` fault against an honest
  peer, which is itself a safety hazard: correct nodes must never accuse
  each other).
* **orphaned kind** — the handler dispatches on a kind the codec can
  never deliver; dead code that hides a missing wire registration.

Convention this rule relies on (documented here, checked by the tests):
handler classes compare the *message parameter*, named ``message`` or
``msg``, via ``message.kind == "..."`` or ``message.kind in (...)``.
Comparisons on other receivers (e.g. ``out.kind`` for protocol outputs)
are deliberately ignored.

The rule also drift-checks ``WIRE_VARIANTS`` against the codec itself:
every registered class must appear in an ``isinstance`` test in
``_to_tree``, and every registered tag/kind must occur as a string
literal in the module.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hbbft_tpu.analysis.engine import Finding, LintProject, Rule, register

WIRE_PATH = "hbbft_tpu/utils/wire.py"

#: message class -> (module path, handler class) owning its dispatch
HANDLERS: Dict[str, Tuple[str, str]] = {
    "SbvMessage": ("hbbft_tpu/protocols/sbv_broadcast.py", "SbvBroadcast"),
    "BroadcastMessage": ("hbbft_tpu/protocols/broadcast.py", "Broadcast"),
    "BaMessage": ("hbbft_tpu/protocols/binary_agreement.py", "BinaryAgreement"),
    "SubsetMessage": ("hbbft_tpu/protocols/subset.py", "Subset"),
    "HbMessage": ("hbbft_tpu/protocols/honey_badger.py", "HoneyBadger"),
    "SqMessage": ("hbbft_tpu/protocols/sender_queue.py", "SenderQueue"),
}

_MSG_PARAM_NAMES = ("message", "msg")


def _load_wire_variants(tree: ast.AST) -> Optional[Dict[str, Tuple[str, Tuple[str, ...]]]]:
    """Extract the WIRE_VARIANTS literal from wire.py's AST (no import)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "WIRE_VARIANTS":
                    try:
                        value = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        return None
                    return {
                        cls: (tag, tuple(kinds))
                        for cls, (tag, kinds) in value.items()
                    }
    return None


def _kind_literals_for_class(tree: ast.AST, class_name: str) -> Tuple[Set[str], int]:
    """Kind strings compared against ``message.kind``/``msg.kind`` inside
    ``class_name``, plus the class's definition line."""
    kinds: Set[str] = set()
    class_line = 1
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            class_line = node.lineno
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Compare):
                    continue
                left = sub.left
                if not (
                    isinstance(left, ast.Attribute)
                    and left.attr == "kind"
                    and isinstance(left.value, ast.Name)
                    and left.value.id in _MSG_PARAM_NAMES
                ):
                    continue
                for op, comparator in zip(sub.ops, sub.comparators):
                    if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(
                        comparator, ast.Constant
                    ):
                        if isinstance(comparator.value, str):
                            kinds.add(comparator.value)
                    elif isinstance(op, (ast.In, ast.NotIn)) and isinstance(
                        comparator, (ast.Tuple, ast.List, ast.Set)
                    ):
                        for elt in comparator.elts:
                            if isinstance(elt, ast.Constant) and isinstance(
                                elt.value, str
                            ):
                                kinds.add(elt.value)
    return kinds, class_line


def _isinstance_classes(tree: ast.AST, func_name: str) -> Set[str]:
    """Class names tested via isinstance(...) inside function ``func_name``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "isinstance"
                    and len(sub.args) == 2
                ):
                    cls = sub.args[1]
                    if isinstance(cls, ast.Name):
                        out.add(cls.id)
    return out


@register
class HandlerExhaustivenessRule(Rule):
    rule_id = "handler-exhaustiveness"
    scope = ("hbbft_tpu/",)

    def check_project(self, project: LintProject) -> List[Finding]:
        findings: List[Finding] = []
        wire = project.module(WIRE_PATH)
        if wire is None:
            return findings  # partial run (--diff) without wire.py: skip
        variants = _load_wire_variants(wire.tree)
        if variants is None:
            return [
                Finding(
                    self.rule_id,
                    WIRE_PATH,
                    1,
                    0,
                    "WIRE_VARIANTS registry missing or not a literal",
                )
            ]

        # -- registry ↔ codec drift ---------------------------------------
        codec_classes = _isinstance_classes(wire.tree, "_to_tree")
        # String literals outside the registry itself — the registry's own
        # entries must not satisfy their own presence check.
        registry_nodes = set()
        for node in ast.walk(wire.tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "WIRE_VARIANTS"
                for t in node.targets
            ):
                registry_nodes = {id(sub) for sub in ast.walk(node)}
        wire_strings = {
            n.value
            for n in ast.walk(wire.tree)
            if isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and id(n) not in registry_nodes
        }
        for cls, (tag, kinds) in sorted(variants.items()):
            if cls not in codec_classes:
                findings.append(
                    Finding(
                        self.rule_id,
                        WIRE_PATH,
                        1,
                        0,
                        f"registered class {cls} is never encoded by _to_tree",
                    )
                )
            for lit in (tag, *kinds):
                if lit not in wire_strings:
                    findings.append(
                        Finding(
                            self.rule_id,
                            WIRE_PATH,
                            1,
                            0,
                            f"registered variant {cls}:{lit!r} does not appear in the wire codec",
                        )
                    )
        for cls in sorted(codec_classes - set(variants)):
            if cls in ("WireError",):
                continue
            findings.append(
                Finding(
                    self.rule_id,
                    WIRE_PATH,
                    1,
                    0,
                    f"class {cls} encoded by _to_tree but missing from WIRE_VARIANTS",
                )
            )

        # -- registry ↔ handler dispatch -----------------------------------
        for cls, (path, handler_cls) in sorted(HANDLERS.items()):
            reg = variants.get(cls)
            if reg is None:
                findings.append(
                    Finding(
                        self.rule_id,
                        WIRE_PATH,
                        1,
                        0,
                        f"handler mapping for {cls} has no WIRE_VARIANTS entry",
                    )
                )
                continue
            _tag, kinds = reg
            if not kinds:
                continue  # single-variant message: nothing to dispatch on
            mod = project.module(path)
            if mod is None:
                continue  # partial run without the handler module
            handled, class_line = _kind_literals_for_class(mod.tree, handler_cls)
            for k in sorted(set(kinds) - handled):
                findings.append(
                    Finding(
                        self.rule_id,
                        path,
                        class_line,
                        0,
                        f"{handler_cls} does not dispatch wire variant {cls}:{k!r}",
                    )
                )
            for k in sorted(handled - set(kinds)):
                findings.append(
                    Finding(
                        self.rule_id,
                        path,
                        class_line,
                        0,
                        f"{handler_cls} dispatches {cls}:{k!r} which no wire variant delivers",
                    )
                )
        return findings
