"""JAX tracer-safety rule: no host syncs inside jitted code.

Scope: ``hbbft_tpu/engine/`` and ``hbbft_tpu/ops/`` — the device layer.

Inside a jit-compiled function every array argument is a tracer; the
following force a host round-trip (``ConcretizationTypeError`` at best, a
silent per-call device sync at worst when tracing succeeds via weak
types) and are flagged:

* ``float(x)`` / ``int(x)`` / ``bool(x)`` on non-constant arguments —
  concretizes a tracer.
* ``.item()`` / ``.tolist()`` — explicit device→host transfer.
* ``np.asarray`` / ``np.array`` / ``onp.asarray`` on traced values —
  silently materializes on host (``jnp.asarray`` is the device-side
  spelling and is fine).
* ``jax.device_get`` — explicit transfer.

A function is considered jitted when it is decorated with ``@jax.jit`` /
``@partial(jax.jit, ...)`` / ``@functools.partial(jax.jit, ...)``, or its
name is passed to ``jax.jit(...)`` anywhere in the same module (the
``ops/backend.py`` ``_jitted_*`` factory idiom).  Inner ``def``\\ s of a
jitted function are jitted too.

Additionally, host-side crank loops must not sync per iteration:
``jax.device_get``/``.item()``/``.tolist()`` inside a ``for``/``while``
body is flagged even outside jit (one transfer per loop iteration is the
classic dispatch-throughput killer — batch the transfer after the loop).

Static-argument hashability: calls to a function jitted with
``static_argnums`` must not pass ``list``/``dict``/``set`` literals in a
static position, and ``static_argnames`` must not receive them by
keyword — jit caches on static args by hash.

A second rule in the family, ``deferred-fetch``, guards the pipelined
dispatch seam (ops/pipeline.py): inside the dispatch layer
(``ops/backend.py`` and ``parallel/backend.py``) every device→host
fetch must route through the pipeline's single sync point
(``pipeline.fetch_to_host``), so ``np.asarray``/``numpy.asarray``/
``jax.device_get``/``.block_until_ready()`` reappearing there is
flagged — an ad-hoc fetch added next to a dispatch silently re-serializes
the host-assembly/device-execute overlap the pipeline exists to create.
(`np.array` on host literals and `jnp.asarray` staging remain fine.)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hbbft_tpu.analysis.engine import Finding, ModuleSource, Rule, register

_CONCRETIZERS = ("float", "int", "bool")
_SYNC_METHODS = ("item", "tolist")
_NUMPY_NAMES = ("np", "numpy", "onp")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jax_jit(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _jit_call_info(call: ast.Call) -> Optional[Tuple[Optional[str], Set[int], Set[str]]]:
    """If ``call`` is ``jax.jit(target?, static_argnums=..., ...)`` return
    (target function name or None, static positions, static names)."""
    if not _is_jax_jit(call.func):
        return None
    target: Optional[str] = None
    if call.args and isinstance(call.args[0], ast.Name):
        target = call.args[0].id
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                continue
            nums.update([v] if isinstance(v, int) else list(v))
        elif kw.arg == "static_argnames":
            try:
                v = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                continue
            names.update([v] if isinstance(v, str) else list(v))
    return target, nums, names


def _decorator_jit_info(fn: ast.FunctionDef) -> Optional[Tuple[Set[int], Set[str]]]:
    """Static-arg info when ``fn`` is decorated as jitted, else None."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return set(), set()
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                info = _jit_call_info(dec)
                if info:
                    return info[1], info[2]
                return set(), set()
            if _dotted(dec.func) in ("partial", "functools.partial"):
                if dec.args and _is_jax_jit(dec.args[0]):
                    info = _jit_call_info(
                        ast.Call(func=dec.args[0], args=[], keywords=dec.keywords)
                    )
                    if info:
                        return info[1], info[2]
                    return set(), set()
    return None


@register
class TracerSafetyRule(Rule):
    rule_id = "tracer-safety"
    # obs/ (PR 13): the critpath/timeseries/flight trio sits on the
    # engine's hot path (per-output stamps, per-epoch snaps) — a stray
    # device sync or device_get in a loop there would stall the pipeline
    # exactly like one in the engine
    scope = (
        "hbbft_tpu/engine/",
        "hbbft_tpu/ops/",
        "hbbft_tpu/obs/critpath.py",
        "hbbft_tpu/obs/timeseries.py",
        "hbbft_tpu/obs/flight.py",
    )

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        findings: List[Finding] = []

        # -- pass 1: which function *bodies* are traced, and which callable
        # names carry static-arg semantics.  For `alias = jax.jit(g, ...)`
        # the body is g's but the static contract lives on calls to
        # `alias`; calling raw `g` is plain Python and is exempt.
        jit_bodies: Set[str] = set()
        static_info: Dict[str, Tuple[Set[int], Set[str]]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Call):
                    info = _jit_call_info(node.value)
                    if info:
                        if info[0] is not None:
                            jit_bodies.add(info[0])
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                static_info[t.id] = (info[1], info[2])
            elif isinstance(node, ast.Call):
                info = _jit_call_info(node)
                if info and info[0] is not None:
                    jit_bodies.add(info[0])
            elif isinstance(node, ast.FunctionDef):
                dec_info = _decorator_jit_info(node)
                if dec_info is not None:
                    jit_bodies.add(node.name)
                    static_info[node.name] = dec_info

        def emit(node: ast.AST, message: str) -> None:
            findings.append(
                Finding(self.rule_id, mod.path, node.lineno, node.col_offset, message)
            )

        # -- pass 2: host syncs inside jitted function bodies -------------
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.FunctionDef) and node.name in jit_bodies:
                self._scan_jit_body(node, emit)

        # -- pass 3: per-iteration syncs in host loops --------------------
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.While)):
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    if _dotted(sub.func) == "jax.device_get":
                        emit(sub, "jax.device_get inside a loop; batch the transfer")
                    elif (
                        isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in _SYNC_METHODS
                        and not sub.args
                    ):
                        emit(
                            sub,
                            f".{sub.func.attr}() inside a loop; batch the transfer",
                        )

        # -- pass 4: unhashable literals in static positions --------------
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = node.func.id if isinstance(node.func, ast.Name) else None
            if name not in static_info:
                continue
            nums, names = static_info[name]
            for i, arg in enumerate(node.args):
                if i in nums and isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    emit(
                        arg,
                        f"unhashable literal passed to static_argnums position {i} of {name}",
                    )
            for kw in node.keywords:
                if kw.arg in names and isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                    emit(
                        kw.value,
                        f"unhashable literal passed to static arg {kw.arg!r} of {name}",
                    )
        return findings

    def _scan_jit_body(self, fn: ast.FunctionDef, emit) -> None:
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            if isinstance(func, ast.Name) and func.id in _CONCRETIZERS:
                if sub.args and not isinstance(sub.args[0], ast.Constant):
                    emit(
                        sub,
                        f"{func.id}() on a traced value inside jitted "
                        f"{fn.name}() concretizes the tracer",
                    )
            elif isinstance(func, ast.Attribute):
                dotted = _dotted(func)
                if dotted == "jax.device_get":
                    emit(sub, f"jax.device_get inside jitted {fn.name}()")
                elif func.attr in _SYNC_METHODS and not sub.args:
                    emit(sub, f".{func.attr}() inside jitted {fn.name}() is a host sync")
                elif dotted is not None and any(
                    dotted == f"{m}.{a}"
                    for m in _NUMPY_NAMES
                    for a in ("asarray", "array")
                ):
                    emit(
                        sub,
                        f"{dotted} inside jitted {fn.name}() materializes on host; "
                        "use jnp",
                    )


@register
class DeferredFetchRule(Rule):
    """The dispatch layer's only host sync point is the deferred-fetch
    seam (ops/pipeline.py ``fetch_to_host``): flag any ``np.asarray``,
    ``jax.device_get`` or ``.block_until_ready()`` in ops/backend.py,
    parallel/backend.py, or the engine/ modules — an inline fetch there
    re-serializes the pipeline (host assembly can no longer overlap
    device execution) and bypasses the device-seconds/overlap
    attribution contract.  The engine/ scope (PR 5) guards the
    round-level assembly seam: the array engine now assembles round
    r+1's item lists while round r's dispatches execute, and a stray
    fetch in the engine would silently collapse that overlap too."""

    rule_id = "deferred-fetch"
    scope = (
        "hbbft_tpu/ops/backend.py",
        "hbbft_tpu/parallel/backend.py",
        "hbbft_tpu/engine/",
        # PR 9: the traffic driver and scenario harness hold engine hooks
        # (batch_listeners / contribution_source / pre_crank) that run
        # while pipeline dispatches may be in flight — a stray host fetch
        # there re-serializes the overlap exactly like one in the engine
        "hbbft_tpu/traffic/driver.py",
        "hbbft_tpu/net/scenarios.py",
        # PR 19: the device erasure/hash plane kernels — their results
        # must flow back through the pipeline seam like every other
        # dispatch kind, so a stray fetch here is the same regression
        "hbbft_tpu/ops/gf256.py",
        "hbbft_tpu/ops/sha256.py",
        # PR 20: the fused tower chain — its kernels/orchestration run
        # INSIDE backend dispatch graphs, so a host fetch here would
        # stall every fused_chain/rlc dispatch mid-trace
        "hbbft_tpu/ops/tower_fused.py",
        "hbbft_tpu/ops/pairing_chain.py",
    )

    def check_module(self, mod: ModuleSource) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            message = None
            if dotted == "jax.device_get":
                message = "jax.device_get in the dispatch layer"
            elif dotted is not None and any(
                dotted == f"{m}.asarray" for m in _NUMPY_NAMES
            ):
                message = f"{dotted} in the dispatch layer"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
            ):
                message = ".block_until_ready() in the dispatch layer"
            if message is not None:
                findings.append(
                    Finding(
                        self.rule_id,
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        message + " — fetches must route through the "
                        "deferred-fetch seam (ops/pipeline.fetch_to_host)",
                    )
                )
        return findings
