"""SHA-256 Merkle tree over erasure-coded shards.

Replaces the reference's `src/broadcast/merkle.rs` § (SURVEY.md §2.1): the
proposer commits to the shard vector with a Merkle root; each `Value`/`Echo`
carries a shard plus its inclusion proof, so receivers can attribute a bad
shard to the proposer (FaultLog evidence) before reconstruction.

The implementation is host-side hashlib ON PURPOSE (SURVEY.md §2.2 allows
a profile-driven host fallback): profiling a full QHB epoch (N=20 mock,
round 2) puts proof validation at ~2.7% of wall time — the O(N²) Echo
verifies scale with the same N² message count that dominates the host
protocol layer, so hashing stays a constant few percent and a device/SIMD
hash kernel would not move the epoch rate.  Revisit if the host message
path gets >10x faster (see PERF.md).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple


def _h_leaf(data: bytes) -> bytes:
    return hashlib.sha256(b"\x00" + data).digest()


def _h_node(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(b"\x01" + left + right).digest()


@dataclass(frozen=True)
class Proof:
    """Inclusion proof: a leaf value, its index, the sibling path, the root.

    Mirrors `merkle::Proof` § — carried inside Broadcast `Value`/`Echo`
    messages.
    """

    value: bytes
    index: int
    path: Tuple[bytes, ...]
    root_hash: bytes
    n_leaves: int

    def validate(self, n_leaves: int) -> bool:
        """Check the proof against its own root for a tree of ``n_leaves``."""
        if n_leaves != self.n_leaves or not 0 <= self.index < n_leaves:
            return False
        if len(self.path) != _depth(n_leaves):
            return False
        acc = _h_leaf(self.value)
        idx = self.index
        for sib in self.path:
            acc = _h_node(acc, sib) if idx % 2 == 0 else _h_node(sib, acc)
            idx //= 2
        return acc == self.root_hash

    def to_bytes(self) -> bytes:
        out = [
            self.index.to_bytes(2, "big"),
            self.n_leaves.to_bytes(2, "big"),
            self.root_hash,
            len(self.path).to_bytes(1, "big"),
            b"".join(self.path),
            len(self.value).to_bytes(4, "big"),
            self.value,
        ]
        return b"".join(out)

    @staticmethod
    def from_bytes(data: bytes) -> "Proof":
        index = int.from_bytes(data[0:2], "big")
        n_leaves = int.from_bytes(data[2:4], "big")
        root = data[4:36]
        plen = data[36]
        path = tuple(data[37 + i * 32 : 37 + (i + 1) * 32] for i in range(plen))
        off = 37 + plen * 32
        vlen = int.from_bytes(data[off : off + 4], "big")
        value = data[off + 4 : off + 4 + vlen]
        return Proof(value, index, path, root, n_leaves)


def _depth(n_leaves: int) -> int:
    d = 0
    size = 1
    while size < n_leaves:
        size *= 2
        d += 1
    return d


class MerkleTree:
    """Merkle tree over a shard vector, padded to a power of two with empty
    leaves (distinct from real leaves via the 0x00/0x01 domain tags)."""

    def __init__(self, leaves: Sequence[bytes]) -> None:
        if not leaves:
            raise ValueError("empty tree")
        self.leaves = list(leaves)
        n = len(leaves)
        size = 1 << _depth(n)
        level = [_h_leaf(v) for v in self.leaves] + [
            _h_leaf(b"") for _ in range(size - n)
        ]
        self.levels: List[List[bytes]] = [level]
        while len(level) > 1:
            level = [
                _h_node(level[i], level[i + 1]) for i in range(0, len(level), 2)
            ]
            self.levels.append(level)

    @classmethod
    def from_levels(
        cls, leaves: Sequence[bytes], levels: Sequence[Sequence[bytes]]
    ) -> "MerkleTree":
        """Adopt already-computed hash levels without re-hashing — the
        device erasure/hash plane (ops/backend.py merkle_build_batch)
        hashes all trees in one batched SHA-256 dispatch and hands the
        fetched levels here.  Callers guarantee ``levels`` is exactly
        what ``__init__`` would have computed for ``leaves``."""
        t = cls.__new__(cls)
        t.leaves = list(leaves)
        t.levels = [list(lvl) for lvl in levels]
        return t

    @property
    def root_hash(self) -> bytes:
        return self.levels[-1][0]

    def proof(self, index: int) -> Proof:
        if not 0 <= index < len(self.leaves):
            raise IndexError(index)
        path = []
        idx = index
        for level in self.levels[:-1]:
            sib = idx ^ 1
            path.append(level[sib])
            idx //= 2
        return Proof(
            value=self.leaves[index],
            index=index,
            path=tuple(path),
            root_hash=self.root_hash,
            n_leaves=len(self.leaves),
        )


class PackedProofs:
    """Every (tree, leaf-index) inclusion proof of many same-shape trees
    as rectangular arrays — the array engine's N² proof workload without
    N² ``Proof`` Python objects (value bytes + path tuples + per-proof
    validate calls dominated the round-5 "host: everything else" bucket
    at N=100; the packed form is a handful of numpy gathers per tree).

    Row order is tree-major, leaf-index minor — identical to
    ``[trees[p].proof(s) for p in ids for s in range(n_leaves)]`` — so
    :meth:`validate` returns the same boolean list the object path does.
    """

    def __init__(self, leaves, paths, indices, roots, n_leaves: int) -> None:
        self.leaves = leaves  # (T·n, leaf_len) uint8
        self.paths = paths  # (T·n, depth, 32) uint8
        self.indices = indices  # (T·n,) int32
        self.roots = roots  # (T·n, 32) uint8
        self.n_leaves = n_leaves

    def __len__(self) -> int:
        return self.leaves.shape[0]

    @classmethod
    def from_trees(
        cls, trees: Sequence["MerkleTree"], n_leaves: int, device: bool = False
    ) -> Optional["PackedProofs"]:
        """Pack all proofs of ``trees`` (each with ``n_leaves`` real
        leaves of one uniform length).  Returns None when the native
        SHA kernel is unavailable or the shapes don't fit its limits —
        callers fall back to per-proof objects.  ``device=True`` skips
        the native-kernel gate and its leaf-size cap: the packed form is
        then destined for the batched device SHA-256 verify
        (ops/backend.py merkle_verify_batch), which has neither limit;
        uniformity checks still apply (the device walk needs rectangles)."""
        import numpy as np

        from hbbft_tpu import native

        if not trees:
            return None
        if not device and not native.sha256_available():
            return None
        leaf_len = len(trees[0].leaves[0])
        if not device and leaf_len + 1 > 4096:
            return None
        for t in trees:
            if len(t.leaves) != n_leaves or any(
                len(v) != leaf_len for v in t.leaves
            ):
                return None
        depth = _depth(n_leaves)
        idx = np.arange(n_leaves, dtype=np.int64)
        per_tree_paths = []
        for t in trees:
            # level d's sibling of leaf i is node (i >> d) ^ 1 — one
            # gather per level instead of n_leaves Python proof walks
            cols = []
            for d in range(depth):
                lvl = np.frombuffer(
                    b"".join(t.levels[d]), dtype=np.uint8
                ).reshape(len(t.levels[d]), 32)
                cols.append(lvl[(idx >> d) ^ 1])
            if depth:
                per_tree_paths.append(np.stack(cols, axis=1))
            else:
                per_tree_paths.append(np.zeros((n_leaves, 0, 32), np.uint8))
        leaves = np.frombuffer(
            b"".join(b"".join(t.leaves) for t in trees), dtype=np.uint8
        ).reshape(len(trees) * n_leaves, leaf_len)
        paths = np.concatenate(per_tree_paths, axis=0)
        indices = np.tile(
            np.arange(n_leaves, dtype=np.int32), len(trees)
        )
        roots = np.repeat(
            np.frombuffer(
                b"".join(t.root_hash for t in trees), dtype=np.uint8
            ).reshape(len(trees), 32),
            n_leaves,
            axis=0,
        )
        return cls(leaves, paths, indices, roots, n_leaves)

    def validate(self, reps: int = 1) -> List[bool]:
        """Validate every packed proof ``reps`` times through the C
        SHA-NI kernel — same per-proof booleans (and the same repeated
        hash WORKLOAD) as ``validate_proofs`` over the object form."""
        from hbbft_tpu import native

        ok = native.merkle_validate_batch(
            self.leaves, self.paths, self.indices, self.roots, reps
        )
        if ok is None:  # kernel refused (shape limits): object fallback
            out = []
            for i in range(len(self)):
                p = Proof(
                    value=self.leaves[i].tobytes(),
                    index=int(self.indices[i]),
                    path=tuple(
                        self.paths[i, d].tobytes()
                        for d in range(self.paths.shape[1])
                    ),
                    root_hash=self.roots[i].tobytes(),
                    n_leaves=self.n_leaves,
                )
                good = True
                for _ in range(reps):
                    good = p.validate(self.n_leaves)
                out.append(good)
            return out
        return [bool(v) for v in ok]


def validate_proofs(proofs: Sequence[Proof], n_leaves: int, reps: int = 1) -> List[bool]:
    """Batched proof validation: the array engine's hash entry point.

    Validates each distinct proof ``reps`` times (N receivers each check
    the same honest echo — the repetition keeps the measured hash workload
    equal to N independent nodes without materializing N× Python objects).
    Returns one bool per distinct proof (identical across repetitions).

    Dispatches to the C SHA-NI batch kernel (hbbft_tpu/native) when
    available, falling back to the hashlib loop.  Proofs are grouped by
    (value length, path depth) so each group packs into rectangular
    arrays; structural checks (leaf count, index range, depth) mirror
    Proof.validate and fail fast without hashing.
    """
    import numpy as np

    from hbbft_tpu import native

    out = [False] * len(proofs)
    depth = _depth(n_leaves)
    groups: dict = {}
    for i, p in enumerate(proofs):
        if (
            p.n_leaves != n_leaves
            or not 0 <= p.index < n_leaves
            or len(p.path) != depth
            or len(p.root_hash) != 32
            or any(len(s) != 32 for s in p.path)
        ):
            continue  # structurally invalid: stays False, no hashing
        groups.setdefault(len(p.value), []).append(i)

    for leaf_len, idxs in groups.items():
        sub = [proofs[i] for i in idxs]
        ok = None
        if native.sha256_available() and leaf_len + 1 <= 4096:
            lv = np.frombuffer(
                b"".join(p.value for p in sub), dtype=np.uint8
            ).reshape(len(sub), leaf_len)
            if depth:
                paths = np.frombuffer(
                    b"".join(b"".join(p.path) for p in sub), dtype=np.uint8
                ).reshape(len(sub), depth, 32)
            else:
                paths = np.zeros((len(sub), 0, 32), dtype=np.uint8)
            indices = np.array([p.index for p in sub], dtype=np.int32)
            roots = np.frombuffer(
                b"".join(p.root_hash for p in sub), dtype=np.uint8
            ).reshape(len(sub), 32)
            ok = native.merkle_validate_batch(lv, paths, indices, roots, reps)
        if ok is None:  # hashlib fallback
            ok = []
            for p in sub:
                good = True
                for _ in range(reps):
                    good = p.validate(n_leaves)
                ok.append(good)
        for i, good in zip(idxs, ok):
            out[i] = bool(good)
    return out
