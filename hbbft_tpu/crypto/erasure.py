"""Reed–Solomon erasure coding over GF(2⁸), matmul-shaped.

Replaces the reference's `reed-solomon-erasure` crate (SURVEY.md §2.2).  The
design is deliberately *matrix-multiplication shaped* so the same math runs
as a numpy host path here and as an int8 GF(2⁸) matmul kernel on TPU
(hbbft_tpu/ops/gf256.py), per BASELINE.json ("Reed–Solomon encode/decode in
`broadcast::` moves to the same backend as GF(2^8) matmul").

Scheme: systematic Lagrange RS.  A block of k data shards (byte columns) is
interpreted, per byte position, as evaluations of a degree-<k polynomial at
points 0..k-1; parity shard j is the evaluation at k+j.  Any k of the n
shards reconstruct by interpolation.  Both encode and decode are
(n−k)×k / k×k GF(2⁸) matrix products against the shard matrix.

Field: GF(2⁸) with the 0x11D reduction polynomial and primitive element 2 —
the common RS field (the `reed-solomon-erasure` crate uses the same
polynomial).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import numpy as np


class GF256:
    """GF(2⁸) arithmetic via log/antilog tables, vectorized with numpy."""

    POLY = 0x11D

    def __init__(self) -> None:
        exp = np.zeros(512, dtype=np.int32)
        log = np.zeros(256, dtype=np.int32)
        # 2 is primitive for the 0x11D polynomial: x·2 = (x<<1) mod poly.
        x = 1
        for i in range(255):
            exp[i] = x
            log[x] = i
            x <<= 1
            if x & 0x100:
                x ^= self.POLY
        exp[255:510] = exp[0:255]
        self.EXP = exp
        self.LOG = log
        # Plain-int copies: scalar field math (Lagrange matrix setup) on 0-d
        # numpy arrays is ~50× slower than int list indexing — and matrix
        # construction dominated N=100 profiles before caching.
        self._exp = [int(v) for v in exp]
        self._log = [int(v) for v in log]

    def mul_int(self, a: int, b: int) -> int:
        if a == 0 or b == 0:
            return 0
        return self._exp[self._log[a] + self._log[b]]

    def mul(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """Elementwise GF(2⁸) product (uint8 arrays, broadcastable)."""
        a = np.asarray(a, dtype=np.int32)
        b = np.asarray(b, dtype=np.int32)
        out = self.EXP[self.LOG[a] + self.LOG[b]]
        return np.where((a == 0) | (b == 0), 0, out).astype(np.uint8)

    def inv(self, a: int) -> int:
        if a == 0:
            raise ZeroDivisionError("GF(2^8) inverse of 0")
        return int(self.EXP[255 - int(self.LOG[a])])

    def matmul(self, m: np.ndarray, x: np.ndarray) -> np.ndarray:
        """GF(2⁸) matrix product: (r×k)·(k×L) with XOR accumulation.

        Uses the native AVX2 kernel (hbbft_tpu/native) when the C toolchain
        is available — the host analogue of the reference's SIMD
        `reed-solomon-erasure` crate — else the numpy table path."""
        from hbbft_tpu import native

        got = native.gf256_matmul(m, x)
        if got is not None:
            return got
        return self.matmul_numpy(m, x)

    def matmul_numpy(self, m: np.ndarray, x: np.ndarray) -> np.ndarray:
        """Pure-numpy reference path (golden check for the C kernel)."""
        m = np.asarray(m, dtype=np.uint8)
        x = np.asarray(x, dtype=np.uint8)
        out = np.zeros((m.shape[0], x.shape[1]), dtype=np.uint8)
        for i in range(m.shape[1]):
            out ^= self.mul(m[:, i : i + 1], x[i : i + 1, :])
        return out

    # -- Lagrange matrices ---------------------------------------------------

    def lagrange_row(self, xs: Sequence[int], y: int) -> np.ndarray:
        """Row vector L with L[j] = ℓ_j(y) for basis over points ``xs``.

        In GF(2⁸), subtraction is XOR.
        """
        mul = self.mul_int
        row = np.zeros(len(xs), dtype=np.uint8)
        for j, xj in enumerate(xs):
            num, den = 1, 1
            for k, xk in enumerate(xs):
                if k == j:
                    continue
                num = mul(num, xk ^ y)
                den = mul(den, xk ^ xj)
            row[j] = mul(num, self._exp[255 - self._log[den]])
        return row

    def lagrange_matrix(self, xs: Sequence[int], ys: Sequence[int]) -> np.ndarray:
        """Matrix mapping values at points ``xs`` to values at points ``ys``."""
        return self._lagrange_matrix_cached(tuple(xs), tuple(ys)).copy()

    @functools.lru_cache(maxsize=4096)
    def _lagrange_matrix_cached(self, xs: tuple, ys: tuple) -> np.ndarray:
        """The same (xs, ys) pairs recur across nodes and epochs — every
        node of a VirtualNet builds identical broadcast/reconstruct
        matrices (SURVEY.md §2.3 inter-instance parallelism)."""
        if not ys:
            return np.zeros((0, len(xs)), dtype=np.uint8)
        return np.stack([self.lagrange_row(xs, y) for y in ys], axis=0)


_GF = GF256()


def gf256() -> GF256:
    return _GF


@functools.lru_cache(maxsize=256)
def rs_codec(data_shards: int, parity_shards: int) -> "RSCodec":
    """Shared codec instances: construction builds Lagrange matrices, and a
    Subset spawns N Broadcasts per node per epoch with identical (k, m)."""
    return RSCodec(data_shards, parity_shards)


class RSCodec:
    """Systematic (k data, m parity) Reed–Solomon codec; n = k+m ≤ 256."""

    def __init__(self, data_shards: int, parity_shards: int) -> None:
        if data_shards < 1 or parity_shards < 0:
            raise ValueError("bad shard counts")
        if data_shards + parity_shards > 256:
            # GF(2⁸) has exactly 256 distinct evaluation points (0..255),
            # so 256 total shards is the hard polynomial-interpolation cap
            # (the N=256 soak config uses all of them).
            raise ValueError("n must be ≤ 256 for GF(2^8)")
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        data_pts = list(range(self.k))
        parity_pts = list(range(self.k, self.n))
        self.encode_matrix = _GF.lagrange_matrix(data_pts, parity_pts)

    def _parity(self, mat: np.ndarray) -> np.ndarray:
        """Hook: (k, L) data matrix → (m, L) parity matrix.  Device codecs
        (hbbft_tpu/ops/gf256.py) override this with the TPU bit-matmul."""
        return _GF.matmul(self.encode_matrix, mat)

    def _interpolate(
        self, xs: Sequence[int], missing: Sequence[int], stack: np.ndarray
    ) -> np.ndarray:
        """Hook: values at points ``xs`` (k×L) → values at ``missing``."""
        return _GF.matmul(_GF.lagrange_matrix(list(xs), list(missing)), stack)

    def shard_length(self, data_len: int) -> int:
        """Shard byte-length for a ``data_len``-byte block (1 for empty —
        encode always emits non-empty shards).  Shared framing contract
        with the batched device plane (ops/backend.py groups encodes by
        this value so equal-length blocks collapse into one matmul)."""
        return -(-data_len // self.k) if data_len else 1

    def encode(self, data: bytes) -> List[bytes]:
        """Split ``data`` into k shards (zero-padded after a length prefix is
        the caller's concern) and append m parity shards."""
        shard_len = self.shard_length(len(data))
        padded = data.ljust(shard_len * self.k, b"\0")
        mat = np.frombuffer(padded, dtype=np.uint8).reshape(self.k, shard_len)
        parity = self._parity(mat)
        return [mat[i].tobytes() for i in range(self.k)] + [
            parity[j].tobytes() for j in range(self.m)
        ]

    def reconstruct(self, shards: Sequence[Optional[bytes]]) -> List[bytes]:
        """Fill in missing (None) shards from any k present ones."""
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shard slots")
        present = [(i, s) for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            raise ValueError(f"need {self.k} shards, have {len(present)}")
        use = present[: self.k]
        xs = [i for i, _ in use]
        missing = [i for i, s in enumerate(shards) if s is None]
        out = list(shards)
        if missing:
            # stack construction only when there is interpolation to do —
            # the all-present case (every lockstep RBC at quiescence) has
            # no RS math at all
            stack = np.stack(
                [np.frombuffer(s, dtype=np.uint8) for _, s in use], axis=0
            )
            rec = self._interpolate(xs, missing, stack)
            for row, idx in enumerate(missing):
                out[idx] = rec[row].tobytes()
        return [s if s is not None else b"" for s in out]

    def decode_data(self, shards: Sequence[Optional[bytes]], data_len: int) -> bytes:
        """Reconstruct and concatenate the k data shards, trimmed to
        ``data_len``."""
        full = self.reconstruct(shards)
        return b"".join(full[: self.k])[:data_len]
