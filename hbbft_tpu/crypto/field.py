"""Prime-field helpers on Python ints.

Shared by the mock group, the pure-Python BLS12-381 golden reference, and the
DKG polynomial math.  The scalar field order ``R`` is BLS12-381's subgroup
order, used by *all* group backends (including the mock) so that Shamir /
Lagrange code paths are bit-identical across backends.

Reference analogue: the `ff`/`pairing` field arithmetic underneath the
`threshold_crypto` crate (external dep — SURVEY.md §2.2).
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Sequence, Tuple

# BLS12-381 base-field modulus (Fq) and subgroup order (Fr).
Q = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001


def modinv(a: int, m: int) -> int:
    """Modular inverse via Python's native extended-gcd pow."""
    a %= m
    if a == 0:
        raise ZeroDivisionError("inverse of 0")
    return pow(a, -1, m)


def lagrange_coeffs_at_zero(xs: Sequence[int], modulus: int = R) -> List[int]:
    """Lagrange basis values λ_j(0) for interpolation points ``xs``.

    Given distinct x-coordinates, returns λ_j such that for any polynomial f
    of degree < len(xs):  f(0) = Σ_j λ_j · f(x_j)  (mod ``modulus``).

    This is the share-combination kernel: combining signature/decryption
    shares is exactly this sum computed "in the exponent"
    (threshold_crypto `combine_signatures` §).

    Memoized: every epoch combines thousands of share sets over the SAME
    x-coordinates (the lowest f+1 verified indices), and the coefficients
    are public constants of those coordinates.
    """
    return list(_lagrange_cached(tuple(xs), modulus))


@functools.lru_cache(maxsize=4096)
def _lagrange_cached(xs: tuple, modulus: int) -> tuple:
    xs = [x % modulus for x in xs]
    if len(set(xs)) != len(xs):
        raise ValueError("interpolation points must be distinct")
    coeffs = []
    for j, xj in enumerate(xs):
        num, den = 1, 1
        for k, xk in enumerate(xs):
            if k == j:
                continue
            num = (num * xk) % modulus
            den = (den * (xk - xj)) % modulus
        coeffs.append((num * modinv(den, modulus)) % modulus)
    return tuple(coeffs)


def interpolate_at_zero(points: Iterable[Tuple[int, int]], modulus: int = R) -> int:
    """Interpolate scalar values: f(0) from {(x_j, f(x_j))}."""
    pts = list(points)
    lam = lagrange_coeffs_at_zero([x for x, _ in pts], modulus)
    acc = 0
    for l, (_, y) in zip(lam, pts):
        acc = (acc + l * y) % modulus
    return acc
