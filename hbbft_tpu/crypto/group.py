"""Abstract bilinear group — the seam between protocol math and curve backends.

The reference delegates all cryptography to the `threshold_crypto` crate
(BLS12-381 via `pairing` — SURVEY.md §2.2).  Here the equivalent seam is a
small abstract *pairing group* interface; everything above it (keys, shares,
polynomials, protocols) is generic, and three backends plug in underneath:

* :class:`MockGroup` — Z_r with the bilinear map e(a, b) = a·b.  Insecure
  (discrete log is trivial) but a genuine bilinear group, so every pairing
  verification equation holds structurally.  This is the first-class
  replacement for the reference's `use-insecure-test-only-mock-crypto`
  feature (SURVEY.md §2.2) and keeps protocol tests off the pairing cost.
* ``bls381.BLS381Group`` — pure-Python BLS12-381, the golden reference.
* the JAX/TPU backend — batched limb-arithmetic kernels, golden-tested
  against the pure-Python group (hbbft_tpu/ops/).

Group elements are opaque hashable values owned by the group.  Scalars are
Python ints mod ``self.r`` (always the BLS12-381 subgroup order, see
crypto/field.py).
"""

from __future__ import annotations

import abc
import hashlib
from typing import Any, List, Sequence, Tuple

from hbbft_tpu.crypto.field import R, lagrange_coeffs_at_zero


class Group(abc.ABC):
    """A pairing-friendly group triple (G1, G2, GT) with scalar field Z_r."""

    name: str = "abstract"
    r: int = R
    g1_size: int = 0  # serialized element size in bytes
    g2_size: int = 0

    # -- generators & identities -------------------------------------------

    @abc.abstractmethod
    def g1(self) -> Any: ...

    @abc.abstractmethod
    def g2(self) -> Any: ...

    @abc.abstractmethod
    def g1_identity(self) -> Any: ...

    @abc.abstractmethod
    def g2_identity(self) -> Any: ...

    # -- group ops ----------------------------------------------------------

    @abc.abstractmethod
    def g1_add(self, a: Any, b: Any) -> Any: ...

    @abc.abstractmethod
    def g1_neg(self, a: Any) -> Any: ...

    @abc.abstractmethod
    def g1_mul(self, scalar: int, a: Any) -> Any: ...

    @abc.abstractmethod
    def g2_add(self, a: Any, b: Any) -> Any: ...

    @abc.abstractmethod
    def g2_neg(self, a: Any) -> Any: ...

    @abc.abstractmethod
    def g2_mul(self, scalar: int, a: Any) -> Any: ...

    # -- hashing to the curve ----------------------------------------------

    @abc.abstractmethod
    def hash_to_g1(self, data: bytes) -> Any: ...

    @abc.abstractmethod
    def hash_to_g2(self, data: bytes) -> Any: ...

    # -- pairing -------------------------------------------------------------

    @abc.abstractmethod
    def pairing_eq(self, a1: Any, b1: Any, a2: Any, b2: Any) -> bool:
        """Check e(a1, b1) == e(a2, b2)."""

    # -- serialization -------------------------------------------------------

    @abc.abstractmethod
    def g1_to_bytes(self, a: Any) -> bytes: ...

    @abc.abstractmethod
    def g1_from_bytes(self, data: bytes) -> Any: ...

    @abc.abstractmethod
    def g2_to_bytes(self, a: Any) -> bytes: ...

    @abc.abstractmethod
    def g2_from_bytes(self, data: bytes) -> Any: ...

    # -- derived helpers (backend-independent) ------------------------------

    def g1_lagrange_combine(self, points: Sequence[Tuple[int, Any]]) -> Any:
        """Interpolate-at-zero "in the exponent" over G1.

        ``points`` are (x_coord, element) pairs; returns Σ λ_j(0) · el_j —
        the share-combination primitive (threshold_crypto
        `combine_signatures`/`decrypt` analogue).
        """
        lam = lagrange_coeffs_at_zero([x for x, _ in points], self.r)
        acc = self.g1_identity()
        for l, (_, el) in zip(lam, points):
            acc = self.g1_add(acc, self.g1_mul(l, el))
        return acc

    def g2_lagrange_combine(self, points: Sequence[Tuple[int, Any]]) -> Any:
        lam = lagrange_coeffs_at_zero([x for x, _ in points], self.r)
        acc = self.g2_identity()
        for l, (_, el) in zip(lam, points):
            acc = self.g2_add(acc, self.g2_mul(l, el))
        return acc

    def hash_bytes(self, data: bytes, out_len: int) -> bytes:
        """Counter-mode SHA-256 XOF used as the symmetric KDF for threshold
        encryption (threshold_crypto `xor_with_hash` analogue)."""
        out = b""
        ctr = 0
        while len(out) < out_len:
            out += hashlib.sha256(ctr.to_bytes(8, "big") + data).digest()
            ctr += 1
        return out[:out_len]


class MockGroup(Group):
    """Z_r as a (degenerate) bilinear group: G1 = G2 = (Z_r, +), e(a,b) = ab.

    Bilinearity: e(x·P, y·Q) = (xP)(yQ) = xy·PQ = e(P, Q)^{xy} — exactly the
    algebra every BLS verification equation relies on, so all protocol-level
    checks behave identically to the real curve.  NOT secure; test/sim only.
    """

    name = "mock"
    g1_size = 32
    g2_size = 32

    def g1(self) -> int:
        return 1

    def g2(self) -> int:
        return 1

    def g1_identity(self) -> int:
        return 0

    def g2_identity(self) -> int:
        return 0

    def g1_add(self, a: int, b: int) -> int:
        return (a + b) % self.r

    def g1_neg(self, a: int) -> int:
        return (-a) % self.r

    def g1_mul(self, scalar: int, a: int) -> int:
        return (scalar * a) % self.r

    g2_add = g1_add
    g2_neg = g1_neg
    g2_mul = g1_mul

    def _hash_to_scalar(self, tag: bytes, data: bytes) -> int:
        h = hashlib.sha256(tag + data).digest() + hashlib.sha256(b"x" + tag + data).digest()
        return int.from_bytes(h, "big") % self.r

    def hash_to_g1(self, data: bytes) -> int:
        return self._hash_to_scalar(b"mock-g1", data)

    def hash_to_g2(self, data: bytes) -> int:
        return self._hash_to_scalar(b"mock-g2", data)

    def pairing_eq(self, a1: int, b1: int, a2: int, b2: int) -> bool:
        return (a1 * b1) % self.r == (a2 * b2) % self.r

    def g1_to_bytes(self, a: int) -> bytes:
        return int(a % self.r).to_bytes(32, "big")

    def g1_from_bytes(self, data: bytes) -> int:
        v = int.from_bytes(data, "big")
        if v >= self.r:
            raise ValueError("not a canonical mock group element")
        return v

    g2_to_bytes = g1_to_bytes
    g2_from_bytes = g1_from_bytes
