"""Secret-sharing polynomials and commitments (threshold_crypto analogue).

`Poly`, `Commitment`, `BivarPoly`, `BivarCommitment` — the Shamir/Pedersen
machinery behind key generation and the in-band DKG (reference: the
`threshold_crypto` crate's `poly` module, external dep — SURVEY.md §2.2).

Scalars live in Z_r (Python ints); commitments live in G1 of an abstract
:class:`~hbbft_tpu.crypto.group.Group`.  Shamir convention follows the
reference: share *i* is the evaluation at x = i+1 (x = 0 holds the secret).
"""

from __future__ import annotations

from typing import Any, List, Sequence

from hbbft_tpu.crypto.group import Group


def _rand_scalar(rng, r: int) -> int:
    return rng.randrange(r)


class Poly:
    """Univariate polynomial over Z_r, coefficients low-to-high degree."""

    def __init__(self, group: Group, coeffs: Sequence[int]) -> None:
        self.G = group
        self.coeffs: List[int] = [c % group.r for c in coeffs] or [0]

    @staticmethod
    def random(group: Group, degree: int, rng) -> "Poly":
        return Poly(group, [_rand_scalar(rng, group.r) for _ in range(degree + 1)])

    @staticmethod
    def constant(group: Group, c: int) -> "Poly":
        return Poly(group, [c])

    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int) -> int:
        acc = 0
        for c in reversed(self.coeffs):
            acc = (acc * x + c) % self.G.r
        return acc

    def add(self, other: "Poly") -> "Poly":
        n = max(len(self.coeffs), len(other.coeffs))
        a = self.coeffs + [0] * (n - len(self.coeffs))
        b = other.coeffs + [0] * (n - len(other.coeffs))
        return Poly(self.G, [(x + y) % self.G.r for x, y in zip(a, b)])

    def commitment(self) -> "Commitment":
        g = self.G
        return Commitment(g, [g.g1_mul(c, g.g1()) for c in self.coeffs])

    def __eq__(self, other) -> bool:
        return isinstance(other, Poly) and self.coeffs == other.coeffs


class Commitment:
    """G1 Feldman commitment to a :class:`Poly`'s coefficients."""

    def __init__(self, group: Group, coeffs: Sequence[Any]) -> None:
        self.G = group
        self.coeffs: List[Any] = list(coeffs)

    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int) -> Any:
        g = self.G
        acc = g.g1_identity()
        for c in reversed(self.coeffs):
            acc = g.g1_add(g.g1_mul(x, acc), c)
        return acc

    def add(self, other: "Commitment") -> "Commitment":
        g = self.G
        n = max(len(self.coeffs), len(other.coeffs))
        a = self.coeffs + [g.g1_identity()] * (n - len(self.coeffs))
        b = other.coeffs + [g.g1_identity()] * (n - len(other.coeffs))
        return Commitment(g, [g.g1_add(x, y) for x, y in zip(a, b)])

    def to_bytes(self) -> bytes:
        g = self.G
        out = [len(self.coeffs).to_bytes(2, "big")]
        out += [g.g1_to_bytes(c) for c in self.coeffs]
        return b"".join(out)

    @staticmethod
    def from_bytes(group: Group, data: bytes) -> "Commitment":
        n = int.from_bytes(data[:2], "big")
        sz = group.g1_size
        coeffs = [group.g1_from_bytes(data[2 + i * sz : 2 + (i + 1) * sz]) for i in range(n)]
        return Commitment(group, coeffs)

    def __eq__(self, other) -> bool:
        return isinstance(other, Commitment) and self.coeffs == other.coeffs

    def __hash__(self) -> int:
        return hash(self.to_bytes())


class BivarPoly:
    """Symmetric bivariate polynomial over Z_r, degree ``t`` in each variable.

    ``coeffs[i][j]`` multiplies x^i·y^j with coeffs[i][j] == coeffs[j][i],
    so f(x, y) == f(y, x) — the symmetry the DKG's Ack cross-checks rely on.
    """

    def __init__(self, group: Group, coeffs: Sequence[Sequence[int]]) -> None:
        self.G = group
        self.coeffs = [[c % group.r for c in row] for row in coeffs]

    @staticmethod
    def random(group: Group, degree: int, rng) -> "BivarPoly":
        n = degree + 1
        coeffs = [[0] * n for _ in range(n)]
        for i in range(n):
            for j in range(i, n):
                v = _rand_scalar(rng, group.r)
                coeffs[i][j] = v
                coeffs[j][i] = v
        return BivarPoly(group, coeffs)

    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int, y: int) -> int:
        r = self.G.r
        acc = 0
        for row in reversed(self.coeffs):
            inner = 0
            for c in reversed(row):
                inner = (inner * y + c) % r
            acc = (acc * x + inner) % r
        return acc

    def row(self, x: int) -> Poly:
        """f(x, ·) as a univariate polynomial in y."""
        r = self.G.r
        out = []
        for j in range(len(self.coeffs)):
            acc = 0
            for i in reversed(range(len(self.coeffs))):
                acc = (acc * x + self.coeffs[i][j]) % r
            out.append(acc)
        return Poly(self.G, out)

    def commitment(self) -> "BivarCommitment":
        g = self.G
        return BivarCommitment(
            g, [[g.g1_mul(c, g.g1()) for c in row] for row in self.coeffs]
        )


class BivarCommitment:
    """G1 commitment to a :class:`BivarPoly`."""

    def __init__(self, group: Group, coeffs: Sequence[Sequence[Any]]) -> None:
        self.G = group
        self.coeffs = [list(row) for row in coeffs]

    def degree(self) -> int:
        return len(self.coeffs) - 1

    def evaluate(self, x: int, y: int) -> Any:
        g = self.G
        acc = g.g1_identity()
        for row in reversed(self.coeffs):
            inner = g.g1_identity()
            for c in reversed(row):
                inner = g.g1_add(g.g1_mul(y, inner), c)
            acc = g.g1_add(g.g1_mul(x, acc), inner)
        return acc

    def col(self, y: int) -> Commitment:
        """Commitment to f(·, y) — the ACKER-variable polynomial with the
        receiver coordinate fixed.  Pre-computing this once per (part,
        receiver) turns every ack cross-check from a full (t+1)² bivariate
        evaluation into a (t+1)-term univariate one (the N=100 era change
        was >600 s before; SURVEY.md §3.4)."""
        g = self.G
        out = []
        for i in range(len(self.coeffs)):
            acc = g.g1_identity()
            for j in reversed(range(len(self.coeffs))):
                acc = g.g1_add(g.g1_mul(y, acc), self.coeffs[i][j])
            out.append(acc)
        return Commitment(g, out)

    def row(self, x: int) -> Commitment:
        """Commitment to f(x, ·)."""
        g = self.G
        out = []
        for j in range(len(self.coeffs)):
            acc = g.g1_identity()
            for i in reversed(range(len(self.coeffs))):
                acc = g.g1_add(g.g1_mul(x, acc), self.coeffs[i][j])
            out.append(acc)
        return Commitment(g, out)

    def to_bytes(self) -> bytes:
        g = self.G
        n = len(self.coeffs)
        out = [n.to_bytes(2, "big")]
        for row in self.coeffs:
            out += [g.g1_to_bytes(c) for c in row]
        return b"".join(out)

    @staticmethod
    def from_bytes(group: Group, data: bytes) -> "BivarCommitment":
        n = int.from_bytes(data[:2], "big")
        sz = group.g1_size
        coeffs = []
        off = 2
        for _ in range(n):
            row = []
            for _ in range(n):
                row.append(group.g1_from_bytes(data[off : off + sz]))
                off += sz
            coeffs.append(row)
        return BivarCommitment(group, coeffs)

    def __eq__(self, other) -> bool:
        return isinstance(other, BivarCommitment) and self.coeffs == other.coeffs
