"""Threshold-crypto key material, signatures, and encryption.

Generic over an abstract bilinear :class:`~hbbft_tpu.crypto.group.Group` —
the API mirrors the `threshold_crypto` crate the reference depends on
(SURVEY.md §2.2): `SecretKey`/`PublicKey` (per-node signing + encryption),
`SecretKeySet`/`PublicKeySet` (Shamir master keys), `SecretKeyShare`/
`PublicKeyShare`, `SignatureShare`, `Ciphertext`/`DecryptionShare`.

Conventions (matching the reference's crate):

* Public keys and decryption shares live in **G1**; signatures and message
  hashes live in **G2**.
* BLS signature:  sig = x·H2(msg);  verify  e(G1, sig) == e(PK, H2(msg)).
* Threshold encryption is Baek–Zheng style:
  U = s·G1,  V = m ⊕ KDF(s·PK),  W = s·H2(U‖V);
  ciphertext validity:     e(G1, W)  == e(U, H2(U‖V));
  decryption share i:      D_i = x_i·U;
  share validity:          e(D_i, H2(U‖V)) == e(PK_i, W);
  combine: Lagrange(D_i) = x·U = s·PK → m = V ⊕ KDF(s·PK).
* Shamir share *i* evaluates polynomials at x = i+1.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

from hbbft_tpu.crypto.group import Group
from hbbft_tpu.crypto.poly import Commitment, Poly


class CryptoError(Exception):
    pass


# ---------------------------------------------------------------------------
# Signatures
# ---------------------------------------------------------------------------


class Signature:
    """A (combined) BLS signature — a G2 element."""

    def __init__(self, group: Group, el: Any) -> None:
        self.G = group
        self.el = el

    def to_bytes(self) -> bytes:
        return self.G.g2_to_bytes(self.el)

    @staticmethod
    def from_bytes(group: Group, data: bytes) -> "Signature":
        return Signature(group, group.g2_from_bytes(data))

    def parity(self) -> bool:
        """Unbiasable coin bit: low bit of the signature's hash digest.

        This is what the common coin extracts from the combined threshold
        signature (reference `threshold_sign` §)."""
        return bool(hashlib.sha256(self.to_bytes()).digest()[0] & 1)

    def derive_randomness(self, n_bytes: int = 32) -> bytes:
        return self.G.hash_bytes(self.to_bytes(), n_bytes)

    def __eq__(self, other) -> bool:
        return isinstance(other, Signature) and self.el == other.el

    def __hash__(self) -> int:
        return hash((id(self.G), self.to_bytes()))


class SignatureShare(Signature):
    """One node's share of a threshold signature (also a G2 element)."""


# ---------------------------------------------------------------------------
# Plain (non-threshold) per-node keys — used for vote / key-gen signing
# ---------------------------------------------------------------------------


class PublicKey:
    def __init__(self, group: Group, el: Any) -> None:
        self.G = group
        self.el = el

    def verify(self, sig: Signature, msg: bytes) -> bool:
        g = self.G
        return g.pairing_eq(g.g1(), sig.el, self.el, g.hash_to_g2(msg))

    def encrypt(self, msg: bytes, rng) -> "Ciphertext":
        return Ciphertext.encrypt(self.G, self.el, msg, rng)

    def to_bytes(self) -> bytes:
        return self.G.g1_to_bytes(self.el)

    @staticmethod
    def from_bytes(group: Group, data: bytes) -> "PublicKey":
        return PublicKey(group, group.g1_from_bytes(data))

    def __eq__(self, other) -> bool:
        return isinstance(other, PublicKey) and self.el == other.el

    def __hash__(self) -> int:
        return hash((id(self.G), self.to_bytes()))

    def __lt__(self, other: "PublicKey") -> bool:
        return self.to_bytes() < other.to_bytes()


class SecretKey:
    def __init__(self, group: Group, x: int) -> None:
        self.G = group
        self.x = x % group.r

    @staticmethod
    def random(group: Group, rng) -> "SecretKey":
        return SecretKey(group, rng.randrange(group.r))

    def public_key(self) -> PublicKey:
        return PublicKey(self.G, self.G.g1_mul(self.x, self.G.g1()))

    def sign(self, msg: bytes) -> Signature:
        return Signature(self.G, self.G.g2_mul(self.x, self.G.hash_to_g2(msg)))

    def decrypt(self, ct: "Ciphertext") -> Optional[bytes]:
        """Returns plaintext, or None if the ciphertext is invalid."""
        if not ct.verify():
            return None
        g = self.G
        shared = g.g1_mul(self.x, ct.u)
        pad = g.hash_bytes(g.g1_to_bytes(shared), len(ct.v))
        return bytes(a ^ b for a, b in zip(ct.v, pad))


# ---------------------------------------------------------------------------
# Threshold encryption ciphertext
# ---------------------------------------------------------------------------


class Ciphertext:
    def __init__(self, group: Group, u: Any, v: bytes, w: Any) -> None:
        self.G = group
        self.u = u  # G1
        self.v = v  # bytes
        self.w = w  # G2

    @staticmethod
    def encrypt(group: Group, pk_el: Any, msg: bytes, rng) -> "Ciphertext":
        g = group
        s = rng.randrange(1, g.r)
        u = g.g1_mul(s, g.g1())
        shared = g.g1_mul(s, pk_el)
        pad = g.hash_bytes(g.g1_to_bytes(shared), len(msg))
        v = bytes(a ^ b for a, b in zip(msg, pad))
        h = g.hash_to_g2(g.g1_to_bytes(u) + v)
        w = g.g2_mul(s, h)
        return Ciphertext(g, u, v, w)

    def hash_point(self) -> Any:
        """H2(U‖V) — the G2 point both validity checks pair against.

        Memoized per instance: verification of every share of this
        ciphertext pairs against the same point, and the batch paths
        (backend verify, array engine) hit it O(N²) times."""
        cached = getattr(self, "_hash_point", None)
        if cached is None:
            cached = self.G.hash_to_g2(self.G.g1_to_bytes(self.u) + self.v)
            self._hash_point = cached
        return cached

    def verify(self) -> bool:
        g = self.G
        return g.pairing_eq(g.g1(), self.w, self.u, self.hash_point())

    def to_bytes(self) -> bytes:
        g = self.G
        return (
            g.g1_to_bytes(self.u)
            + g.g2_to_bytes(self.w)
            + len(self.v).to_bytes(4, "big")
            + self.v
        )

    @staticmethod
    def from_bytes(group: Group, data: bytes) -> "Ciphertext":
        g1s, g2s = group.g1_size, group.g2_size
        u = group.g1_from_bytes(data[:g1s])
        w = group.g2_from_bytes(data[g1s : g1s + g2s])
        vlen = int.from_bytes(data[g1s + g2s : g1s + g2s + 4], "big")
        v = data[g1s + g2s + 4 : g1s + g2s + 4 + vlen]
        if len(v) != vlen:
            raise CryptoError("truncated ciphertext")
        return Ciphertext(group, u, v, w)

    def digest(self) -> bytes:
        """Memoized like :meth:`hash_point`: the batch verify paths use
        the digest as their grouping key O(N³) times per epoch."""
        cached = getattr(self, "_digest", None)
        if cached is None:
            cached = hashlib.sha256(self.to_bytes()).digest()
            self._digest = cached
        return cached

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Ciphertext)
            and self.u == other.u
            and self.v == other.v
            and self.w == other.w
        )

    def __hash__(self) -> int:
        return hash(self.digest())


class DecryptionShare:
    """One node's decryption share D_i = x_i·U — a G1 element."""

    def __init__(self, group: Group, el: Any) -> None:
        self.G = group
        self.el = el

    def to_bytes(self) -> bytes:
        return self.G.g1_to_bytes(self.el)

    @staticmethod
    def from_bytes(group: Group, data: bytes) -> "DecryptionShare":
        return DecryptionShare(group, group.g1_from_bytes(data))

    def __eq__(self, other) -> bool:
        return isinstance(other, DecryptionShare) and self.el == other.el

    def __hash__(self) -> int:
        return hash((id(self.G), self.to_bytes()))


# ---------------------------------------------------------------------------
# Threshold key set (Shamir over Z_r)
# ---------------------------------------------------------------------------


class SecretKeyShare(SecretKey):
    """Share i of the master secret: x_i = f(i+1).  Signing/decrypting with
    it produces shares rather than full signatures/plaintexts."""

    def sign_share(self, msg: bytes) -> SignatureShare:
        return SignatureShare(self.G, self.G.g2_mul(self.x, self.G.hash_to_g2(msg)))

    def decrypt_share(self, ct: "Ciphertext") -> Optional[DecryptionShare]:
        if not ct.verify():
            return None
        return DecryptionShare(self.G, self.G.g1_mul(self.x, ct.u))

    def decrypt_share_unchecked(self, ct: "Ciphertext") -> DecryptionShare:
        return DecryptionShare(self.G, self.G.g1_mul(self.x, ct.u))


class PublicKeyShare(PublicKey):
    """Share i of the master public key: PK_i = f(i+1)·G1."""

    def verify_sig_share(self, share: SignatureShare, msg: bytes) -> bool:
        g = self.G
        return g.pairing_eq(g.g1(), share.el, self.el, g.hash_to_g2(msg))

    def verify_sig_share_on_point(self, share: SignatureShare, h2: Any) -> bool:
        g = self.G
        return g.pairing_eq(g.g1(), share.el, self.el, h2)

    def verify_decryption_share(self, share: DecryptionShare, ct: Ciphertext) -> bool:
        g = self.G
        return g.pairing_eq(share.el, ct.hash_point(), self.el, ct.w)


class PublicKeySet:
    """Master public key: a G1 commitment to the secret polynomial."""

    def __init__(self, commitment: Commitment) -> None:
        self.commitment = commitment
        self.G = commitment.G

    def threshold(self) -> int:
        """t: any t+1 shares reconstruct; ≤ t shares reveal nothing."""
        return self.commitment.degree()

    def public_key(self) -> PublicKey:
        return PublicKey(self.G, self.commitment.evaluate(0))

    def public_key_share(self, i: int) -> PublicKeyShare:
        return PublicKeyShare(self.G, self.commitment.evaluate(i + 1))

    def encrypt(self, msg: bytes, rng) -> Ciphertext:
        return Ciphertext.encrypt(self.G, self.commitment.evaluate(0), msg, rng)

    def combine_signatures(self, shares: Dict[int, SignatureShare]) -> Signature:
        """Lagrange-combine ≥ t+1 verified signature shares (indices are
        share numbers i, interpolated at x = i+1)."""
        if len(shares) <= self.threshold():
            raise CryptoError(
                f"need {self.threshold() + 1} shares, got {len(shares)}"
            )
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        return Signature(self.G, self.G.g2_lagrange_combine(pts))

    def combine_decryption_shares(
        self, shares: Dict[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        if len(shares) <= self.threshold():
            raise CryptoError(
                f"need {self.threshold() + 1} shares, got {len(shares)}"
            )
        g = self.G
        pts = [(i + 1, s.el) for i, s in sorted(shares.items())]
        combined = g.g1_lagrange_combine(pts)  # = s·PK
        pad = g.hash_bytes(g.g1_to_bytes(combined), len(ct.v))
        return bytes(a ^ b for a, b in zip(ct.v, pad))

    def to_bytes(self) -> bytes:
        return self.commitment.to_bytes()

    @staticmethod
    def from_bytes(group: Group, data: bytes) -> "PublicKeySet":
        return PublicKeySet(Commitment.from_bytes(group, data))

    def __eq__(self, other) -> bool:
        return isinstance(other, PublicKeySet) and self.commitment == other.commitment

    def __hash__(self) -> int:
        return hash(self.to_bytes())


class SecretKeySet:
    """Dealer-side master secret: a random degree-t polynomial over Z_r."""

    def __init__(self, poly: Poly) -> None:
        self.poly = poly
        self.G = poly.G

    @staticmethod
    def random(group: Group, threshold: int, rng) -> "SecretKeySet":
        return SecretKeySet(Poly.random(group, threshold, rng))

    def threshold(self) -> int:
        return self.poly.degree()

    def secret_key_share(self, i: int) -> SecretKeyShare:
        return SecretKeyShare(self.G, self.poly.evaluate(i + 1))

    def public_keys(self) -> PublicKeySet:
        return PublicKeySet(self.poly.commitment())
