"""Pure-Python BLS12-381 — the golden-reference pairing group.

Replaces the reference's `threshold_crypto`/`pairing` Rust crates (SURVEY.md
§2.2) with a from-scratch implementation of the BLS12-381 curve: the Fq →
Fq2 → Fq6 → Fq12 tower, G1/G2 affine arithmetic, a generic Miller loop over
E(Fq12) via the untwist map, and the final exponentiation done directly with
a big-integer exponent (clarity over speed — this backend exists to be
*obviously correct*, golden-testing both the protocol layer and the JAX/TPU
limb kernels in hbbft_tpu/ops/).

Conventions:
* Tower: Fq2 = Fq[u]/(u²+1), Fq6 = Fq2[v]/(v³−ξ) with ξ = 1+u,
  Fq12 = Fq6[w]/(w²−v); so w⁶ = ξ, and the D-twist untwist map
  ψ(x′,y′) = (x′/w², y′/w³) carries E′: y²=x³+4ξ (G2) onto E: y²=x³+4.
* Hash-to-curve: deterministic try-and-increment + cofactor clearing.
  Internal consistency is what the framework needs (all backends share this
  construction); it is NOT the IETF hash-to-curve suite.
* Serialization: ZCash-style compressed points (48B G1 / 96B G2) with the
  standard 3-bit flag prefix.

Sanity is enforced by tests: subgroup orders, bilinearity
e(aP,bQ) = e(P,Q)^{ab}, non-degeneracy, and signature/encryption round
trips shared with the mock group's suite.
"""

from __future__ import annotations

import functools
import hashlib
from typing import Any, List, Optional, Tuple

from hbbft_tpu.crypto.field import Q, R
from hbbft_tpu.crypto.group import Group

# BLS parameter x (negative): the curve is parameterized by x = -0xd201000000010000.
BLS_X = 0xD201000000010000
BLS_X_IS_NEG = True

G1_B = 4
G1_COFACTOR = 0x396C8C005555E1568C00AAAB0000AAAB

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# G2 effective cofactor (h2): clearing it maps any twist point into the
# r-order subgroup.
G2_COFACTOR = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5


# ---------------------------------------------------------------------------
# Tower fields.  Elements are tuples of ints/tuples; modules-level functions
# keep the golden ref allocation-light and trivially portable to limb form.
# ---------------------------------------------------------------------------

# -- Fq2: a = (a0, a1) = a0 + a1·u, u² = −1 ---------------------------------


def fq2_add(a, b):
    return ((a[0] + b[0]) % Q, (a[1] + b[1]) % Q)


def fq2_sub(a, b):
    return ((a[0] - b[0]) % Q, (a[1] - b[1]) % Q)


def fq2_neg(a):
    return ((-a[0]) % Q, (-a[1]) % Q)


def fq2_mul(a, b):
    # (a0+a1u)(b0+b1u) = a0b0 - a1b1 + (a0b1 + a1b0)u
    return (
        (a[0] * b[0] - a[1] * b[1]) % Q,
        (a[0] * b[1] + a[1] * b[0]) % Q,
    )


def fq2_sqr(a):
    return fq2_mul(a, a)


def fq2_scalar(a, k: int):
    return ((a[0] * k) % Q, (a[1] * k) % Q)


def fq2_conj(a):
    return (a[0], (-a[1]) % Q)


def fq2_inv(a):
    # 1/(a0+a1u) = (a0 - a1u)/(a0² + a1²)
    norm = (a[0] * a[0] + a[1] * a[1]) % Q
    inv = pow(norm, -1, Q)
    return ((a[0] * inv) % Q, (-a[1] * inv) % Q)


def fq2_mul_xi(a):
    """Multiply by ξ = 1 + u."""
    return ((a[0] - a[1]) % Q, (a[0] + a[1]) % Q)


FQ2_ZERO = (0, 0)
FQ2_ONE = (1, 0)


def fq2_is_zero(a) -> bool:
    return a[0] == 0 and a[1] == 0


def fq2_sqrt(a) -> Optional[Tuple[int, int]]:
    """Square root in Fq2 via the complex method (q ≡ 3 mod 4)."""
    if fq2_is_zero(a):
        return FQ2_ZERO
    a0, a1 = a
    if a1 == 0:
        # sqrt of an Fq element: either sqrt(a0) or sqrt(-a0)·u.
        s = _fq_sqrt(a0)
        if s is not None:
            return (s, 0)
        s = _fq_sqrt((-a0) % Q)
        if s is None:
            return None
        return (0, s)
    norm = (a0 * a0 + a1 * a1) % Q
    alpha = _fq_sqrt(norm)
    if alpha is None:
        return None
    inv2 = pow(2, -1, Q)
    delta = ((a0 + alpha) * inv2) % Q
    x0 = _fq_sqrt(delta)
    if x0 is None:
        delta = ((a0 - alpha) * inv2) % Q
        x0 = _fq_sqrt(delta)
        if x0 is None:
            return None
    x1 = (a1 * pow(2 * x0 % Q, -1, Q)) % Q
    cand = (x0, x1)
    return cand if fq2_sqr(cand) == a else None


def _fq_sqrt(a: int) -> Optional[int]:
    """Square root in Fq (q ≡ 3 mod 4): a^((q+1)/4), verified."""
    a %= Q
    s = pow(a, (Q + 1) // 4, Q)
    return s if (s * s) % Q == a else None


# -- Fq6: a = (c0, c1, c2) over Fq2, v³ = ξ ---------------------------------


def fq6_add(a, b):
    return tuple(fq2_add(x, y) for x, y in zip(a, b))


def fq6_sub(a, b):
    return tuple(fq2_sub(x, y) for x, y in zip(a, b))


def fq6_neg(a):
    return tuple(fq2_neg(x) for x in a)


def fq6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    # Karatsuba-style (school form is fine for golden ref)
    c0 = fq2_add(t0, fq2_mul_xi(fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), fq2_add(t1, t2))))
    c1 = fq2_add(
        fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), fq2_add(t0, t1)),
        fq2_mul_xi(t2),
    )
    c2 = fq2_add(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), fq2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fq6_mul_by_v(a):
    """Multiply by v: (c0,c1,c2) → (ξ·c2, c0, c1)."""
    return (fq2_mul_xi(a[2]), a[0], a[1])


def fq6_inv(a):
    a0, a1, a2 = a
    c0 = fq2_sub(fq2_sqr(a0), fq2_mul_xi(fq2_mul(a1, a2)))
    c1 = fq2_sub(fq2_mul_xi(fq2_sqr(a2)), fq2_mul(a0, a1))
    c2 = fq2_sub(fq2_sqr(a1), fq2_mul(a0, a2))
    t = fq2_add(
        fq2_mul_xi(fq2_add(fq2_mul(a2, c1), fq2_mul(a1, c2))), fq2_mul(a0, c0)
    )
    t_inv = fq2_inv(t)
    return (fq2_mul(c0, t_inv), fq2_mul(c1, t_inv), fq2_mul(c2, t_inv))


FQ6_ZERO = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


# -- Fq12: a = (c0, c1) over Fq6, w² = v ------------------------------------


def fq12_add(a, b):
    return (fq6_add(a[0], b[0]), fq6_add(a[1], b[1]))


def fq12_sub(a, b):
    return (fq6_sub(a[0], b[0]), fq6_sub(a[1], b[1]))


def fq12_neg(a):
    return (fq6_neg(a[0]), fq6_neg(a[1]))


def fq12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), fq6_add(t0, t1))
    return (c0, c1)


def fq12_sqr(a):
    return fq12_mul(a, a)


def fq12_inv(a):
    a0, a1 = a
    t = fq6_sub(fq6_mul(a0, a0), fq6_mul_by_v(fq6_mul(a1, a1)))
    t_inv = fq6_inv(t)
    return (fq6_mul(a0, t_inv), fq6_neg(fq6_mul(a1, t_inv)))


def fq12_conj(a):
    """Conjugation = Frobenius^6: (c0, c1) → (c0, −c1)."""
    return (a[0], fq6_neg(a[1]))


def fq12_pow(a, e: int):
    if e < 0:
        return fq12_pow(fq12_inv(a), -e)
    result = FQ12_ONE
    base = a
    while e:
        if e & 1:
            result = fq12_mul(result, base)
        base = fq12_sqr(base)
        e >>= 1
    return result


FQ12_ZERO = (FQ6_ZERO, FQ6_ZERO)
FQ12_ONE = (FQ6_ONE, FQ6_ZERO)


def fq12_from_fq(x: int):
    return (((x % Q, 0), FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


# w ∈ Fq12 (the tower generator), w² = v:
FQ12_W = (FQ6_ZERO, FQ6_ONE)
FQ12_W2 = (
    (FQ2_ZERO, FQ2_ONE, FQ2_ZERO),
    FQ6_ZERO,
)  # w² = v
FQ12_W3 = (FQ6_ZERO, (FQ2_ZERO, FQ2_ONE, FQ2_ZERO))  # w³ = v·w


def fq12_from_fq2(x) -> Any:
    """Embed Fq2 into Fq12 (constant coefficient)."""
    return ((x, FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


# ---------------------------------------------------------------------------
# Elliptic curve arithmetic — affine, generic over a field implementation.
# Points are (x, y) tuples or None (infinity).
# ---------------------------------------------------------------------------


class _Fld:
    """Tiny vtable so the same curve code serves Fq, Fq2 and Fq12."""

    def __init__(self, add, sub, mul, inv, neg, zero, one, eq=None):
        self.add, self.sub, self.mul, self.inv, self.neg = add, sub, mul, inv, neg
        self.zero, self.one = zero, one


FQ = _Fld(
    add=lambda a, b: (a + b) % Q,
    sub=lambda a, b: (a - b) % Q,
    mul=lambda a, b: (a * b) % Q,
    inv=lambda a: pow(a, -1, Q),
    neg=lambda a: (-a) % Q,
    zero=0,
    one=1,
)
FQ2 = _Fld(fq2_add, fq2_sub, fq2_mul, fq2_inv, fq2_neg, FQ2_ZERO, FQ2_ONE)
FQ12 = _Fld(fq12_add, fq12_sub, fq12_mul, fq12_inv, fq12_neg, FQ12_ZERO, FQ12_ONE)


def ec_double(F: _Fld, p):
    if p is None:
        return None
    x, y = p
    if y == F.zero:
        return None
    # λ = 3x²/2y
    three_x2 = F.mul(F.mul(x, x), 3 if F is FQ else _small(F, 3))
    lam = F.mul(three_x2, F.inv(F.mul(y, 2 if F is FQ else _small(F, 2))))
    xr = F.sub(F.sub(F.mul(lam, lam), x), x)
    yr = F.sub(F.mul(lam, F.sub(x, xr)), y)
    return (xr, yr)


def _small(F: _Fld, k: int):
    """k·1 in the field (for the scalar constants in the formulas)."""
    acc = F.zero
    for _ in range(k):
        acc = F.add(acc, F.one)
    return acc


def ec_add(F: _Fld, p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if y1 == y2:
            return ec_double(F, p)
        return None
    lam = F.mul(F.sub(y2, y1), F.inv(F.sub(x2, x1)))
    xr = F.sub(F.sub(F.mul(lam, lam), x1), x2)
    yr = F.sub(F.mul(lam, F.sub(x1, xr)), y1)
    return (xr, yr)


def ec_neg(F: _Fld, p):
    if p is None:
        return None
    return (p[0], F.neg(p[1]))


def ec_mul(F: _Fld, k: int, p):
    if k < 0:
        return ec_mul(F, -k, ec_neg(F, p))
    result = None
    acc = p
    while k:
        if k & 1:
            result = ec_add(F, result, acc)
        acc = ec_double(F, acc)
        k >>= 1
    return result


def g1_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - (x * x * x + G1_B)) % Q == 0


def g2_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    b = fq2_scalar(fq2_mul_xi(FQ2_ONE), G1_B)  # 4(1+u)
    return fq2_sub(fq2_sqr(y), fq2_add(fq2_mul(fq2_sqr(x), x), b)) == FQ2_ZERO


# ---------------------------------------------------------------------------
# Subgroup membership (fast endomorphism checks).
#
# The device scalar ladders (ops/curve.py) carry unequal-add safety proofs
# that hold only for points of order r, and pairing checks cannot see
# cofactor-torsion components — so every point deserialized from untrusted
# bytes must be confirmed to lie in the r-order subgroup before it reaches
# a ladder (the reference's pairing crate enforces the same invariant in
# its checked deserialization; SURVEY.md §2.2 threshold_crypto row).
#
# Full-order checks (r·P == ∞, 255-bit ladder) are the fallback; the fast
# path uses the standard eigenvalue identities (Scott, "A note on group
# membership tests for G1, G2 and GT on BLS pairing-friendly curves"):
#   G1: φ(x, y) = (β·x, y) with β a primitive cube root of unity acts on
#       the r-subgroup as multiplication by λ = x²−1 (λ³ ≡ 1 mod r since
#       r = x⁴−x²+1)  →  check φ(P) == λ·P           (126-bit ladder)
#   G2: ψ = twist∘Frobenius∘untwist acts on G2 as multiplication by the
#       curve parameter x (q ≡ t−1 = x mod r)        (64-bit ladder)
# Both identities are self-validated against the generators at import; if
# the constant resolution ever failed we would fall back to the full-order
# check rather than accept a wrong identity.
# ---------------------------------------------------------------------------


def _fq2_pow(a, e: int):
    acc = FQ2_ONE
    while e:
        if e & 1:
            acc = fq2_mul(acc, a)
        a = fq2_sqr(a)
        e >>= 1
    return acc


def _find_beta() -> int:
    for base in (2, 3, 5, 7, 11, 13):
        b = pow(base, (Q - 1) // 3, Q)
        if b != 1:
            return b
    raise AssertionError("no cube non-residue found")


_G1_LAMBDA = BLS_X * BLS_X - 1  # eigenvalue of φ on G1 (x² − 1 ≡ (−x²)² mod r)


def _resolve_beta() -> Optional[int]:
    """Pick the cube root of unity whose φ matches multiplication by λ on
    the generator; None if neither candidate validates (then the
    full-order fallback is used — correctness never depends on φ)."""
    beta = _find_beta()
    for b in (beta, beta * beta % Q):
        if (b * G1_GEN[0] % Q, G1_GEN[1]) == ec_mul(FQ, _G1_LAMBDA, G1_GEN):
            return b
    return None


_BETA = _resolve_beta()


def g1_in_subgroup(p) -> bool:
    """Order-r membership for an on-curve G1 point: φ(P) == λ·P."""
    if p is None:
        return True
    if _BETA is None:  # pragma: no cover - β resolves for BLS12-381
        return ec_mul(FQ, R, p) is None
    return ((_BETA * p[0]) % Q, p[1]) == ec_mul(FQ, _G1_LAMBDA, p)


def _resolve_psi():
    """Pick the (c_x, c_y) pair for ψ(x, y) = (c_x·x̄, c_y·ȳ) by validating
    ψ(G2_GEN) == x·G2_GEN; returns None if no candidate matches (then the
    full-order fallback is used — correctness never depends on ψ)."""
    t3 = _fq2_pow(fq2_mul_xi(FQ2_ONE), (Q - 1) // 3)  # (1+u)^((q-1)/3)
    t2 = _fq2_pow(fq2_mul_xi(FQ2_ONE), (Q - 1) // 2)  # (1+u)^((q-1)/2)
    want = ec_mul(FQ2, -BLS_X if BLS_X_IS_NEG else BLS_X, G2_GEN)
    for cx, cy in (
        (fq2_inv(t3), fq2_inv(t2)),
        (t3, t2),
        (fq2_conj(fq2_inv(t3)), fq2_conj(fq2_inv(t2))),
        (fq2_conj(t3), fq2_conj(t2)),
    ):
        x, y = G2_GEN
        if (fq2_mul(cx, fq2_conj(x)), fq2_mul(cy, fq2_conj(y))) == want:
            return cx, cy
    return None


_PSI_CONSTS = _resolve_psi()
#: the signed BLS parameter u — the single source for every site that
#: needs it (ψ eigenvalue, both cofactor clearings)
_U = -BLS_X if BLS_X_IS_NEG else BLS_X
_G2_EIGEN = _U


def g2_in_subgroup(p) -> bool:
    """Order-r membership for an on-curve G2 point: ψ(P) == x·P."""
    if p is None:
        return True
    if _PSI_CONSTS is None:  # pragma: no cover - ψ resolves for BLS12-381
        return ec_mul(FQ2, R, p) is None
    return _psi(p) == ec_mul(FQ2, _G2_EIGEN, p)


def _psi(p):
    """The twist endomorphism (requires _PSI_CONSTS; p not None)."""
    cx, cy = _PSI_CONSTS
    x, y = p
    return (fq2_mul(cx, fq2_conj(x)), fq2_mul(cy, fq2_conj(y)))


def clear_cofactor_g1(p):
    """Map an on-curve G1 point into the r-order subgroup.

    Fast path: [1−u]·P (the standard BLS12 effective cofactor — a 64-bit
    ladder instead of the 126-bit h1 multiplication).  Falls back to the
    full-cofactor multiply if the φ self-validation ever failed."""
    if _BETA is None:  # pragma: no cover - β resolves for BLS12-381
        return ec_mul(FQ, G1_COFACTOR, p)
    return ec_mul(FQ, 1 - _U, p)


def clear_cofactor_g2(p):
    """Map an on-curve twist point into the r-order G2 subgroup.

    Budroni–Pintore fast clearing: [u²−u−1]·P + [u−1]·ψ(P) + ψ²(2P) —
    three 64-bit ladders plus endomorphism applications, ~3× cheaper than
    the 508-bit effective-cofactor ladder.  This DEFINES the hash-to-G2
    output (it differs from the naive h2 multiple by a fixed scalar),
    which is fine: the framework is its own hash-to-curve universe and
    nothing persists hash outputs across versions."""
    if p is None:
        return None
    if _PSI_CONSTS is None:  # pragma: no cover - ψ resolves for BLS12-381
        return ec_mul(FQ2, G2_COFACTOR, p)
    uP = ec_mul(FQ2, _U, p)
    u1P = ec_add(FQ2, uP, ec_neg(FQ2, p))  # [u−1]P
    t = ec_add(FQ2, ec_mul(FQ2, _U, u1P), ec_neg(FQ2, p))  # [u²−u−1]P
    psiP = _psi(p)
    t = ec_add(FQ2, t, ec_add(FQ2, ec_mul(FQ2, _U, psiP), ec_neg(FQ2, psiP)))
    return ec_add(FQ2, t, _psi(_psi(ec_double(FQ2, p))))


# ---------------------------------------------------------------------------
# Pairing: untwist → generic Miller loop over E(Fq12) → final exponentiation.
# ---------------------------------------------------------------------------


def _untwist(q2):
    """ψ: E′(Fq2) → E(Fq12), (x,y) ↦ (x/w², y/w³)."""
    if q2 is None:
        return None
    x, y = q2
    xw = fq12_mul(fq12_from_fq2(x), fq12_inv(FQ12_W2))
    yw = fq12_mul(fq12_from_fq2(y), fq12_inv(FQ12_W3))
    return (xw, yw)


def _line(F: _Fld, p1, p2, t):
    """Evaluate the line through p1, p2 at point t (all in E(Fq12))."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        lam = F.mul(F.sub(y2, y1), F.inv(F.sub(x2, x1)))
        return F.sub(F.sub(yt, y1), F.mul(lam, F.sub(xt, x1)))
    if y1 == y2:
        three = _small(F, 3)
        two = _small(F, 2)
        lam = F.mul(F.mul(three, F.mul(x1, x1)), F.inv(F.mul(two, y1)))
        return F.sub(F.sub(yt, y1), F.mul(lam, F.sub(xt, x1)))
    return F.sub(xt, x1)  # vertical line


def miller_loop(q12, p12):
    """f_{|x|, Q}(P) with the standard double-and-add Miller loop."""
    if q12 is None or p12 is None:
        return FQ12_ONE
    F = FQ12
    r = q12
    f = FQ12_ONE
    for bit in bin(BLS_X)[3:]:  # skip the leading 1
        f = fq12_mul(fq12_sqr(f), _line(F, r, r, p12))
        r = ec_double(F, r)
        if bit == "1":
            f = fq12_mul(f, _line(F, r, q12, p12))
            r = ec_add(F, r, q12)
    if BLS_X_IS_NEG:
        # x < 0: f_{x,Q} = conj(f_{|x|,Q}) up to final exponentiation.
        f = fq12_conj(f)
    return f


_FINAL_EXP = (Q**12 - 1) // R


def pairing(p1, q2):
    """e(P, Q) for P ∈ G1(Fq), Q ∈ G2(Fq2) — full optimal-ate value."""
    if p1 is None or q2 is None:
        return FQ12_ONE
    p12 = (fq12_from_fq(p1[0]), fq12_from_fq(p1[1]))
    q12 = _untwist(q2)
    f = miller_loop(q12, p12)
    return fq12_pow(f, _FINAL_EXP)


def pairing_eq(a1, b1, a2, b2) -> bool:
    """e(a1, b1) == e(a2, b2), via e(a1,b1)·e(−a2,b2) == 1."""
    if a1 is None or b1 is None:
        return a2 is None or b2 is None or pairing(a2, b2) == FQ12_ONE
    if a2 is None or b2 is None:
        return pairing(a1, b1) == FQ12_ONE
    p12_a = (fq12_from_fq(a1[0]), fq12_from_fq(a1[1]))
    p12_b = (fq12_from_fq(a2[0]), fq12_from_fq((-a2[1]) % Q))
    f = fq12_mul(miller_loop(_untwist(b1), p12_a), miller_loop(_untwist(b2), p12_b))
    return fq12_pow(f, _FINAL_EXP) == FQ12_ONE


# ---------------------------------------------------------------------------
# Hashing to the curve (try-and-increment; internally consistent, not IETF).
# ---------------------------------------------------------------------------


def _hash_fq(tag: bytes, data: bytes, ctr: int) -> int:
    h = b""
    for i in range(2):  # 64 bytes → uniform enough mod Q
        h += hashlib.sha256(tag + ctr.to_bytes(4, "big") + bytes([i]) + data).digest()
    return int.from_bytes(h, "big") % Q


def hash_to_g1(data: bytes):
    ctr = 0
    while True:
        x = _hash_fq(b"bls381-g1", data, ctr)
        y2 = (x * x * x + G1_B) % Q
        y = _fq_sqrt(y2)
        if y is not None:
            # Deterministic sign choice: take the "smaller" root.
            y = min(y, Q - y)
            p = clear_cofactor_g1((x, y))
            if p is not None:
                return p
        ctr += 1


def _hash_to_g2_pure(data: bytes):
    ctr = 0
    while True:
        x = (
            _hash_fq(b"bls381-g2c0", data, ctr),
            _hash_fq(b"bls381-g2c1", data, ctr),
        )
        b = fq2_scalar(fq2_mul_xi(FQ2_ONE), G1_B)
        y2 = fq2_add(fq2_mul(fq2_sqr(x), x), b)
        y = fq2_sqrt(y2)
        if y is not None:
            neg = fq2_neg(y)
            y = min(y, neg)  # lexicographic tuple order: deterministic sign
            p = clear_cofactor_g2((x, y))
            if p is not None:
                return p
        ctr += 1


def hash_to_g2(data: bytes):
    """Native C kernel when available (point-for-point identical, golden-
    checked at first use — native/hashg2_kernel.c), else the pure path.

    The pure path costs 13.65 ms/doc (~87% in the affine cofactor
    clearing); the DKG hashes 2(N²+N³) docs per era change, so this is
    the macro-scale host wall (PERF.md round 5)."""
    from hbbft_tpu import native

    p = native.hashg2(data, pure_fn=_hash_to_g2_pure)
    if p is not None:
        return p
    return _hash_to_g2_pure(data)


# ---------------------------------------------------------------------------
# Serialization (ZCash-style compressed).
# ---------------------------------------------------------------------------


def g1_to_bytes(p) -> bytes:
    if p is None:
        out = bytearray(48)
        out[0] = 0b1100_0000
        return bytes(out)
    x, y = p
    flag_sign = 1 if y > (Q - 1) // 2 else 0
    data = bytearray(x.to_bytes(48, "big"))
    data[0] |= 0b1000_0000 | (flag_sign << 5)
    return bytes(data)


@functools.lru_cache(maxsize=16384)
def g1_from_bytes(data: bytes):
    """Checked deserialization: on-curve AND order-r (g1_in_subgroup) — the
    device ladders' precondition. LRU'd because the protocol re-parses the
    same ciphertext bytes N times per epoch (honey_badger.py decrypt setup)
    and the subgroup ladder is ~17 ms of host Python."""
    if len(data) != 48:
        raise ValueError("G1 point must be 48 bytes")
    flags = data[0]
    if not flags & 0b1000_0000:
        raise ValueError("uncompressed encoding unsupported")
    if flags & 0b0100_0000:
        # canonical infinity: sign bit clear, all remaining bits zero
        if flags != 0b1100_0000 or any(data[1:]):
            raise ValueError("non-canonical infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0b0001_1111]) + data[1:], "big")
    if x >= Q:
        raise ValueError("x out of range")
    y = _fq_sqrt((x * x * x + G1_B) % Q)
    if y is None:
        raise ValueError("not on curve")
    sign = (flags >> 5) & 1
    if (1 if y > (Q - 1) // 2 else 0) != sign:
        y = Q - y
    if not g1_in_subgroup((x, y)):
        raise ValueError("not in the r-order subgroup")
    return (x, y)


def g2_to_bytes(p) -> bytes:
    if p is None:
        out = bytearray(96)
        out[0] = 0b1100_0000
        return bytes(out)
    (x0, x1), (y0, y1) = p
    sign = 1 if (y1, y0) > ((Q - y1) % Q, (Q - y0) % Q) else 0
    data = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    data[0] |= 0b1000_0000 | (sign << 5)
    return bytes(data)


@functools.lru_cache(maxsize=16384)
def g2_from_bytes(data: bytes):
    """Checked deserialization: on-curve AND order-r (g2_in_subgroup)."""
    if len(data) != 96:
        raise ValueError("G2 point must be 96 bytes")
    flags = data[0]
    if not flags & 0b1000_0000:
        raise ValueError("uncompressed encoding unsupported")
    if flags & 0b0100_0000:
        # canonical infinity: sign bit clear, all remaining bits zero
        if flags != 0b1100_0000 or any(data[1:]):
            raise ValueError("non-canonical infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0b0001_1111]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= Q or x1 >= Q:
        raise ValueError("x out of range")
    x = (x0, x1)
    b = fq2_scalar(fq2_mul_xi(FQ2_ONE), G1_B)
    y = fq2_sqrt(fq2_add(fq2_mul(fq2_sqr(x), x), b))
    if y is None:
        raise ValueError("not on curve")
    y0, y1 = y
    sign = (flags >> 5) & 1
    have = 1 if (y1, y0) > ((Q - y1) % Q, (Q - y0) % Q) else 0
    if have != sign:
        y = fq2_neg(y)
    if not g2_in_subgroup((x, y)):
        raise ValueError("not in the r-order subgroup")
    return (x, y)


# ---------------------------------------------------------------------------
# Group implementation
# ---------------------------------------------------------------------------


class BLS381Group(Group):
    """Real BLS12-381 backend for the abstract Group seam."""

    name = "bls381"
    g1_size = 48
    g2_size = 96

    def g1(self):
        return G1_GEN

    def g2(self):
        return G2_GEN

    def g1_identity(self):
        return None

    def g2_identity(self):
        return None

    def g1_add(self, a, b):
        return ec_add(FQ, a, b)

    def g1_neg(self, a):
        return ec_neg(FQ, a)

    def g1_mul(self, scalar: int, a):
        return ec_mul(FQ, scalar % R, a)

    def g2_add(self, a, b):
        return ec_add(FQ2, a, b)

    def g2_neg(self, a):
        return ec_neg(FQ2, a)

    def g2_mul(self, scalar: int, a):
        return ec_mul(FQ2, scalar % R, a)

    def hash_to_g1(self, data: bytes):
        return hash_to_g1(data)

    def hash_to_g2(self, data: bytes):
        return hash_to_g2(data)

    def pairing_eq(self, a1, b1, a2, b2) -> bool:
        return pairing_eq(a1, b1, a2, b2)

    def g1_to_bytes(self, a) -> bytes:
        return g1_to_bytes(a)

    def g1_from_bytes(self, data: bytes):
        return g1_from_bytes(data)

    def g2_to_bytes(self, a) -> bytes:
        return g2_to_bytes(a)

    def g2_from_bytes(self, data: bytes):
        return g2_from_bytes(data)
