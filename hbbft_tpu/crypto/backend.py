"""CryptoBackend — the north-star seam between protocols and device kernels.

BASELINE.json's north star: "introduce a `CryptoBackend` trait behind the
existing `DistAlgorithm` step boundary so that `threshold_sign`,
`threshold_decrypt`, and the `binary_agreement` common coin hand their
BLS12-381 pairing checks, multi-scalar-mults, and Lagrange share-combination
to a batched device kernel".

A backend bundles:

* a :class:`~hbbft_tpu.crypto.group.Group` (the curve implementation),
* key-material factories,
* **batched** verify/combine entry points — the protocols and the VirtualNet
  runtime only ever call these with *lists* of independent work items, so a
  device backend can resolve a whole crank-round of pairing checks in one
  dispatch (SURVEY.md §7 "deferred verification").

Implementations:

* :class:`MockBackend`   — MockGroup; replaces the reference's
  `use-insecure-test-only-mock-crypto` Cargo feature (SURVEY.md §2.2).
* :class:`CpuBackend`    — pure-Python BLS12-381 golden reference.
* ``TpuBackend`` (hbbft_tpu/ops/backend.py) — JAX batched kernels.
"""

from __future__ import annotations

import abc
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from hbbft_tpu.crypto.group import Group, MockGroup
from hbbft_tpu.crypto.keys import (
    Ciphertext,
    DecryptionShare,
    PublicKeySet,
    PublicKeyShare,
    SecretKey,
    SecretKeySet,
    Signature,
    SignatureShare,
)


class CryptoBackend(abc.ABC):
    """Factory + batched crypto operations over one group backend."""

    #: True on backends whose erasure/hash plane runs on the device
    #: (TpuBackend): the engine uses it to decide whether PackedProofs
    #: may skip the native-SHA gate (crypto/merkle.py from_trees).
    device_rs_plane: bool = False

    def __init__(self, group: Group) -> None:
        self.group = group
        from hbbft_tpu.obs.hostbuckets import HostBuckets
        from hbbft_tpu.utils.metrics import Counters

        #: operative-metric tallies (SURVEY.md §5): shares verified/combined,
        #: pairing checks, device dispatches.
        self.counters = Counters()
        #: opt-in :class:`~hbbft_tpu.obs.tracer.Tracer`; when attached, the
        #: batched entry points emit dispatch spans + batch-size histograms
        #: (host backends span the batched host call; TpuBackend spans the
        #: actual jitted dispatch+fetch with ``device=True``).
        self.tracer = None
        #: host-time attribution regions (obs/hostbuckets.py): the array
        #: engine wraps its epoch phases in ``buckets.region(...)`` blocks
        #: so ``host_seconds`` splits into named ``host_bucket_*``
        #: counters; device backends nest their staging blocks under it.
        self.buckets = HostBuckets(
            self.counters, tracer_ref=lambda: self.tracer
        )

    def _traced(self, kind: str, n_items: int, fn: Callable[[], Any]) -> Any:
        """Run one batched backend call under a dispatch span when tracing.

        ``kind`` reuses the ``device_seconds_*`` label vocabulary as the
        span category.  Zero-cost when no tracer is attached; empty
        batches (no-op flushes) are not recorded — a flood of items=0
        samples would drag the batch-size percentiles to zero."""
        tr = self.tracer
        if tr is None or not n_items:
            return fn()
        t0 = time.perf_counter()
        out = fn()
        t1 = time.perf_counter()
        tr.complete(
            f"dispatch:{kind}", t0, t1, cat=kind, track="device",
            items=n_items, device=False,
        )
        tr.hist("dispatch_batch_items").record(n_items)
        return out

    # -- key material --------------------------------------------------------

    def generate_key_set(self, threshold: int, rng) -> SecretKeySet:
        return SecretKeySet.random(self.group, threshold, rng)

    def generate_secret_key(self, rng) -> SecretKey:
        return SecretKey.random(self.group, rng)

    # -- batched verification (the hot loop; SURVEY.md §3.2) -----------------

    def verify_sig_shares(
        self, items: Sequence[Tuple[PublicKeyShare, bytes, SignatureShare]]
    ) -> List[bool]:
        """Verify a batch of (pk_share, document, sig_share) triples."""
        c = self.counters
        c.sig_shares_verified += len(items)
        c.pairing_checks += len(items)
        return self._traced(
            "pairing",
            len(items),
            lambda: [pk.verify_sig_share(share, doc) for pk, doc, share in items],
        )

    def verify_dec_shares(
        self, items: Sequence[Tuple[PublicKeyShare, Ciphertext, DecryptionShare]]
    ) -> List[bool]:
        """Verify a batch of (pk_share, ciphertext, dec_share) triples."""
        c = self.counters
        c.dec_shares_verified += len(items)
        c.pairing_checks += len(items)
        return self._traced(
            "pairing",
            len(items),
            lambda: [pk.verify_decryption_share(share, ct) for pk, ct, share in items],
        )

    def verify_signatures(
        self, items: Sequence[Tuple[Any, bytes, Signature]]
    ) -> List[bool]:
        """Verify a batch of full (public_key, message, signature) triples
        (per-node vote/key-gen signatures — SURVEY.md §3.2 DHB path)."""
        self.counters.signatures_verified += len(items)
        self.counters.pairing_checks += len(items)
        return self._traced(
            "pairing",
            len(items),
            lambda: [pk.verify(sig, msg) for pk, msg, sig in items],
        )

    def verify_ciphertexts(self, items: Sequence[Ciphertext]) -> List[bool]:
        self.counters.ciphertexts_verified += len(items)
        self.counters.pairing_checks += len(items)
        return self._traced(
            "pairing", len(items), lambda: [ct.verify() for ct in items]
        )

    # -- deferred verification (cross-round host pipelining) -----------------
    #
    # The array engine overlaps round r+1's item-list assembly with round
    # r's verification dispatches: each *_deferred entry point SUBMITS the
    # batch and returns a zero-arg resolver producing the same List[bool]
    # the synchronous twin returns.  Device backends submit the work
    # behind the bounded in-flight queue (ops/pipeline.py) and resolve on
    # call; the defaults here compute eagerly (host backends have nothing
    # to overlap), so every backend satisfies the contract: identical
    # results and counter accounting, dispatch counts unchanged.

    def verify_sig_shares_deferred(
        self, items: Sequence[Tuple[PublicKeyShare, bytes, SignatureShare]]
    ) -> Callable[[], List[bool]]:
        out = self.verify_sig_shares(items)
        return lambda: out

    def verify_dec_shares_deferred(
        self, items: Sequence[Tuple[PublicKeyShare, Ciphertext, DecryptionShare]]
    ) -> Callable[[], List[bool]]:
        out = self.verify_dec_shares(items)
        return lambda: out

    def verify_ciphertexts_deferred(
        self, items: Sequence[Ciphertext]
    ) -> Callable[[], List[bool]]:
        out = self.verify_ciphertexts(items)
        return lambda: out

    # -- combination ---------------------------------------------------------

    def combine_signatures(
        self,
        pk_set: PublicKeySet,
        shares: Dict[int, SignatureShare],
        doc: Optional[bytes] = None,
    ) -> Signature:
        """Lagrange-combine ≥ threshold+1 verified shares into a signature.

        `doc` (the signed document) is optional context: host backends
        ignore it, device backends use it to re-verify the combined
        signature against the master public key (defense in depth for the
        batched ladder path)."""
        self.counters.sig_shares_combined += len(shares)
        return pk_set.combine_signatures(shares)

    def combine_decryption_shares(
        self, pk_set: PublicKeySet, shares: Dict[int, DecryptionShare], ct: Ciphertext
    ) -> bytes:
        self.counters.dec_shares_combined += len(shares)
        return pk_set.combine_decryption_shares(shares, ct)

    def combine_dec_shares_batch(
        self,
        pk_set: PublicKeySet,
        items: Sequence[Tuple[Dict[int, DecryptionShare], Ciphertext]],
    ) -> List[bytes]:
        """Combine many share sets at once.

        Device backends override this with a single batched dispatch (the
        share-combination kernel is BASELINE config 5's "ICI all-gather"
        shape); the default is the per-item loop.
        """
        return self._traced(
            "combine",
            len(items),
            lambda: [
                self.combine_decryption_shares(pk_set, shares, ct)
                for shares, ct in items
            ],
        )

    def sign_shares_batch(
        self, items: Sequence[Tuple[Any, bytes]]
    ) -> List[SignatureShare]:
        """Produce signature shares for many (secret_key_share, doc) pairs
        at once — the share-GENERATION side of the common coin (each item
        is one x_i·H2(doc) G2 scalar multiplication; SURVEY.md §3.2 marks
        the coin as the hottest loop).  Device backends override with one
        batched ladder dispatch."""
        return self._traced(
            "sign",
            len(items),
            lambda: [sk.sign_share(doc) for sk, doc in items],
        )

    def combine_sig_shares_batch(
        self,
        pk_set: PublicKeySet,
        items: Sequence[Tuple[Dict[int, SignatureShare], Optional[bytes]]],
    ) -> List[Signature]:
        """Combine many signature-share sets at once (each item: shares,
        optional doc for the combined-signature re-verify).  Device
        backends override with a batched G2 Lagrange dispatch; the default
        is the per-item loop."""
        return self._traced(
            "combine",
            len(items),
            lambda: [
                self.combine_signatures(pk_set, shares, doc=doc)
                for shares, doc in items
            ],
        )

    def decrypt_shares_batch(
        self, items: Sequence[Tuple[Any, Ciphertext]]
    ) -> List[DecryptionShare]:
        """Produce decryption shares for many (secret_key_share, ciphertext)
        pairs at once — the share-GENERATION side of threshold decryption
        (each item is one x_i·U scalar multiplication).

        The whole-network simulation emits N² of these per epoch (every
        node shares every accepted proposer's ciphertext); device backends
        override with one batched ladder dispatch.
        """
        return self._traced(
            "decrypt",
            len(items),
            lambda: [sk.decrypt_share_unchecked(ct) for sk, ct in items],
        )

    def g1_mul_batch(
        self, scalars: Sequence[int], points: Sequence[Any], kind: str = "dkg"
    ) -> List[Any]:
        """Batched independent G1 scalar multiplications s_i·P_i — the
        primitive the batched era-change DKG (engine/dkg_batch.py) feeds
        with commitment/encryption/decryption ladders.  Device backends
        override with batched ladder dispatches."""
        g = self.group
        return [g.g1_mul(s, p) for s, p in zip(scalars, points)]

    def g2_mul_batch(
        self, scalars: Sequence[int], points: Sequence[Any], kind: str = "dkg"
    ) -> List[Any]:
        """Batched independent G2 scalar multiplications (ciphertext W
        components in the batched DKG)."""
        g = self.group
        return [g.g2_mul(s, p) for s, p in zip(scalars, points)]

    def g1_lincomb(self, scalars: Sequence[int], points: Sequence[Any]) -> Any:
        """One multi-scalar combination Σ s_i·P_i — the aggregated side of
        the DKG's RLC commitment checks and era-change cross-checks (one
        MSM replaces N³ per-item Horner evaluations).  Default: batched
        muls + host fold; TpuBackend overrides with a single
        linear_combine_g1 dispatch per lane-capped chunk, riding the
        GLV joint-table ladder (ops/backend.py)."""
        g = self.group
        acc = g.g1_identity()
        for el in self.g1_mul_batch(scalars, points):
            acc = g.g1_add(acc, el)
        return acc

    # -- erasure/hash plane (PR 19) ------------------------------------------
    #
    # The RBC plane's RS encode/reconstruct and Merkle build/verify, batched
    # across proposers exactly like the crypto entry points batch across
    # shares.  Defaults are the host codec/hashlib loops (bit-identical to
    # calling the codec / MerkleTree directly); TpuBackend overrides route
    # them through the GF(2⁸) bit-matmul + device SHA-256 dispatches behind
    # the same DispatchPipeline seam (ops/backend.py).

    def rs_encode_batch(
        self, codec, datas: Sequence[bytes]
    ) -> List[List[bytes]]:
        """RS-encode many data blocks with one codec: per block, k data
        shards + m parity shards (``RSCodec.encode`` semantics)."""
        return self._traced(
            "rs_enc", len(datas), lambda: [codec.encode(d) for d in datas]
        )

    def rs_reconstruct_batch(
        self, codec, shard_lists: Sequence[Sequence[Optional[bytes]]]
    ) -> List[List[bytes]]:
        """Reconstruct many shard vectors (``RSCodec.reconstruct``
        semantics, including its error raises and the zero-math
        all-present fast case)."""
        return self._traced(
            "rs_dec",
            len(shard_lists),
            lambda: [codec.reconstruct(list(s)) for s in shard_lists],
        )

    def merkle_build_batch(self, shard_lists: Sequence[Sequence[bytes]]) -> List[Any]:
        """Build one MerkleTree per shard vector."""
        from hbbft_tpu.crypto.merkle import MerkleTree

        return self._traced(
            "merkle",
            len(shard_lists),
            lambda: [MerkleTree(list(sl)) for sl in shard_lists],
        )

    def merkle_verify_batch(self, packed, reps: int = 1) -> List[bool]:
        """Validate a ``PackedProofs`` batch (``reps`` repetitions keep the
        measured hash workload equal to N independent receivers)."""
        return self._traced(
            "merkle", len(packed), lambda: packed.validate(reps)
        )

    # -- misc ----------------------------------------------------------------

    @property
    def name(self) -> str:
        return type(self).__name__

    def flush(self) -> None:
        """Device backends override to force pending batches to resolve."""

    def new_era(self, era: int) -> None:
        """Era-turnover hook (the engine calls it after every DKG):
        device backends drop per-era staged key material (the limb-row
        staging cache); host backends have nothing staged."""


class MockBackend(CryptoBackend):
    """Fast insecure backend for protocol-logic tests (mock bilinear group).

    ``pipeline_chunk`` (None = off) routes the batched verifies through
    the SAME DispatchPipeline machinery the device backend uses
    (ops/pipeline.py — stdlib-only, no JAX import), splitting each batch
    into chunks whose per-chunk results are delivered via deferred
    callbacks resolved in a deterministic OUT-OF-ORDER permutation.
    Tier-1 thereby exercises the pipeline's core safety claim — delivery
    callbacks write disjoint slots, so completion order cannot change
    results — without device hardware or JAX compile time.
    """

    #: chunk size for the simulated-async verify path (None = plain loop)
    pipeline_chunk: Optional[int] = None
    #: schedule-explorer hook (analysis/schedules.py): ``resolve_order(k)
    #: -> List[int]`` picks the resolution permutation of the k pending
    #: chunks; None keeps the legacy deterministic last-submitted-first
    resolve_order: Optional[Callable[[int], List[int]]] = None
    #: per-chunk resolution listeners ``cb(lo, results)`` — fired from the
    #: delivery callback (i.e. at RESOLVE time, mid-flush); the explorer's
    #: seeded traffic mutation rides this
    chunk_listeners: Sequence[Callable] = ()

    def __init__(self) -> None:
        super().__init__(MockGroup())
        from hbbft_tpu.ops.pipeline import DispatchPipeline

        # depth large enough to hold every chunk: the mock resolves them
        # all at once, permuted, instead of streaming
        self._pipe = DispatchPipeline(
            counters=None, tracer_ref=None, depth_fn=lambda: 1 << 30
        )
        #: submission-order batch numbering for chunk identity (the
        #: explorer's event keys; schedule-independent by construction)
        self._batch_seq = 0

    def _piped_submit(self, items: Sequence, compute: Callable[[Sequence], List]):
        """Submit chunked deferred deliveries; returns (out, finish) where
        ``finish()`` resolves every pending chunk in a deterministic
        OUT-OF-ORDER permutation — last-submitted-first, or whatever the
        ``resolve_order`` hook picks — and returns ``out`` populated."""
        step = self.pipeline_chunk or len(items) or 1
        out: List[Any] = [None] * len(items)
        b = self._batch_seq
        self._batch_seq += 1
        for ci, lo in enumerate(range(0, len(items), step)):
            chunk = items[lo : lo + step]

            def deliver(res, lo=lo):
                out[lo : lo + len(res)] = res
                for cb in self.chunk_listeners:
                    cb(lo, res)

            self._pipe.submit(
                lambda chunk=chunk: compute(chunk), fetch=None,
                kind=f"b{b}.c{ci}", items=len(chunk),
                on_result=deliver,
            )

        def finish():
            self._pipe.flush(order=self._resolution_order())
            return out

        return out, finish

    def _resolution_order(self) -> List[int]:
        k = len(self._pipe)
        if self.resolve_order is not None:
            return self.resolve_order(k)
        return list(reversed(range(k)))

    def _piped(self, items: Sequence, compute: Callable[[Sequence], List]) -> List:
        """Chunked deferred delivery with deterministic out-of-order
        resolution (chunks resolve last-submitted-first)."""
        return self._piped_submit(items, compute)[1]()

    def verify_sig_shares(self, items) -> List[bool]:
        # Inlined mock math (e(a,b) = a·b over Z_r): the generic loop costs
        # several Python frames per item, and the array engine pushes 10⁶
        # items per epoch through here.  Same equation as
        # PublicKeyShare.verify_sig_share.
        c = self.counters
        c.sig_shares_verified += len(items)
        c.pairing_checks += len(items)
        r = self.group.r
        h2 = self.group.hash_to_g2

        def compute(chunk):
            return [
                share.el % r == (pk.el * h2(doc)) % r for pk, doc, share in chunk
            ]

        if self.pipeline_chunk:
            return self._traced(
                "pairing", len(items), lambda: self._piped(items, compute)
            )
        return self._traced("pairing", len(items), lambda: compute(items))

    def verify_dec_shares(self, items) -> List[bool]:
        # Same equation as PublicKeyShare.verify_decryption_share.
        c = self.counters
        c.dec_shares_verified += len(items)
        c.pairing_checks += len(items)
        r = self.group.r

        def compute(chunk):
            return [
                (share.el * ct.hash_point()) % r == (pk.el * ct.w) % r
                for pk, ct, share in chunk
            ]

        if self.pipeline_chunk:
            return self._traced(
                "pairing", len(items), lambda: self._piped(items, compute)
            )
        return self._traced("pairing", len(items), lambda: compute(items))

    def verify_sig_shares_deferred(self, items):
        """Deferred twin through the simulated-async pipeline when
        ``pipeline_chunk`` is set, so tier-1 exercises the engine's
        cross-round overlap seam (submit → assemble elsewhere → resolve
        out of order) without JAX."""
        if not self.pipeline_chunk:
            return super().verify_sig_shares_deferred(items)
        c = self.counters
        c.sig_shares_verified += len(items)
        c.pairing_checks += len(items)
        r = self.group.r
        h2 = self.group.hash_to_g2

        def compute(chunk):
            return [
                share.el % r == (pk.el * h2(doc)) % r for pk, doc, share in chunk
            ]

        _, finish = self._piped_submit(items, compute)
        return lambda: self._traced("pairing", len(items), finish)

    def verify_dec_shares_deferred(self, items):
        if not self.pipeline_chunk:
            return super().verify_dec_shares_deferred(items)
        c = self.counters
        c.dec_shares_verified += len(items)
        c.pairing_checks += len(items)
        r = self.group.r

        def compute(chunk):
            return [
                (share.el * ct.hash_point()) % r == (pk.el * ct.w) % r
                for pk, ct, share in chunk
            ]

        _, finish = self._piped_submit(items, compute)
        return lambda: self._traced("pairing", len(items), finish)


class CpuBackend(CryptoBackend):
    """Pure-Python BLS12-381 — the golden reference backend.

    Slow (Python-int pairings) but real: used to golden-test both the
    protocol layer and the JAX kernels.  Imported lazily to keep MockBackend
    import-light.
    """

    def __init__(self) -> None:
        from hbbft_tpu.crypto.bls381 import BLS381Group

        super().__init__(BLS381Group())
