"""Simulation benchmark CLI — the `examples/simulation.rs` equivalent.

Runs N QueueingHoneyBadger nodes (wrapped in SenderQueue) over a simulated
network with per-message latency λ + size/bandwidth delay and a CPU factor
on message handling, then prints a per-epoch table and tx/s — the same
vehicle the reference uses to measure itself (SURVEY.md §3.5).

Virtual-time model (mirroring the reference's TestNode queues):

* each node has a virtual clock; handling a message advances it by
  cpu_factor · handling_cost;
* a message sent at sender-time t arrives no earlier than
  t + λ + size/bandwidth; the recipient processes it at
  max(recipient_clock, arrival).

Deferred crypto (CryptoWork) is accumulated and flushed in batches of
``--crypto-window`` items so a device backend resolves whole windows in
one dispatch — the SURVEY.md §7 round-barrier design in its virtual-time
form.

Observability (hbbft_tpu/obs): ``--trace PATH`` (or ``HBBFT_TPU_TRACE=
PATH``) records protocol/device spans + latency histograms and writes a
Chrome-trace-event/Perfetto ``trace.json`` (``.jsonl`` → raw event
lines); ``--heartbeat S`` emits a JSON health line every S seconds;
``--stall-timeout T`` arms the stall detector, which after T seconds
without progress dumps a why-stalled report naming the blocked BA/RBC
instances.

Usage:
    python examples/simulation.py -n 10 -f 3 -b 100 --epochs 5
    python examples/simulation.py -n 4 -f 1 --backend cpu   # real BLS, slow
    python examples/simulation.py --backend tpu             # device batches
    python examples/simulation.py -n 10 -f 3 --engine array --trace trace.json
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import pickle
import random
import sys
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.types import CryptoWork, Step
from hbbft_tpu.crypto.backend import CpuBackend, MockBackend
from hbbft_tpu.obs import HealthReporter, Tracer, why_stalled
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import SenderQueue


def make_backend(name: str):
    if name == "mock":
        return MockBackend()
    if name == "cpu":
        return CpuBackend()
    if name == "tpu":
        from hbbft_tpu.ops.backend import TpuBackend

        return TpuBackend()
    if name == "mesh":
        from hbbft_tpu.parallel import MeshBackend

        return MeshBackend()
    raise ValueError(f"unknown backend {name!r}")


class SimNode:
    def __init__(self, nid: int, algo: SenderQueue) -> None:
        self.id = nid
        self.algo = algo
        self.clock = 0.0  # virtual seconds
        self.outputs: List[Any] = []
        self.sent_msgs = 0


class Simulation:
    """Virtual-time event loop over N sans-I/O nodes."""

    def __init__(self, args, backend, rng: random.Random) -> None:
        self.args = args
        self.backend = backend
        self.rng = rng
        ids = list(range(args.num_nodes))
        netinfos = NetworkInfo.generate_map(ids, rng, backend)
        self.nodes: Dict[int, SimNode] = {}
        for nid in ids:
            qhb = (
                QueueingHoneyBadger.builder(netinfos[nid], backend, rng)
                .batch_size(args.batch_size)
                .session_id(b"simulation")
                .build()
            )
            self.nodes[nid] = SimNode(nid, SenderQueue(qhb))
        self._all_ids = sorted(self.nodes)
        self._size_cache: Dict[Any, int] = {}
        self.events: List[Tuple[float, int, int, int, Any]] = []  # (t, seq, to, frm, payload)
        self._seq = 0
        self.delivered = 0
        self._pending_work: List[Tuple[int, CryptoWork]] = []
        self._resumed = False
        self.faults = 0
        #: opt-in observability (attached by main() after construction)
        self.tracer: Optional[Tracer] = None
        self.health: Optional[HealthReporter] = None

    # -- plumbing ------------------------------------------------------------

    def _payload_size(self, payload: Any) -> int:
        """Serialized size for the virtual bandwidth model.

        pickle.dumps per delivery was 14% of an N=20 run; messages are
        frozen dataclasses (hashable), so identical broadcast payloads hit
        a per-run cache instead of re-serializing per recipient.  The cache
        is instance-scoped (dies with the Simulation) and bounded.
        """
        cache = self._size_cache
        try:
            s = cache.get(payload)
        except TypeError:  # unhashable payload — serialize directly
            return len(pickle.dumps(payload, protocol=4))
        if s is None:
            s = len(pickle.dumps(payload, protocol=4))
            if len(cache) >= 8192:
                cache.clear()
            cache[payload] = s
        return s

    def _msg_delay(self, payload: Any) -> float:
        size = self._payload_size(payload)
        return self.args.lam / 1000.0 + size / (self.args.bandwidth * 1024.0)

    def _emit(self, node: SimNode, step: Step) -> None:
        node.outputs.extend(step.output)
        if step.fault_log.entries:
            self.faults += len(step.fault_log.entries)
        for work in step.work:
            self._pending_work.append((node.id, work))
        all_ids = self._all_ids
        for tm in step.messages:
            t = node.clock + self._msg_delay(tm.message)  # size once per msg
            for to in tm.target.recipients(all_ids, our_id=node.id):
                self._seq += 1
                node.sent_msgs += 1
                heapq.heappush(self.events, (t, self._seq, to, node.id, tm.message))

    def _flush_work(self) -> None:
        while self._pending_work:
            batch, self._pending_work = self._pending_work, []
            by_kind: Dict[str, List[Tuple[int, CryptoWork]]] = defaultdict(list)
            for owner, w in batch:
                by_kind[w.kind].append((owner, w))
            for kind, items in by_kind.items():
                payloads = [w.payload for _, w in items]
                if kind == "verify_sig_share":
                    results = self.backend.verify_sig_shares(payloads)
                elif kind == "verify_dec_share":
                    results = self.backend.verify_dec_shares(payloads)
                elif kind == "verify_signature":
                    results = self.backend.verify_signatures(payloads)
                elif kind == "verify_ciphertext":
                    results = self.backend.verify_ciphertexts(payloads)
                else:
                    raise RuntimeError(f"unknown work kind {kind!r}")
                for (owner, w), res in zip(items, results):
                    follow = w.on_result(res)
                    if follow:
                        self._emit(self.nodes[owner], follow)

    # -- checkpoint/resume ---------------------------------------------------

    def checkpoint(self) -> bytes:
        """Serialize the whole simulation — every node's protocol stack,
        clocks, outputs, the in-flight event heap, and the shared RNG — to
        canonical snapshot bytes (utils/snapshot.py; the SURVEY.md §5
        checkpoint capability at simulation scope)."""
        from hbbft_tpu.utils.snapshot import save_node

        if self._pending_work:
            raise RuntimeError(
                "checkpoint only at a flushed barrier (pending CryptoWork)"
            )
        return save_node(
            {
                "algos": {nid: n.algo for nid, n in self.nodes.items()},
                "clocks": {nid: n.clock for nid, n in self.nodes.items()},
                "outputs": {nid: n.outputs for nid, n in self.nodes.items()},
                "sent": {nid: n.sent_msgs for nid, n in self.nodes.items()},
                "events": self.events,
                "seq": self._seq,
                "delivered": self.delivered,
                "rng": self.rng,
            }
        )

    @classmethod
    def from_checkpoint(cls, args, backend, blob: bytes) -> "Simulation":
        """Resume without rebuilding nodes: skips the N-node key generation
        ``__init__`` performs (seconds of BLS keygen on the cpu backend)
        and fills the whole simulation from the snapshot."""
        sim = cls.__new__(cls)
        sim.args = args
        sim.backend = backend
        sim.rng = random.Random()  # replaced by the snapshot's rng below
        sim.nodes = {}
        sim._all_ids = []
        sim._size_cache = {}
        sim.events = []
        sim._seq = 0
        sim.delivered = 0
        sim._pending_work = []
        sim._resumed = False
        sim.faults = 0
        sim.tracer = None
        sim.health = None
        sim.restore(blob)
        return sim

    def restore(self, blob: bytes) -> None:
        """Replace this simulation's state with a :meth:`checkpoint`'s.

        The backend stays this instance's (environment, not state); key
        material rides inside the serialized NetworkInfos."""
        from hbbft_tpu.utils.snapshot import SnapshotError, load_node

        state = load_node(blob, self.backend)
        if not isinstance(state, dict) or "algos" not in state:
            raise SnapshotError(
                f"snapshot holds {type(state).__name__}, not an object-engine "
                "simulation (array snapshots resume via --engine array)"
            )
        snap_ids = sorted(state["algos"])
        if len(snap_ids) != self.args.num_nodes:
            raise SnapshotError(
                f"snapshot has {len(snap_ids)} nodes, -n/--num-nodes is "
                f"{self.args.num_nodes}"
            )
        if self.nodes and sorted(self.nodes) != snap_ids:
            raise SnapshotError(
                f"snapshot has nodes {snap_ids}, this simulation has "
                f"{sorted(self.nodes)}"
            )
        if not self.nodes:  # from_checkpoint shell
            self.nodes = {nid: SimNode(nid, None) for nid in snap_ids}
            self._all_ids = snap_ids
        self._resumed = True
        for nid, node in self.nodes.items():
            node.algo = state["algos"][nid]
            node.clock = state["clocks"][nid]
            node.outputs = state["outputs"][nid]
            node.sent_msgs = state["sent"][nid]
        self.events = state["events"]
        self._seq = state["seq"]
        self.delivered = state["delivered"]
        self.rng = state["rng"]
        self._pending_work = []
        self._size_cache.clear()

    # -- run -----------------------------------------------------------------

    def run(self) -> List[dict]:
        a = self.args
        # Seed every node's queue with its share of transactions — unless
        # this simulation was restored from a checkpoint (whose queue state
        # rode in with the snapshot, even if no epoch completed before it).
        if not self._resumed:
            for nid, node in sorted(self.nodes.items()):
                for k in range(a.txns):
                    tx = f"tx-{nid}-{k}-".encode() + bytes(a.tx_size)
                    self._emit(node, node.algo.handle_input(("user", tx), rng=self.rng))
            self._flush_work()

        target = a.epochs
        rows = []
        done_epochs = min(len(n.outputs) for n in self.nodes.values())
        wall0 = time.perf_counter()
        tracer = self.tracer
        t_epoch = wall0
        while done_epochs < target:
            if not self.events:
                self._flush_work()
                if not self.events:
                    # quiesced short of the target: no later tick will
                    # ever see the stall timeout, so report it NOW —
                    # this is the state why_stalled names culprits for.
                    # Only when the stall detector is armed: --heartbeat
                    # alone must not emit stall records.
                    if (
                        self.health is not None
                        and self.health.stall_timeout_s
                        and done_epochs < target
                    ):
                        self.health.report_quiesced(
                            epoch=done_epochs, msgs=self.delivered
                        )
                    break
            burst = 0
            while self.events and burst < a.crypto_window:
                t, _, to, frm, payload = heapq.heappop(self.events)
                node = self.nodes[to]
                node.clock = max(node.clock, t) + a.cpu_factor / 1000.0
                self.delivered += 1
                if tracer is None:
                    step = node.algo.handle_message(frm, payload, rng=self.rng)
                else:
                    t0 = time.perf_counter()
                    step = node.algo.handle_message(frm, payload, rng=self.rng)
                    t1 = time.perf_counter()
                    tracer.hist("crank_latency_us").record((t1 - t0) * 1e6)
                    if tracer.crank_spans:
                        tracer.complete(
                            f"crank:{type(payload).__name__}", t0, t1,
                            cat="crank", track="crank", to=to,
                        )
                self._emit(node, step)
                burst += 1
            self._flush_work()
            if tracer is not None:
                tracer.hist("event_queue_depth").record(len(self.events))
                h = tracer.hist("sender_queue_depth")
                for n_ in self.nodes.values():
                    out = getattr(n_.algo, "_outgoing", None)
                    if out is not None:
                        h.record(sum(len(v) for v in out.values()))
            if self.health is not None:
                self.health.tick(
                    epoch=done_epochs, msgs=self.delivered, faults=self.faults
                )

            min_epochs = min(len(n.outputs) for n in self.nodes.values())
            while done_epochs < min_epochs:
                if tracer is not None:
                    now = time.perf_counter()
                    tracer.complete(
                        f"epoch:{done_epochs}", t_epoch, now, cat="epoch",
                        epoch=done_epochs,
                    )
                    t_epoch = now
                batch = self.nodes[0].outputs[done_epochs]
                vtime = max(n.clock for n in self.nodes.values())
                txns = sum(len(c) for c in getattr(batch, "contributions", {}).values())
                c = self.backend.counters
                rows.append(
                    {
                        "epoch": done_epochs,
                        "virtual_ms": round(vtime * 1000.0, 2),
                        "wall_s": round(time.perf_counter() - wall0, 3),
                        "txns": txns,
                        "msgs": self.delivered,
                        # operative crypto counters (SURVEY.md §5): cumulative
                        "shares_verified": c.sig_shares_verified
                        + c.dec_shares_verified,
                        "pairing_checks": c.pairing_checks,
                        "shares_combined": c.sig_shares_combined
                        + c.dec_shares_combined,
                        "dispatches": c.device_dispatches,
                    }
                )
                done_epochs += 1
        return rows


def run_array(
    args,
    backend,
    rng: random.Random,
    tracer: Optional[Tracer] = None,
    health: Optional[HealthReporter] = None,
) -> List[dict]:
    """Drive the lockstep array engine (hbbft_tpu/engine) with the same
    transaction/virtual-time model and produce the same table rows.

    Virtual time per lockstep round: λ + max-message-size/bandwidth +
    cpu_factor (every node handles its inbound burst concurrently in the
    lockstep model, so handling cost is per-round, not per-message)."""
    from hbbft_tpu.engine import ArrayHoneyBadgerNet

    churn_at = set(getattr(args, "churn_at", None) or [])
    bad = [e for e in churn_at if not 0 <= e < args.epochs]
    if bad:  # validate BEFORE paying N-node key generation
        raise SystemExit(f"--churn-at indices out of range: {bad}")
    if args.resume:
        with open(args.resume, "rb") as fh:
            net = ArrayHoneyBadgerNet.restore(fh.read(), backend)
        if len(net.ids) != args.num_nodes:
            raise SystemExit(
                f"snapshot holds N={len(net.ids)} nodes, CLI says "
                f"-n {args.num_nodes}"
            )
        if net.epoch >= args.epochs:
            raise SystemExit(
                f"snapshot already at epoch {net.epoch} >= --epochs {args.epochs}"
            )
        stale = [e for e in churn_at if e < net.epoch]
        if stale:
            raise SystemExit(
                f"--churn-at {sorted(stale)} precede the snapshot's epoch "
                f"{net.epoch}; churn indices are absolute"
            )
        # explicit flags override; otherwise the snapshot's workload wins
        # (a resumed soak must not silently change shape)
        if args.coin_rounds is not None:
            net.coin_rounds = args.coin_rounds
        net.dynamic = net.dynamic or bool(churn_at)
        print(
            f"resumed array engine at epoch {net.epoch}, era {net.era}, "
            f"coin_rounds={net.coin_rounds}, dynamic={net.dynamic}"
        )
    else:
        net = ArrayHoneyBadgerNet(
            range(args.num_nodes),
            backend=backend,
            seed=args.seed,
            coin_rounds=args.coin_rounds or 0,
            dynamic=bool(churn_at),
        )
    net.tracer = tracer
    # Tables are PER-RUN (virtual clock, msgs, and the cumulative crypto
    # counters all start at this run's zero — backend counters are
    # environment, not snapshot state); only the epoch INDEX is absolute,
    # so concatenated soak tables line up by epoch without mixing bases.
    rows: List[dict] = []
    vtime = 0.0
    wall0 = time.perf_counter()
    delivered = 0
    # absolute epoch indices: a resumed run continues to the same total
    # horizon the object engine uses (--epochs 2 --checkpoint, then
    # --epochs 4 --resume runs epochs 2..3)
    for epoch in range(net.epoch, args.epochs):
        if epoch in churn_at:
            crep = net.era_change()
            # fold the churn's network/rounds cost into the SAME virtual
            # clock the epochs use (its crypto already lands in the
            # cumulative counter columns)
            vtime += crep.rounds * (
                args.lam / 1000.0 + args.cpu_factor / 1000.0
            )
            delivered += crep.messages_delivered
            print(
                f"  era change before epoch {epoch}: era={net.era} "
                f"votes={crep.votes_verified} kg_acks={crep.kg_acks_handled} "
                f"msgs={crep.messages_delivered}"
            )
        contribs = {}
        for nid in net.ids:
            txs = [
                f"tx-{nid}-{epoch}-{k}-".encode() + bytes(args.tx_size)
                for k in range(args.batch_size)
            ]
            contribs[nid] = b"\x00".join(txs)
        batches = net.run_epoch(contribs)
        rep = net.reports[-1]
        # Largest message is a Value/Echo proof ≈ shard + path; bound it by
        # the framed contribution size / data shards + 32·depth overhead.
        framed = max(len(c) for c in contribs.values()) + 4
        shard = -(-framed // net.codec.k)
        max_msg = shard + 32 * 8 + 64
        vtime += rep.rounds * (
            args.lam / 1000.0
            + max_msg / (args.bandwidth * 1024.0)
            + args.cpu_factor / 1000.0
        )
        delivered += rep.messages_delivered
        batch = batches[net.ids[0]]
        # synthetic queue model: every contribution carries batch_size txns
        txns = len(batch.contributions) * args.batch_size
        c = backend.counters
        rows.append(
            {
                "epoch": epoch,
                "virtual_ms": round(vtime * 1000.0, 2),
                "wall_s": round(time.perf_counter() - wall0, 3),
                "txns": txns,
                "msgs": delivered,
                "shares_verified": c.sig_shares_verified
                + c.dec_shares_verified,
                "pairing_checks": c.pairing_checks,
                "shares_combined": c.sig_shares_combined
                + c.dec_shares_combined,
                "dispatches": c.device_dispatches,
            }
        )
        if health is not None:
            health.tick(epoch=epoch + 1, msgs=delivered)
    if args.checkpoint:
        with open(args.checkpoint, "wb") as fh:
            fh.write(net.checkpoint())
        print(f"checkpoint written to {args.checkpoint}")
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("-n", "--num-nodes", type=int, default=4)
    p.add_argument("-f", "--num-faulty", type=int, default=1)
    p.add_argument("-b", "--batch-size", type=int, default=100)
    p.add_argument("-t", "--tx-size", type=int, default=10, help="bytes per txn payload")
    p.add_argument("--txns", type=int, default=200, help="txns queued per node")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--lam", type=float, default=100.0, help="latency λ in ms")
    p.add_argument("--bandwidth", type=float, default=2000.0, help="KB/s per link")
    p.add_argument("--cpu-factor", type=float, default=1.0, help="handling cost ms")
    p.add_argument("--crypto-window", type=int, default=64,
                   help="messages handled between crypto batch flushes")
    p.add_argument("--backend", choices=("mock", "cpu", "tpu", "mesh"), default="mock")
    p.add_argument(
        "--engine",
        choices=("object", "array"),
        default="object",
        help="object = per-message VirtualNet runtime; array = lockstep "
        "whole-network engine (hbbft_tpu/engine)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--coin-rounds", type=int, default=None, dest="coin_rounds",
        help="array engine: real threshold-sign coin rounds per BA "
        "instance (the split-input schedule; 0 = fixed-coin fast path)",
    )
    p.add_argument(
        "--churn-at", type=int, nargs="*", dest="churn_at", default=None,
        help="array engine: epoch indices before which a vote->DKG->era "
        "change runs (BASELINE config 3 churn)",
    )
    p.add_argument(
        "--checkpoint",
        metavar="FILE",
        help="write a canonical whole-simulation snapshot here after the run "
        "(both engines)",
    )
    p.add_argument(
        "--resume",
        metavar="FILE",
        help="resume from a --checkpoint snapshot; --epochs is the TOTAL "
        "epoch count including pre-checkpoint epochs",
    )
    p.add_argument(
        "--trace",
        metavar="PATH",
        default=os.environ.get("HBBFT_TPU_TRACE"),
        help="record spans + histograms; write a Chrome-trace/Perfetto "
        "JSON (or raw JSONL if PATH ends in .jsonl) here "
        "(default: $HBBFT_TPU_TRACE)",
    )
    p.add_argument(
        "--crank-spans",
        action="store_true",
        help="with --trace on the object engine: one span per delivered "
        "message (small runs only — large runs fill the event buffer)",
    )
    p.add_argument(
        "--heartbeat", type=float, default=0.0, metavar="S",
        help="emit a JSON health heartbeat every S wall seconds (0 = off)",
    )
    p.add_argument(
        "--stall-timeout", type=float, default=0.0, metavar="T",
        help="after T seconds without progress, dump a why-stalled report "
        "naming the blocked BA/RBC instances (0 = off)",
    )
    args = p.parse_args(argv)

    if args.num_nodes <= 3 * args.num_faulty:
        p.error(f"N={args.num_nodes} cannot tolerate f={args.num_faulty} (need N>3f)")

    rng = random.Random(args.seed)
    backend = make_backend(args.backend)
    tracer: Optional[Tracer] = None
    if args.trace:
        tracer = Tracer()
        tracer.crank_spans = args.crank_spans
        backend.tracer = tracer
    health: Optional[HealthReporter] = None
    if args.heartbeat or args.stall_timeout:
        health = HealthReporter(
            # --heartbeat 0 means OFF, even with the stall detector armed
            interval_s=args.heartbeat if args.heartbeat else float("inf"),
            stall_timeout_s=args.stall_timeout,
            counters_fn=backend.counters.snapshot,
            # mesh backends report per-device dispatch balance per beat
            shard_stats_fn=getattr(backend, "shard_stats", None),
        )
    print(
        f"hbbft_tpu simulation: N={args.num_nodes} f={args.num_faulty} "
        f"batch={args.batch_size} backend={args.backend} engine={args.engine}"
    )
    if args.engine == "array":
        rows = run_array(args, backend, rng, tracer=tracer, health=health)
    else:
        if args.churn_at is not None or args.coin_rounds:
            p.error("--churn-at/--coin-rounds require --engine array")
        if args.resume:
            with open(args.resume, "rb") as fh:
                sim = Simulation.from_checkpoint(args, backend, fh.read())
        else:
            sim = Simulation(args, backend, rng)
        sim.tracer = tracer
        if health is not None:
            health.stall_report_fn = lambda: why_stalled(sim.nodes)
            sim.health = health
        rows = sim.run()
        if args.checkpoint:
            with open(args.checkpoint, "wb") as fh:
                fh.write(sim.checkpoint())
            print(f"checkpoint written to {args.checkpoint}")
    if tracer is not None:
        tracer.write(args.trace)
        print(
            f"trace written to {args.trace} "
            f"({len(tracer)} events, {tracer.dropped} dropped)"
        )
        summary = tracer.hist_summary()
        if summary:
            print("histograms: " + json.dumps(summary))
    print(
        f"{'epoch':>6} {'virt ms':>10} {'wall s':>8} {'txns':>6} {'msgs':>8} "
        f"{'shr.vrf':>8} {'pairchk':>8} {'shr.cmb':>8} {'disp':>6}"
    )
    total_tx = 0
    for r in rows:
        total_tx += r["txns"]
        print(
            f"{r['epoch']:>6} {r['virtual_ms']:>10} {r['wall_s']:>8} "
            f"{r['txns']:>6} {r['msgs']:>8} {r['shares_verified']:>8} "
            f"{r['pairing_checks']:>8} {r['shares_combined']:>8} "
            f"{r['dispatches']:>6}"
        )
    if rows:
        vt = rows[-1]["virtual_ms"] / 1000.0
        wt = rows[-1]["wall_s"]
        print(
            f"total: {total_tx} txns in {len(rows)} epochs; "
            f"{total_tx / vt if vt else 0:.1f} tx/s virtual; "
            f"{len(rows) / wt if wt else 0:.2f} epochs/s wall"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
