"""Toy real-socket HBBFT node — the `examples/node.rs` analogue.

Runs N ThresholdSign nodes as asyncio TCP peers on localhost exchanging
canonically-encoded protocol messages, demonstrating that the sans-I/O
state machines embed behind real transport exactly as the reference's do
(SURVEY.md §2.1 "Example node"): the embedder owns sockets and delivery;
the protocol only sees handle_message/Step.

This is a demonstration, not a production transport: key material comes
from a trusted dealer in-process, peers are localhost ports, and the run
ends once every node outputs the combined signature.

Usage:
    python examples/node.py -n 4 --doc "sign this"
"""

from __future__ import annotations

import argparse
import asyncio
import os
import random
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.types import Step
from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.protocols.threshold_sign import ThresholdSign
from hbbft_tpu.utils import canonical, wire

BASE_PORT = 42_000


def encode_frame(sender: int, msg: Any) -> bytes:
    """(sender, message) → length-prefixed canonical wire bytes.

    The real wire discipline (utils/wire.py): deterministic TLV, decode
    validates shapes and never executes code — unlike pickle, which an
    earlier revision of this demo used.
    """
    payload = canonical.encode((sender, wire.encode_message(msg)))
    return len(payload).to_bytes(4, "big") + payload


async def read_frame(reader: asyncio.StreamReader, group) -> Any:
    header = await reader.readexactly(4)
    payload = await reader.readexactly(int.from_bytes(header, "big"))
    sender, msg_bytes = canonical.decode(payload)
    if not isinstance(sender, int) or not isinstance(msg_bytes, bytes):
        raise wire.WireError("malformed frame")
    return sender, wire.decode_message(msg_bytes, group)


class PeerNode:
    def __init__(self, nid: int, n: int, algo: ThresholdSign) -> None:
        self.id = nid
        self.n = n
        self.algo = algo
        self.writers: Dict[int, asyncio.StreamWriter] = {}
        self.outputs: List[Any] = []
        self.done = asyncio.Event()
        self.rng = random.Random(1000 + nid)

    async def serve(self) -> asyncio.AbstractServer:
        return await asyncio.start_server(
            self._on_conn, "127.0.0.1", BASE_PORT + self.id
        )

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                try:
                    sender, payload = await read_frame(
                        reader, self.algo.backend.group
                    )
                except wire.WireError as e:
                    # Malformed frame: drop the connection (framing is lost),
                    # keep the node alive.
                    print(f"node {self.id}: dropping peer: {e}", file=sys.stderr)
                    return
                step = self.algo.handle_message(sender, payload, rng=self.rng)
                await self._process(step)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    async def connect_all(self) -> None:
        for peer in range(self.n):
            if peer == self.id:
                continue
            for _ in range(100):
                try:
                    _, writer = await asyncio.open_connection(
                        "127.0.0.1", BASE_PORT + peer
                    )
                    self.writers[peer] = writer
                    break
                except ConnectionError:
                    await asyncio.sleep(0.05)

    async def start(self) -> None:
        step = self.algo.handle_input(None, rng=self.rng)
        await self._process(step)

    async def _process(self, step: Step) -> None:
        self.outputs.extend(step.output)
        if self.outputs:
            self.done.set()
        # Resolve deferred crypto work eagerly (single-item batches; a real
        # embedder would window these like examples/simulation.py does).
        for work in step.work:
            if work.kind == "verify_sig_share":
                (res,) = self.algo.backend.verify_sig_shares([work.payload])
            elif work.kind == "verify_signature":
                (res,) = self.algo.backend.verify_signatures([work.payload])
            else:
                raise RuntimeError(f"unexpected work kind {work.kind!r}")
            follow = work.on_result(res)
            if follow:
                await self._process(follow)
        for tm in step.messages:
            peers = tm.target.recipients(list(range(self.n)), our_id=self.id)
            frame = encode_frame(self.id, tm.message)
            for to in peers:
                if to == self.id:
                    continue
                w = self.writers.get(to)
                if w is not None:
                    w.write(frame)
                    await w.drain()


async def run(n: int, doc: bytes) -> int:
    rng = random.Random(7)
    backend = MockBackend()
    netinfos = NetworkInfo.generate_map(list(range(n)), rng, backend)
    nodes = [
        PeerNode(i, n, ThresholdSign(netinfos[i], backend, doc=doc))
        for i in range(n)
    ]
    servers = [await node.serve() for node in nodes]
    for node in nodes:
        await node.connect_all()
    await asyncio.gather(*(node.start() for node in nodes))
    await asyncio.wait_for(
        asyncio.gather(*(node.done.wait() for node in nodes)), timeout=30
    )
    sigs = {node.outputs[0].to_bytes() for node in nodes}
    for server in servers:
        server.close()
    if len(sigs) == 1:
        print(f"all {n} nodes agreed on signature {sigs.pop().hex()[:32]}…")
        return 0
    print("nodes disagreed!", file=sys.stderr)
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("-n", "--num-nodes", type=int, default=4)
    p.add_argument("--doc", default="example document")
    args = p.parse_args(argv)
    return asyncio.run(run(args.num_nodes, args.doc.encode()))


if __name__ == "__main__":
    raise SystemExit(main())
