"""Array engine: correctness + differential tests vs the object runtime."""

import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.engine import ArrayHoneyBadgerNet
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.honey_badger import HoneyBadger


def _contribs(ids, seed=11, size=24):
    rng = random.Random(seed)
    return {i: bytes(rng.randrange(256) for _ in range(size)) for i in ids}


@pytest.mark.parametrize("n", [4, 7, 10])
def test_epoch_agreement_and_contents(n):
    net = ArrayHoneyBadgerNet(range(n), backend=MockBackend(), seed=5)
    contribs = _contribs(net.ids)
    batches = net.run_epoch(contribs)
    first = batches[net.ids[0]]
    for nid in net.ids:
        assert batches[nid] == first
    # the lockstep honest path accepts every proposer
    assert first.contributions == contribs
    assert first.epoch == 0


def test_multi_epoch_counts():
    n = 5
    net = ArrayHoneyBadgerNet(range(n), backend=MockBackend(), seed=5)
    net.run_epochs(3, payload_size=16)
    assert [r.epoch for r in net.reports] == [0, 1, 2]
    r = net.reports[-1]
    # exact lockstep message count: Value n(n−1) + 7 all-to-all phases
    assert r.messages_delivered == n * (n - 1) + 7 * n * n * (n - 1)
    # O(N³) echo validations + N² value validations
    assert r.proofs_validated == n * n + n * n * n
    assert r.dec_shares_verified == n * n * (n - 1)


def test_dedup_mode_agrees_with_full():
    ids = range(6)
    full = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=9)
    dedup = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=9, dedup_verifies=True)
    contribs = _contribs(list(ids))
    assert full.run_epoch(contribs)[0] == dedup.run_epoch(contribs)[0]


def test_differential_vs_object_engine():
    """The object VirtualNet runtime and the array engine must produce
    consistent epoch batches: same epoch number, and the array batch
    (which accepts all N proposers under lockstep) contains every
    contribution the object engine committed."""
    ids = list(range(4))
    contribs = _contribs(ids)

    net = (
        NetBuilder(ids)
        .backend(MockBackend())
        .using(lambda ni, b: HoneyBadger.builder(ni, b).build())
        .build(seed=21)
    )
    for nid in ids:
        net.send_input(nid, contribs[nid])
    net.crank_to_quiescence()
    obj_batches = [n.outputs[0] for n in net.correct_nodes()]
    assert all(b == obj_batches[0] for b in obj_batches)

    arr = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=21)
    arr_batch = arr.run_epoch(contribs)[0]

    assert arr_batch.epoch == obj_batches[0].epoch == 0
    for nid, value in obj_batches[0].contributions.items():
        assert arr_batch.contributions[nid] == value


def test_sha_kernel_matches_hashlib():
    import hashlib

    import numpy as np

    from hbbft_tpu import native

    if not native.sha256_available():
        pytest.skip("no C toolchain")
    rng = np.random.default_rng(3)
    for length in (1, 31, 55, 56, 63, 64, 65, 127, 128, 200, 1000):
        data = rng.integers(0, 256, size=(4, length), dtype=np.uint8)
        out = native.sha256_batch(data)
        for i in range(4):
            assert out[i].tobytes() == hashlib.sha256(data[i].tobytes()).digest()


def test_dynamic_mode():
    """DHB flavor: contributions ride the internal envelope; batches are
    identical to plain HB mode for the same inputs (no churn)."""
    ids = range(5)
    contribs = _contribs(list(ids))
    hb = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=2)
    dhb = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=2, dynamic=True)
    assert hb.run_epoch(contribs)[0] == dhb.run_epoch(contribs)[0]


def test_coin_rounds_mode():
    """coin_rounds=R executes R real threshold-sign coin rounds per BA
    instance (sign → verify → combine → parity; SURVEY.md §3.2 hottest
    loop) and all receivers derive the same bit — batches unchanged."""
    ids = range(7)
    contribs = _contribs(list(ids))
    plain = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=3)
    coin = ArrayHoneyBadgerNet(
        ids, backend=MockBackend(), seed=3, coin_rounds=2
    )
    assert plain.run_epoch(contribs)[0] == coin.run_epoch(contribs)[0]
    rep = coin.reports[-1]
    n = 7
    assert rep.coin_rounds == 2
    assert rep.coin_signs == 2 * n * n
    assert rep.sig_shares_verified == 2 * n * n * (n - 1)
    assert rep.sig_combines == 2 * n * n
    # coin rounds add 4 broadcast storms each (BVal, Aux, Conf, share)
    assert (
        rep.messages_delivered
        == plain.reports[-1].messages_delivered + 2 * 4 * n * n * (n - 1)
    )


def test_coin_rounds_real_crypto_bit_agreement():
    """Real-curve coin: receivers combine DIFFERENT f+1 share subsets;
    signature uniqueness must give every receiver the same parity bit
    (this is the unbiasable-coin property BinaryAgreement relies on)."""
    from hbbft_tpu.crypto.backend import CpuBackend

    ids = range(4)
    net = ArrayHoneyBadgerNet(
        ids, backend=CpuBackend(), seed=5, coin_rounds=1, dedup_verifies=True
    )
    net.run_epoch(_contribs(list(ids)))  # asserts bit agreement internally
    assert net.reports[-1].coin_rounds == 1


def test_era_change_turnover():
    """vote → DKG → era (SURVEY.md §3.4): keys rotate, consensus still
    holds post-turnover, old-key signatures stop verifying."""
    ids = range(7)
    net = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=6)
    pk0 = net.pk_set
    sk0 = net.netinfos[0].secret_key_share
    net.run_epochs(1, payload_size=8)
    rep = net.era_change()
    assert net.era == 1
    assert net.pk_set != pk0
    assert rep.kg_parts_handled == 49
    assert rep.kg_acks_handled == 49 * 7
    assert rep.votes_verified == 7 * 6
    # epochs post-turnover still reach consensus (decrypt asserts inside)
    out = net.run_epochs(2, payload_size=8)
    assert out[0][0].contributions == out[0][3].contributions
    # a share signed under the OLD keys fails against the NEW key set
    doc = b"stale-era"
    old_share = sk0.sign_share(doc)
    assert net.backend.verify_sig_shares(
        [(net.pk_set.public_key_share(0), doc, old_share)]
    ) == [False]


def test_run_epochs_churn_at():
    ids = range(5)
    net = ArrayHoneyBadgerNet(ids, backend=MockBackend(), seed=7)
    net.run_epochs(3, payload_size=8, churn_at=[1, 2])
    assert net.era == 2
    assert len(net.churn_reports) == 2
    assert len(net.reports) == 3


def test_checkpoint_resume_byte_identical():
    """Soak resumability (BASELINE configs 3/5 at 1k epochs): restoring a
    checkpoint continues byte-identically with era and RNG state intact."""
    a = ArrayHoneyBadgerNet(range(7), backend=MockBackend(), seed=5, dynamic=True)
    a.run_epochs(2, payload_size=16, churn_at=[1])
    blob = a.checkpoint()
    cont = a.run_epochs(2, payload_size=16)
    b = ArrayHoneyBadgerNet.restore(blob, MockBackend())
    assert b.era == 1 and b.epoch == 2
    cont2 = b.run_epochs(2, payload_size=16)
    for x, y in zip(cont, cont2):
        assert x[0] == y[0]
    # corrupted snapshot fails loudly
    import pytest as _pytest
    from hbbft_tpu.utils.snapshot import SnapshotError
    with _pytest.raises(SnapshotError):
        ArrayHoneyBadgerNet.restore(b"HBTPUSNAP1garbage", MockBackend())
