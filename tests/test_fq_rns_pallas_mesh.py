"""Fused RNS kernel × mesh composition (interpret mode, virtual 8-device
CPU mesh): the HBBFT_TPU_RNS_FUSED routing must compose with BOTH ways
device code runs across a mesh —

* jit + NamedSharding (the framework's own MeshBackend path,
  parallel/mesh.py): the pallas_call sees sharded operands under jit;
* explicit shard_map (the embedder pattern): pallas_call nests inside
  the per-device function (requires check_vma=False — pallas out_shapes
  carry no replication/varying-mesh-axes annotation).

Interpret mode here, but the nesting/sharding semantics are the same
ones Mosaic sees on real chips (tools/tpu_window.sh step 8)."""

import random

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

import jax

# jax moved shard_map to the top level only in later releases; the image's
# jax still ships it under jax.experimental.
try:
    from jax import shard_map as _shard_map

    _NO_CHECK = {"check_vma": False}
except ImportError:  # pragma: no cover - version-dependent import path
    from jax.experimental.shard_map import shard_map as _shard_map

    # older releases call the same escape hatch check_rep
    _NO_CHECK = {"check_rep": False}


def shard_map(f, **kw):
    if "check_vma" in kw:
        kw.pop("check_vma")
        kw.update(_NO_CHECK)
    return _shard_map(f, **kw)
from jax.sharding import Mesh, PartitionSpec as P

from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq_rns as R
from hbbft_tpu.ops import fq_rns_pallas as K
from hbbft_tpu.parallel.mesh import device_mesh, shard_batch


def _inputs(rng, lanes):
    xs = [rng.randrange(Q) for _ in range(lanes)]
    ys = [rng.randrange(Q) for _ in range(lanes)]
    return xs, ys, jnp.asarray(R.from_ints(xs)), jnp.asarray(R.from_ints(ys))


def test_fused_mul_under_jit_with_sharded_inputs():
    """The MeshBackend composition: operands device_put with the batch
    axis split over the mesh, kernel called under jit."""
    assert len(jax.devices()) >= 8, "conftest must provide the virtual mesh"
    mesh = device_mesh(8)
    rng = random.Random(31)
    xs, ys, a, b = _inputs(rng, 16)
    a, b = shard_batch((a, b), mesh)

    fn = jax.jit(lambda a, b: K.mul(a, b, interpret=True))
    got = R.to_ints(np.asarray(fn(a, b)))
    assert got == [x * y % Q for x, y in zip(xs, ys)]


def test_fused_mul_inside_shard_map():
    assert len(jax.devices()) >= 8
    mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
    rng = random.Random(32)
    xs, ys, a, b = _inputs(rng, 16)

    sharded = shard_map(
        lambda ab, bb: K.mul(ab, bb, interpret=True),
        mesh=mesh,
        in_specs=(P("d", None), P("d", None)),
        out_specs=P("d", None),
        check_vma=False,  # pallas out_shapes carry no replication/vma info
    )
    got = R.to_ints(np.asarray(sharded(a, b)))
    assert got == [x * y % Q for x, y in zip(xs, ys)]


def test_fused_pow_under_jit_with_sharded_inputs():
    assert len(jax.devices()) >= 8
    mesh = device_mesh(8)
    rng = random.Random(33)
    xs = [rng.randrange(1, Q) for _ in range(8)]
    a = shard_batch(jnp.asarray(R.from_ints(xs)), mesh)
    e = 0b110101  # small: interpret-mode scan cost

    fn = jax.jit(lambda x: K.pow_fixed(x, e, interpret=True))
    got = R.to_ints(np.asarray(fn(a)))
    assert got == [pow(x, e, Q) for x in xs]
