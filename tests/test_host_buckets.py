"""Host-side epoch pipeline (PR 5 tentpole).

Covers the three layers end to end on CPU:

* attribution — HostBuckets exclusive-time region accounting (nesting,
  fetch-block subtraction), the sums-to-host_seconds invariant, the
  traced-run ±5% soundness check and the ``trace_report --host-buckets``
  CLI gate;
* elimination — the vectorized fast paths (packed Merkle proofs,
  batched canonical encode/decode, index-arithmetic assembly/scatter)
  pinned bit-identical to the legacy loops;
* overlap — the ``HBBFT_TPU_NO_HOSTPIPE`` A/B: identical Batches,
  identical EpochReport counters, identical ``device_dispatches``, with
  the deferred-verify seam exercised out of order through MockBackend's
  simulated-async pipeline;
* failure attribution — Byzantine-detection raises survive the deferred
  reordering (and ``python -O``, being raises rather than asserts).
"""

import dataclasses
import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.engine import ArrayHoneyBadgerNet, EngineInvariantError
from hbbft_tpu.obs import HOST_BUCKETS, HostBuckets, Tracer
from hbbft_tpu.utils.metrics import Counters


def _bucket_sum(counters) -> float:
    return sum(
        getattr(counters, f"host_bucket_{name}") for name in HOST_BUCKETS
    )


def _contribs(ids, seed=11, size=24):
    rng = random.Random(seed)
    return {i: bytes(rng.randrange(256) for _ in range(size)) for i in ids}


# ---------------------------------------------------------------------------
# HostBuckets unit behavior
# ---------------------------------------------------------------------------


def test_region_exclusive_accounting_nests():
    """A child region's time must land in the child's bucket only; the
    parent bills its exclusive remainder; epoch() bills the total."""
    import time

    c = Counters()
    hb = HostBuckets(c)
    with hb.epoch():
        with hb.region("assemble"):
            time.sleep(0.02)
            with hb.region("staging"):
                time.sleep(0.02)
        time.sleep(0.01)  # unattributed → "other"
    assert c.host_bucket_staging >= 0.015
    # parent excludes the child's slice
    assert c.host_bucket_assemble >= 0.015
    assert c.host_bucket_assemble < c.host_bucket_staging + 0.02
    assert c.host_bucket_other >= 0.005
    assert c.host_seconds == pytest.approx(_bucket_sum(c), rel=1e-6)


def test_region_subtracts_fetch_blocked_time():
    """Time the pipeline spent blocked in a device fetch inside a region
    is device WAIT — it must not inflate the region's host bucket (nor
    host_seconds)."""
    import time

    c = Counters()
    hb = HostBuckets(c)
    with hb.epoch():
        with hb.region("dispatch"):
            time.sleep(0.01)
            # what DispatchPipeline._resolve bills during a fetch
            c.fetch_blocked_seconds += 5.0
    assert c.host_bucket_dispatch < 1.0
    assert c.host_seconds < 1.0
    assert c.host_seconds == pytest.approx(_bucket_sum(c), rel=1e-6)


def test_region_unknown_bucket_raises():
    hb = HostBuckets(Counters())
    with pytest.raises(AttributeError):
        with hb.epoch(), hb.region("not-a-bucket"):
            pass


def test_region_outside_epoch_is_a_noop():
    """Backend staging blocks run from bench micro-rows too; billing
    them without an epoch frame would break the buckets-sum-to-
    host_seconds invariant the --host-buckets gate validates."""
    c = Counters()
    tr = Tracer()
    hb = HostBuckets(c, tracer_ref=lambda: tr)
    with hb.region("staging"):
        pass
    assert c.host_bucket_staging == 0.0
    assert c.host_seconds == 0.0
    assert len(tr.events) == 0


def test_region_emits_exclusive_span_args():
    c = Counters()
    tr = Tracer()
    hb = HostBuckets(c, tracer_ref=lambda: tr)
    with hb.epoch():
        with hb.region("encode"):
            pass
    spans = [e for e in tr.events if e.get("ph") == "B"]
    assert {e["args"]["bucket"] for e in spans} == {"encode", "other"}
    for e in spans:
        assert e["args"]["host"] is True
        assert isinstance(e["args"]["exclusive_s"], float)


# ---------------------------------------------------------------------------
# Engine epochs: the sums-to-total invariant + traced validation
# ---------------------------------------------------------------------------


def _fresh_net(n=7, tracer=None, chunk=None, **kw):
    be = MockBackend()
    be.pipeline_chunk = chunk
    net = ArrayHoneyBadgerNet(range(n), backend=be, seed=3, tracer=tracer, **kw)
    if tracer is not None:
        be.tracer = tracer
    return net, be


def test_epoch_buckets_sum_to_host_seconds():
    net, be = _fresh_net(coin_rounds=1)
    net.run_epochs(2, payload_size=32)
    c = be.counters
    assert c.host_seconds > 0
    assert _bucket_sum(c) == pytest.approx(c.host_seconds, rel=1e-6)
    # era changes are attributed the same way
    before = c.host_seconds
    net.era_change()
    assert c.host_seconds > before
    assert _bucket_sum(c) == pytest.approx(c.host_seconds, rel=1e-6)


def test_traced_host_buckets_validate_and_cli(tmp_path):
    """Attribution soundness (the acceptance check): on a traced CPU run
    the host-bucket spans sum to host_seconds within ±5% and the
    unattributed bucket stays under 10%; the CLI gate passes/fails on
    exactly that."""
    from tools.trace_report import (
        check_host_buckets,
        load_events,
        main as tr_main,
        validate_chrome_trace,
    )

    # a real-coin shape: with actual per-round crypto in the epoch the
    # inter-region glue (span emission, report arithmetic) is a ~1%
    # residue — the microsecond-scale N=7 plain epoch would put the
    # 10% unattributed bar within clock-noise distance
    net, be = _fresh_net(n=10, coin_rounds=1)
    net.run_epochs(1, payload_size=64)  # warm: module imports, native .so
    # snapshot/delta measurement window, NOT a mid-run reset(): the
    # counters stay monotonic so run-end aggregates read after this
    # test's window would remain unskewed (same discipline as
    # obs/timeseries.MetricsLog)
    base = be.counters.snapshot()
    tr = Tracer()
    net.tracer = tr
    be.tracer = tr
    net.run_epochs(2, payload_size=64)
    host = be.counters.delta(base)["host_seconds"]
    path = str(tmp_path / "host_trace.json")
    tr.write(path)
    events = load_events(path)
    assert validate_chrome_trace(events) == []
    ok, buckets = check_host_buckets(events, host)
    assert ok, (buckets, host)
    assert buckets.get("other", 0.0) < 0.10 * host
    assert tr_main([path, "--host-buckets", str(host)]) == 0
    assert tr_main([path, "--host-buckets", str(host * 3)]) == 1


# ---------------------------------------------------------------------------
# The A/B: vectorized + overlapped vs HBBFT_TPU_NO_HOSTPIPE=1
# ---------------------------------------------------------------------------


def _run_arm(no_hostpipe, monkeypatch, n=7, chunk=4, **kw):
    if no_hostpipe:
        monkeypatch.setenv("HBBFT_TPU_NO_HOSTPIPE", "1")
    else:
        monkeypatch.delenv("HBBFT_TPU_NO_HOSTPIPE", raising=False)
    net, be = _fresh_net(n=n, chunk=chunk, **kw)
    contribs = _contribs(net.ids)
    batches = [net.run_epoch(contribs), net.run_epochs(1, payload_size=16)[0]]
    reports = [dataclasses.asdict(r) for r in net.reports]
    for r in reports:
        # wall-clock attribution, not part of the identity contract
        r.pop("phase_seconds", None)
    return batches, reports, be.counters.device_dispatches


@pytest.mark.parametrize(
    "kw",
    [
        {},
        {"dynamic": True},
        {"coin_rounds": 1},
        {"dedup_verifies": True},
    ],
    ids=["plain", "dynamic", "coin", "dedup"],
)
def test_hostpipe_ab_bit_identical(monkeypatch, kw):
    """The acceptance invariant: the vectorized + cross-round-overlapped
    epoch produces bit-identical Batches, identical EpochReport
    counters, and identical device_dispatches vs the kill-switch arm —
    with the deferred verifies resolving OUT OF ORDER through the mock
    pipeline."""
    fast = _run_arm(False, monkeypatch, **kw)
    legacy = _run_arm(True, monkeypatch, **kw)
    assert fast[0] == legacy[0], "host pipeline changed Batch outputs"
    assert fast[1] == legacy[1], "host pipeline changed EpochReport"
    assert fast[2] == legacy[2], "host pipeline changed dispatch counts"


def test_era_change_ab_identical(monkeypatch):
    for no_hostpipe in (False, True):
        if no_hostpipe:
            monkeypatch.setenv("HBBFT_TPU_NO_HOSTPIPE", "1")
        else:
            monkeypatch.delenv("HBBFT_TPU_NO_HOSTPIPE", raising=False)
        net, _ = _fresh_net(n=7)
        net.run_epochs(3, payload_size=16, churn_at=[1])
        if no_hostpipe:
            legacy = [b[0] for b in net.run_epochs(1, payload_size=16)]
        else:
            fast = [b[0] for b in net.run_epochs(1, payload_size=16)]
    assert fast == legacy


# ---------------------------------------------------------------------------
# Byzantine-detection raises (the assert→raise satellite)
# ---------------------------------------------------------------------------


class _RejectingBackend(MockBackend):
    """Rejects every decryption share — the engine must RAISE (not
    silently emit a batch), in both arms, even though the fast arm
    resolves the verification after the speculative combines."""

    def verify_dec_shares(self, items):
        super().verify_dec_shares(items)  # keep counter accounting
        return [False] * len(items)

    def verify_dec_shares_deferred(self, items):
        out = self.verify_dec_shares(items)
        return lambda: out


@pytest.mark.parametrize("no_hostpipe", [False, True])
def test_rejected_share_raises_not_asserts(monkeypatch, no_hostpipe):
    if no_hostpipe:
        monkeypatch.setenv("HBBFT_TPU_NO_HOSTPIPE", "1")
    else:
        monkeypatch.delenv("HBBFT_TPU_NO_HOSTPIPE", raising=False)
    net = ArrayHoneyBadgerNet(range(4), backend=_RejectingBackend(), seed=1)
    with pytest.raises(EngineInvariantError, match="decryption share"):
        net.run_epoch(_contribs(net.ids))


def test_engine_invariant_is_not_bare_assert():
    """EngineInvariantError is a real exception class, not AssertionError
    — `python -O` cannot strip these checks."""
    assert not issubclass(EngineInvariantError, AssertionError)


# ---------------------------------------------------------------------------
# Vectorized primitives pinned to the object paths
# ---------------------------------------------------------------------------


def test_canonical_batch_roundtrips_match_scalar():
    from hbbft_tpu.utils import canonical

    objs = [
        b"payload",
        b"",
        ("icontrib", b"x" * 40, [], []),
        {"k": 1, "j": b"v"},
        b"\x04" * 9,  # bytes that LOOK like a tag byte
    ]
    batch = canonical.encode_batch(objs)
    assert batch == [canonical.encode(o) for o in objs]
    assert canonical.decode_batch(batch) == [
        canonical.decode(b) for b in batch
    ]


def test_packed_proofs_match_object_proofs():
    import hashlib

    from hbbft_tpu import native
    from hbbft_tpu.crypto.merkle import (
        MerkleTree,
        PackedProofs,
        validate_proofs,
    )

    if not native.sha256_available():
        pytest.skip("no C toolchain")
    rng = random.Random(9)
    n = 6
    trees = [
        MerkleTree(
            [bytes(rng.randrange(256) for _ in range(13)) for _ in range(n)]
        )
        for _ in range(4)
    ]
    packed = PackedProofs.from_trees(trees, n)
    assert packed is not None and len(packed) == 4 * n
    proofs = [t.proof(s) for t in trees for s in range(n)]
    for reps in (1, 3):
        assert packed.validate(reps=reps) == validate_proofs(
            proofs, n, reps=reps
        )
    # a corrupted root must fail exactly that row
    bad = PackedProofs(
        packed.leaves.copy(), packed.paths.copy(),
        packed.indices.copy(), packed.roots.copy(), n,
    )
    import numpy as np

    bad.roots[5] = np.frombuffer(
        hashlib.sha256(b"evil").digest(), dtype=np.uint8
    )
    got = bad.validate()
    assert got[5] is False and all(got[:5]) and all(got[6:])


def test_packed_proofs_none_without_uniform_shapes():
    from hbbft_tpu.crypto.merkle import MerkleTree, PackedProofs

    trees = [MerkleTree([b"aa", b"bb"]), MerkleTree([b"ccc", b"ddd"])]
    assert PackedProofs.from_trees(trees, 2) is None  # leaf_len differs
