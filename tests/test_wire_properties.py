"""Canonical round-trip property over the full wire-variant registry.

Walks ``WIRE_VARIANTS`` — the same enumeration the handler-exhaustiveness
lint rule cross-references — and proves every (class, kind) variant
encodes to canonical bytes and decodes back to an equal message whose
re-encoding is byte-identical (the fixed-point property signatures
depend on).  The constructor table below is keyed by the registry, so
adding a wire variant without extending this test fails loudly here and
in tests/test_lint.py simultaneously.
"""

import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.crypto.merkle import MerkleTree
from hbbft_tpu.protocols.binary_agreement import BaMessage
from hbbft_tpu.protocols.bool_set import BoolSet
from hbbft_tpu.protocols.broadcast import BroadcastMessage
from hbbft_tpu.protocols.dynamic_honey_badger import DhbMessage
from hbbft_tpu.protocols.honey_badger import HbMessage
from hbbft_tpu.protocols.sbv_broadcast import SbvMessage
from hbbft_tpu.protocols.sender_queue import SqMessage
from hbbft_tpu.protocols.subset import SubsetMessage
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecryptMessage
from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage
from hbbft_tpu.utils.wire import WIRE_VARIANTS, decode_message, encode_message


@pytest.fixture(scope="module")
def group():
    return MockBackend().group


@pytest.fixture(scope="module")
def crypto(group):
    rng = random.Random(11)
    sks = SecretKeySet.random(group, 1, rng)
    sig = sks.secret_key_share(0).sign_share(b"doc")
    ct = sks.public_keys().public_key().encrypt(b"wire-prop-plaintext!", rng)
    dec = sks.secret_key_share(1).decrypt_share_unchecked(ct)
    tree = MerkleTree([bytes([i]) * 8 for i in range(4)])
    return {"sig": sig, "dec": dec, "tree": tree}


def _examples(crypto):
    """Representative message(s) for every (class, kind) in the registry."""
    sig, dec, tree = crypto["sig"], crypto["dec"], crypto["tree"]
    sbv = SbvMessage.bval(True)
    tsig = ThresholdSignMessage(sig)
    tdec = ThresholdDecryptMessage(dec)
    bc_ready = BroadcastMessage.ready(tree.root_hash)
    ba = BaMessage.term(0, False)
    ss = SubsetMessage(2, "agreement", ba)
    hb = HbMessage.subset(1, ss)
    return {
        ("SbvMessage", "bval"): [SbvMessage.bval(False), sbv],
        ("SbvMessage", "aux"): [SbvMessage.aux(True)],
        ("ThresholdSignMessage", None): [tsig],
        ("ThresholdDecryptMessage", None): [tdec],
        ("BroadcastMessage", "value"): [BroadcastMessage.value(tree.proof(1))],
        ("BroadcastMessage", "echo"): [BroadcastMessage.echo(tree.proof(3))],
        ("BroadcastMessage", "ready"): [bc_ready],
        ("BaMessage", "sbv"): [BaMessage.sbv(4, sbv)],
        ("BaMessage", "conf"): [BaMessage.conf(2, BoolSet.both())],
        ("BaMessage", "coin"): [BaMessage.coin(5, tsig)],
        ("BaMessage", "term"): [ba, BaMessage.term(7, True)],
        ("SubsetMessage", "broadcast"): [SubsetMessage(0, "broadcast", bc_ready)],
        ("SubsetMessage", "agreement"): [ss],
        ("HbMessage", "subset"): [hb],
        ("HbMessage", "dec_share"): [HbMessage.dec_share(3, 1, tdec)],
        ("DhbMessage", None): [DhbMessage(0, hb)],
        ("SqMessage", "epoch_started"): [SqMessage.epoch_started(2, 9)],
        ("SqMessage", "algo"): [SqMessage.algo(DhbMessage(1, hb))],
    }


def test_examples_cover_exactly_the_registry(crypto):
    """Registry drift breaks this test the same commit it breaks the lint
    rule: the example table must cover every registered (class, kind)."""
    registered = set()
    for cls, (_tag, kinds) in WIRE_VARIANTS.items():
        if kinds:
            registered.update((cls, k) for k in kinds)
        else:
            registered.add((cls, None))
    assert set(_examples(crypto)) == registered


def test_every_variant_roundtrips_canonically(group, crypto):
    for (cls, kind), msgs in sorted(
        _examples(crypto).items(), key=lambda kv: (kv[0][0], str(kv[0][1]))
    ):
        for msg in msgs:
            data = encode_message(msg)
            assert isinstance(data, bytes) and data, (cls, kind)
            out = decode_message(data, group)
            assert type(out) is type(msg), (cls, kind)
            if kind is not None:
                assert out.kind == kind
            # Canonical fixed point: decode∘encode is byte-stable.
            assert encode_message(out) == data, (cls, kind)
            # And a second decode yields an equal encoding again.
            assert encode_message(decode_message(data, group)) == data


def test_registry_tags_are_unique():
    tags = [tag for tag, _ in WIRE_VARIANTS.values()]
    assert len(tags) == len(set(tags)), "wire tags must be unambiguous"
    for _tag, kinds in WIRE_VARIANTS.values():
        assert len(kinds) == len(set(kinds))
