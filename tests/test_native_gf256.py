"""Golden tests: native C GF(2⁸) kernel vs the numpy table implementation."""

import numpy as np
import pytest

from hbbft_tpu import native
from hbbft_tpu.crypto.erasure import RSCodec, gf256


@pytest.mark.skipif(not native.available(), reason="no C toolchain")
def test_matmul_matches_numpy():
    gf = gf256()
    rng = np.random.default_rng(3)
    for r, k, L in [(1, 1, 1), (3, 5, 7), (34, 66, 1000), (8, 8, 31)]:
        m = rng.integers(0, 256, size=(r, k), dtype=np.uint8)
        x = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
        got = native.gf256_matmul(m, x)
        want = gf.matmul_numpy(m, x)
        assert np.array_equal(got, want)


@pytest.mark.skipif(not native.available(), reason="no C toolchain")
def test_codec_roundtrip_uses_native():
    codec = RSCodec(4, 4)
    data = bytes(range(200)) * 3
    shards = codec.encode(data)
    # Drop up to m shards, reconstruct.
    lossy = list(shards)
    lossy[0] = None
    lossy[5] = None
    lossy[7] = None
    assert codec.decode_data(lossy, len(data)) == data
