"""Golden tests: fused whole-block pairing kernels vs the unfused path.

All run in Pallas interpret mode on CPU (the conftest forces the host
platform, so pairing's dispatch switch keeps the unfused path as the
reference while the fused module is called directly).

Cost control (the CPU compile cache is deliberately off — see conftest):
the kernels run with a reduced TILE so interpret-mode work shrinks 4×,
and the expensive unfused reference computations are module-scoped
fixtures shared across tests.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import R as SUBR
from hbbft_tpu.ops import pairing, pairing_fused, tower


@pytest.fixture(scope="module", autouse=True)
def small_tile():
    """Shrink the fused kernels' lane tile for interpret-mode speed."""
    calls = (
        pairing_fused._step_call,
        pairing_fused._cyclo_run_call,
        pairing_fused._mul12_call,
    )
    old = pairing_fused.TILE
    pairing_fused.TILE = 128
    for c in calls:
        c.cache_clear()
    yield
    pairing_fused.TILE = old
    for c in calls:
        c.cache_clear()


@pytest.fixture(scope="module")
def rng():
    return random.Random(77)


@pytest.fixture(scope="module")
def points(rng):
    """Batch of 3: two random multiples and one generator pair."""
    quads = []
    for a in (rng.randrange(1, SUBR), rng.randrange(1, SUBR), 1):
        quads.append(
            (
                gold.ec_mul(gold.FQ, a, gold.G1_GEN),
                gold.ec_mul(gold.FQ2, (a * 7 + 1) % SUBR, gold.G2_GEN),
            )
        )
    P = pairing.g1_affine_to_device([q[0] for q in quads])
    Qa = pairing.g2_affine_to_device([q[1] for q in quads])
    return P, Qa


@pytest.fixture(scope="module")
def miller_want(points):
    """Unfused reference Miller value (compiled once per run)."""
    P, Qa = points
    return pairing.miller_loop(P, Qa)


def test_mul12_kernel_matches_tower(rng):
    def rand_f12():
        return tower.fq12_stack(
            [
                tuple(
                    tuple(
                        (rng.randrange(gold.Q), rng.randrange(gold.Q))
                        for _ in range(3)
                    )
                    for _ in range(2)
                )
            ]
        )

    a, b = rand_f12(), rand_f12()
    want = tower.fq12_to_ints(tower.fq12_mul(a, b), 0)
    pa = pairing_fused.pack_rows(pairing_fused._leaves_f12(a), 1)
    pb = pairing_fused.pack_rows(pairing_fused._leaves_f12(b), 1)
    out = pairing_fused.fused_mul12(pa, pb, 1)
    got = tower.fq12_to_ints(pairing_fused.unpack_f12(out, 1), 0)
    assert got == want


def test_cyclo_run_kernel_matches_tower(points, miller_want):
    # A genuinely cyclotomic element: the easy part of a Miller value.
    m = tower.fq12_mul(
        tower.fq12_conj(miller_want), tower.fq12_inv(miller_want)
    )
    m = tower.fq12_mul(tower.fq12_frobenius_n(m, 2), m)

    want = m
    for _ in range(3):
        want = tower.fq12_cyclo_sqr(want)

    lanes = 3
    pm = pairing_fused.pack_rows(pairing_fused._leaves_f12(m), lanes)
    out = pairing_fused._cyclo_run_call(3, 1, True)(
        pm, jnp.asarray(pairing_fused._FOLD_T)
    )
    got = pairing_fused.unpack_f12(out, lanes)
    for i in range(lanes):
        assert tower.fq12_to_ints(got, i) == tower.fq12_to_ints(want, i)


def test_fused_miller_loop_matches_unfused(points, miller_want):
    P, Qa = points
    got = pairing_fused.miller_loop(P, Qa)
    for i in range(3):
        assert tower.fq12_to_ints(got, i) == tower.fq12_to_ints(
            miller_want, i
        )


def test_fused_final_exp_matches_unfused(miller_want):
    want = pairing.final_exponentiation_fast(miller_want)
    got = pairing_fused.final_exp_fast(miller_want)
    for i in range(3):
        assert tower.fq12_to_ints(got, i) == tower.fq12_to_ints(want, i)


def test_fused_miller_loop_rank2_batch(points, miller_want):
    """Multi-dim batch shapes flatten through pack/unpack and come back."""
    P, Qa = points
    # Build a (2, 2) batch by repeating lanes 0 and 1.
    take = lambda t, idx: jax.tree_util.tree_map(  # noqa: E731
        lambda c: jnp.asarray(c)[idx], t
    )
    idx = jnp.asarray([0, 1, 1, 0])
    P4, Q4 = take(P, idx), take(Qa, idx)
    r2 = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda c: c.reshape((2, 2) + c.shape[1:]), t
    )
    got = pairing_fused.miller_loop(r2(P4), r2(Q4))
    assert jnp.asarray(got[0][0][0]).shape[:-1] == (2, 2)
    flat = jax.tree_util.tree_map(
        lambda c: c.reshape((4,) + c.shape[2:]), got
    )
    for i, j in ((0, 0), (1, 1), (2, 1), (3, 0)):
        assert tower.fq12_to_ints(flat, i) == tower.fq12_to_ints(
            miller_want, j
        )


def test_fused_verification_end_to_end():
    """FE_fused(ML_fused(−G1, aG2)·ML_fused(aG1, G2)) == 1."""
    args = pairing.example_verify_batch(2, distinct=2)
    f = tower.fq12_mul(
        pairing_fused.miller_loop(args[0], args[1]),
        pairing_fused.miller_loop(args[2], args[3]),
    )
    out = pairing_fused.final_exp_fast(f)
    for i in range(2):
        assert pairing.is_one_host(out, i)
