"""DynamicHoneyBadger integration tests (reference
`tests/dynamic_honey_badger.rs` § shape): vote out a validator, vote one in
from a JoinPlan, switch the encryption schedule — consensus keeps running
across era changes and all correct nodes agree on every batch."""

import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.crypto.keys import SecretKey
from hbbft_tpu.net.adversary import ReorderingAdversary
from hbbft_tpu.net.virtual_net import NetBuilder, Node
from hbbft_tpu.protocols.change import Change, ChangeState
from hbbft_tpu.protocols.dynamic_honey_badger import (
    DhbBatch,
    DynamicHoneyBadger,
    JoinPlan,
)
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule


def build(n, f=0, adversary=None, seed=0):
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .crank_limit(5_000_000)
        .using(
            lambda ni, be, rng: DynamicHoneyBadger(
                ni, be, rng=rng, session_id=b"test-dhb"
            )
        )
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


def drive_epoch(net, epoch_idx, contribute=lambda i, e: ("tx", i, e)):
    """All current validators propose; crank until everyone has the batch."""
    for i in sorted(net.nodes):
        algo = net.nodes[i].algorithm
        if algo.netinfo.is_validator():
            net._process_step(
                net.nodes[i], algo.propose(contribute(i, epoch_idx))
            )
    net.crank_until(
        lambda n: all(
            len(node.outputs) >= epoch_idx + 1 for node in n.correct_nodes()
        )
    )


def assert_batches_agree(net):
    nodes = net.correct_nodes()
    n_common = min(len(n.outputs) for n in nodes)
    ref = nodes[0].outputs[:n_common]
    for n in nodes[1:]:
        assert n.outputs[:n_common] == ref, f"node {n.id} diverged"
    return ref


def test_steady_state_epochs():
    net = build(4)
    for e in range(3):
        drive_epoch(net, e)
    batches = assert_batches_agree(net)
    assert [b.era for b in batches] == [0, 0, 0]
    assert all(b.change == ChangeState.none() for b in batches)
    for e, b in enumerate(batches):
        assert len(b.contributions) >= 3
        for p, c in b.contributions.items():
            assert c == ("tx", p, e)


def test_vote_to_remove_validator():
    net = build(4, seed=1)
    # Everyone votes to remove node 3.
    for i in sorted(net.nodes):
        net._process_step(net.nodes[i], net.nodes[i].algorithm.vote_to_remove(3))
    epoch = 0
    # Drive epochs until the change completes (vote commit -> DKG -> era).
    for _ in range(12):
        drive_epoch(net, epoch)
        epoch += 1
        last = net.nodes[0].outputs[-1]
        if last.change == ChangeState.complete(Change.remove(3)):
            break
    else:
        raise AssertionError(
            f"change never completed: {[b.change for b in net.nodes[0].outputs]}"
        )
    assert_batches_agree(net)
    # After era change: 3 validators, node 3 is an observer.
    for i in (0, 1, 2):
        ni = net.nodes[i].algorithm.netinfo
        assert ni.num_nodes() == 3 and ni.is_validator()
        assert net.nodes[i].algorithm.era == 1
    assert not net.nodes[3].algorithm.netinfo.is_validator()
    # Consensus still works in the new era (node 3 left out).
    n_before = len(net.nodes[0].outputs)
    for i in (0, 1, 2):
        algo = net.nodes[i].algorithm
        net._process_step(net.nodes[i], algo.propose(("postchange", i)))
    net.crank_until(
        lambda n: all(
            len(n.nodes[i].outputs) > n_before for i in (0, 1, 2)
        )
    )
    new_batch = net.nodes[0].outputs[n_before]
    assert new_batch.era == 1
    assert len(new_batch.contributions) >= 2


def test_vote_to_add_validator_with_join_plan():
    net = build(4, seed=2)
    backend = net.backend
    rng = random.Random(777)
    joiner_sk = SecretKey.random(backend.group, rng)
    joiner_pk = joiner_sk.public_key()

    # The joiner starts as an observer from a JoinPlan of era 0.
    plan = net.nodes[0].algorithm.join_plan()
    joiner = DynamicHoneyBadger.new_joining(
        our_id=4,
        secret_key=joiner_sk,
        join_plan=plan,
        backend=backend,
        rng=rng,
        session_id=b"test-dhb",
    )
    net.nodes[4] = Node(id=4, algorithm=joiner, faulty=False)
    net._sorted_ids = sorted(net.nodes)
    net._node_order = {n: i for i, n in enumerate(net._sorted_ids)}
    assert not joiner.netinfo.is_validator()

    # Validators vote the joiner in.
    for i in range(4):
        net._process_step(
            net.nodes[i], net.nodes[i].algorithm.vote_to_add(4, joiner_pk)
        )
    epoch = 0
    for _ in range(12):
        drive_epoch(net, epoch)
        epoch += 1
        last = net.nodes[0].outputs[-1]
        if last.change.kind == "complete":
            break
    else:
        raise AssertionError("add-change never completed")
    assert_batches_agree(net)
    # New era: 5 validators including the joiner, who now holds a key share.
    for i in range(5):
        algo = net.nodes[i].algorithm
        assert algo.era == 1, f"node {i} era {algo.era}"
        assert algo.netinfo.num_nodes() == 5
        assert algo.netinfo.is_validator(), f"node {i} not validator"
    # The new era commits batches with the joiner contributing.
    n_before = min(len(net.nodes[i].outputs) for i in range(5))
    for i in range(5):
        algo = net.nodes[i].algorithm
        net._process_step(net.nodes[i], algo.propose(("era1", i)))
    net.crank_until(
        lambda n: all(len(n.nodes[i].outputs) > n_before for i in range(5))
    )
    batch = net.nodes[4].outputs[-1]
    assert batch.era == 1 and len(batch.contributions) >= 4


def test_encryption_schedule_change():
    net = build(4, seed=3)
    sched = EncryptionSchedule.every_nth(2)
    for i in sorted(net.nodes):
        net._process_step(
            net.nodes[i],
            net.nodes[i].algorithm.vote_for(Change.set_schedule(sched)),
        )
    drive_epoch(net, 0)
    batches = assert_batches_agree(net)
    assert batches[0].change == ChangeState.complete(Change.set_schedule(sched))
    for i in sorted(net.nodes):
        algo = net.nodes[i].algorithm
        assert algo.era == 1
        assert algo.encryption_schedule == sched
        # Keys carried over: still 4 validators.
        assert algo.netinfo.num_nodes() == 4 and algo.netinfo.is_validator()


@pytest.mark.parametrize("seed", range(2))
def test_remove_under_reordering_adversary(seed):
    net = build(4, f=1, adversary=ReorderingAdversary(), seed=seed)
    for i in sorted(net.nodes):
        net._process_step(net.nodes[i], net.nodes[i].algorithm.vote_to_remove(3))
    epoch = 0
    for _ in range(15):
        drive_epoch(net, epoch)
        epoch += 1
        if net.correct_nodes()[0].outputs[-1].change.kind == "complete":
            break
    else:
        raise AssertionError("change never completed under adversary")
    assert_batches_agree(net)
