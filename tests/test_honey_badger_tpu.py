"""End-to-end HoneyBadger consensus on the REAL device crypto path.

The full protocol stack — threshold encryption, ACS, batched
pairing-verification of decryption shares, device Lagrange combines — runs
with TpuBackend (JAX BLS12-381) in round-barrier defer mode, and the
committed batches must match a MockBackend run's structure.  This is the
"minimum end-to-end slice" of SURVEY.md §7 proven at the HoneyBadger level.

Host-side golden crypto (encryption, hashing) makes this the slowest test
in the suite; it runs one epoch at N=4.
"""

import pytest

from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.ops.backend import TpuBackend
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule, HoneyBadger


@pytest.mark.slow
def test_honey_badger_epoch_on_device_crypto():
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .backend(TpuBackend())
        .defer_mode("round")
        .crank_limit(1_000_000)
        .using(
            lambda ni, be: HoneyBadger(
                ni,
                be,
                session_id=b"tpu-hb",
                encryption_schedule=EncryptionSchedule.always(),
            )
        )
        .build(seed=11)
    )
    for i in sorted(net.nodes):
        net.send_input(i, {"from": i})
    net.crank_until(
        lambda n: all(len(node.outputs) >= 1 for node in n.correct_nodes()),
        max_cranks=500_000,
    )
    batches = [node.outputs[0] for node in net.correct_nodes()]
    assert all(b == batches[0] for b in batches)
    assert len(batches[0].contributions) >= 3  # ≥ N − f contributions
    # Every correct node's contribution made it in (validity).
    for node in net.correct_nodes():
        assert any(
            c == {"from": node.id} for c in batches[0].contributions.values()
        )
