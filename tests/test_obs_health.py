"""HealthReporter + why_stalled tests.

The stall tests are the subsystem's reason to exist: a seeded run whose
coin (or echo) messages are dropped must produce a why-stalled report
NAMING the blocked instance and the quorum it lacks.
"""

import json

from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.obs.health import HealthReporter, render_why_stalled, why_stalled
from hbbft_tpu.protocols.binary_agreement import BaMessage, BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast, BroadcastMessage


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# heartbeat
# ---------------------------------------------------------------------------


def test_heartbeat_period_rates_and_counter_deltas():
    clock = _Clock()
    beats = []
    counters = {"device_seconds": 0.0, "pairing_checks": 0}
    hr = HealthReporter(
        interval_s=10.0,
        counters_fn=lambda: dict(counters),
        sink=beats.append,
        clock=clock,
    )
    assert hr.tick(epoch=0, msgs=0) is None  # not due yet
    clock.t += 10.0
    counters["device_seconds"] = 2.5
    counters["pairing_checks"] = 40
    beat = hr.tick(epoch=1, msgs=500, faults=0)
    assert beat is not None and beats == [beat]
    assert beat["heartbeat"] == 1
    assert beat["epoch"] == 1 and beat["msgs"] == 500
    assert beat["counters_delta"] == {"device_seconds": 2.5, "pairing_checks": 40}
    assert beat["device_share"] == 0.25  # 2.5 s device over a 10 s beat
    clock.t += 10.0
    beat2 = hr.tick(epoch=2, msgs=1500)
    assert beat2["msgs_per_s"] == 100.0  # (1500-500)/10
    assert beat2["counters_delta"] == {}  # nothing moved since beat 1
    json.dumps(beats)  # heartbeats must be JSON-serializable as emitted


def test_stall_fires_once_and_rearms_on_progress():
    clock = _Clock()
    records = []
    hr = HealthReporter(
        interval_s=1e9,  # heartbeats off
        stall_timeout_s=30.0,
        stall_report_fn=lambda: {"nodes": {}, "summary": ["ba blocked"]},
        sink=records.append,
        clock=clock,
    )
    hr.tick(epoch=0, msgs=10)
    clock.t += 29.0
    assert hr.tick(epoch=0, msgs=10) is None  # not yet
    clock.t += 2.0
    rec = hr.tick(epoch=0, msgs=10)
    assert rec is not None and rec["stall"] and hr.stalled
    assert rec["why"]["summary"] == ["ba blocked"]
    clock.t += 100.0
    assert hr.tick(epoch=0, msgs=10) is None  # one-shot per episode
    # msgs moving is NOT progress when an epoch is supplied: a livelock
    # (messages churning, no epoch completing) must stay stalled
    assert hr.tick(epoch=0, msgs=11) is None and hr.stalled
    rec2 = hr.tick(epoch=1, msgs=11)  # epoch progress re-arms
    assert rec2 is None and not hr.stalled
    clock.t += 31.0
    assert hr.tick(epoch=1, msgs=11)["stall"]


def test_stall_msgs_progress_without_epoch():
    """With no epoch supplied, msgs is the progress signal."""
    clock = _Clock()
    records = []
    hr = HealthReporter(
        interval_s=1e9,
        stall_timeout_s=30.0,
        sink=records.append,
        clock=clock,
    )
    hr.tick(msgs=10)
    clock.t += 31.0
    assert hr.tick(msgs=11) is None  # msgs moved: re-armed
    clock.t += 31.0
    assert hr.tick(msgs=11)["stall"]


# ---------------------------------------------------------------------------
# why_stalled
# ---------------------------------------------------------------------------


def _drain_without(net, drop, max_cranks=500_000):
    """Crank to quiescence while dropping messages matching ``drop``."""
    for _ in range(max_cranks):
        net.queue[:] = [m for m in net.queue if not drop(m)]
        if not net.queue:
            net._flush_work()
            net.queue[:] = [m for m in net.queue if not drop(m)]
            if not net.queue:
                return
        net.crank()
    raise AssertionError("did not quiesce")


def test_why_stalled_names_ba_blocked_on_coin():
    """Seeded split-input BA with every coin share dropped: the run
    quiesces undecided at the first real-coin round (round 2), and the
    report names the blocked coin round and its share count."""
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .crank_limit(500_000)
        .using(lambda ni, be: BinaryAgreement(ni, be, session_id=b"stall"))
        .build(seed=0)  # seed 0: all 4 nodes reach round 2 undecided
    )
    for nid in sorted(net.nodes):
        net.send_input(nid, nid % 2 == 0)  # split inputs: no fast path

    def is_coin(m):
        return isinstance(m.payload, BaMessage) and m.payload.kind == "coin"

    _drain_without(net, is_coin)
    assert any(n.algorithm.decision is None for n in net.nodes.values())

    report = why_stalled(net)
    assert report["summary"], "stalled run must produce a nonempty summary"
    blocked = [
        ba
        for state in report["nodes"].values()
        for ba in state.get("ba", {}).values()
    ]
    assert blocked, "report must name blocked BA instances"
    coin_blocked = [ba for ba in blocked if ba["blocked_on"] == "coin"]
    assert coin_blocked, f"expected coin-blocked BA, got {blocked}"
    for ba in coin_blocked:
        assert ba["coin_round"] == 2  # first real-coin round (round % 3 == 2)
        assert ba["coin_shares_verified"] < ba["coin_shares_needed"]
    text = render_why_stalled(report)
    assert "blocked on coin round 2" in text
    json.dumps(report)  # report must be a plain JSON document


def test_why_stalled_names_rbc_missing_echo_quorum():
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .crank_limit(100_000)
        .using(lambda ni, be: Broadcast(ni, proposer_id=0))
        .build(seed=3)
    )
    net.send_input(0, b"payload")

    def is_echo(m):
        return isinstance(m.payload, BroadcastMessage) and m.payload.kind == "echo"

    _drain_without(net, is_echo)
    report = why_stalled(net)
    rbcs = [
        rbc
        for state in report["nodes"].values()
        for rbc in state.get("rbc", {}).values()
    ]
    assert rbcs, "undelivered RBC must appear in the report"
    assert any(r["echoes"] < r["echoes_needed"] for r in rbcs)
    assert "lacks quorum" in render_why_stalled(report)


def test_why_stalled_is_empty_for_a_finished_run():
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .crank_limit(500_000)
        .using(lambda ni, be: BinaryAgreement(ni, be, session_id=b"done"))
        .build(seed=1)
    )
    for nid in sorted(net.nodes):
        net.send_input(nid, True)  # unanimous: decides on the fixed coin
    net.crank_to_quiescence()
    assert all(n.algorithm.decision is not None for n in net.nodes.values())
    report = why_stalled(net)
    assert report["summary"] == [] and report["nodes"] == {}
    assert "no blocked protocol instances" in render_why_stalled(report)
