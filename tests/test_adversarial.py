"""Garbage-injection adversaries across the composed stack (VERDICT round-1
item 5; reference `RandomAdversary` shape, SURVEY.md §4): faulty nodes'
traffic is replaced by random *well-typed* messages for every protocol
layer, and consensus must still hold among correct nodes.  Plus an
end-to-end FaultLog-attribution check through DynamicHoneyBadger: a forged
vote signature yields exactly the right fault against the right proposer.
"""

import pytest

from hbbft_tpu.net.adversary import RandomAdversary
from hbbft_tpu.net.generators import generator_for
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import HoneyBadger
from hbbft_tpu.protocols.subset import Subset
from hbbft_tpu.protocols.votes import SignedVote


def _correct_proposer(net):
    return next(n.id for n in net.correct_nodes())


@pytest.mark.parametrize("seed", range(3))
def test_broadcast_garbage_injection(seed):
    net = (
        NetBuilder(range(7))
        .num_faulty(2)
        .adversary(RandomAdversary(generator_for("broadcast"), p_replace=1.0))
        .crank_limit(500_000)
        .using(lambda ni, be: Broadcast(ni, proposer_id=0))
        .build(seed=seed)
    )
    if net.nodes[0].faulty:
        pytest.skip("proposer faulty under this seed; covered elsewhere")
    net.send_input(0, b"garbage-resistant payload")
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [b"garbage-resistant payload"]
    # Garbage proofs must be attributed, not crash: some fault was logged.
    faults = [f for n in net.correct_nodes() for f in n.faults_observed]
    assert all(net.nodes[f.node_id].faulty for f in faults)


@pytest.mark.parametrize("seed", range(3))
def test_binary_agreement_garbage_injection(seed):
    net = (
        NetBuilder(range(7))
        .num_faulty(2)
        .adversary(RandomAdversary(generator_for("binary_agreement"), p_replace=1.0))
        .crank_limit(500_000)
        .using(lambda ni, be: BinaryAgreement(ni, be, session_id=b"adv-ba"))
        .build(seed=seed)
    )
    for i in sorted(net.nodes):
        net.send_input(i, i % 2 == 0)
    net.crank_to_quiescence()
    decisions = {n.id: n.outputs for n in net.correct_nodes()}
    vals = set()
    for nid, out in decisions.items():
        assert len(out) == 1, f"node {nid} decided {out}"
        vals.add(out[0])
    assert len(vals) == 1, f"divergent decisions {decisions}"


@pytest.mark.parametrize("seed", range(3))
def test_subset_garbage_injection(seed):
    net = (
        NetBuilder(range(7))
        .num_faulty(2)
        .adversary(RandomAdversary(generator_for("subset"), p_replace=1.0))
        .crank_limit(2_000_000)
        .using(lambda ni, be: Subset(ni, be, session_id=b"adv-subset"))
        .build(seed=seed)
    )
    for i in sorted(net.nodes):
        net.send_input(i, b"contribution-%d" % i)
    net.crank_to_quiescence()
    # All correct nodes output the same contribution set.
    outs = {}
    for n in net.correct_nodes():
        contribs = sorted(
            (o.proposer, o.value) for o in n.outputs if o.kind == "contribution"
        )
        outs[n.id] = contribs
    ref = next(iter(outs.values()))
    assert all(v == ref for v in outs.values()), f"divergent subsets {outs}"
    # ≥ N - f contributions survive garbage injection.
    assert len(ref) >= 5


@pytest.mark.parametrize("seed", range(2))
def test_honey_badger_garbage_injection(seed):
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .adversary(RandomAdversary(generator_for("honey_badger"), p_replace=1.0))
        .crank_limit(2_000_000)
        .using(lambda ni, be: HoneyBadger(ni, be, session_id=b"adv-hb"))
        .build(seed=seed)
    )
    for i in sorted(net.nodes):
        net.send_input(i, ("tx", i))
    net.crank_to_quiescence()
    batches = {n.id: n.outputs for n in net.correct_nodes()}
    n_common = min(len(b) for b in batches.values())
    assert n_common >= 1, f"no epoch completed: {batches}"
    ref = next(iter(batches.values()))[:n_common]
    for nid, b in batches.items():
        assert b[:n_common] == ref, f"node {nid} diverged"


def test_dhb_forged_vote_fault_attribution():
    """A forged vote signature inside a committed contribution must produce
    exactly one `invalid_vote_signature` fault per correct node, attributed
    to the proposer that carried it — and the vote must not count."""
    net = (
        NetBuilder(range(4))
        .num_faulty(0)
        .crank_limit(5_000_000)
        .using(
            lambda ni, be, rng: DynamicHoneyBadger(
                ni, be, rng=rng, session_id=b"adv-dhb"
            )
        )
        .build(seed=1)
    )
    forger = 2
    algo = net.nodes[forger].algorithm
    from hbbft_tpu.protocols.change import Change

    algo.vote_for(Change.remove(3))
    assert algo._pending_votes, "vote not queued"
    v = algo._pending_votes[-1]
    algo._pending_votes[-1] = SignedVote(
        v.voter, v.era, v.num, v.change, b"\x00" * len(v.sig_bytes)
    )

    for i in sorted(net.nodes):
        net._process_step(
            net.nodes[i], net.nodes[i].algorithm.propose(("tx", i))
        )
    net.crank_until(
        lambda n: all(len(node.outputs) >= 1 for node in n.correct_nodes())
    )

    for node in net.correct_nodes():
        if node.id == forger:
            continue  # the forger doesn't re-verify its own queued vote
        kinds = [
            (f.node_id, f.kind)
            for f in node.faults_observed
            if f.kind == "dynamic_honey_badger:invalid_vote_signature"
        ]
        assert kinds == [(forger, "dynamic_honey_badger:invalid_vote_signature")], (
            f"node {node.id}: {node.faults_observed}"
        )
        # The forged vote must not have been counted.
        assert not node.algorithm.vote_counter.tally(), (
            node.algorithm.vote_counter.tally()
        )
