"""Pipelined device dispatch + staging cache (PR 3 tentpole).

Covers the deferred-fetch seam's contract end to end on CPU:

* queue machinery (ops/pipeline.py): bounded depth, FIFO forcing,
  deterministic out-of-order flush, kill switch;
* TpuBackend pipelined vs ``HBBFT_TPU_NO_PIPELINE=1`` — bit-identical
  outputs, identical ``device_dispatches``;
* chunk-boundary edge cases at n == cap and n == cap+1 for both the
  pairing lane cap and the ladder lane cap, and the ``_lane_capped_step``
  pad-floor clamp;
* staging cache: cross-call hits, second-epoch behavior, era
  invalidation;
* MockBackend's simulated async completion order (tier-1 exercises
  out-of-order delivery without JAX compiles);
* tracer/trace_report acceptance: overlapped device spans validate and
  sum to counters.device_seconds within ±5%.
"""

import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.ops.pipeline import DispatchPipeline, pipeline_depth


# ---------------------------------------------------------------------------
# Queue machinery (no JAX)
# ---------------------------------------------------------------------------


def test_pipeline_depth_env(monkeypatch):
    monkeypatch.delenv("HBBFT_TPU_NO_PIPELINE", raising=False)
    monkeypatch.delenv("HBBFT_TPU_PIPELINE_DEPTH", raising=False)
    assert pipeline_depth() == 2
    monkeypatch.setenv("HBBFT_TPU_PIPELINE_DEPTH", "5")
    assert pipeline_depth() == 5
    monkeypatch.setenv("HBBFT_TPU_NO_PIPELINE", "1")
    assert pipeline_depth() == 0  # kill switch wins


def test_bounded_queue_forces_oldest_fifo():
    resolved = []
    pipe = DispatchPipeline(depth_fn=lambda: 2)
    for i in range(5):
        pipe.submit(
            lambda i=i: i, fetch=None,
            on_result=lambda v: resolved.append(v),
        )
        assert len(pipe) <= 2
    assert resolved == [0, 1, 2]  # forced out oldest-first
    pipe.flush()
    assert resolved == [0, 1, 2, 3, 4]


def test_sync_submit_drains_pending_first():
    resolved = []
    pipe = DispatchPipeline(depth_fn=lambda: 8)
    for i in range(3):
        pipe.submit(lambda i=i: i, fetch=None, on_result=resolved.append)
    p = pipe.submit(lambda: 99, fetch=None, on_result=resolved.append, sync=True)
    assert p.value == 99
    assert resolved == [0, 1, 2, 99]  # older entries resolved in order
    assert len(pipe) == 0


def test_flush_out_of_order_is_deterministic_and_disjoint():
    out = [None] * 4
    pipe = DispatchPipeline(depth_fn=lambda: 16)
    for i in range(4):
        pipe.submit(
            lambda i=i: i * 10, fetch=None,
            on_result=lambda v, i=i: out.__setitem__(i, v),
        )
    pipe.flush(order=[3, 1, 2, 0])
    assert out == [0, 10, 20, 30]  # completion order cannot change results


def test_overlap_excludes_other_entries_fetch_block():
    """Host time spent BLOCKED in entry A's fetch must not count as
    entry B's 'overlap' — otherwise overlap_fraction reads near-maximal
    with zero actual assembly hidden (the attribution the TPU-window
    before/after comparison relies on)."""
    import time as _time

    from hbbft_tpu.utils.metrics import Counters

    c = Counters()
    pipe = DispatchPipeline(counters=c, depth_fn=lambda: 4)
    slow_fetch = lambda raw: (_time.sleep(0.05), raw)[1]  # noqa: E731
    pipe.submit(lambda: "a", fetch=slow_fetch, kind="sign", items=1)
    pipe.submit(lambda: "b", fetch=None, kind="sign", items=1)
    pipe.flush()  # A resolves first: its 50ms block sits inside B's window
    assert c.overlap_seconds < 0.04, c.overlap_seconds


def test_overlap_and_pipelined_counters():
    from hbbft_tpu.utils.metrics import Counters

    c = Counters()
    pipe = DispatchPipeline(counters=c, depth_fn=lambda: 2)
    pipe.submit(lambda: 1, fetch=None, kind="sign", items=1)
    pipe.flush()
    assert c.pipelined_dispatches == 1
    assert c.device_seconds > 0
    assert c.device_seconds_sign > 0
    assert c.overlap_seconds >= 0
    # sync entries are not counted as pipelined
    pipe.submit(lambda: 1, fetch=None, kind="sign", items=1, sync=True)
    assert c.pipelined_dispatches == 1


# ---------------------------------------------------------------------------
# MockBackend simulated async completion (tier-1, no JAX compiles)
# ---------------------------------------------------------------------------


def _mock_items(n: int, rng):
    be = MockBackend()
    sks = be.generate_key_set(2, rng)
    pks = sks.public_keys()
    items = []
    for i in range(n):
        doc = b"doc-%d" % (i % 5)
        share = sks.secret_key_share(i % 7).sign_share(doc)
        pk = pks.public_key_share((i % 7) if i % 11 else (i + 1) % 7)
        items.append((pk, doc, share))  # mix of valid and pk-mismatched
    return items


def test_mock_pipeline_out_of_order_matches_plain():
    items = _mock_items(37, random.Random(3))
    plain = MockBackend()
    piped = MockBackend()
    piped.pipeline_chunk = 4  # 10 chunks, resolved last-first
    want = plain.verify_sig_shares(items)
    assert piped.verify_sig_shares(items) == want
    assert True in want and False in want  # the batch actually mixes


def test_mock_pipeline_array_engine_epochs_bit_identical():
    """Tier-1 pipeline smoke (CPU, small N): two lockstep epochs through
    the out-of-order mock pipeline produce the same Batches as the plain
    mock path."""
    from hbbft_tpu.engine import ArrayHoneyBadgerNet

    def run(pipeline_chunk):
        be = MockBackend()
        be.pipeline_chunk = pipeline_chunk
        net = ArrayHoneyBadgerNet(range(6), backend=be, seed=5)
        return net.run_epochs(2, payload_size=32)

    plain, piped = run(None), run(100)
    assert plain == piped


# ---------------------------------------------------------------------------
# TpuBackend: pipelined vs sync, chunk boundaries, staging cache
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tpu_setup():
    from hbbft_tpu.ops.backend import TpuBackend

    backend = TpuBackend()
    rng = random.Random(77)
    sks = backend.generate_key_set(1, rng)  # t=1: combines need 2 shares
    return backend, sks, sks.public_keys(), rng


def _fresh_tpu():
    from hbbft_tpu.ops.backend import TpuBackend

    return TpuBackend()


def test_pipelined_vs_sync_bit_identical(tpu_setup, monkeypatch):
    """The acceptance invariant: pipelined and HBBFT_TPU_NO_PIPELINE=1
    runs produce bit-identical protocol outputs and identical
    device_dispatches counts, across multi-chunk ladder, RLC-verify and
    batched-combine paths."""
    _, sks, pks, rng = tpu_setup
    cts = [pks.encrypt(bytes([65 + j]) * 9, rng) for j in range(3)]
    gen_items = [
        (sks.secret_key_share(i % 3), cts[j]) for j in range(3) for i in range(3)
    ]
    doc = b"pipeline-ab"

    def run():
        be = _fresh_tpu()
        be.device_combine_threshold = 2
        be.device_lane_cap = 4  # force multi-chunk ladders/combines
        shares = be.decrypt_shares_batch(gen_items)
        ver_items = [
            (pks.public_key_share(i % 3), cts[j], shares[j * 3 + (i % 3)])
            for j in range(3)
            for i in range(3)
        ]
        ver = be.verify_dec_shares(ver_items)
        comb_items = [
            ({0: shares[j * 3], 2: shares[j * 3 + 2]}, cts[j]) for j in range(3)
        ]
        plains = be.combine_dec_shares_batch(pks, comb_items)
        sig_shares = be.sign_shares_batch(
            [(sks.secret_key_share(i), doc) for i in range(3)]
        )
        return (
            [s.el for s in shares],
            ver,
            plains,
            [s.el for s in sig_shares],
            be.counters.device_dispatches,
            be.counters.pipelined_dispatches,
        )

    monkeypatch.delenv("HBBFT_TPU_NO_PIPELINE", raising=False)
    piped = run()
    monkeypatch.setenv("HBBFT_TPU_NO_PIPELINE", "1")
    sync = run()
    assert piped[:4] == sync[:4], "pipelining changed protocol outputs"
    assert piped[4] == sync[4], "pipelining changed dispatch counts"
    assert piped[5] > 0 and sync[5] == 0  # the modes actually differed


def test_deferred_verify_matches_sync(tpu_setup):
    """The verify_*_deferred twins (PR 5 cross-round overlap seam):
    submit-now/resolve-later must return the same booleans as the sync
    entry points with identical device_dispatches — on a mixed batch
    that exercises a passing RLC group, a contaminated group's exact
    per-leaf fallback, and the direct paths.  Shapes deliberately reuse
    the buckets this module compiles elsewhere (RLC (4,4), product2 and
    ladder b=4) — the suite's XLA:CPU compile budget is tight.
    """
    _, sks, pks, rng = tpu_setup
    cts = [pks.encrypt(b"deferred-ab-%d" % j, rng) for j in range(2)]
    items = []
    for j, ct in enumerate(cts):
        for i in range(3):
            # item 4 (ct 1, i 1) checks against the wrong pk share: its
            # group fails and drops to exact per-leaf checks
            pk = pks.public_key_share((i + 1) % 3 if j == 1 and i == 1 else i)
            items.append(
                (pk, ct, sks.secret_key_share(i).decrypt_share_unchecked(ct))
            )
    doc = b"deferred-sig"
    sig_items = [
        (pks.public_key_share(i), doc, sks.secret_key_share(i).sign_share(doc))
        for i in range(3)
    ]
    gen_items = [(sks.secret_key_share(i % 3), cts[0]) for i in range(4)]

    def run(deferred):
        be = _fresh_tpu()
        be.device_combine_threshold = 2
        if deferred:
            resolve_dec = be.verify_dec_shares_deferred(items)
            resolve_ct = be.verify_ciphertexts_deferred(cts)
            resolve_sig = be.verify_sig_shares_deferred(sig_items)
            # engine-style interleaving: another batched call runs while
            # the verifies are in flight
            gen = be.decrypt_shares_batch(gen_items)
            out = (resolve_dec(), resolve_ct(), resolve_sig())
        else:
            out = (
                be.verify_dec_shares(items),
                be.verify_ciphertexts(cts),
                be.verify_sig_shares(sig_items),
            )
            gen = be.decrypt_shares_batch(gen_items)
        return out, [g.el for g in gen], be.counters.device_dispatches

    sync_out, sync_gen, sync_disp = run(False)
    defer_out, defer_gen, defer_disp = run(True)
    assert defer_out == sync_out, "deferred verify changed results"
    assert defer_gen == sync_gen
    assert defer_disp == sync_disp, "deferred verify changed dispatch counts"
    assert sync_out[0][4] is False and all(
        v for i, v in enumerate(sync_out[0]) if i != 4
    )


def test_check_batch_chunk_boundaries(tpu_setup):
    """Pairing lane cap at n == cap and n == cap+1: every chunk verifies
    and per-item results stay in order (True/False mix)."""
    backend, sks, pks, rng = tpu_setup
    cap = 4
    old_cap = backend.pairing_lane_cap
    backend.pairing_lane_cap = cap
    try:
        for n in (cap, cap + 1):
            cts = [pks.encrypt(bytes([j % 250]) * 7, rng) for j in range(n)]
            want = [j % 3 != 1 for j in range(n)]
            # build a mixed batch by swapping w for the generator on the
            # False lanes (a well-formed but wrong point)
            quads = []
            g1 = backend.group.g1()
            for ct, ok in zip(cts, want):
                h = backend._hash_g2(backend.group.g1_to_bytes(ct.u) + ct.v)
                w = ct.w if ok else backend.group.g2()
                quads.append((g1, w, ct.u, h))
            d0 = backend.counters.device_dispatches
            got = backend._check_batch(quads)
            assert got == want
            expect_chunks = (n + cap - 1) // cap
            assert backend.counters.device_dispatches == d0 + expect_chunks
    finally:
        backend.pairing_lane_cap = old_cap


def test_ladder_chunk_boundaries(tpu_setup):
    """Ladder lane cap at n == cap (one dispatch) and n == cap+1 (device
    chunk + sub-threshold host tail, exactly the pre-pipeline recursion
    semantics) — outputs match the host golden bit-for-bit."""
    backend, sks, pks, rng = tpu_setup
    ct = pks.encrypt(b"ladder-edge", rng)
    backend.device_combine_threshold = 2
    backend.device_lane_cap = 4
    try:
        for n, expect_disp in ((4, 1), (5, 1)):
            items = [(sks.secret_key_share(i % 3), ct) for i in range(n)]
            d0 = backend.counters.device_dispatches
            got = backend.decrypt_shares_batch(items)
            assert backend.counters.device_dispatches == d0 + expect_disp
            want = [sk.decrypt_share_unchecked(c) for sk, c in items]
            assert [g.el for g in got] == [w.el for w in want]
    finally:
        backend.device_combine_threshold = type(backend).device_combine_threshold
        backend.device_lane_cap = type(backend).device_lane_cap


def test_lane_capped_step_pad_floor(tpu_setup):
    """cap // k below the _pad_bucket floor is clamped UP to the floor
    (a smaller step would dispatch the same padded lanes with waste);
    above the floor the power-of-two round-down still applies."""
    backend = tpu_setup[0]
    old_cap = backend.device_lane_cap
    try:
        backend.device_lane_cap = 4
        assert backend._lane_capped_step(2) == 4  # 4//2=2 < floor 4
        backend.device_lane_cap = 1 << 15
        assert backend._lane_capped_step(3) == 8192  # pow2 round-down
        assert backend._lane_capped_step(34) == 512
        assert backend._lane_capped_step(1 << 14) == 4  # floor again
        assert backend._lane_capped_step(1 << 20) == 4  # k > cap: floor
    finally:
        backend.device_lane_cap = old_cap


def test_staging_cache_second_epoch_hits_and_era_invalidation(tpu_setup):
    """Two-epoch shape: the second epoch's staging re-uses the first's
    key material (hit counter grows, conversion counter nearly stops);
    era turnover clears the staged rows."""
    _, sks, pks, rng = tpu_setup
    be = _fresh_tpu()
    be.device_combine_threshold = 2

    def epoch(e):
        doc = b"epoch-%d-coin" % e
        shares = be.sign_shares_batch(
            [(sks.secret_key_share(i), doc) for i in range(3)]
        )
        assert be.verify_sig_shares(
            [(pks.public_key_share(i), doc, shares[i]) for i in range(3)]
        ) == [True] * 3

    h0, m0 = be.counters.stage_cache_hits, be.counters.stage_cache_misses
    epoch(0)
    h1, m1 = be.counters.stage_cache_hits, be.counters.stage_cache_misses
    epoch(1)
    h2, m2 = be.counters.stage_cache_hits, be.counters.stage_cache_misses
    assert h2 > h1, "second epoch must hit the staging cache"
    # epoch 2 converts only its fresh shares/H2 point; the key material
    # (pk shares, generator) is already staged
    assert (m2 - m1) < (m1 - m0)
    assert len(be._stage) > 0
    be.new_era(1)
    assert len(be._stage) == 0  # era-keyed invalidation


def test_staging_cache_rows_unit():
    """StagingCache.rows is a drop-in for fq.from_ints (values, dtype,
    shape), with LRU eviction bounded by capacity."""
    import numpy as np

    from hbbft_tpu.ops import fq
    from hbbft_tpu.ops.staging import StagingCache

    vals = [0, 1, 2**300 + 17, 1, 0]
    cache = StagingCache(capacity=2)
    got = cache.rows(vals)
    want = fq.from_ints(vals)
    assert got.shape == want.shape and got.dtype == want.dtype
    assert np.array_equal(got, want)
    assert len(cache) == 2  # LRU bound held (3 uniques, capacity 2)
    # disabled cache falls straight through
    off = StagingCache(capacity=0)
    assert np.array_equal(off.rows(vals), want)
    assert len(off) == 0


# ---------------------------------------------------------------------------
# Tracer / trace_report acceptance
# ---------------------------------------------------------------------------


def test_pipelined_trace_validates_and_sums_to_device_seconds(
    tpu_setup, tmp_path
):
    """Overlapped device spans (slot tracks) still pass the Chrome-trace
    validator and sum to counters.device_seconds within ±5% — the
    trace_report acceptance check for pipelined dispatch."""
    import json

    from hbbft_tpu.obs import Tracer
    from tools.trace_report import (
        check_device_seconds,
        load_events,
        validate_chrome_trace,
    )

    _, sks, pks, rng = tpu_setup
    be = _fresh_tpu()
    be.tracer = Tracer()
    be.device_combine_threshold = 2
    be.device_lane_cap = 4  # several in-flight chunks
    ct = pks.encrypt(b"traced-run", rng)
    items = [(sks.secret_key_share(i % 3), ct) for i in range(9)]
    shares = be.decrypt_shares_batch(items)
    assert be.verify_dec_shares(
        [(pks.public_key_share(i % 3), ct, shares[i]) for i in range(9)]
    ) == [True] * 9
    assert be.counters.pipelined_dispatches > 0
    path = str(tmp_path / "pipeline_trace.json")
    be.tracer.write(path)
    events = load_events(path)
    assert validate_chrome_trace(events) == []
    ok, got = check_device_seconds(events, be.counters.device_seconds)
    assert ok, (got, be.counters.device_seconds)
    # slot tracks are present in the metadata (overlap went multi-track)
    doc = json.load(open(path))
    tracks = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert any(t.startswith("device/") for t in tracks)


def test_trace_report_device_seconds_cli_flag(tpu_setup, tmp_path):
    from hbbft_tpu.obs import Tracer
    from tools.trace_report import main as tr_main

    _, sks, pks, rng = tpu_setup
    be = _fresh_tpu()
    be.tracer = Tracer()
    be.device_combine_threshold = 2
    doc = b"cli-check"
    shares = be.sign_shares_batch(
        [(sks.secret_key_share(i), doc) for i in range(3)]
    )
    assert len(shares) == 3
    path = str(tmp_path / "t.json")
    be.tracer.write(path)
    dev = be.counters.device_seconds
    assert tr_main([path, "--device-seconds", str(dev)]) == 0
    assert tr_main([path, "--device-seconds", str(dev * 3)]) == 1
