"""Batched era-change DKG (engine/dkg_batch.py) vs the per-node path.

The batched path must be a drop-in for the lockstep SyncKeyGen loop:
same workload counts, a key set every node agrees on, and working
consensus (decrypt-equality epochs) under the NEW keys.  Keys cannot be
byte-identical across paths (the rng is consumed in a different order),
so equivalence is semantic: both paths yield self-consistent key sets
that the engine runs correctly under — plus the RLC aggregation must
actually reject corrupted ciphertexts/values.
"""

import os

import pytest

from hbbft_tpu.crypto.backend import CpuBackend, MockBackend
from hbbft_tpu.engine import ArrayHoneyBadgerNet
from hbbft_tpu.engine.dkg_batch import (
    _batched_decrypt,
    batched_encrypt,
    DkgStats,
    batched_era_dkg,
)


def _mk_net(n, backend, seed=7):
    return ArrayHoneyBadgerNet(
        range(n), backend=backend, seed=seed, dynamic=True
    )


def test_batched_era_change_mock_end_to_end():
    net = _mk_net(6, MockBackend())
    net.run_epochs(1, payload_size=32)
    era0, pk0 = net.era, net.pk_master
    rep = net.era_change()  # default mode: batched
    assert net.era == era0 + 1
    assert net.pk_master != pk0  # fresh master key
    n = 6
    assert rep.kg_parts_handled == n * n
    assert rep.kg_acks_handled == n * n * n
    assert rep.ciphertexts_verified == n * n + n * n * n
    # post-turnover epochs assert decrypt equality internally
    net.run_epochs(2, payload_size=32)


def test_batched_matches_pernode_workload_counts(monkeypatch):
    reps = {}
    for mode in ("batched", "pernode"):
        monkeypatch.setenv("HBBFT_TPU_DKG", mode)
        net = _mk_net(5, MockBackend())
        net.run_epochs(1, payload_size=32)
        reps[mode] = net.era_change()
        net.run_epochs(1, payload_size=32)  # both key sets must WORK
    for field in ("kg_parts_handled", "kg_acks_handled", "messages_delivered"):
        assert getattr(reps["batched"], field) == getattr(
            reps["pernode"], field
        ), field


def test_batched_dkg_direct_consistency():
    """Direct API: the returned shares interpolate to the master key and
    agree with the commitment (the function's own final check), and the
    stats account for every ladder the phases dispatched."""
    import random

    backend = MockBackend()
    g = backend.group
    rng = random.Random(3)
    ids = list(range(4))
    sk_xs = {i: rng.randrange(1, g.r) for i in ids}
    pk_els = {i: g.g1_mul(sk_xs[i], g.g1()) for i in ids}
    pk_set, shares, stats = batched_era_dkg(backend, ids, sk_xs, pk_els, 1, rng)
    assert pk_set.threshold() == 1
    for k, nid in enumerate(ids):
        assert g.g1_mul(shares[nid].x, g.g1()) == pk_set.public_key_share(k).el
    n, m = 4, 2
    assert stats.parts_handled == n * n
    assert stats.acks_handled == n * n * n
    # ladders: commitments n·m² + row enc 3n² + row dec n² + ack enc 3n³
    # + ack dec n³ + share consistency n
    assert stats.ladder_muls == (
        n * m * m + 3 * n * n + n * n + 3 * n**3 + n**3 + n
    )
    assert stats.msm_terms == 2 * n * m * m


def test_batched_decrypt_rejects_tampered_ciphertext():
    import random

    backend = MockBackend()
    g = backend.group
    rng = random.Random(5)
    x = rng.randrange(1, g.r)
    pk = g.g1_mul(x, g.g1())
    stats = DkgStats()
    cts = batched_encrypt(backend, [pk, pk], [b"aaaa", b"bbbb"], rng, stats)
    cts[1].v = bytes([cts[1].v[0] ^ 1]) + cts[1].v[1:]  # malleate
    with pytest.raises(ValueError, match="invalid ciphertext"):
        _batched_decrypt(backend, cts, [x, x], stats)


@pytest.mark.slow
def test_batched_era_change_real_crypto_small():
    """Real BLS12-381 (CpuBackend golden) at N=4: the batched path's RLC
    checks, pairing batch, and key derivation must hold over the actual
    curve, and consensus must run under the new keys."""
    net = _mk_net(4, CpuBackend(), seed=11)
    rep = net.era_change()
    assert rep.kg_parts_handled == 16
    assert rep.kg_acks_handled == 64
    net.run_epochs(1, payload_size=16)


def _run_dkg_with_corruption(monkeypatch, corrupt_call: int):
    """Run batched_era_dkg with _batched_decrypt's output corrupted on the
    given call (1 = row phase, 2 = ack phase): bump the first decoded
    integer by one and re-encode, so the ciphertext/pairing layer is
    untouched and only the RLC aggregate can catch it."""
    import random

    from hbbft_tpu.engine import dkg_batch
    from hbbft_tpu.utils import canonical

    real = dkg_batch._batched_decrypt
    calls = {"n": 0}

    def corrupting(backend, cts, sk_xs, stats):
        out = real(backend, cts, sk_xs, stats)
        calls["n"] += 1
        if calls["n"] == corrupt_call:
            val = canonical.decode(out[0])
            if isinstance(val, list):
                val = [val[0] + 1] + val[1:]
            else:
                val = val + 1
            out[0] = canonical.encode(val)
        return out

    monkeypatch.setattr(dkg_batch, "_batched_decrypt", corrupting)
    backend = MockBackend()
    g = backend.group
    rng = random.Random(9)
    ids = list(range(4))
    sk_xs = {i: rng.randrange(1, g.r) for i in ids}
    pk_els = {i: g.g1_mul(sk_xs[i], g.g1()) for i in ids}
    return dkg_batch.batched_era_dkg(backend, ids, sk_xs, pk_els, 1, rng)


def test_row_rlc_rejects_corrupted_row(monkeypatch):
    with pytest.raises(ValueError, match="row-commitment check failed"):
        _run_dkg_with_corruption(monkeypatch, corrupt_call=1)


def test_ack_rlc_rejects_corrupted_value(monkeypatch):
    with pytest.raises(ValueError, match="ack-value check failed"):
        _run_dkg_with_corruption(monkeypatch, corrupt_call=2)
