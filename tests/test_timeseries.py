"""Per-epoch telemetry series (obs/timeseries.py MetricsLog): counter
deltas over snapshots (never a mid-run reset), timing exclusion for
replay identity, histogram windows, host-bucket splits, the ring bound,
and the JSONL export."""

import json

from hbbft_tpu.obs.critpath import EpochCritPath
from hbbft_tpu.obs.timeseries import MetricsLog, snap_net


def test_rows_carry_counter_deltas_not_totals():
    log = MetricsLog()
    log.snap(0, counters={"cranks": 10, "messages_delivered": 100})
    log.snap(1, counters={"cranks": 25, "messages_delivered": 100})
    r0, r1 = log.rows_list()
    assert r0["counters"] == {"cranks": 10, "messages_delivered": 100}
    # zero deltas are elided; the underlying counters stayed monotonic
    assert r1["counters"] == {"cranks": 15}


def test_timing_fields_excluded_by_default():
    log = MetricsLog()
    log.snap(0, counters={"cranks": 5, "device_seconds": 1.25})
    assert log.rows_list()[0]["counters"] == {"cranks": 5}
    timed = MetricsLog(include_timing=True)
    timed.snap(0, counters={"cranks": 5, "device_seconds": 1.25})
    assert timed.rows_list()[0]["counters"] == {
        "cranks": 5, "device_seconds": 1.25,
    }


def test_host_buckets_split_out():
    log = MetricsLog(include_timing=True)
    log.snap(
        0,
        counters={"host_bucket_staging": 0.5, "host_bucket_other": 0.1, "cranks": 1},
    )
    row = log.rows_list()[0]
    assert row["host_buckets"] == {"staging": 0.5, "other": 0.1}
    assert row["counters"] == {"cranks": 1}


def test_hist_windows_are_deltas():
    class FakeTracer:
        def __init__(self):
            self.summary = {}

        def hist_summary(self):
            return self.summary

    tr = FakeTracer()
    log = MetricsLog()
    tr.summary = {"dispatch_batch_items": {"count": 4, "p50": 8.0}}
    log.snap(0, tracer=tr)
    tr.summary = {"dispatch_batch_items": {"count": 4, "p50": 8.0}}
    log.snap(1, tracer=tr)  # no new samples: window elided
    tr.summary = {"dispatch_batch_items": {"count": 9, "p50": 16.0}}
    log.snap(2, tracer=tr)
    r0, r1, r2 = log.rows_list()
    assert r0["hist"]["dispatch_batch_items"]["window_count"] == 4
    assert "hist" not in r1
    assert r2["hist"]["dispatch_batch_items"]["window_count"] == 5


def test_gate_normalized_from_path_or_dict():
    log = MetricsLog()
    p = EpochCritPath(
        epoch=0, gate_phase="ba.decide", gate_instance=2,
        gate_node=repr(1), gate_round=3, cranks=40,
    )
    log.snap(0, gate=p)
    log.snap(1, gate={"phase": "rbc.output", "instance": 0, "cranks": 9})
    r0, r1 = log.rows_list()
    assert r0["gate"] == {
        "phase": "ba.decide", "instance": 2, "node": repr(1),
        "round": 3, "cranks": 40,
    }
    assert r1["gate"]["phase"] == "rbc.output" and r1["gate"]["cranks"] == 9


def test_ring_bound_and_dropped():
    log = MetricsLog(capacity=3)
    for e in range(5):
        log.snap(e)
    assert len(log) == 3
    assert log.dropped == 2
    assert [r["epoch"] for r in log.rows_list()] == [2, 3, 4]
    assert log.last()["epoch"] == 4


def test_jsonl_roundtrip(tmp_path):
    log = MetricsLog()
    log.snap(0, counters={"cranks": 3}, controller_b=16, mempool_depth=40)
    path = str(tmp_path / "series.jsonl")
    log.to_jsonl(path)
    with open(path) as f:
        rows = [json.loads(line) for line in f]
    assert rows == log.rows_list()
    assert rows[0]["b"] == 16 and rows[0]["mempool"] == 40


def test_snap_net_duck_typed():
    class FakeCrash:
        def stats(self):
            return {"crashes": 2, "restarts": 1}

    class FakeNet:
        crash = FakeCrash()
        cranks = 120
        now = 60

        def metrics(self):
            return {"cranks": 120}

        def down_node_ids(self):
            return [3]

    log = MetricsLog()
    row = snap_net(log, FakeNet(), 7, controller_b=8, mempool_depth=5)
    assert row["epoch"] == 7
    assert row["crash"] == {"crashes": 2, "restarts": 1, "down": [repr(3)]}
    assert row["cranks"] == 120 and row["now"] == 60
    assert row["b"] == 8 and row["mempool"] == 5
