"""HoneyBadger integration tests (reference `tests/honey_badger.rs` § shape):
all correct nodes output identical batch sequences; every correct node's
contribution eventually commits; encryption schedules and adversaries don't
break agreement."""

import pytest

from hbbft_tpu.net.adversary import ReorderingAdversary, SilentAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    EncryptionSchedule,
    HoneyBadger,
)


def build(n, f=0, adversary=None, defer_mode="eager", seed=0, schedule=None):
    schedule = schedule or EncryptionSchedule.always()
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .defer_mode(defer_mode)
        .crank_limit(5_000_000)
        .using(
            lambda ni, be: HoneyBadger(
                ni, be, session_id=b"test-hb", encryption_schedule=schedule
            )
        )
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


def run_epochs(net, n_epochs, defer_mode="eager"):
    """Each epoch every node proposes a contribution; crank until all
    correct nodes emitted the epoch's batch."""
    for e in range(n_epochs):
        for i in sorted(net.nodes):
            net.send_input(i, {"from": i, "epoch": e})
        net.crank_until(
            lambda net: all(
                len(node.outputs) >= e + 1 for node in net.correct_nodes()
            )
        )


def assert_identical_batches(net, n_epochs):
    ref = None
    for node in net.correct_nodes():
        batches = node.outputs[:n_epochs]
        assert len(batches) == n_epochs
        for i, b in enumerate(batches):
            assert isinstance(b, Batch) and b.epoch == i
        if ref is None:
            ref = batches
        assert batches == ref, f"node {node.id} diverged"


@pytest.mark.parametrize("n,f", [(1, 0), (4, 1)])
@pytest.mark.parametrize("defer_mode", ["eager"])
def test_batches_identical(n, f, defer_mode):
    net = build(n, f, defer_mode=defer_mode)
    run_epochs(net, 3)
    assert_identical_batches(net, 3)
    # Every epoch commits ≥ N - f contributions, each intact.
    for b in net.correct_nodes()[0].outputs[:3]:
        assert len(b.contributions) >= n - f
        for p, c in b.contributions.items():
            assert c == {"from": p, "epoch": b.epoch}


def test_round_mode_agrees_with_eager():
    batches = {}
    for mode in ("eager", "round"):
        net = build(4, 1, defer_mode=mode, seed=42)
        for i in sorted(net.nodes):
            net.send_input(i, (i, "x"))
        if mode == "round":
            while net.queue or net._pending_work:
                net.crank_round()
        else:
            net.crank_to_quiescence()
        batches[mode] = [n.outputs[0] for n in net.correct_nodes()]
    # Same seed ⇒ identical first batch in both crypto modes.
    assert batches["eager"] == batches["round"]


@pytest.mark.parametrize(
    "schedule",
    [
        EncryptionSchedule.never(),
        EncryptionSchedule.every_nth(2),
        EncryptionSchedule.tick_tock(1, 1),
    ],
)
def test_encryption_schedules(schedule):
    net = build(4, 1, schedule=schedule, seed=3)
    run_epochs(net, 3)
    assert_identical_batches(net, 3)


@pytest.mark.parametrize("seed", range(3))
def test_adversarial_reordering(seed):
    net = build(4, 1, adversary=ReorderingAdversary(), seed=seed)
    run_epochs(net, 2)
    assert_identical_batches(net, 2)


@pytest.mark.parametrize("seed", range(3))
def test_silent_faulty(seed):
    net = build(7, 2, adversary=SilentAdversary(), seed=seed)
    for i in sorted(net.nodes):
        net.send_input(i, ("tx", i))
    net.crank_until(
        lambda net: all(len(n.outputs) >= 1 for n in net.correct_nodes())
    )
    ref = None
    for node in net.correct_nodes():
        b = node.outputs[0]
        assert len(b.contributions) >= 5
        if ref is None:
            ref = b
        assert b == ref


def test_garbage_ciphertext_skipped_not_fatal():
    """A faulty proposer whose subset payload isn't a valid ciphertext gets
    skipped with a fault, and the epoch still completes."""
    from hbbft_tpu.net.adversary import Adversary

    class GarbageProposal(Adversary):
        def tamper(self, net, msg):
            # Corrupt only broadcast Value messages originating at the faulty
            # node's own proposal (its shard dissemination).
            from hbbft_tpu.protocols.honey_badger import HbMessage
            from hbbft_tpu.protocols.subset import SubsetMessage
            from hbbft_tpu.protocols.broadcast import BroadcastMessage

            m = msg.payload
            if (
                isinstance(m, HbMessage)
                and m.kind == "subset"
                and isinstance(m.payload, SubsetMessage)
                and m.payload.proposer == msg.sender
                and isinstance(m.payload.payload, BroadcastMessage)
                and m.payload.payload.kind == "value"
            ):
                proof = m.payload.payload.payload
                # Flip bytes in the shard: Merkle proof stays self-consistent?
                # No - produce a *valid-looking* but wrong value by reusing the
                # proof of garbage content via a fresh broadcast. Simplest:
                # leave proof alone but truncate... just corrupt the value.
                from hbbft_tpu.crypto.merkle import MerkleTree

                n = net.nodes[msg.sender].algorithm.netinfo.num_nodes()
                shards = [b"garbage!" for _ in range(n)]
                tree = MerkleTree(shards)
                idx = proof.index
                new_msg = HbMessage.subset(
                    m.epoch,
                    SubsetMessage(
                        m.payload.proposer,
                        "broadcast",
                        BroadcastMessage.value(tree.proof(idx)),
                    ),
                )
                return [type(msg)(msg.sender, msg.to, new_msg)]
            return [msg]

    net = build(4, 1, adversary=GarbageProposal(), seed=2)
    for i in sorted(net.nodes):
        net.send_input(i, ("c", i))
    net.crank_until(
        lambda net: all(len(n.outputs) >= 1 for n in net.correct_nodes())
    )
    ref = None
    for node in net.correct_nodes():
        b = node.outputs[0]
        if ref is None:
            ref = b
        assert b == ref
