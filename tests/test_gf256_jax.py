"""Golden tests: JAX GF(2⁸) bit-matmul codec vs the numpy host codec."""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto.erasure import RSCodec, gf256
from hbbft_tpu.ops.gf256 import JaxRSCodec, expand_gf_matrix, gf256_matmul

import jax.numpy as jnp


def test_bit_matmul_matches_table_matmul():
    rng = np.random.default_rng(0)
    gf = gf256()
    for r, k, L in [(2, 3, 5), (4, 4, 16), (7, 11, 33)]:
        m = rng.integers(0, 256, size=(r, k), dtype=np.uint8)
        x = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
        want = gf.matmul(m, x)
        got = np.asarray(gf256_matmul(jnp.asarray(expand_gf_matrix(m)), jnp.asarray(x)))
        np.testing.assert_array_equal(got, want)


def test_jax_codec_roundtrip_matches_host_codec():
    rng = random.Random(1)
    for k, m in [(2, 2), (3, 2), (4, 4), (10, 4)]:
        host = RSCodec(k, m)
        dev = JaxRSCodec(k, m)
        data = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 200)))
        hs = host.encode(data)
        ds = dev.encode(data)
        assert hs == ds
        # erase up to m shards, reconstruct on device
        n = k + m
        erased = list(ds)
        for idx in rng.sample(range(n), m):
            erased[idx] = None
        rec = dev.reconstruct(erased)
        assert rec == hs
        assert dev.decode_data(erased, len(data)) == data


def test_jax_codec_interoperates_with_host_shards():
    host = RSCodec(5, 3)
    dev = JaxRSCodec(5, 3)
    data = bytes(range(97))
    shards = host.encode(data)
    erased = [None, shards[1], shards[2], None, shards[4], shards[5], None, shards[7]]
    assert dev.decode_data(erased, len(data)) == data
