"""Golden tests: JAX GF(2⁸) bit-matmul codec vs the numpy host codec."""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto.erasure import RSCodec, gf256
from hbbft_tpu.ops.gf256 import JaxRSCodec, expand_gf_matrix, gf256_matmul

import jax.numpy as jnp


def test_bit_matmul_matches_table_matmul():
    rng = np.random.default_rng(0)
    gf = gf256()
    for r, k, L in [(2, 3, 5), (4, 4, 16), (7, 11, 33)]:
        m = rng.integers(0, 256, size=(r, k), dtype=np.uint8)
        x = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
        want = gf.matmul(m, x)
        got = np.asarray(gf256_matmul(jnp.asarray(expand_gf_matrix(m)), jnp.asarray(x)))
        np.testing.assert_array_equal(got, want)


def test_jax_codec_roundtrip_matches_host_codec():
    rng = random.Random(1)
    for k, m in [(2, 2), (3, 2), (4, 4), (10, 4)]:
        host = RSCodec(k, m)
        dev = JaxRSCodec(k, m)
        data = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 200)))
        hs = host.encode(data)
        ds = dev.encode(data)
        assert hs == ds
        # erase up to m shards, reconstruct on device
        n = k + m
        erased = list(ds)
        for idx in rng.sample(range(n), m):
            erased[idx] = None
        rec = dev.reconstruct(erased)
        assert rec == hs
        assert dev.decode_data(erased, len(data)) == data


def test_jax_codec_interoperates_with_host_shards():
    host = RSCodec(5, 3)
    dev = JaxRSCodec(5, 3)
    data = bytes(range(97))
    shards = host.encode(data)
    erased = [None, shards[1], shards[2], None, shards[4], shards[5], None, shards[7]]
    assert dev.decode_data(erased, len(data)) == data


def test_gf256_matmul_bf16_mode_matches():
    """The bf16-MXU dot strategy must be bit-identical to the int8 path
    (bits are bf16-exact; 8k-term sums ≪ 2^24 accumulate exactly), and
    the flag must actually select a bf16 dot (HLO sentinel guards
    against the branch silently regressing to int8)."""
    import subprocess
    import sys
    import os as _os

    code = """
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
from hbbft_tpu.ops.gf256 import JaxRSCodec, gf256_matmul
from hbbft_tpu.crypto.erasure import RSCodec
rng = np.random.default_rng(7)
dev = JaxRSCodec(10, 6)
host = RSCodec(10, 6)
mat = rng.integers(0, 256, size=(10, 257), dtype=np.uint8)
got = np.asarray(dev._parity(jnp.asarray(mat)))
assert np.array_equal(got, host._parity(mat)), "bf16 parity mismatch"
# sentinel: the traced computation must contain a bf16 dot, not int8
hlo = jax.jit(gf256_matmul.__wrapped__).lower(
    dev._encode_bits, jnp.asarray(mat)
).as_text()
assert "bf16" in hlo and "dot" in hlo, "bf16 branch not engaged"
print("BF16_OK")
"""
    env = dict(_os.environ)
    env["HBBFT_TPU_GF_DOT"] = "bf16"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
    )
    assert "BF16_OK" in proc.stdout, proc.stdout + proc.stderr
