"""Golden tests: JAX Jacobian G1/G2 ops vs the pure-Python bls381 reference."""

import random

import numpy as np
import pytest

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import R
from hbbft_tpu.ops import curve


@pytest.fixture(scope="module")
def rng():
    return random.Random(7)


def rnd_g1(rng):
    return gold.ec_mul(gold.FQ, rng.randrange(1, R), gold.G1_GEN)


def rnd_g2(rng):
    return gold.ec_mul(gold.FQ2, rng.randrange(1, R), gold.G2_GEN)


def test_g1_roundtrip(rng):
    pts = [rnd_g1(rng) for _ in range(4)] + [None]
    dev = curve.g1_to_device(pts)
    assert curve.g1_from_device(dev) == pts


def test_g2_roundtrip(rng):
    pts = [rnd_g2(rng) for _ in range(3)] + [None]
    dev = curve.g2_to_device(pts)
    assert curve.g2_from_device(dev) == pts


def test_g1_double_add(rng):
    pts = [rnd_g1(rng) for _ in range(6)]
    others = [rnd_g1(rng) for _ in range(6)]
    P = curve.g1_to_device(pts)
    Qp = curve.g1_to_device(others)
    got_d = curve.g1_from_device(curve.jac_double(curve._F1, P))
    assert got_d == [gold.ec_double(gold.FQ, p) for p in pts]
    got_a = curve.g1_from_device(curve.jac_add(curve._F1, P, Qp))
    assert got_a == [gold.ec_add(gold.FQ, p, q) for p, q in zip(pts, others)]


def test_g1_add_infinity(rng):
    p = rnd_g1(rng)
    P = curve.g1_to_device([p, None, None])
    Qp = curve.g1_to_device([None, p, None])
    got = curve.g1_from_device(curve.jac_add(curve._F1, P, Qp))
    assert got == [p, p, None]


def test_g2_double_add(rng):
    pts = [rnd_g2(rng) for _ in range(4)]
    others = [rnd_g2(rng) for _ in range(4)]
    P = curve.g2_to_device(pts)
    Qp = curve.g2_to_device(others)
    got_d = curve.g2_from_device(curve.jac_double(curve._F2, P))
    assert got_d == [gold.ec_double(gold.FQ2, p) for p in pts]
    got_a = curve.g2_from_device(curve.jac_add(curve._F2, P, Qp))
    assert got_a == [gold.ec_add(gold.FQ2, p, q) for p, q in zip(pts, others)]


def test_safe_scalar(rng):
    for s in [0, 1, 2, R - 1, R - 2, (R - 1) // 2, (R + 1) // 2] + [
        rng.randrange(R) for _ in range(50)
    ]:
        s2, negate = curve.safe_scalar(s)
        assert s2 < (1 << curve.SCALAR_BITS)
        assert (R - s2 if negate else s2) % R == s % R


def test_g1_scalar_mul(rng):
    pts = [rnd_g1(rng) for _ in range(4)]
    raw = [rng.randrange(R) for _ in range(3)] + [1]
    safe = [curve.safe_scalar(s) for s in raw]
    bits = curve.scalars_to_bits([s for s, _ in safe])
    P = curve.g1_to_device(pts)
    prod = curve.g1_scalar_mul_batch(P, bits)
    prod = curve.jac_select(
        curve._F1,
        np.array([neg for _, neg in safe]),
        curve.jac_neg(curve._F1, prod),
        prod,
    )
    got = curve.g1_from_device(prod)
    assert got == [gold.ec_mul(gold.FQ, s, p) for s, p in zip(raw, pts)]


def test_g2_scalar_mul(rng):
    pts = [rnd_g2(rng) for _ in range(2)]
    raw = [rng.randrange(R) for _ in range(2)]
    safe = [curve.safe_scalar(s) for s in raw]
    bits = curve.scalars_to_bits([s for s, _ in safe])
    P = curve.g2_to_device(pts)
    prod = curve.g2_scalar_mul_batch(P, bits)
    prod = curve.jac_select(
        curve._F2,
        np.array([neg for _, neg in safe]),
        curve.jac_neg(curve._F2, prod),
        prod,
    )
    got = curve.g2_from_device(prod)
    assert got == [gold.ec_mul(gold.FQ2, s, p) for s, p in zip(raw, pts)]


def test_linear_combine_g1_matches_lagrange(rng):
    """Σ λ_i·P_i on device == golden g1_lagrange_combine."""
    group = gold.BLS381Group()
    secret = rng.randrange(R)
    from hbbft_tpu.crypto.field import lagrange_coeffs_at_zero

    # Shamir-style: P_i = f(i+1)·G, reconstruct f(0)·G.
    coeffs = [secret] + [rng.randrange(R) for _ in range(2)]

    def f(x):
        return sum(c * x**k for k, c in enumerate(coeffs)) % R

    xs = [1, 2, 4, 5]
    pts = [gold.ec_mul(gold.FQ, f(x), gold.G1_GEN) for x in xs]
    lam = lagrange_coeffs_at_zero(xs)
    safe = [curve.safe_scalar(l) for l in lam]
    bits = curve.scalars_to_bits([s for s, _ in safe])
    negs = np.array([n for _, n in safe])
    combined = curve.linear_combine_g1(curve.g1_to_device(pts), bits, negs)
    got = curve.g1_from_device(combined)[0]
    want = gold.ec_mul(gold.FQ, secret, gold.G1_GEN)
    assert got == want


def test_linear_combine_g2(rng):
    pts = [rnd_g2(rng) for _ in range(3)]
    lam = [rng.randrange(R) for _ in range(3)]
    safe = [curve.safe_scalar(l) for l in lam]
    bits = curve.scalars_to_bits([s for s, _ in safe])
    negs = np.array([n for _, n in safe])
    combined = curve.linear_combine_g2(curve.g2_to_device(pts), bits, negs)
    got = curve.g2_from_device(combined)[0]
    want = None
    for l, p in zip(lam, pts):
        want = gold.ec_add(gold.FQ2, want, gold.ec_mul(gold.FQ2, l, p))
    assert got == want


def test_glv_gls_decomposition_properties():
    """≥50k random scalars per group: the Babai decompositions respect
    their magnitude bounds and reconstruct k exactly.

    G1: k ≡ ±k1 ± λ·k2 (mod r) with |k1|,|k2| ≤ 2^127 (the bound the
    GLV_HALF_BITS=128 window packing relies on).
    G2: k ≡ Σ ±k_j·u^j (mod r) with |k_j| < 2^63 (GLS_QUARTER_BITS=64).
    Edge scalars (0, 1, r−1, λ, r−λ, u mod r, crafted degenerate forms)
    ride along with the random sample."""
    rng = random.Random(31)
    lam = curve._G1_LAM
    mu = curve._G2_U % R
    edges = [0, 1, 2, R - 1, R - 2, lam, R - lam, mu, R - mu,
             (5 + 5 * lam) % R, (R - 7 - lam * (lam + 1)) % R]
    for k in edges + [rng.randrange(R) for _ in range(50_000)]:
        (a, na), (b, nb) = curve.glv_decompose_g1(k)
        assert a <= 1 << 127 and b <= 1 << 127
        sa = -a if na else a
        sb = -b if nb else b
        assert (sa + lam * sb - k) % R == 0
        quads = curve.gls_decompose_g2(k)
        assert all(q < 1 << 63 for q, _ in quads)
        total = sum(
            (-q if n else q) * pow(mu, j, R) for j, (q, n) in enumerate(quads)
        )
        assert (total - k) % R == 0


def test_g1_glv_ladder_matches_host(rng):
    """Joint-table GLV ladder vs the golden reference at the group level,
    including the λ-sized and zero edge scalars.  Jitted: one compiled
    graph instead of minutes of eager op dispatch on XLA:CPU."""
    import jax

    ks = [rng.randrange(R), curve._G1_LAM, 0]
    pts = [rnd_g1(rng) for _ in range(len(ks))]
    bits, negs = curve.prep_g1_scalars(ks)
    assert bits.shape == (len(ks), 2, curve.GLV_HALF_BITS)
    got = curve.g1_from_device(
        jax.jit(curve.g1_scalar_mul_signed)(curve.g1_to_device(pts), bits, negs)
    )
    want = [gold.ec_mul(gold.FQ, k, p) if k % R else None for k, p in zip(ks, pts)]
    assert got == want


def test_g2_gls_ladder_matches_host(rng):
    import jax

    ks = [rng.randrange(R), (curve._G2_U) % R]
    pts = [rnd_g2(rng) for _ in range(len(ks))]
    bits, negs = curve.prep_g2_scalars(ks)
    assert bits.shape == (len(ks), 4, curve.GLS_QUARTER_BITS)
    got = curve.g2_from_device(
        jax.jit(curve.g2_scalar_mul_signed)(curve.g2_to_device(pts), bits, negs)
    )
    want = [gold.ec_mul(gold.FQ2, k, p) for k, p in zip(ks, pts)]
    assert got == want


def test_ladder_field_mul_accounting():
    """The analytic per-lane costs behind the ladder_field_muls counter:
    the GLV G1 scan is the predicted 2368 vs the w2 baseline 3810 (the
    ≥1.5× acceptance number), GLS G2 is 1920, and the RLC-width w2 form
    scales with width."""
    g1_bits, _ = curve.prep_g1_scalars([5])
    g2_bits, _ = curve.prep_g2_scalars([5])
    assert curve.ladder_scan_field_muls(g1_bits, True) == 2368
    assert curve.ladder_scan_field_muls(g2_bits, True) == 1920
    w2 = np.zeros((1, curve.SCALAR_BITS), dtype=np.int32)
    assert curve.ladder_scan_field_muls(w2, False) == 3810
    rlc = np.zeros((1, 4, 64), dtype=np.int32)
    assert curve.ladder_scan_field_muls(rlc, False) == 32 * 30
    assert 3810 / 2368 > 1.6
    assert curve.glv_table_field_muls(g1_bits) > 0
    assert curve.glv_table_field_muls(g2_bits) > 0


def test_windowed_and_binary_ladders_agree(monkeypatch):
    """The 2-bit windowed ladder (default for even widths) and the binary
    scan form (HBBFT_TPU_LADDER_BINARY=1) must produce identical points;
    both golden-checked against the host reference."""
    import random

    import jax
    import jax.numpy as jnp

    from hbbft_tpu.crypto import bls381 as gold
    from hbbft_tpu.ops import curve

    rng = random.Random(41)
    width = 16  # small width: cheap XLA:CPU compile, still even → windowed
    scalars = [rng.randrange(1, 1 << width) for _ in range(3)] + [0]
    bits = jnp.asarray(curve.scalars_to_bits(scalars, width))
    P = curve.g1_to_device([gold.G1_GEN] * len(scalars))

    # an ambient flag would alias the two paths (both binary)
    monkeypatch.delenv("HBBFT_TPU_LADDER_BINARY", raising=False)
    windowed = curve.g1_from_device(jax.jit(curve.g1_scalar_mul_batch)(P, bits))
    monkeypatch.setenv("HBBFT_TPU_LADDER_BINARY", "1")
    binary = curve.g1_from_device(jax.jit(curve.g1_scalar_mul_batch)(P, bits))

    want = [
        gold.ec_mul(gold.FQ, s, gold.G1_GEN) if s else None for s in scalars
    ]
    assert windowed == want
    assert binary == want
