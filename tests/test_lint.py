"""Protocol-invariant linter: tier-1 gate + per-rule seeded violations.

The gate test runs the full rule suite over ``hbbft_tpu/`` exactly as
``tools/lint.py`` does and fails on any finding beyond the checked-in
baseline — so a PR that introduces nondeterministic iteration, an
unhandled wire variant, a raising handler, or a host sync in jitted code
breaks tier-1.

Each rule family also gets unit tests proving it (a) catches a seeded
violation and (b) honours ``# lint: allow[rule] reason`` suppressions.
"""

import textwrap
from pathlib import Path

from hbbft_tpu.analysis.engine import (
    Baseline,
    Finding,
    LintProject,
    ModuleSource,
    all_rules,
    iter_python_files,
    run_lint,
)
from hbbft_tpu.analysis.rules_byzantine import ByzantineInputRule
from hbbft_tpu.analysis.rules_determinism import DeterminismRule
from hbbft_tpu.analysis.rules_exhaustiveness import WIRE_PATH, HandlerExhaustivenessRule
from hbbft_tpu.analysis.rules_seam import SeamRaceRule, seam_contexts_for_testing
from hbbft_tpu.analysis.rules_tracer import TracerSafetyRule

REPO_ROOT = Path(__file__).resolve().parent.parent
BASELINE_PATH = REPO_ROOT / "tools" / "lint_baseline.json"


def lint_sources(rule, sources):
    """Run one rule over {path: source} with suppression filtering."""
    modules = {p: ModuleSource(p, textwrap.dedent(src)) for p, src in sources.items()}
    project = LintProject(REPO_ROOT, modules)
    out = []
    for f in rule.check_project(project):
        mod = project.module(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            continue
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# Tier-1 gate
# ---------------------------------------------------------------------------


def test_package_lint_clean():
    """Full run over hbbft_tpu/: zero findings beyond the baseline."""
    findings = run_lint(REPO_ROOT, iter_python_files(REPO_ROOT / "hbbft_tpu"))
    new = Baseline.load(BASELINE_PATH).new_findings(findings)
    assert not new, "new lint findings:\n" + "\n".join(f.render() for f in new)


def test_lint_output_deterministic():
    paths = iter_python_files(REPO_ROOT / "hbbft_tpu")
    a = run_lint(REPO_ROOT, paths)
    b = run_lint(REPO_ROOT, list(reversed(paths)))
    assert a == b
    assert a == sorted(a, key=Finding.sort_key)


# ---------------------------------------------------------------------------
# Rule family 1: determinism
# ---------------------------------------------------------------------------

DET_PATH = "hbbft_tpu/protocols/_seeded.py"


def test_determinism_catches_violations():
    findings = lint_sources(
        DeterminismRule(),
        {
            DET_PATH: """\
            import time
            import os

            def emit(self):
                now = time.time()
                salt = os.urandom(8)
                for peer in self.echos.values():
                    self.send(peer)
                order = sorted(self.ids, key=lambda x: id(x))
                return now, salt, order
            """
        },
    )
    msgs = [f.message for f in findings]
    assert any("nondeterministic module 'time'" in m for m in msgs)
    assert any("time.time()" in m for m in msgs)
    assert any("os.urandom" in m for m in msgs)
    assert any(".values()" in m for m in msgs)
    assert any("id()" in m for m in msgs)


def test_determinism_set_iteration_and_safe_sinks():
    findings = lint_sources(
        DeterminismRule(),
        {
            DET_PATH: """\
            class P:
                def __init__(self):
                    self.peers = set()

                def bad(self):
                    return [p for p in self.peers]

                def good(self):
                    total = sum(1 for v in self.counts.values() if v)
                    roots = {r for r in self.readys.values()}
                    ordered = sorted(self.peers)
                    return total, roots, ordered
            """
        },
    )
    assert len(findings) == 1
    assert "set-typed 'self.peers'" in findings[0].message
    assert findings[0].line == 6


def test_determinism_enumerate_leaks_order_through_sinks():
    """enumerate() bakes arrival order into values, so it is flagged even
    when the comprehension builds an unordered container."""
    findings = lint_sources(
        DeterminismRule(),
        {
            DET_PATH: """\
            class P:
                def __init__(self):
                    self.peers = set()

                def bad(self):
                    return {k: i for i, k in enumerate(self.peers)}

                def also_bad(self):
                    for i, v in enumerate(self.m.values()):
                        self.rank[v] = i

                def fine(self):
                    return {k: i for i, k in enumerate(sorted(self.peers))}
            """
        },
    )
    assert len(findings) == 2
    assert all("enumerate over nondeterministic order" in f.message for f in findings)


def test_determinism_respects_suppression():
    src = """\
    class P:
        def count(self):
            n = 0
            for v in self.latest.values():  # lint: allow[determinism] counting commutes
                n += 1
            return n
    """
    assert lint_sources(DeterminismRule(), {DET_PATH: src}) == []
    # The same code without a reason is NOT suppressed.
    bare = src.replace(" counting commutes", "")
    assert len(lint_sources(DeterminismRule(), {DET_PATH: bare})) == 1


def test_determinism_out_of_scope_paths_ignored():
    src = "import time\n"
    assert lint_sources(DeterminismRule(), {"hbbft_tpu/ops/_x.py": src}) == []
    assert len(lint_sources(DeterminismRule(), {"hbbft_tpu/core/_x.py": src})) == 1


def test_determinism_covers_traffic_package():
    """The traffic subsystem carries the seeded-replay contract: wall
    clocks and ambient randomness are banned exactly as in protocols/
    (generators must draw entropy only from the injected rng)."""
    src = """\
    import random

    class Source:
        def arrivals(self, epoch):
            return [random.random() for _ in range(3)]
    """
    findings = lint_sources(
        DeterminismRule(), {"hbbft_tpu/traffic/_seeded.py": src}
    )
    msgs = [f.message for f in findings]
    assert any("nondeterministic module 'random'" in m for m in msgs)
    assert any("random.random()" in m for m in msgs)


# ---------------------------------------------------------------------------
# Rule family 2: handler exhaustiveness
# ---------------------------------------------------------------------------

_FAKE_WIRE = """\
WIRE_VARIANTS = {
    "FooMessage": ("foo", ("ping", "pong")),
}


def _to_tree(msg):
    if isinstance(msg, FooMessage):
        if msg.kind == "ping":
            return ("foo", "ping")
        return ("foo", "pong")
    raise ValueError
"""

_FAKE_HANDLER_TMPL = """\
class Foo:
    def handle_message(self, sender_id, message):
        if message.kind == "ping":
            return self._ping(sender_id)
        {extra}
        return self.fault(sender_id, "unknown")
"""


def _exhaustiveness(handler_src, wire_src=_FAKE_WIRE):
    rule = HandlerExhaustivenessRule()
    rule_handlers = {"FooMessage": ("hbbft_tpu/protocols/_foo.py", "Foo")}
    import hbbft_tpu.analysis.rules_exhaustiveness as rx

    saved = rx.HANDLERS
    rx.HANDLERS = rule_handlers
    try:
        return lint_sources(
            rule,
            {WIRE_PATH: wire_src, "hbbft_tpu/protocols/_foo.py": handler_src},
        )
    finally:
        rx.HANDLERS = saved


def test_exhaustiveness_flags_unhandled_variant():
    findings = _exhaustiveness(_FAKE_HANDLER_TMPL.format(extra="pass"))
    assert any("does not dispatch wire variant FooMessage:'pong'" in f.message for f in findings)


def test_exhaustiveness_flags_orphaned_kind():
    src = _FAKE_HANDLER_TMPL.format(
        extra='if message.kind in ("pong", "zap"):\n            return None'
    )
    findings = _exhaustiveness(src)
    assert any("dispatches FooMessage:'zap'" in f.message for f in findings)
    assert not any("does not dispatch" in f.message for f in findings)


def test_exhaustiveness_clean_handler_passes():
    src = _FAKE_HANDLER_TMPL.format(
        extra='if message.kind == "pong":\n            return None'
    )
    assert _exhaustiveness(src) == []


def test_exhaustiveness_detects_registry_codec_drift():
    wire = _FAKE_WIRE.replace('("ping", "pong")', '("ping", "pong", "ghost")')
    src = _FAKE_HANDLER_TMPL.format(
        extra='if message.kind in ("pong", "ghost"):\n            return None'
    )
    findings = _exhaustiveness(src, wire_src=wire)
    assert any("'ghost'" in f.message and "wire codec" in f.message for f in findings)


def test_exhaustiveness_real_registry_matches_handlers():
    """The real wire registry and protocol handlers agree (redundant with
    the gate test, but pins the rule to its real cross-file inputs)."""
    paths = [REPO_ROOT / WIRE_PATH] + [
        REPO_ROOT / p for p, _ in __import__(
            "hbbft_tpu.analysis.rules_exhaustiveness", fromlist=["HANDLERS"]
        ).HANDLERS.values()
    ]
    findings = run_lint(REPO_ROOT, paths, rules=[HandlerExhaustivenessRule()])
    assert findings == []


# ---------------------------------------------------------------------------
# Rule family 3: byzantine-input discipline
# ---------------------------------------------------------------------------

BYZ_PATH = "hbbft_tpu/protocols/_byz.py"


def test_byzantine_flags_raise_in_handler():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_message(self, sender_id, message):
                    if not isinstance(message, tuple):
                        raise ValueError("bad message")
                    return None
            """
        },
    )
    assert len(findings) == 1
    assert "raises on remote input" in findings[0].message


def test_byzantine_allows_locally_converted_raise():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_part(self, sender_id, part):
                    idx = self.index.get(sender_id)
                    try:
                        if not part:
                            raise ValueError
                    except ValueError:
                        return self.fault(sender_id, "malformed")
                    return None
            """
        },
    )
    assert findings == []


def test_byzantine_flags_write_before_membership_check():
    src = """\
    class P:
        def handle_message(self, sender_id, message):
            self.future.setdefault(message.epoch, []).append((sender_id, message))
            if self.netinfo.node_index(sender_id) is None:
                return self.fault(sender_id, "non_validator")
            return None
    """
    findings = lint_sources(ByzantineInputRule(), {BYZ_PATH: src})
    assert len(findings) == 1
    assert "writes state before checking sender_id membership" in findings[0].message


def test_byzantine_membership_check_first_passes():
    src = """\
    class P:
        def handle_message(self, sender_id, message):
            if self.netinfo.node_index(sender_id) is None:
                return self.fault(sender_id, "non_validator")
            self.future.setdefault(message.epoch, []).append((sender_id, message))
            return None
    """
    assert lint_sources(ByzantineInputRule(), {BYZ_PATH: src}) == []


def test_byzantine_respects_suppression():
    src = """\
    class P:
        def handle_message(self, sender_id, message):
            # lint: allow[byzantine-input] epoch tracker accepts observers by design
            self.peer_epochs[sender_id] = message
            return None
    """
    assert lint_sources(ByzantineInputRule(), {BYZ_PATH: src}) == []


def test_byzantine_self_membership_check_does_not_count():
    """`self.netinfo.is_validator()` checks OUR membership, not the
    sender's — it must not satisfy the membership-before-write contract."""
    src = """\
    class P:
        def handle_message(self, sender_id, message):
            if not self.netinfo.is_validator():
                return None
            self.queue.setdefault(sender_id, []).append(message)
            return None
    """
    findings = lint_sources(ByzantineInputRule(), {BYZ_PATH: src})
    assert len(findings) == 1
    assert "writes state before checking" in findings[0].message


def test_byzantine_handle_input_out_of_scope():
    src = """\
    class P:
        def handle_input(self, input, rng=None):
            raise ValueError("unknown input kind")
    """
    assert lint_sources(ByzantineInputRule(), {BYZ_PATH: src}) == []


TRAFFIC_PATH = "hbbft_tpu/traffic/_seeded.py"


def test_byzantine_traffic_submit_write_before_validate_flagged():
    """Client-facing admission in hbbft_tpu/traffic/: the first self-state
    write must come after a *valid*-named call (every submitted byte is
    attacker-controlled)."""
    src = """\
    class Pool:
        def submit(self, tx):
            self.pending.append(tx)
            if not self._validate(tx):
                return "invalid"
            return "accepted"
    """
    findings = lint_sources(ByzantineInputRule(), {TRAFFIC_PATH: src})
    assert len(findings) == 1
    assert "writes state before validating" in findings[0].message


def test_byzantine_traffic_submit_validate_first_passes():
    src = """\
    class Pool:
        def submit(self, tx):
            if not self._validate(tx):
                self.invalid += 1
                return "invalid"
            self.pending.append(tx)
            return "accepted"
    """
    assert lint_sources(ByzantineInputRule(), {TRAFFIC_PATH: src}) == []


def test_byzantine_traffic_submit_raise_flagged():
    src = """\
    class Pool:
        def submit(self, tx):
            if not self._validate(tx):
                raise ValueError("bad tx")
            return "accepted"
    """
    findings = lint_sources(ByzantineInputRule(), {TRAFFIC_PATH: src})
    assert len(findings) == 1
    assert "raises on client input" in findings[0].message


def test_byzantine_submit_outside_traffic_scope_ignored():
    src = """\
    class Pool:
        def submit(self, tx):
            self.pending.append(tx)
            return "accepted"
    """
    assert lint_sources(ByzantineInputRule(), {BYZ_PATH: src}) == []


# ---------------------------------------------------------------------------
# Rule family 4: JAX tracer safety
# ---------------------------------------------------------------------------

TRACER_PATH = "hbbft_tpu/ops/_seeded.py"


def test_tracer_flags_host_syncs_in_jitted_fn():
    findings = lint_sources(
        TracerSafetyRule(),
        {
            TRACER_PATH: """\
            import jax
            import numpy as np

            @jax.jit
            def kernel(x):
                n = int(x.shape[0])
                y = float(x[0])
                z = x.sum().item()
                h = np.asarray(x)
                return y + z, h, n
            """
        },
    )
    msgs = [f.message for f in findings]
    assert any("float() on a traced value" in m for m in msgs)
    assert any(".item() inside jitted" in m for m in msgs)
    assert any("np.asarray inside jitted" in m for m in msgs)


def test_tracer_factory_idiom_and_loops():
    findings = lint_sources(
        TracerSafetyRule(),
        {
            TRACER_PATH: """\
            import jax

            def f(x):
                return bool(x)

            jitted = jax.jit(f)

            def crank(items):
                out = []
                for x in items:
                    out.append(jax.device_get(x))
                return out
            """
        },
    )
    msgs = [f.message for f in findings]
    assert any("bool() on a traced value" in m for m in msgs)
    assert any("jax.device_get inside a loop" in m for m in msgs)


def test_tracer_unhashable_static_arg():
    findings = lint_sources(
        TracerSafetyRule(),
        {
            TRACER_PATH: """\
            import jax

            def g(x, shape):
                return x

            fast_g = jax.jit(g, static_argnums=(1,))

            def use(x):
                a = fast_g(x, [4, 4])   # unhashable at the jit boundary
                b = g(x, [4, 4])        # plain Python call: legal
                return a, b
            """
        },
    )
    unhashable = [f for f in findings if "unhashable literal" in f.message]
    assert len(unhashable) == 1
    assert "of fast_g" in unhashable[0].message
    assert unhashable[0].line == 9


def test_deferred_fetch_rule_flags_dispatch_layer_syncs():
    """The pipelined-dispatch seam guard: ad-hoc fetches in the dispatch
    layer (ops/backend.py, parallel/backend.py) are flagged; the same
    code outside the scope — e.g. the seam module itself — is not."""
    from hbbft_tpu.analysis.rules_tracer import DeferredFetchRule

    src = """\
    import numpy as np
    import jax

    def bad_fetch(out):
        a = np.asarray(out)
        b = jax.device_get(out)
        out.block_until_ready()
        c = np.array([1, 2, 3])      # host literal staging: fine
        return a, b, c
    """
    findings = lint_sources(
        DeferredFetchRule(), {"hbbft_tpu/ops/backend.py": src}
    )
    msgs = [f.message for f in findings]
    assert len(findings) == 3
    assert any("np.asarray" in m for m in msgs)
    assert any("jax.device_get" in m for m in msgs)
    assert any("block_until_ready" in m for m in msgs)
    assert all("deferred-fetch seam" in m for m in msgs)
    # outside the dispatch-layer scope the identical source is clean
    # (host conversion helpers and the pipeline seam itself live there)
    assert lint_sources(
        DeferredFetchRule(), {"hbbft_tpu/ops/pipeline.py": src}
    ) == []
    assert lint_sources(
        DeferredFetchRule(), {"hbbft_tpu/ops/curve.py": src}
    ) == []


def test_deferred_fetch_real_dispatch_layer_is_clean():
    """The refactored backend itself must satisfy its own seam rule."""
    from hbbft_tpu.analysis.engine import run_lint
    from hbbft_tpu.analysis.rules_tracer import DeferredFetchRule

    findings = [
        f
        for f in run_lint(REPO_ROOT, rules=[DeferredFetchRule()])
        if f.rule == "deferred-fetch"
    ]
    assert findings == [], [f.render() for f in findings]


def test_tracer_clean_and_suppressed():
    clean = """\
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(x):
        return jnp.asarray(x) + 1

    def host(x):
        return float(x)
    """
    assert lint_sources(TracerSafetyRule(), {TRACER_PATH: clean}) == []
    suppressed = """\
    import jax

    @jax.jit
    def kernel(x, n):
        k = int(n)  # lint: allow[tracer-safety] n is a static python int
        return x[:k]
    """
    assert lint_sources(TracerSafetyRule(), {TRACER_PATH: suppressed}) == []


def test_tracer_out_of_scope_protocols_ignored():
    src = """\
    import jax

    @jax.jit
    def kernel(x):
        return float(x)
    """
    assert lint_sources(TracerSafetyRule(), {"hbbft_tpu/protocols/_x.py": src}) == []


# ---------------------------------------------------------------------------
# Engine mechanics: suppressions, baseline, registry
# ---------------------------------------------------------------------------


def test_bare_suppression_is_reported_and_not_honoured():
    src = textwrap.dedent(
        """\
        import time  # lint: allow[determinism]
        """
    )
    mod = ModuleSource("hbbft_tpu/core/_x.py", src)
    assert mod.bare_allows == [(1, "determinism")]
    assert not mod.is_suppressed("determinism", 1)


def test_allow_syntax_in_string_literals_is_ignored():
    """Docstrings/strings *quoting* the allow syntax are not comments:
    no phantom suppressions, no spurious lint-allow findings."""
    src = textwrap.dedent(
        '''\
        """Docs: write `# lint: allow[determinism]` to suppress a line."""
        X = "# lint: allow[determinism] not a real comment"
        import time
        '''
    )
    mod = ModuleSource("hbbft_tpu/core/_x.py", src)
    assert mod.bare_allows == []
    assert mod.allowed == {}
    findings = lint_sources(DeterminismRule(), {"hbbft_tpu/core/_x.py": src})
    assert len(findings) == 1  # the import is still flagged


def test_suppression_on_preceding_comment_line():
    src = textwrap.dedent(
        """\
        # lint: allow[determinism] ordering provably irrelevant here
        import time
        """
    )
    mod = ModuleSource("hbbft_tpu/core/_x.py", src)
    assert mod.is_suppressed("determinism", 2)
    assert not mod.is_suppressed("determinism", 1)


def test_baseline_grandfathers_by_count():
    f1 = Finding("r", "p.py", 3, 0, "msg")
    f2 = Finding("r", "p.py", 9, 0, "msg")
    f3 = Finding("r", "p.py", 12, 0, "other")
    baseline = Baseline.from_findings([f1])
    new = baseline.new_findings([f1, f2, f3])
    # One "msg" absorbed (the earliest), the second plus "other" are new.
    assert new == [f2, f3]


def test_baseline_roundtrip(tmp_path):
    baseline = Baseline.from_findings(
        [Finding("r", "p.py", 3, 0, "msg"), Finding("r", "p.py", 9, 0, "msg")]
    )
    path = tmp_path / "baseline.json"
    baseline.save(path)
    loaded = Baseline.load(path)
    assert loaded.counts == baseline.counts
    assert Baseline.load(tmp_path / "missing.json").counts == {}


def test_all_rules_registered():
    ids = {r.rule_id for r in all_rules()}
    assert ids == {
        "determinism",
        "handler-exhaustiveness",
        "byzantine-input",
        "tracer-safety",
        "deferred-fetch",
        "glv-table-order",
        "seam-race",
        "snapshot-coverage",
        "replay-purity",
        "hook-detachment",
    }


# ---------------------------------------------------------------------------
# Rule family 6: glv-table-order (determinism family, ops/curve.py)
# ---------------------------------------------------------------------------

CURVE_PATH = "hbbft_tpu/ops/curve.py"


def _glv_rule():
    from hbbft_tpu.analysis.rules_determinism import GlvTableOrderRule

    return GlvTableOrderRule()


def test_glv_table_order_catches_non_range_iteration():
    findings = lint_sources(
        _glv_rule(),
        {
            CURVE_PATH: """\
            def _joint_table(F, parts, digit_base):
                entries = {}
                for idx in sorted({1, 5, 3}):
                    entries[idx] = parts[0]
                extra = [p for p in entries.values()]
                return entries
            """
        },
    )
    msgs = [f.message for f in findings]
    assert len(msgs) == 2  # the for loop and the comprehension
    assert all("range(" in m for m in msgs)


def test_glv_table_order_accepts_range_build_and_requires_presence():
    clean = lint_sources(
        _glv_rule(),
        {
            CURVE_PATH: """\
            def _joint_table(F, parts, digit_base):
                entries = [None]
                for idx in range(1, digit_base ** len(parts)):
                    entries.append(parts[0])
                return entries
            """
        },
    )
    assert clean == []
    missing = lint_sources(
        _glv_rule(),
        {CURVE_PATH: "def other():\n    return 1\n"},
    )
    assert len(missing) == 1
    assert "no _joint_table" in missing[0].message


def test_glv_table_order_suppression():
    findings = lint_sources(
        _glv_rule(),
        {
            CURVE_PATH: """\
            def _joint_table(F, parts, digit_base):
                out = []
                # lint: allow[glv-table-order] provably fixed tuple order
                for idx in (1, 2, 3):
                    out.append(parts[0])
                return out
            """
        },
    )
    assert findings == []


def test_glv_table_order_real_module_clean():
    """The real ops/curve.py build satisfies the fixed-order guard."""
    src = (REPO_ROOT / CURVE_PATH).read_text(encoding="utf-8")
    findings = lint_sources(_glv_rule(), {CURVE_PATH: src})
    assert findings == []


# ---------------------------------------------------------------------------
# Fault-kind registry cross-check (handler-exhaustiveness family, PR 7)
# ---------------------------------------------------------------------------

from hbbft_tpu.analysis.rules_exhaustiveness import (  # noqa: E402
    FAULT_LOG_PATH,
    SCENARIOS_PATH,
)

_FAKE_FAULT_LOG = """\
FAULT_KINDS = {
    "broadcast": ("multiple_echos",),
}
"""

_FAKE_BROADCAST = """\
class Broadcast:
    def _handle_echo(self, sender_id, proof):
        return Step.from_fault(sender_id, "broadcast:multiple_echos")
"""


def _fault_kind_lint(sources):
    return lint_sources(HandlerExhaustivenessRule(), sources)


def test_fault_kinds_clean_registry_passes():
    findings = _fault_kind_lint(
        {
            FAULT_LOG_PATH: _FAKE_FAULT_LOG,
            "hbbft_tpu/protocols/broadcast.py": _FAKE_BROADCAST,
        }
    )
    assert findings == []


def test_fault_kinds_flags_unregistered_emission():
    src = _FAKE_BROADCAST + (
        "    def _handle_x(self, sender_id):\n"
        '        return Step.from_fault(sender_id, "broadcast:unheard_of")\n'
    )
    findings = _fault_kind_lint(
        {
            FAULT_LOG_PATH: _FAKE_FAULT_LOG,
            "hbbft_tpu/protocols/broadcast.py": src,
        }
    )
    assert any(
        "'broadcast:unheard_of'" in f.message and "not registered" in f.message
        for f in findings
    )


def test_fault_kinds_flags_registered_but_never_emitted():
    reg = _FAKE_FAULT_LOG.replace(
        '("multiple_echos",)', '("multiple_echos", "ghost_kind")'
    )
    findings = _fault_kind_lint(
        {
            FAULT_LOG_PATH: reg,
            "hbbft_tpu/protocols/broadcast.py": _FAKE_BROADCAST,
        }
    )
    assert any(
        "'broadcast:ghost_kind'" in f.message and "no protocol module" in f.message
        for f in findings
    )


def test_fault_kinds_flags_unregistered_scenario_expectation():
    scen = 'EXPECT = ("broadcast:multiple_echos", "broadcast:imaginary")\n'
    findings = _fault_kind_lint(
        {
            FAULT_LOG_PATH: _FAKE_FAULT_LOG,
            "hbbft_tpu/protocols/broadcast.py": _FAKE_BROADCAST,
            SCENARIOS_PATH: scen,
        }
    )
    assert any(
        "scenario expects unregistered" in f.message
        and "'broadcast:imaginary'" in f.message
        for f in findings
    )


def test_fault_kinds_real_registry_matches_protocols():
    """The checked-in FAULT_KINDS registry, the protocol modules, and the
    scenario harness agree — the same gate test_package_lint_clean
    enforces, pinned to its cross-file inputs."""
    from hbbft_tpu.analysis.rules_exhaustiveness import FAULT_PREFIX_MODULES

    paths = (
        [REPO_ROOT / FAULT_LOG_PATH, REPO_ROOT / SCENARIOS_PATH, REPO_ROOT / WIRE_PATH]
        + [REPO_ROOT / p for p in sorted(FAULT_PREFIX_MODULES.values())]
    )
    findings = run_lint(REPO_ROOT, paths, rules=[HandlerExhaustivenessRule()])
    assert [f for f in findings if "fault" in f.message.lower()] == []


# ---------------------------------------------------------------------------
# Byzantine-input extension: adversary/scenario tamper hooks
# ---------------------------------------------------------------------------

ADV_PATH = "hbbft_tpu/net/adversary.py"


def test_byzantine_flags_raise_in_tamper_hook():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            ADV_PATH: """\
            class BadAdversary:
                def tamper(self, net, msg):
                    if msg.payload is None:
                        raise ValueError("bad payload")
                    return [msg]
            """
        },
    )
    assert any(
        "raises inside an adversary hook" in f.message for f in findings
    )


def test_byzantine_flags_unguarded_payload_dereference():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            ADV_PATH: """\
            class BadAdversary:
                def tamper(self, net, msg):
                    kind = msg.payload.kind
                    return [] if kind == "echo" else [msg]
            """
        },
    )
    assert any(
        "without an isinstance" in f.message for f in findings
    )


def test_byzantine_guarded_tamper_hook_passes():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            ADV_PATH: """\
            class GoodAdversary:
                def tamper(self, net, msg):
                    if not isinstance(msg.payload, EchoMessage):
                        return [msg]
                    return [] if msg.payload.kind == "echo" else [msg]

                def pre_crank(self, net):
                    if net.queue:
                        net.queue.sort(key=len)
            """
        },
    )
    assert findings == []


def test_byzantine_hooks_outside_net_scope_ignored():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            "hbbft_tpu/protocols/_x.py": """\
            class NotAnAdversary:
                def tamper(self, net, msg):
                    raise RuntimeError("protocols modules keep handler rules")
            """
        },
    )
    assert findings == []  # tamper is only a hook name in the net/ scope


def test_determinism_covers_adversary_and_scenarios():
    """The determinism family now guards the attack/schedule harness:
    ambient entropy in net/adversary.py or net/scenarios.py is flagged."""
    rule = DeterminismRule()
    assert any("net/adversary" in s for s in rule.scope)
    findings = lint_sources(
        rule,
        {
            ADV_PATH: """\
            import random

            class Sneaky:
                def tamper(self, net, msg):
                    return [] if random.random() < 0.5 else [msg]
            """
        },
    )
    assert any("nondeterministic module 'random'" in f.message for f in findings)


# ---------------------------------------------------------------------------
# Rule family 7: seam-race (PR 9 — submit/resolve boundary discipline)
# ---------------------------------------------------------------------------

SEAM_PATH = "hbbft_tpu/engine/_seeded.py"


def test_seam_race_flags_submit_write_resolve_read():
    findings = lint_sources(
        SeamRaceRule(),
        {
            SEAM_PATH: """\
            class Engine:
                def __init__(self):
                    self.acc = []

                def _submit_chunk(self, pipe, chunk):
                    self.acc.append(len(chunk))
                    pipe.submit(chunk)

                def _resolve(self, res):
                    return list(self.acc)
            """
        },
    )
    assert len(findings) == 1
    f = findings[0]
    assert "self.acc is written on the submit path" in f.message
    assert "read on the resolve path" in f.message
    assert "Engine._resolve" in f.message


def test_seam_race_flags_submit_read_of_resolve_written_state():
    findings = lint_sources(
        SeamRaceRule(),
        {
            SEAM_PATH: """\
            class Engine:
                def __init__(self):
                    self.last = 0

                def _submit_chunk(self, pipe, chunk):
                    size = self.last + len(chunk)
                    pipe.submit(chunk, items=size)

                def _resolve(self, res):
                    self.last = len(res)
            """
        },
    )
    assert len(findings) == 1
    assert "self.last is read on the submit path" in findings[0].message
    assert "written on the resolve path" in findings[0].message


def test_seam_race_write_once_and_pipeline_api_are_clean():
    findings = lint_sources(
        SeamRaceRule(),
        {
            SEAM_PATH: """\
            class Engine:
                def __init__(self):
                    self.cap = 8

                def _submit_chunk(self, pipe, chunk, out, lo):
                    def deliver(res):
                        out[lo : lo + len(res)] = res

                    pipe.submit(chunk[: self.cap], on_result=deliver)

                def _resolve(self, res):
                    return res[: self.cap]
            """
        },
    )
    # self.cap is read on both sides but never written outside __init__
    # (write-once), and the delivered value rides the on_result plumbing
    assert findings == []


def test_seam_race_same_context_access_is_not_a_crossing():
    findings = lint_sources(
        SeamRaceRule(),
        {
            SEAM_PATH: """\
            class Engine:
                def __init__(self):
                    self.n = 0

                def flush(self, pipe):
                    self.n += 1
                    pipe.submit(self.n)
                    pipe.flush()
            """
        },
    )
    # flush is tagged both submit (it submits) and resolve (its name);
    # a write+read inside ONE function body is sequential, not a seam
    assert findings == []


def test_seam_race_respects_suppression():
    findings = lint_sources(
        SeamRaceRule(),
        {
            SEAM_PATH: """\
            class Engine:
                def __init__(self):
                    self.acc = []

                def _submit_chunk(self, pipe, chunk):
                    # lint: allow[seam-race] sizing-only, never in verdicts
                    self.acc.append(len(chunk))
                    pipe.submit(chunk)

                def _resolve(self, res):
                    return list(self.acc)
            """
        },
    )
    assert findings == []


def test_seam_race_out_of_scope_paths_ignored():
    src = """\
    class Engine:
        def _submit_chunk(self, pipe, chunk):
            self.acc.append(len(chunk))
            pipe.submit(chunk)

        def _resolve(self, res):
            return list(self.acc)
    """
    assert lint_sources(
        SeamRaceRule(), {"hbbft_tpu/protocols/broadcast2.py": src}
    ) == []


def test_seam_race_classifies_resolver_closures():
    """Nested delivery callbacks and returned resolvers are resolve-path
    contexts; the enclosing submit method stays submit-path."""
    src = """\
    class Engine:
        def _submit_batch(self, pipe, items):
            def deliver(res):
                self.done = True

            def finish():
                return pipe.flush()

            pipe.submit(items, on_result=deliver)
            return finish
    """
    mod = ModuleSource(SEAM_PATH, textwrap.dedent(src))
    tags = seam_contexts_for_testing(mod, "Engine")
    assert tags["Engine._submit_batch"] == {"submit"}
    assert "resolve" in tags["Engine._submit_batch.deliver"]
    # finish is RETURNED from a submit-tagged method: a deferred resolver
    assert "resolve" in tags["Engine._submit_batch.finish"]


def test_seam_race_catches_counter_mutant_shape():
    """The seeded ``counter`` mutant (analysis/mutations.py) is exactly
    the source shape this rule exists for: mapped into the rule's scope,
    its submit-path read of resolve-written state is flagged."""
    src = (REPO_ROOT / "hbbft_tpu" / "analysis" / "mutations.py").read_text(
        encoding="utf-8"
    )
    findings = lint_sources(
        SeamRaceRule(), {"hbbft_tpu/ops/backend.py": src}
    )
    assert any("_last_resolved_lo" in f.message for f in findings), [
        f.render() for f in findings
    ]


def test_seam_race_catches_shard_mutant_shape():
    """The seeded ``shard`` mutant (PR 18): the resolution-order scatter
    cursor is a submit-path write read by the per-device delivery
    closures — mapped into scope, the crossing is flagged by name."""
    src = (REPO_ROOT / "hbbft_tpu" / "analysis" / "mutations.py").read_text(
        encoding="utf-8"
    )
    findings = lint_sources(
        SeamRaceRule(), {"hbbft_tpu/ops/backend.py": src}
    )
    assert any("_scatter_cursor" in f.message for f in findings), [
        f.render() for f in findings
    ]


# ---------------------------------------------------------------------------
# byzantine-input: interprocedural upgrade (PR 9 — one call level)
# ---------------------------------------------------------------------------


def test_byzantine_interprocedural_helper_write_flagged():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_message(self, sender_id, payload):
                    self._store(sender_id, payload)
                    return None

                def _store(self, sid, payload):
                    self.states[sid] = payload
            """
        },
    )
    assert len(findings) == 1
    f = findings[0]
    assert "P._store writes state" in f.message
    assert "sid membership" in f.message
    assert "reached from P.handle_message" in f.message


def test_byzantine_interprocedural_helper_check_credits_caller():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_message(self, sender_id, payload):
                    if not self._known(sender_id):
                        return None
                    self.states[sender_id] = payload
                    return None

                def _known(self, sid):
                    return sid in self.validators
            """
        },
    )
    # _known is not a *membership-named* call, but its body performs the
    # check on the forwarded parameter — the handler's own later write is
    # credited through the delegation
    assert findings == []


def test_byzantine_interprocedural_validation_call_credits_caller():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_message(self, sender_id, payload):
                    self._admit(sender_id)
                    self.states[sender_id] = payload
                    return None

                def _admit(self, sid):
                    self._validate_peer(sid)
            """
        },
    )
    assert findings == []


def test_byzantine_interprocedural_skips_remote_handler_helpers():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_message(self, sender_id, payload):
                    self.handle_part(sender_id, payload)
                    return None

                def handle_part(self, sender_id, part):
                    if self.netinfo.is_validator(sender_id):
                        self.parts[sender_id] = part
                    return None
            """
        },
    )
    # handle_part is itself a remote handler: scanned independently (and
    # clean), never re-entered through the delegation pass
    assert findings == []


def test_byzantine_interprocedural_dedups_shared_helper():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_message(self, sender_id, payload):
                    self._store(sender_id, payload)
                    return None

                def handle_part(self, sender_id, part):
                    self._store(sender_id, part)
                    return None

                def _store(self, sid, payload):
                    self.states[sid] = payload
            """
        },
    )
    # two handlers reach the same unguarded helper write: one finding
    # per write site, not one per caller
    assert len(findings) == 1


# ---------------------------------------------------------------------------
# deferred-fetch scope: traffic driver + scenario harness (PR 9)
# ---------------------------------------------------------------------------


def test_deferred_fetch_covers_traffic_and_scenario_hooks():
    from hbbft_tpu.analysis.rules_tracer import DeferredFetchRule

    src = """\
    import numpy as np

    def peek_inflight(out):
        return np.asarray(out)
    """
    rule = DeferredFetchRule()
    for path in ("hbbft_tpu/traffic/driver.py", "hbbft_tpu/net/scenarios.py"):
        assert rule.applies_to(path)
        findings = lint_sources(DeferredFetchRule(), {path: src})
        assert len(findings) == 1, path
        assert "np.asarray" in findings[0].message


# ---------------------------------------------------------------------------
# stale-suppression + baseline pruning (PR 9)
# ---------------------------------------------------------------------------


def _write_module(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src), encoding="utf-8")
    return p


def test_stale_suppression_flags_dead_allow(tmp_path):
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        x = 1  # lint: allow[determinism] nothing here is nondeterministic
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "stale-suppression"
    assert f.line == 1
    assert "allow[determinism]" in f.message
    assert "matches no finding" in f.message


def test_stale_suppression_quiet_for_live_allow(tmp_path):
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        import time  # lint: allow[determinism] fixture: import is justified


        def emit(self):
            now = time.time()  # lint: allow[determinism] fixture: justified
            return now
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert findings == [], [f.render() for f in findings]


def test_stale_suppression_checks_comment_line_binding(tmp_path):
    """A comment-line allow binds to the next source line (skipping the
    rest of the justification comment); fired suppressions are live."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        import time  # lint: allow[determinism] fixture: import is justified


        def emit(self):
            # lint: allow[determinism] fixture: wall clock is justified
            # (a second comment line continues the justification)
            now = time.time()
            return now
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert findings == [], [f.render() for f in findings]


def test_stale_suppression_not_reported_on_subset_runs(tmp_path):
    """A single-rule run cannot tell dead from not-exercised: the stale
    pass only runs with the full rule set."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        x = 1  # lint: allow[tracer-safety] out-of-scope fixture allow
        """,
    )
    findings = run_lint(tmp_path, [p], rules=[DeterminismRule()])
    assert findings == []


def test_baseline_rewrite_prunes_vanished_entries(tmp_path):
    """--baseline prunes grandfathered entries whose findings no longer
    occur and reports the pruned count."""
    import json as _json
    import subprocess
    import sys

    bl = tmp_path / "baseline.json"
    bl.write_text(
        _json.dumps(
            {
                "version": 1,
                "findings": [
                    {
                        "rule": "determinism",
                        "path": "hbbft_tpu/_gone.py",
                        "message": "finding that no longer occurs",
                        "count": 3,
                    }
                ],
            }
        ),
        encoding="utf-8",
    )
    proc = subprocess.run(
        [
            sys.executable,
            "tools/lint.py",
            "--baseline",
            "--baseline-file",
            str(bl),
        ],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "3 pruned" in proc.stdout
    data = _json.loads(bl.read_text(encoding="utf-8"))
    assert all(e["path"] != "hbbft_tpu/_gone.py" for e in data["findings"])


def test_byzantine_interprocedural_write_before_check_in_helper_flagged():
    """The credit is statement-ordered inside the helper too: a write
    that precedes the helper's own membership check is still unguarded
    (refactoring write-then-check into a helper must not pass)."""
    findings = lint_sources(
        ByzantineInputRule(),
        {
            BYZ_PATH: """\
            class P:
                def handle_message(self, sender_id, payload):
                    self._store(sender_id, payload)
                    return None

                def _store(self, sid, payload):
                    self.states[sid] = payload
                    if sid in self.validators:
                        self.seen.add(sid)
            """
        },
    )
    assert len(findings) == 1
    assert "P._store writes state" in findings[0].message


def test_stale_suppression_rule_keyed_against_same_line_allows(tmp_path):
    """A dead allow does not hide behind a DIFFERENT rule's live allow
    on the same target line."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        # lint: allow[tracer-safety] fixture: never fires in this scope
        import time  # lint: allow[determinism] fixture: import justified


        def emit(self):
            now = time.time()  # lint: allow[determinism] fixture: justified
            return now
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert len(findings) == 1
    assert findings[0].rule == "stale-suppression"
    assert findings[0].line == 1
    assert "allow[tracer-safety]" in findings[0].message


def test_stale_suppression_escape_hatch_converges(tmp_path):
    """A deliberately kept dead allow is silenced with
    allow[stale-suppression], and the silencing comment is itself
    counted as live — the escape hatch terminates."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        # lint: allow[stale-suppression] fixture: kept for a pending PR
        x = 1  # lint: allow[determinism] fixture: dead but kept
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert findings == [], [f.render() for f in findings]


def test_comment_allow_binding_stops_at_blank_lines(tmp_path):
    """A comment-only allow binds across continuation COMMENT lines but
    not across a blank line — a dead allow above a blank line must not
    capture (and silently suppress) the next code block."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        # lint: allow[determinism] justification for since-deleted code

        import time
        """,
    )
    findings = run_lint(tmp_path, [p])
    rules = sorted(f.rule for f in findings)
    # the genuine violation IS reported, and the allow is reported stale
    assert "determinism" in rules
    assert "stale-suppression" in rules


def test_stale_suppression_escape_hatch_for_comment_only_allow(tmp_path):
    """The hatch also silences a kept COMMENT-ONLY dead allow: the
    allow[stale-suppression] comment above it binds to the same code
    line, and both comments count as live."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        # lint: allow[stale-suppression] fixture: kept for a pending PR
        # lint: allow[determinism] fixture: dead but deliberately kept
        x = 1
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert findings == [], [f.render() for f in findings]


def test_lone_stale_suppression_allow_is_itself_stale(tmp_path):
    """An allow[stale-suppression] protecting nothing is dead code."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        # lint: allow[stale-suppression] fixture: protects nothing
        x = 1
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert len(findings) == 1
    assert findings[0].rule == "stale-suppression"
    assert "allow[stale-suppression]" in findings[0].message


def test_dataflow_doubly_nested_defs_summarized_once():
    """A grandchild def belongs to its DIRECT parent's summary only —
    double-summarizing would give one closure two contexts with
    different seam tags."""
    from hbbft_tpu.analysis.dataflow import summarize_module

    src = """\
    class C:
        def outer(self):
            def h():
                def g2():
                    return self.x

                return g2

            return h
    """
    mod = ModuleSource(SEAM_PATH, textwrap.dedent(src))
    cls = summarize_module(mod).classes["C"]
    outer = cls.methods["outer"]
    assert set(outer.nested) == {"h"}
    assert set(outer.nested["h"].nested) == {"g2"}


def test_seam_race_positional_submit_closure_stays_submit_path():
    """submit()'s first positional argument is the launch thunk — it
    runs synchronously at submit time, so a named def passed there is
    NOT a resolver (only on_result=/fetch= closures are)."""
    src = """\
    class Engine:
        def _submit_chunk(self, pipe, chunk):
            def launch():
                return self.staged

            pipe.submit(launch)
    """
    mod = ModuleSource(SEAM_PATH, textwrap.dedent(src))
    tags = seam_contexts_for_testing(mod, "Engine")
    assert tags["Engine._submit_chunk.launch"] == {"submit"}


def test_no_wildcard_allow_form(tmp_path):
    """There is deliberately no blanket allow[*]: it would self-suppress
    its own stale-suppression finding, making dead blankets
    undetectable.  The form does not parse as a suppression at all."""
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        import time  # lint: allow[*] not a recognized suppression form
        """,
    )
    findings = run_lint(tmp_path, [p])
    assert any(f.rule == "determinism" for f in findings)
    assert all(f.rule != "stale-suppression" for f in findings)


# ---------------------------------------------------------------------------
# Crash/restart axis scopes (PR 11): net/crash.py rides the byzantine
# hook contract, the fault-kind cross-check, and the seam-race inventory
# ---------------------------------------------------------------------------

CRASH_PATH = "hbbft_tpu/net/crash.py"


def test_byzantine_flags_raise_in_crash_crank_hook():
    """The crash manager's crank hooks carry the adversary-hook
    contract: a recovery failure must become an attributed fault, never
    an exception out of the crank loop."""
    findings = lint_sources(
        ByzantineInputRule(),
        {
            CRASH_PATH: """\
            class BadManager:
                def on_deliver(self, net, msg):
                    if msg.to not in self.tracks:
                        raise KeyError(msg.to)
                def after_crank(self, net):
                    raise RuntimeError("checkpoint failed")
            """
        },
    )
    assert (
        sum("raises inside an adversary hook" in f.message for f in findings)
        == 2
    )


def test_byzantine_crash_hook_with_fault_path_passes():
    findings = lint_sources(
        ByzantineInputRule(),
        {
            CRASH_PATH: """\
            class GoodManager:
                def on_deliver(self, net, msg):
                    t = self.tracks.get(msg.to)
                    if t is not None:
                        t.wal.append(msg)
                def _restart(self, net, nid):
                    try:
                        self._replay(net, nid)
                    except Exception:
                        self._fault(net, nid, "crash:recovery_failed")
            """
        },
    )
    assert findings == []


def test_fault_kinds_crash_namespace_cross_checked():
    """The emitted-kind scan covers non-protocols owner modules: a crash
    kind registered but never emitted by net/crash.py is flagged, and an
    unregistered crash:* emission in net/crash.py is flagged."""
    fake_log = """\
FAULT_KINDS = {
    "broadcast": ("multiple_echos",),
    "crash": ("recovery_failed", "ghost_kind"),
}
"""
    fake_crash = """\
class CrashManager:
    def _restart(self, net, nid):
        self._fault(net, nid, "crash:recovery_failed")
"""
    findings = _fault_kind_lint(
        {
            FAULT_LOG_PATH: fake_log,
            "hbbft_tpu/protocols/broadcast.py": _FAKE_BROADCAST,
            CRASH_PATH: fake_crash,
        }
    )
    assert any(
        "'crash:ghost_kind'" in f.message and "no protocol module" in f.message
        for f in findings
    ), [f.render() for f in findings]
    fake_crash_bad = fake_crash + (
        "    def _crash(self, net, nid):\n"
        '        self._fault(net, nid, "crash:not_registered")\n'
    )
    findings = _fault_kind_lint(
        {
            FAULT_LOG_PATH: fake_log,
            "hbbft_tpu/protocols/broadcast.py": _FAKE_BROADCAST,
            CRASH_PATH: fake_crash_bad,
        }
    )
    assert any(
        "'crash:not_registered'" in f.message and "not registered" in f.message
        for f in findings
    )


def test_seam_race_covers_crash_live_vs_replay_seam():
    """net/crash.py is in the seam-race scope with live-side hooks
    (on_deliver/on_send/_checkpoint) seeding "submit" and the recovery
    side (_restart/_replay) seeding "resolve": state crossing the
    checkpoint→replay boundary is inventoried like pipeline seam state."""
    src = """\
    class Manager:
        def on_deliver(self, net, msg):
            self.wal.append(msg)

        def _restart(self, net, nid):
            for ev in self.wal:
                net.replay(ev)
    """
    findings = lint_sources(
        SeamRaceRule(), {CRASH_PATH: textwrap.dedent(src)}
    )
    assert any("self.wal" in f.message for f in findings), [
        f.render() for f in findings
    ]
    # the blessed form: an allow at the anchor line documents the seam
    suppressed = lint_sources(
        SeamRaceRule(),
        {
            CRASH_PATH: textwrap.dedent(
                """\
                class Manager:
                    def on_deliver(self, net, msg):
                        # lint: allow[seam-race] replay runs between cranks
                        self.wal.append(msg)

                    def _restart(self, net, nid):
                        for ev in self.wal:
                            net.replay(ev)
                """
            )
        },
    )
    assert not any("self.wal" in f.message for f in suppressed)


# ---------------------------------------------------------------------------
# Control-plane scopes (PR 12): hbbft_tpu/control/ rides the determinism
# contract (entropy only from the injected rng, no wall clocks) and the
# seam-race inventory covers the tracker -> controller -> engine-hook
# crossing (traffic/driver.py is submit-seeded via mempool.submit)
# ---------------------------------------------------------------------------


def test_determinism_covers_control_package():
    src = """\
    import time

    class Controller:
        def decide(self, obs):
            return time.monotonic()
    """
    findings = lint_sources(
        DeterminismRule(), {"hbbft_tpu/control/_seeded.py": src}
    )
    msgs = [f.message for f in findings]
    assert any("nondeterministic module 'time'" in m for m in msgs)
    assert any("time.monotonic()" in m for m in msgs)
    assert any("hbbft_tpu/control/" in s for s in DeterminismRule.scope)


def test_seam_race_covers_control_and_traffic_driver():
    assert any("hbbft_tpu/control/" in s for s in SeamRaceRule.scope)
    assert "hbbft_tpu/traffic/driver.py" in SeamRaceRule.scope
    # a submit/resolve crossing under the control scope is flagged like
    # any pipeline seam (nothing in the real package has one — CI pins
    # the zero-finding state)
    findings = lint_sources(
        SeamRaceRule(),
        {
            "hbbft_tpu/control/_seeded.py": """\
            class Controller:
                def __init__(self):
                    self.pending = []

                def _submit_decision(self, hook, b):
                    self.pending.append(b)
                    hook.submit(b)

                def _resolve(self, res):
                    return list(self.pending)
            """
        },
    )
    assert len(findings) == 1
    assert "self.pending" in findings[0].message


# ---------------------------------------------------------------------------
# Rule family 8: snapshot-coverage / replay-purity / hook-detachment (PR 17)
# ---------------------------------------------------------------------------

from hbbft_tpu.analysis.rules_snapshot import (  # noqa: E402
    HookDetachmentRule,
    ReplayPurityRule,
    SnapshotCoverageRule,
    replay_reach_for_testing,
)
from hbbft_tpu.analysis.stateinv import state_module_paths  # noqa: E402

#: real _STATE_MODULES paths — synthetic sources are mapped here so the
#: rules (whose scope is the registry, parsed from utils/snapshot.py on
#: disk) pick them up
STATE_PATH = "hbbft_tpu/net/crash.py"
STATE_PATH2 = "hbbft_tpu/protocols/queueing_honey_badger.py"


def test_state_module_paths_resolve_from_disk():
    """Unit tests lint synthetic module sets: the registry still resolves
    (from the repo's utils/snapshot.py) so scoping works."""
    project = LintProject(REPO_ROOT, {})
    paths = state_module_paths(project)
    assert STATE_PATH in paths
    assert STATE_PATH2 in paths
    assert "hbbft_tpu/net/virtual_net.py" in paths
    assert all(p.endswith(".py") for p in paths)


def test_snapshot_coverage_catches_runtime_callable_write():
    findings = lint_sources(
        SnapshotCoverageRule(),
        {
            STATE_PATH: """\
            class Node:
                def __init__(self):
                    self.seen = 0

                def on_deliver(self, payload):
                    self.notify = lambda: payload
            """
        },
    )
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "snapshot-coverage"
    assert "self.notify" in f.message and "lambda" in f.message
    assert "save_node rejects callables" in f.message


def test_snapshot_coverage_env_declared_callable_is_clean():
    findings = lint_sources(
        SnapshotCoverageRule(),
        {
            STATE_PATH: """\
            class Node:
                notify = None
                _SNAPSHOT_ENV_ATTRS = ("notify",)

                def on_deliver(self, payload):
                    self.notify = lambda: payload
            """
        },
    )
    assert findings == []


def test_snapshot_coverage_flags_dead_env_declaration():
    findings = lint_sources(
        SnapshotCoverageRule(),
        {
            STATE_PATH: """\
            class Node:
                tracer = None
                _SNAPSHOT_ENV_ATTRS = ("tracer", "ghost")

                def crank(self):
                    if self.tracer is not None:
                        self.tracer.span("x")
            """
        },
    )
    assert len(findings) == 1
    assert "ghost" in findings[0].message
    assert "dead declaration" in findings[0].message


def test_snapshot_coverage_flags_env_attr_without_class_default():
    findings = lint_sources(
        SnapshotCoverageRule(),
        {
            STATE_PATH: """\
            class Node:
                _SNAPSHOT_ENV_ATTRS = ("tracer",)

                def __init__(self, tracer):
                    self.tracer = tracer
            """
        },
    )
    assert len(findings) == 1
    assert "no class-body default" in findings[0].message
    assert "AttributeError" in findings[0].message


def test_snapshot_coverage_suppression_honoured():
    findings = lint_sources(
        SnapshotCoverageRule(),
        {
            STATE_PATH: """\
            class Node:
                def on_deliver(self, payload):
                    # lint: allow[snapshot-coverage] fixture: justified
                    self.notify = lambda: payload
            """
        },
    )
    assert findings == []


def test_replay_purity_hook_invocation_flagged_with_chain():
    findings = lint_sources(
        ReplayPurityRule(),
        {
            STATE_PATH: """\
            class Mgr:
                listeners = ()
                _SNAPSHOT_ENV_ATTRS = ("listeners",)

                def _restart(self, wal):
                    for e in wal:
                        self._apply(e)

                def _apply(self, e):
                    for fn in self.listeners:
                        fn(e)
            """
        },
    )
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "replay-purity"
    assert "invokes checkpoint-detached hook self.listeners" in f.message
    assert "Mgr._restart" in f.message and "Mgr._apply" in f.message


def test_replay_purity_guarded_env_read_is_clean_unguarded_flagged():
    src = """\
    class Mgr:
        sink = None
        log = None
        _SNAPSHOT_ENV_ATTRS = ("sink", "log")

        def _restart(self, wal):
            if self.sink is not None:
                size = self.sink
            rows = [self.log]
    """
    findings = lint_sources(ReplayPurityRule(), {STATE_PATH: src})
    assert len(findings) == 1
    assert "self.log" in findings[0].message
    assert "read of checkpoint-detached env attr" in findings[0].message


def test_replay_purity_entropy_and_wallclock_flagged():
    findings = lint_sources(
        ReplayPurityRule(),
        {
            STATE_PATH: """\
            import random
            import time

            class Mgr:
                def _replay(self, wal):
                    jitter = random.random()
                    now = time.monotonic()
            """
        },
    )
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2
    assert "entropy outside the logged rng stream: random.random()" in msgs[0]
    assert "wall-clock read: time.monotonic()" in msgs[1]


def test_replay_purity_propagates_across_modules_by_name():
    """The seed in net/crash.py reaches handler methods in other modules
    (caller→callee by name, like seam-race's tag propagation)."""
    sources = {
        STATE_PATH: """\
        class Mgr:
            def _restart(self, net, node):
                node.algorithm.handle_message(None, ("m",))
        """,
        STATE_PATH2: """\
        class Proto:
            sample_listener = None
            _SNAPSHOT_ENV_ATTRS = ("sample_listener",)

            def handle_message(self, sender, msg):
                self.sample_listener(msg)
        """,
    }
    findings = lint_sources(ReplayPurityRule(), sources)
    assert len(findings) == 1
    f = findings[0]
    assert f.path == STATE_PATH2
    assert "self.sample_listener" in f.message
    assert "Mgr._restart" in f.message  # chain names the seed
    modules = {
        p: ModuleSource(p, textwrap.dedent(s)) for p, s in sources.items()
    }
    reach = replay_reach_for_testing(LintProject(REPO_ROOT, modules))
    assert f"{STATE_PATH2}:Proto.handle_message" in reach


def test_replay_purity_only_seeds_in_crash_module():
    """``_replay_term`` in binary_agreement (a protocol-internal cache
    replay) must NOT seed: seeds live in net/crash.py only."""
    findings = lint_sources(
        ReplayPurityRule(),
        {
            "hbbft_tpu/protocols/binary_agreement.py": """\
            class BA:
                probe = None
                _SNAPSHOT_ENV_ATTRS = ("probe",)

                def _replay_term(self, b):
                    self.probe(b)
            """
        },
    )
    assert findings == []


def test_replay_purity_suppression_honoured():
    findings = lint_sources(
        ReplayPurityRule(),
        {
            STATE_PATH: """\
            class Mgr:
                listeners = ()
                _SNAPSHOT_ENV_ATTRS = ("listeners",)

                def _restart(self, wal):
                    # lint: allow[replay-purity] fixture: justified
                    for fn in self.listeners:
                        fn(wal)
            """
        },
    )
    assert findings == []


def test_hook_detachment_flags_param_assigned_invoked_attr():
    findings = lint_sources(
        HookDetachmentRule(),
        {
            STATE_PATH: """\
            class Node:
                def __init__(self, on_commit):
                    self.on_commit = on_commit

                def commit(self, batch):
                    self.on_commit(batch)
            """
        },
    )
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "hook-detachment"
    assert "self.on_commit" in f.message
    assert "parameter on_commit" in f.message


def test_hook_detachment_env_declared_or_uncalled_is_clean():
    findings = lint_sources(
        HookDetachmentRule(),
        {
            STATE_PATH: """\
            class Node:
                on_commit = None
                _SNAPSHOT_ENV_ATTRS = ("on_commit",)

                def __init__(self, on_commit, doc):
                    self.on_commit = on_commit
                    self.doc = doc  # param-assigned but never invoked

                def commit(self, batch):
                    if self.on_commit is not None:
                        self.on_commit(batch)
            """
        },
    )
    assert findings == []


def test_hook_detachment_suppression_honoured():
    findings = lint_sources(
        HookDetachmentRule(),
        {
            STATE_PATH: """\
            class Node:
                def __init__(self, on_commit):
                    # lint: allow[hook-detachment] fixture: justified
                    self.on_commit = on_commit

                def commit(self, batch):
                    self.on_commit(batch)
            """
        },
    )
    assert findings == []


# -- the three seeded snapshot mutants (analysis/mutations.py) -------------


def _mutations_source():
    return (REPO_ROOT / "hbbft_tpu" / "analysis" / "mutations.py").read_text(
        encoding="utf-8"
    )


def test_snapshot_mutant_coverage_caught_minimal():
    """Mutant 1: the undeclared runtime callable is caught with exactly
    one finding naming the attr, the class, and the writing method."""
    findings = lint_sources(
        SnapshotCoverageRule(), {STATE_PATH: _mutations_source()}
    )
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "snapshot-coverage"
    assert "self._notify" in f.message
    assert "UndeclaredCallableStateNode" in f.message
    assert "on_deliver" in f.message
    assert "lambda" in f.message


def test_snapshot_mutant_replay_hook_and_read_caught_minimal():
    """Mutants 2+3: the replay-path hook invocation and the unguarded
    env read are each caught with exactly one finding, chains intact."""
    findings = lint_sources(
        ReplayPurityRule(), {STATE_PATH: _mutations_source()}
    )
    assert len(findings) == 2
    hook = [f for f in findings if "batch_listeners" in f.message]
    read = [f for f in findings if "metrics_log" in f.message]
    assert len(hook) == 1 and len(read) == 1
    assert "invokes checkpoint-detached hook" in hook[0].message
    assert "ReplayHookNode._replay" in hook[0].message  # chain to seed
    assert "read of checkpoint-detached env attr" in read[0].message
    assert "ReplayEnvReadNode._restart" in read[0].message


def test_snapshot_mutants_out_of_scope_at_real_path():
    """At its real path (hbbft_tpu/analysis/) the mutants module is out
    of every snapshot-rule scope: the package gate stays clean."""
    src = _mutations_source()
    real = "hbbft_tpu/analysis/mutations.py"
    for rule in (SnapshotCoverageRule(), ReplayPurityRule(), HookDetachmentRule()):
        assert lint_sources(rule, {real: src}) == []


# -- stale-suppression coverage for the new families (satellite 6) ---------


def test_stale_suppression_covers_snapshot_family(tmp_path):
    """A dead allow[snapshot-coverage] / allow[replay-purity] is flagged
    stale; a live one is not."""
    _write_module(
        tmp_path,
        "hbbft_tpu/utils/snapshot.py",
        """\
        _STATE_MODULES = ("hbbft_tpu.protocols.x",)
        """,
    )
    p = _write_module(
        tmp_path,
        "hbbft_tpu/protocols/x.py",
        """\
        class Node:
            def on_deliver(self, payload):
                # lint: allow[snapshot-coverage] fixture: justified live
                self.notify = lambda: payload
                x = 1  # lint: allow[replay-purity] fixture: dead allow
        """,
    )
    reg = tmp_path / "hbbft_tpu" / "utils" / "snapshot.py"
    findings = run_lint(tmp_path, [p, reg])
    assert [f.rule for f in findings] == ["stale-suppression"]
    assert "allow[replay-purity]" in findings[0].message


# -- seam-race scope: the mesh backend seam (satellite 1) ------------------


def test_seam_race_covers_parallel():
    assert "hbbft_tpu/parallel/" in SeamRaceRule.scope
    findings = lint_sources(
        SeamRaceRule(),
        {
            "hbbft_tpu/parallel/_seeded.py": """\
            class MeshBackend:
                def __init__(self):
                    self.pending = []

                def _submit_shard(self, pipe, items):
                    self.pending.append(items)
                    pipe.submit(items)

                def _resolve_shard(self, res):
                    return list(self.pending)
            """
        },
    )
    assert len(findings) == 1
    assert "self.pending" in findings[0].message


# -- tools/lint.py --json (satellite 2) ------------------------------------


def test_lint_json_output_schema_pinned(tmp_path):
    import json as _json
    import subprocess as _sp
    import sys as _sys

    out = tmp_path / "findings.json"
    proc = _sp.run(
        [_sys.executable, "tools/lint.py", "--json", str(out)],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = _json.loads(out.read_text(encoding="utf-8"))
    assert doc["schema"] == "hbbft-tpu-lint/1"
    assert doc["new"] == []  # the tree is clean: the gate pins this
    assert isinstance(doc["grandfathered"], int)
    # the human summary stays on stdout when --json targets a file
    assert "lint: 0 new finding(s)" in proc.stdout


def test_lint_json_stable_sort_and_stdout_mode(tmp_path):
    """--json - puts the document on stdout (summary to stderr) and the
    findings list rides Finding.sort_key order."""
    import json as _json

    from tools.lint import findings_document

    f1 = Finding("replay-purity", "b.py", 9, 0, "zzz")
    f2 = Finding("snapshot-coverage", "a.py", 2, 1, "aaa")
    f3 = Finding("snapshot-coverage", "a.py", 1, 5, "mmm")
    doc = findings_document([f1, f2, f3], grandfathered=0)
    assert [(e["path"], e["line"]) for e in doc["new"]] == [
        ("a.py", 1), ("a.py", 2), ("b.py", 9)
    ]
    _json.dumps(doc)  # serializable


# ---------------------------------------------------------------------------
# fused tower chain scopes (PR 20): ops/tower_fused.py + ops/pairing_chain.py
# ---------------------------------------------------------------------------


def test_deferred_fetch_covers_fused_tower_modules():
    """The fused kernels/orchestration run INSIDE backend dispatch graphs;
    a host fetch there stalls every fused_chain/rlc dispatch mid-trace."""
    from hbbft_tpu.analysis.rules_tracer import DeferredFetchRule

    src = """\
    import numpy as np

    def peek_carry(rows):
        return np.asarray(rows)
    """
    rule = DeferredFetchRule()
    for path in (
        "hbbft_tpu/ops/tower_fused.py",
        "hbbft_tpu/ops/pairing_chain.py",
    ):
        assert rule.applies_to(path)
        findings = lint_sources(DeferredFetchRule(), {path: src})
        assert len(findings) == 1, path
        assert "np.asarray" in findings[0].message


def test_seam_race_covers_fused_tower_modules():
    """Scope registration plus a seeded violation: module-level routing
    state shared between a submit-side helper and a delivery callback is
    exactly the crossing the rule inventories."""
    assert "hbbft_tpu/ops/tower_fused.py" in SeamRaceRule.scope
    assert "hbbft_tpu/ops/pairing_chain.py" in SeamRaceRule.scope
    findings = lint_sources(
        SeamRaceRule(),
        {
            "hbbft_tpu/ops/pairing_chain.py": """\
            class ChainRouter:
                def __init__(self):
                    self.mode_latch = None

                def _submit_chain(self, pipe, items):
                    self.mode_latch = "native"
                    pipe.submit(items)

                def _resolve_chain(self, res):
                    return self.mode_latch
            """
        },
    )
    assert len(findings) == 1
    assert "self.mode_latch" in findings[0].message


def test_tracer_safety_covers_fused_tower_modules():
    """ops/ is already in TracerSafetyRule scope as a directory — pin that
    the new modules resolve under it (a scope refactor that enumerates
    files must not drop them)."""
    rule = TracerSafetyRule()
    assert rule.applies_to("hbbft_tpu/ops/tower_fused.py")
    assert rule.applies_to("hbbft_tpu/ops/pairing_chain.py")
