"""Golden tests: batched JAX pairing vs the pure-Python bls381 reference.

The pure-Python pairing takes seconds per evaluation, so the suite uses a
small number of carefully chosen cases: exact value match, bilinearity
through the device path, the product-check identity used by verification,
and infinity handling.
"""

import random

import pytest

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import R
from hbbft_tpu.ops import pairing, tower


@pytest.fixture(scope="module")
def rng():
    return random.Random(42)


def test_pairing_matches_golden(rng):
    a = rng.randrange(1, R)
    b = rng.randrange(1, R)
    P1 = gold.G1_GEN
    P2 = gold.ec_mul(gold.FQ, a, gold.G1_GEN)
    Q1 = gold.G2_GEN
    Q2 = gold.ec_mul(gold.FQ2, b, gold.G2_GEN)

    Pd = pairing.g1_affine_to_device([P1, P2])
    Qd = pairing.g2_affine_to_device([Q1, Q2])
    f = pairing.pairing(Pd, Qd)

    assert tower.fq12_to_ints(f, 0) == gold.pairing(P1, Q1)
    assert tower.fq12_to_ints(f, 1) == gold.pairing(P2, Q2)


def test_bilinearity_product_check(rng):
    # e(aP, Q) · e(-P, aQ) == 1
    a = rng.randrange(1, R)
    aP = gold.ec_mul(gold.FQ, a, gold.G1_GEN)
    aQ = gold.ec_mul(gold.FQ2, a, gold.G2_GEN)
    negP = gold.ec_neg(gold.FQ, gold.G1_GEN)

    # and a deliberately broken second item
    b = (a + 1) % R
    bQ = gold.ec_mul(gold.FQ2, b, gold.G2_GEN)

    pairs = [
        (
            pairing.g1_affine_to_device([aP, aP]),
            pairing.g2_affine_to_device([gold.G2_GEN, gold.G2_GEN]),
        ),
        (
            pairing.g1_affine_to_device([negP, negP]),
            pairing.g2_affine_to_device([aQ, bQ]),
        ),
    ]
    ok = pairing.product_check(pairs)
    assert list(ok) == [True, False]


def test_pairing_infinity(rng):
    Pd = pairing.g1_affine_to_device([None, gold.G1_GEN])
    Qd = pairing.g2_affine_to_device([gold.G2_GEN, None])
    f = pairing.pairing(Pd, Qd)
    assert pairing.is_one_host(f, 0)
    assert pairing.is_one_host(f, 1)


def test_fast_final_exp_decomposition_identity():
    """Integer identity behind final_exponentiation_fast (exact check)."""
    from hbbft_tpu.crypto.bls381 import BLS_X
    from hbbft_tpu.crypto.field import Q, R as SUBR

    x = -BLS_X  # the BLS parameter is negative
    H = (Q**4 - Q**2 + 1) // SUBR
    c3 = (x - 1) ** 2
    c2 = c3 * x
    c1 = c2 * x - c3
    c0 = c1 * x + 3
    assert c0 + c1 * Q + c2 * Q**2 + c3 * Q**3 == 3 * H
    assert SUBR % 3 != 0  # gcd(3, R) = 1 → f^{3H}==1 ⟺ f^H==1


def test_fast_final_exp_is_cube(rng):
    """FE_fast(f) == FE(f)³ on a real Miller output."""
    a = rng.randrange(1, R)
    P = pairing.g1_affine_to_device([gold.ec_mul(gold.FQ, a, gold.G1_GEN)])
    Qd = pairing.g2_affine_to_device([gold.G2_GEN])
    ml = pairing.miller_loop(P, Qd)
    exact = tower.fq12_to_ints(pairing.final_exponentiation(ml), 0)
    fast = tower.fq12_to_ints(pairing.final_exponentiation_fast(ml), 0)
    cube = gold.fq12_mul(gold.fq12_mul(exact, exact), exact)
    assert fast == cube


def test_miller_product_matches_separate(rng):
    """FE(ML(P,Q)·ML(P',Q')) == e(P,Q)·e(P',Q') (golden side)."""
    a = rng.randrange(1, R)
    b = rng.randrange(1, R)
    P = gold.ec_mul(gold.FQ, a, gold.G1_GEN)
    Qq = gold.ec_mul(gold.FQ2, b, gold.G2_GEN)

    pairs = [
        (
            pairing.g1_affine_to_device([P]),
            pairing.g2_affine_to_device([gold.G2_GEN]),
        ),
        (
            pairing.g1_affine_to_device([gold.G1_GEN]),
            pairing.g2_affine_to_device([Qq]),
        ),
    ]
    f = pairing.final_exponentiation(pairing.miller_product(pairs))
    want = gold.fq12_mul(
        gold.pairing(P, gold.G2_GEN), gold.pairing(gold.G1_GEN, Qq)
    )
    assert tower.fq12_to_ints(f, 0) == want
