"""Subset (ACS) integration tests (reference `tests/subset.rs` § shape):
all correct nodes output the same set of ≥ N−f contributions, including
every contribution proposed by all correct nodes... under adversarial
scheduling and silent faults."""

import pytest

from hbbft_tpu.net.adversary import ReorderingAdversary, SilentAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.subset import Subset, SubsetOutput


def build(n, f=0, adversary=None, defer_mode="eager", seed=0):
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .defer_mode(defer_mode)
        .crank_limit(2_000_000)
        .using(lambda ni, be: Subset(ni, be, session_id=b"test-subset"))
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


def run_to_done(net, defer_mode="eager"):
    if defer_mode == "round":
        while net.queue or net._pending_work:
            net.crank_round()
    else:
        net.crank_to_quiescence()


def contributions(node):
    return {
        o.proposer: o.value for o in node.outputs if o.kind == "contribution"
    }


@pytest.mark.parametrize("n,f", [(1, 0), (2, 0), (4, 1), (7, 2)])
@pytest.mark.parametrize("defer_mode", ["eager", "round"])
def test_all_agree_on_subset(n, f, defer_mode):
    net = build(n, f, defer_mode=defer_mode)
    for i in sorted(net.nodes):
        net.send_input(i, b"contribution-%d" % i)
    run_to_done(net, defer_mode)
    ref = None
    for node in net.correct_nodes():
        assert node.outputs and node.outputs[-1].kind == "done", (
            f"node {node.id} incomplete: {node.outputs}"
        )
        cs = contributions(node)
        assert len(cs) >= n - f
        for p, v in cs.items():
            assert v == b"contribution-%d" % p
        if ref is None:
            ref = cs
        assert cs == ref, f"node {node.id} diverged"


@pytest.mark.parametrize("seed", range(4))
def test_adversarial_reordering(seed):
    net = build(4, 1, adversary=ReorderingAdversary(), seed=seed)
    for i in sorted(net.nodes):
        net.send_input(i, b"c%d" % i)
    run_to_done(net)
    ref = None
    for node in net.correct_nodes():
        assert node.outputs[-1].kind == "done"
        cs = contributions(node)
        if ref is None:
            ref = cs
        assert cs == ref


@pytest.mark.parametrize("seed", range(4))
def test_silent_faulty_nodes(seed):
    net = build(7, 2, adversary=SilentAdversary(), seed=seed)
    for i in sorted(net.nodes):
        net.send_input(i, b"c%d" % i)
    run_to_done(net)
    ref = None
    for node in net.correct_nodes():
        assert node.outputs[-1].kind == "done"
        cs = contributions(node)
        assert len(cs) >= 7 - 2
        if ref is None:
            ref = cs
        assert cs == ref
