"""Control-plane tests: SLO spec, load traces, and the adaptive batch
controller — unit-level control law, end-to-end array-driver runs,
seeded-replay bit-identity, the kill switch, snapshot/restore, and the
composed-gauntlet soak cell with the controller on.

The determinism contract mirrors the traffic subsystem's: decisions are
a pure function of observed virtual-time state (+ the injected rng for
the optional probe dither), so same seed ⇒ identical B trace, batch
digests, and tracker fingerprint.
"""

import hashlib
import json
import random

import pytest

from hbbft_tpu.control import (
    LADDER,
    SLO,
    AdaptiveBatchController,
    LoadTrace,
    make_trace,
    swing10x,
)
from hbbft_tpu.control.controller import Observation, _effective_drain
from hbbft_tpu.control.trace import diurnal, spike, step
from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.engine import ArrayHoneyBadgerNet
from hbbft_tpu.obs.health import HealthReporter, why_stalled
from hbbft_tpu.traffic import (
    ArrayTrafficDriver,
    OpenLoopSource,
    PayloadSizes,
    ZipfPopulation,
)


# ---------------------------------------------------------------------------
# SLO spec
# ---------------------------------------------------------------------------


def test_slo_rejects_infeasible_targets():
    with pytest.raises(ValueError):
        SLO(p99_epochs=1.5)  # below the submit->sample->commit floor
    with pytest.raises(ValueError):
        SLO(p99_epochs=4.0, margin=0.0)
    with pytest.raises(ValueError):
        SLO(p99_epochs=4.0, min_tx_per_epoch=-1)


def test_slo_compliance_and_headroom():
    slo = SLO(p99_epochs=4.0, min_tx_per_epoch=50.0, margin=0.8)
    assert slo.compliant(3.9, 60.0)
    assert not slo.compliant(4.1, 60.0)
    assert not slo.compliant(3.0, 40.0)  # throughput floor missed
    assert slo.compliant(None)  # idle violates nothing
    assert slo.headroom(3.2) and not slo.headroom(3.3)
    d = slo.describe()
    assert d["p99_epochs"] == 4.0 and d["min_tx_per_epoch"] == 50.0


# ---------------------------------------------------------------------------
# Load traces
# ---------------------------------------------------------------------------


def test_trace_shapes_are_pure_functions_of_epoch():
    st = step(low=1.0, high=4.0, at=8)
    assert st.factor(7) == 1.0 and st.factor(8) == 4.0 and st.factor(99) == 4.0
    sp = spike(low=1.0, high=10.0, at=5, width=2)
    assert [sp.factor(e) for e in (4, 5, 6, 7)] == [1.0, 10.0, 10.0, 1.0]
    sw = swing10x(period=12)
    assert sw.factor(0) == 1.0 and sw.factor(5) == 1.0
    assert sw.factor(6) == 10.0 and sw.factor(11) == 10.0
    assert sw.factor(12) == 1.0  # periodic
    assert sw.peak() == 10.0
    di = diurnal(low=1.0, high=3.0, period=24)
    assert di.factor(0) == pytest.approx(1.0)
    assert di.factor(12) == pytest.approx(3.0)
    assert 1.0 < di.factor(6) < 3.0


def test_trace_registry_and_validation():
    assert make_trace("swing10x").describe()["trace"] == "swing"
    with pytest.raises(ValueError):
        make_trace("nope")
    with pytest.raises(ValueError):
        LoadTrace("sawtooth")


def test_traced_source_modulates_rate_replayably():
    tr = step(low=1.0, high=5.0, at=2)
    src = OpenLoopSource(40.0, ZipfPopulation(100, 1.0), trace=tr)
    rng = random.Random(4)
    waves = [len(src.arrivals(rng, e)) for e in range(4)]
    assert sum(waves[:2]) < sum(waves[2:])  # the step really stepped
    assert src.describe()["trace"]["trace"] == "step"
    src2 = OpenLoopSource(40.0, ZipfPopulation(100, 1.0), trace=tr)
    rng2 = random.Random(4)
    assert [len(src2.arrivals(rng2, e)) for e in range(4)] == waves


# ---------------------------------------------------------------------------
# Controller: the control law (unit level, synthetic observations)
# ---------------------------------------------------------------------------


def _obs(epoch, *, p99=None, tx=0.0, arr=0.0, last=None, depth=0,
         bp=False, n=16):
    return Observation(
        epoch=epoch, p99=p99, tx_per_epoch=tx, arrivals_per_epoch=arr,
        mempool_depth=depth, backpressure=bp, validators=n,
        arrivals_last=arr if last is None else last,
    )


def test_controller_requires_ladder_membership():
    with pytest.raises(ValueError):
        AdaptiveBatchController(SLO(4.0), initial_b=33)
    with pytest.raises(ValueError):
        AdaptiveBatchController(SLO(4.0), initial_b=8, ladder=(8, 8, 16))


def test_steady_load_parks_on_one_rung():
    c = AdaptiveBatchController(SLO(4.0), initial_b=128)
    for e in range(30):
        c.decide(_obs(e, p99=2.5, tx=100.0, arr=100.0, depth=110))
    trace = c.b_trace()
    # settles (down from the oversized initial rung) and then HOLDS:
    # no oscillation under steady load
    settled = trace[-15:]
    assert len(set(settled)) == 1
    assert settled[0] < 128  # it did trade slack for efficiency
    # the dead band holds: capacity comfortably covers demand
    assert settled[0] * 16 > 100


def test_pressure_ramps_multiple_rungs_in_one_decision():
    c = AdaptiveBatchController(SLO(4.0), initial_b=16)
    b = c.decide(_obs(0, tx=100.0, arr=100.0, last=1000.0, depth=900))
    # one decision must clear the 10x spike, not pay log2(10) epochs
    assert b * 16 * 0.9 >= 1000.0
    assert c.decisions[-1][2] == "up:pressure"


def test_stale_p99_does_not_escalate_a_drained_pool():
    c = AdaptiveBatchController(SLO(4.0), initial_b=64, window=2)
    for e in range(6):
        # p99 far over target, but the pool is drained: the breach is a
        # ramp tail, not a live backlog — B must not escalate
        c.decide(_obs(e, p99=9.0, tx=500.0, arr=100.0, depth=10))
    assert max(c.b_trace()) == 64


def test_down_requires_consecutive_eligibility():
    c = AdaptiveBatchController(SLO(4.0), initial_b=64, hold_epochs=3)
    eligible = _obs(0, p99=2.5, tx=50.0, arr=50.0, depth=30)
    # demand above the next-rung-down threshold (0.7·16·32 = 358) but
    # inside the current rung's capacity: not down-eligible, not an up
    busy = _obs(0, p99=2.5, tx=500.0, arr=500.0, depth=500)
    c.decide(eligible)
    c.decide(eligible)
    c.decide(busy)  # resets the hold counter
    c.decide(eligible)
    c.decide(eligible)
    assert c.current_b == 64  # two consecutive, not three
    c.decide(eligible)
    assert c.current_b == 32


def test_throughput_floor_triggers_up():
    slo = SLO(4.0, min_tx_per_epoch=200.0)
    c = AdaptiveBatchController(slo, initial_b=16)
    b = c.decide(_obs(0, p99=2.0, tx=100.0, arr=100.0, depth=150))
    assert b > 16
    assert c.decisions[-1][2] in ("up:floor", "up:pressure")


def test_kill_switch_pins_initial_rung(monkeypatch):
    monkeypatch.setenv("HBBFT_TPU_NO_ADAPTIVE_B", "1")
    c = AdaptiveBatchController(SLO(4.0), initial_b=32)
    for e in range(5):
        b = c.decide(_obs(e, tx=100.0, arr=100.0, last=2000.0, depth=5000))
    assert b == 32 and c.current_b == 32
    assert all(r == "killswitch" for _, _, r in c.decisions)


def test_probe_jitter_draws_only_from_injected_rng():
    def run(seed):
        c = AdaptiveBatchController(
            SLO(4.0), initial_b=64, rng=random.Random(seed),
            hold_epochs=2, probe_jitter=3,
        )
        for e in range(20):
            c.decide(_obs(e, p99=2.5, tx=50.0, arr=50.0, depth=30))
        return c.b_trace()

    assert run(7) == run(7)  # bit-identical replay
    # without a jitter the rng is never consumed
    r = random.Random(9)
    before = r.getstate()
    c = AdaptiveBatchController(SLO(4.0), initial_b=64, rng=r)
    for e in range(10):
        c.decide(_obs(e, p99=2.5, tx=50.0, arr=50.0, depth=30))
    assert r.getstate() == before


def test_effective_drain_model():
    assert _effective_drain(0, 64, 16) == 0.0
    # B >= D: everyone proposes everything — the whole pool drains
    assert _effective_drain(50, 64, 16) == pytest.approx(50.0)
    # decorrelated overlap: eff is below raw N*B but grows with B
    lo = _effective_drain(2000, 32, 16)
    hi = _effective_drain(2000, 128, 16)
    assert lo < 32 * 16 and lo < hi < 2000


def test_controller_snapshot_roundtrip_continues_identically():
    from hbbft_tpu.utils.snapshot import load_node, save_node

    c = AdaptiveBatchController(
        SLO(4.0, min_tx_per_epoch=10.0), initial_b=32,
        rng=random.Random(5), probe_jitter=2,
    )
    for e in range(6):
        c.decide(_obs(e, p99=2.5, tx=50.0, arr=40.0, depth=20))
    c2 = load_node(save_node(c), MockBackend())
    assert c2.current_b == c.current_b
    assert c2.decisions == c.decisions
    for e in range(6, 14):
        o = _obs(e, p99=2.2, tx=50.0, arr=40.0, depth=15)
        assert c.decide(o) == c2.decide(o)
    assert c.b_trace() == c2.b_trace()


# ---------------------------------------------------------------------------
# End to end: array driver under load traces
# ---------------------------------------------------------------------------


def _swing_run(seed=7, adaptive=True, fixed_b=32, epochs=16, n=8,
               rate=50.0, period=8, slo_p99=4.0):
    net = ArrayHoneyBadgerNet(range(n), backend=MockBackend(), seed=1)
    src = OpenLoopSource(
        rate, ZipfPopulation(5_000, 1.1), PayloadSizes("fixed", 24),
        trace=swing10x(period=period),
    )
    ctrl = (
        AdaptiveBatchController(SLO(slo_p99), initial_b=fixed_b)
        if adaptive
        else None
    )
    drv = ArrayTrafficDriver(
        net, src, random.Random(seed), batch_size=fixed_b,
        mempool_capacity=4 * int(rate) * 10, controller=ctrl,
        mempool_shards=4,
    )
    digests = []

    def dl(batches):
        b = batches[net.ids[0]]
        h = hashlib.sha256()
        for p in net.ids:
            h.update(bytes(b.contributions[p]))
        digests.append(h.hexdigest())

    net.batch_listeners.append(dl)
    rep = drv.run(epochs)
    return drv, rep, digests


def test_seeded_replay_bit_identity_of_b_trace_digests_fingerprint():
    a_drv, a_rep, a_dig = _swing_run(seed=21)
    b_drv, b_rep, b_dig = _swing_run(seed=21)
    assert a_rep["controller"]["b_trace"] == b_rep["controller"]["b_trace"]
    assert a_dig == b_dig
    assert a_drv.tracker.fingerprint() == b_drv.tracker.fingerprint()
    c_drv, c_rep, c_dig = _swing_run(seed=22)
    assert c_dig != a_dig  # the seed really is the input


def test_controller_converges_to_slo_on_swing_trace():
    drv, rep, _ = _swing_run()
    trace = rep["controller"]["b_trace"]
    # walked up for the high phase and back down after it
    assert max(trace) > trace[0] and min(trace[4:]) < max(trace)
    # holds the declared SLO over the whole run (fixed B=32 at this
    # shape blows p99 past 10 epochs — asserted below)
    assert rep["tracker"]["commit_latency"]["p99"] <= 4.0
    assert rep["controller"]["compliant"]


def test_small_fixed_b_violates_where_controller_holds():
    _, rep, _ = _swing_run(adaptive=False, fixed_b=8)
    assert rep["tracker"]["commit_latency"]["p99"] > 4.0


def test_controller_converges_on_step_and_spike_traces():
    def run(tr, epochs):
        net = ArrayHoneyBadgerNet(range(8), backend=MockBackend(), seed=1)
        src = OpenLoopSource(
            50.0, ZipfPopulation(2_000, 1.1), PayloadSizes("fixed", 24),
            trace=tr,
        )
        ctrl = AdaptiveBatchController(SLO(4.0), initial_b=16)
        drv = ArrayTrafficDriver(
            net, src, random.Random(3), batch_size=16,
            mempool_capacity=4_000, controller=ctrl,
        )
        rep = drv.run(epochs)
        return drv, rep["controller"]["b_trace"], rep

    # STEP: sustained 6x — B walks up and the END state (once the
    # observation window has turned over past the one-time ramp) sits
    # inside the SLO.  The ramp epoch's own tail is bounded but not
    # under the target; that is the reaction cost of any feedback loop.
    drv, trace, rep = run(step(low=1.0, high=6.0, at=5), 22)
    assert max(trace) > 16
    assert drv.tracker.recent_summary(4, now=22)["p99"] <= 4.0
    assert rep["tracker"]["commit_latency"]["p99"] < 8.0

    # SPIKE: a 3-epoch flash crowd — B rises for it and DECAYS back once
    # the backlog drains (a spike must not pin the run on a big rung)
    drv, trace, rep = run(spike(low=1.0, high=8.0, at=6, width=3), 18)
    assert max(trace) > 16
    assert trace[-1] < max(trace)
    assert drv.tracker.recent_summary(4, now=18)["p99"] <= 4.0


def test_hysteresis_no_oscillation_under_steady_load():
    net = ArrayHoneyBadgerNet(range(8), backend=MockBackend(), seed=1)
    src = OpenLoopSource(50.0, ZipfPopulation(2_000, 1.1), PayloadSizes("fixed", 24))
    ctrl = AdaptiveBatchController(SLO(4.0), initial_b=64)
    drv = ArrayTrafficDriver(
        net, src, random.Random(9), batch_size=64,
        mempool_capacity=4_000, controller=ctrl,
    )
    rep = drv.run(16)
    tail = rep["controller"]["b_trace"][-8:]
    assert len(set(tail)) == 1  # parked on one rung, not flapping


def test_kill_switch_reproduces_fixed_b_run_bit_identically(monkeypatch):
    monkeypatch.setenv("HBBFT_TPU_NO_ADAPTIVE_B", "1")
    k_drv, k_rep, k_dig = _swing_run(seed=11, adaptive=True, fixed_b=32)
    monkeypatch.delenv("HBBFT_TPU_NO_ADAPTIVE_B")
    f_drv, f_rep, f_dig = _swing_run(seed=11, adaptive=False, fixed_b=32)
    assert k_dig == f_dig
    assert k_drv.tracker.fingerprint() == f_drv.tracker.fingerprint()
    assert set(k_rep["controller"]["b_trace"]) == {32}
    # ...and with the switch off the same seed takes a different path
    a_drv, _, a_dig = _swing_run(seed=11, adaptive=True, fixed_b=32)
    assert a_dig != f_dig


def test_why_stalled_and_heartbeat_report_b_and_compliance():
    beats = []
    health = HealthReporter(interval_s=0.0, sink=beats.append)
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=3)
    src = OpenLoopSource(40.0, ZipfPopulation(200, 1.0))
    ctrl = AdaptiveBatchController(SLO(4.0), initial_b=16)
    drv = ArrayTrafficDriver(
        net, src, random.Random(1), batch_size=16,
        mempool_capacity=128, controller=ctrl, health=health,
    )
    drv.run(3)
    assert beats and "batch_size" in beats[-1]
    assert beats[-1]["batch_size"] == ctrl.current_b
    assert beats[-1]["slo_compliant"] is True

    class _Stub:
        nodes = {}
        traffic = drv

    report = why_stalled(_Stub())
    assert report["traffic"]["controller"]["batch_size"] == ctrl.current_b
    assert any("adaptive batch B=" in s for s in report["summary"])


def test_engine_hook_is_checkpoint_detached():
    net = ArrayHoneyBadgerNet(range(4), backend=MockBackend(), seed=2)
    ctrl = AdaptiveBatchController(SLO(4.0), initial_b=16)
    src = OpenLoopSource(10.0, ZipfPopulation(50, 1.0))
    ArrayTrafficDriver(
        net, src, random.Random(0), batch_size=16, controller=ctrl
    )
    assert net.batch_size_provider is not None
    restored = ArrayHoneyBadgerNet.restore(net.checkpoint(), MockBackend())
    assert restored.batch_size_provider is None


# ---------------------------------------------------------------------------
# QHB hooks (object runtime)
# ---------------------------------------------------------------------------


def _qhb(batch_size=3):
    from hbbft_tpu.core.network_info import NetworkInfo
    from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger

    be = MockBackend()
    rng = random.Random(0)
    ni = NetworkInfo.generate_map([0, 1, 2, 3], rng, be)[0]
    return QueueingHoneyBadger(ni, be, rng=rng, batch_size=batch_size)


def test_qhb_batch_size_input_is_state_and_does_not_propose():
    q = _qhb()
    step = q.handle_input(("batch_size", 9))
    assert q.batch_size == 9
    assert not step.messages and not step.output  # no proposal triggered
    from hbbft_tpu.utils.snapshot import load_node, save_node

    q2 = load_node(save_node(q), MockBackend())
    assert q2.batch_size == 9  # input-borne B is snapshotted state


def test_qhb_provider_hook_overrides_and_detaches():
    q = _qhb(batch_size=2)
    for i in range(10):
        q.queue.push(("tx", i))
    q.batch_size_provider = lambda: 7
    samples = []
    q.sample_listener = samples.append
    q._try_propose()
    assert len(samples[-1]) == 7  # provider, not the stored batch_size
    from hbbft_tpu.utils.snapshot import load_node, save_node

    q2 = load_node(save_node(q), MockBackend())
    assert q2.batch_size_provider is None and q2.batch_size == 2


def test_object_driver_applies_b_as_inputs():
    from hbbft_tpu.net.virtual_net import NetBuilder
    from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger
    from hbbft_tpu.traffic import ObjectTrafficDriver

    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .crank_limit(10_000_000)
        .using(
            lambda ni, be, rng: QueueingHoneyBadger(
                ni, be, rng=rng, batch_size=4, session_id=b"ctl"
            )
        )
        .build(seed=0)
    )
    ctrl = AdaptiveBatchController(SLO(6.0), initial_b=4, ladder=(2, 4, 8, 16))
    src = OpenLoopSource(12.0, ZipfPopulation(100, 1.0))
    drv = ObjectTrafficDriver(
        net, src, random.Random(6), batch_size=4, mempool_capacity=256,
        controller=ctrl,
    )
    rep = drv.run(4)
    assert rep["committed"] > 0
    assert rep["controller"]["b_trace"]  # decisions were made
    # the live QHBs carry the input-borne B as plain state
    applied = {net.nodes[nid].algorithm.batch_size for nid in drv.ids}
    assert applied == {ctrl.current_b}


# ---------------------------------------------------------------------------
# Composed gauntlet: controller × crash/restart (snapshot + WAL replay)
# ---------------------------------------------------------------------------


def test_soak_cell_with_controller_survives_crash_restart():
    from hbbft_tpu.net.scenarios import Cell, run_cell

    cell = Cell(
        attack="passive", schedule="uniform", churn="none",
        crash="one_restart", traffic="swing_adaptive",
        n=4, epochs=10, seed=2,
    )
    r1 = run_cell(cell)
    assert r1.ok, (r1.error, r1.missing_expected, r1.misattributed)
    assert r1.crashes == 1 and r1.restarts == 1
    assert r1.tx_committed > 0
    # the B trace is part of the replay contract: bit-stable fingerprint
    r2 = run_cell(cell)
    assert r1.fingerprint() == r2.fingerprint()


def test_adaptive_traffic_specs_registered():
    from hbbft_tpu.net.scenarios import TRAFFICS

    assert TRAFFICS["one_x_adaptive"].adaptive
    assert TRAFFICS["swing_adaptive"].trace == "swing10x"


# ---------------------------------------------------------------------------
# trace_report: SLO-compliance regression gate
# ---------------------------------------------------------------------------


def _slo_rows_doc(tx_per_s, p99, compliant):
    return {
        "meta": {},
        "rows": [
            {
                "metric": "slo_traffic",
                "value": tx_per_s,
                "curve": [
                    {
                        "n": 16, "batch_size": "adaptive",
                        "tx_per_s": tx_per_s, "latency_p99": p99,
                        "slo_compliant": compliant,
                    }
                ],
            }
        ],
    }


def test_trace_report_gates_slo_compliance(tmp_path):
    from tools.trace_report import diff_traffic, report_traffic

    old = tmp_path / "old.json"
    old.write_text(json.dumps(_slo_rows_doc(1000.0, 3.8, True)))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_slo_rows_doc(1005.0, 3.9, True)))
    assert report_traffic(str(old), str(ok), 0.10) == 0

    lost = tmp_path / "lost.json"
    # tx/s and p99 both inside tolerance — ONLY compliance flipped
    lost.write_text(json.dumps(_slo_rows_doc(1001.0, 4.1, False)))
    assert report_traffic(str(old), str(lost), 0.10) == 1
    entries = diff_traffic(str(old), str(lost), 0.10)
    assert entries[0]["slo_regression"]
    assert not entries[0]["tx_regression"]
