"""Core contract tests: canonical serde, Target routing, Step combinators,
erasure coding, Merkle proofs, and the mock threshold-crypto layer."""

import random

import pytest

from hbbft_tpu.core.fault_log import Fault, FaultLog
from hbbft_tpu.core.network_info import NetworkInfo
from hbbft_tpu.core.types import Step, Target, TargetedMessage, absorb_child_step
from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.crypto.erasure import RSCodec, gf256
from hbbft_tpu.crypto.group import MockGroup
from hbbft_tpu.crypto.keys import SecretKey, SecretKeySet
from hbbft_tpu.crypto.merkle import MerkleTree, Proof
from hbbft_tpu.crypto.poly import BivarPoly, Poly
from hbbft_tpu.utils import canonical


# ---------------------------------------------------------------------------
# canonical serde
# ---------------------------------------------------------------------------


def test_canonical_roundtrip():
    objs = [
        None,
        True,
        False,
        0,
        -1,
        2**400,
        -(2**400),
        b"",
        b"\x00\xff",
        "héllo",
        [1, [2, 3]],
        (1, b"x", None),
        {"b": 1, "a": [True]},
        {(1, 2): "t"},
    ]
    for o in objs:
        assert canonical.decode(canonical.encode(o)) == o


def test_canonical_dict_order_independent():
    a = canonical.encode({"x": 1, "y": 2})
    b = canonical.encode(dict([("y", 2), ("x", 1)]))
    assert a == b


def test_canonical_distinguishes_types():
    assert canonical.encode(0) != canonical.encode(False)
    assert canonical.encode([1]) != canonical.encode((1,))
    assert canonical.encode("a") != canonical.encode(b"a")


# ---------------------------------------------------------------------------
# Target / Step
# ---------------------------------------------------------------------------


def test_target_routing():
    ids = [0, 1, 2, 3]
    assert Target.all().recipients(ids, our_id=1) == [0, 2, 3]
    assert Target.node(2).recipients(ids, our_id=1) == [2]
    assert sorted(Target.nodes([0, 3]).recipients(ids, our_id=0)) == [3]
    assert sorted(Target.all_except([2]).recipients(ids, our_id=1)) == [0, 3]


def test_step_extend_and_absorb():
    s1 = Step.from_output("a")
    s2 = Step.from_msg(Target.all(), "m").add_fault(7, "k")
    s1.extend(s2)
    assert s1.output == ["a"] and len(s1.messages) == 1 and len(s1.fault_log) == 1

    child = Step.from_output(10)
    child.messages.append(TargetedMessage(Target.node(1), "inner"))
    parent = absorb_child_step(
        child,
        wrap_msg=lambda m: ("wrapped", m),
        on_output=lambda o: Step.from_output(o * 2),
    )
    assert parent.output == [20]
    assert parent.messages[0].message == ("wrapped", "inner")


# ---------------------------------------------------------------------------
# GF(2^8) + Reed-Solomon
# ---------------------------------------------------------------------------


def test_gf256_field_axioms():
    import numpy as np

    gf = gf256()
    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, 100).astype(np.uint8)
    b = rng.integers(0, 256, 100).astype(np.uint8)
    c = rng.integers(0, 256, 100).astype(np.uint8)
    # commutativity, associativity, distributivity over XOR
    assert (gf.mul(a, b) == gf.mul(b, a)).all()
    assert (gf.mul(gf.mul(a, b), c) == gf.mul(a, gf.mul(b, c))).all()
    assert (gf.mul(a, b ^ c) == (gf.mul(a, b) ^ gf.mul(a, c))).all()
    # inverses
    for x in range(1, 256):
        assert int(gf.mul(x, gf.inv(x))) == 1


@pytest.mark.parametrize("k,m", [(1, 0), (2, 1), (2, 2), (4, 2), (6, 4), (10, 22)])
def test_rs_roundtrip(k, m):
    rng = random.Random(42)
    codec = RSCodec(k, m)
    data = bytes(rng.randrange(256) for _ in range(137))
    shards = codec.encode(data)
    assert len(shards) == k + m
    # Drop any m shards; reconstruct.
    lost = rng.sample(range(k + m), m)
    partial = [None if i in lost else s for i, s in enumerate(shards)]
    assert codec.decode_data(partial, len(data)) == data
    full = codec.reconstruct(partial)
    assert full == shards


def test_rs_insufficient_shards():
    codec = RSCodec(4, 2)
    shards = codec.encode(b"hello world")
    partial = [shards[0], None, None, shards[3], None, None]
    with pytest.raises(ValueError):
        codec.reconstruct(partial)


# ---------------------------------------------------------------------------
# Merkle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 13])
def test_merkle_proofs(n):
    leaves = [bytes([i]) * (i + 1) for i in range(n)]
    tree = MerkleTree(leaves)
    for i in range(n):
        p = tree.proof(i)
        assert p.validate(n)
        assert Proof.from_bytes(p.to_bytes()) == p
    # Tampered value fails.
    p = tree.proof(0)
    bad = Proof(b"evil", p.index, p.path, p.root_hash, p.n_leaves)
    assert not bad.validate(n)


# ---------------------------------------------------------------------------
# Mock threshold crypto
# ---------------------------------------------------------------------------


def test_mock_bls_signature():
    g = MockGroup()
    rng = random.Random(1)
    sk = SecretKey.random(g, rng)
    pk = sk.public_key()
    sig = sk.sign(b"hello")
    assert pk.verify(sig, b"hello")
    assert not pk.verify(sig, b"other")


def test_threshold_signature_combine():
    g = MockGroup()
    rng = random.Random(2)
    sk_set = SecretKeySet.random(g, threshold=2, rng=rng)
    pk_set = sk_set.public_keys()
    doc = b"the document"
    shares = {}
    for i in range(7):
        share = sk_set.secret_key_share(i).sign_share(doc)
        assert pk_set.public_key_share(i).verify_sig_share(share, doc)
        shares[i] = share
    # Any 3 shares combine to the same signature, which verifies under master.
    sig_a = pk_set.combine_signatures({i: shares[i] for i in [0, 1, 2]})
    sig_b = pk_set.combine_signatures({i: shares[i] for i in [3, 5, 6]})
    assert sig_a == sig_b
    assert pk_set.public_key().verify(sig_a, doc)
    # Wrong share fails verification.
    bad = sk_set.secret_key_share(0).sign_share(b"oops")
    assert not pk_set.public_key_share(1).verify_sig_share(bad, doc)


def test_threshold_encryption():
    g = MockGroup()
    rng = random.Random(3)
    sk_set = SecretKeySet.random(g, threshold=1, rng=rng)
    pk_set = sk_set.public_keys()
    msg = b"secret payload !"
    ct = pk_set.encrypt(msg, rng)
    assert ct.verify()
    shares = {}
    for i in [0, 2]:
        d = sk_set.secret_key_share(i).decrypt_share(ct)
        assert pk_set.public_key_share(i).verify_decryption_share(d, ct)
        shares[i] = d
    assert pk_set.combine_decryption_shares(shares, ct) == msg
    # A share for a different ciphertext fails.
    ct2 = pk_set.encrypt(b"another message!", rng)
    d_bad = sk_set.secret_key_share(0).decrypt_share(ct2)
    assert not pk_set.public_key_share(0).verify_decryption_share(d_bad, ct)


def test_plain_encryption_roundtrip():
    g = MockGroup()
    rng = random.Random(4)
    sk = SecretKey.random(g, rng)
    ct = sk.public_key().encrypt(b"dkg row bytes", rng)
    assert sk.decrypt(ct) == b"dkg row bytes"


def test_poly_and_bivar():
    g = MockGroup()
    rng = random.Random(5)
    p = Poly.random(g, 3, rng)
    c = p.commitment()
    for x in [0, 1, 5, 1234]:
        assert c.evaluate(x) == g.g1_mul(p.evaluate(x), g.g1())
    b = BivarPoly.random(g, 2, rng)
    bc = b.commitment()
    # symmetry
    assert b.evaluate(3, 8) == b.evaluate(8, 3)
    # row consistency
    row2 = b.row(2)
    assert row2.evaluate(5) == b.evaluate(2, 5)
    assert bc.row(2).evaluate(5) == g.g1_mul(b.evaluate(2, 5), g.g1())
    # commitment eval matches
    assert bc.evaluate(4, 9) == g.g1_mul(b.evaluate(4, 9), g.g1())


def test_network_info_generate_map():
    rng = random.Random(6)
    infos = NetworkInfo.generate_map(list(range(4)), rng, MockBackend())
    assert len(infos) == 4
    ni = infos[0]
    assert ni.num_nodes() == 4 and ni.num_faulty() == 1 and ni.num_correct() == 3
    assert ni.is_validator()
    # Same master public key everywhere.
    pks = {i: infos[i].public_key_set for i in range(4)}
    assert all(pks[i] == pks[0] for i in range(4))
    # Share i signs; master key verifies combined.
    doc = b"x"
    shares = {
        i: infos[i].secret_key_share.sign_share(doc) for i in range(2)
    }
    sig = pks[0].combine_signatures(shares)
    assert pks[0].public_key().verify(sig, doc)
