"""Schedule-space race explorer: sensitivity fixtures + replay contract.

Tier-1 runs the smoke sweep (all four honest seams — pipeline, traffic,
virtualnet, and the PR-18 cross-shard completion order — agree across
every explored schedule) and pins the detector's sensitivity: each seeded
order-dependent mutant in ``analysis/mutations.py`` must be caught with
a minimized counterexample that replays to the identical divergence in a
fresh process (``tools/race_explorer.py --replay``).  The slow arm runs
the full N∈{4,7} sweep (≥1000 non-equivalent schedules, DPOR-reduced).
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from hbbft_tpu.analysis import schedules
from hbbft_tpu.analysis.mutations import MUTANT_NAMES
from hbbft_tpu.analysis.schedules import (
    Event,
    RaceTracker,
    ScheduleController,
    clocks_concurrent,
    events_dependent,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
EXPLORER = REPO_ROOT / "tools" / "race_explorer.py"


def _cli(*args, **kw):
    return subprocess.run(
        [sys.executable, str(EXPLORER), *args],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
        **kw,
    )


# ---------------------------------------------------------------------------
# Controller / trace machinery
# ---------------------------------------------------------------------------


def test_controller_default_schedule_is_all_zeros():
    c = ScheduleController()
    assert c.choose(3, "x") == 0
    assert c.choose(1, "degenerate") == 0  # arity-1: not recorded
    assert c.permutation(3, "p") == [0, 1, 2]
    # only the arity>1 decisions were recorded
    assert c.trace == [0, 0, 0]


def test_controller_replays_preset_choices():
    c = ScheduleController([2, 1])
    assert c.choose(3, "x") == 2
    assert c.permutation(3, "p") == [1, 0, 2]  # picks idx 1, then defaults
    # a fresh controller with the recorded trace reproduces the run
    c2 = ScheduleController(list(c.trace))
    assert c2.choose(3, "x") == 2
    assert c2.permutation(3, "p") == [1, 0, 2]
    assert c2.trace == c.trace


def test_controller_preset_wraps_modulo_arity():
    c = ScheduleController([7])
    assert c.choose(3, "x") == 1  # 7 % 3 — mutated presets stay in range


# ---------------------------------------------------------------------------
# Vector clocks / dependence
# ---------------------------------------------------------------------------


def test_vector_clocks_order_causal_chains_and_expose_races():
    t = RaceTracker()
    a = t.record("submit:b0.c0", "main", "submit")
    b = t.record(
        "resolve:b0.c0", "chunk:0", "resolve",
        writes=(("batch", "b0"),), causes=(a.index,),
    )
    c = t.record(
        "resolve:b0.c1", "chunk:1", "resolve", writes=(("batch", "b0"),)
    )
    # submit happens-before its own resolve (causal edge joins clocks)
    assert not clocks_concurrent(a, b)
    # the two chunk resolutions are causally unordered AND conflict on
    # the batch object: exactly one racing pair
    assert clocks_concurrent(b, c)
    assert ("resolve:b0.c0", "resolve:b0.c1") in t.racing_pairs()


def test_canonical_form_is_order_free_for_independent_events():
    def build(order):
        t = RaceTracker()
        evs = {
            "x": ("node:1", (("node", "1"),)),
            "y": ("node:2", (("node", "2"),)),
        }
        for k in order:
            task, writes = evs[k]
            t.record(k, task, "crank", writes=writes)
        return t

    assert build("xy").canonical_form() == build("yx").canonical_form()


def test_canonical_form_distinguishes_dependent_orders():
    def build(order):
        t = RaceTracker()
        for k in order:
            t.record(k, f"task:{k}", "resolve", writes=(("batch", "b0"),))
        return t

    assert build("xy").canonical_form() != build("yx").canonical_form()


def test_events_dependent_same_task_and_footprint():
    e1 = Event(0, "a", "t1", "crank", frozenset({("n", 1)}), frozenset(), ())
    e2 = Event(1, "b", "t1", "crank", frozenset(), frozenset(), ())
    e3 = Event(2, "c", "t2", "crank", frozenset(), frozenset({("n", 1)}), ())
    e4 = Event(3, "d", "t3", "crank", frozenset({("m", 9)}), frozenset(), ())
    assert events_dependent(e1, e2)  # same task
    assert events_dependent(e1, e3)  # write/read conflict
    assert not events_dependent(e1, e4)  # disjoint everything


# ---------------------------------------------------------------------------
# Honest seams: smoke sweep agrees (tier-1 subset of the full sweep)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("target,n,max_runs", [
    ("pipeline", 4, 30),
    ("traffic", 4, 20),
    ("virtualnet", 4, 40),
    ("shard", 4, 40),
])
def test_smoke_sweep_schedule_independent(target, n, max_runs):
    ex = schedules.explore(target, n, seed=0, max_runs=max_runs)
    assert ex.ok, f"divergence on honest target {target}: {ex.divergence}"
    assert ex.runs > 1, "explorer never left the default schedule"
    assert ex.classes >= 2, "no schedule freedom explored"


def test_dpor_prunes_commuting_deliveries():
    # deliveries to different nodes without a causal edge commute: the
    # virtualnet target must prune a large share of the naive branches
    ex = schedules.explore("virtualnet", 4, seed=0, max_runs=40)
    assert ex.ok
    assert ex.pruned > 0, "DPOR reduction inactive"
    # and equivalence classes stay well below executed runs
    assert ex.classes < ex.runs + ex.pruned


def test_explorer_counts_equivalent_revisits_once():
    ex = schedules.explore("virtualnet", 4, seed=0, max_runs=40)
    # classes + revisits == runs (every executed run lands in a class)
    assert ex.classes + ex.revisits == ex.runs


# ---------------------------------------------------------------------------
# Seeded mutants: the detector's sensitivity fixtures
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mutant", MUTANT_NAMES)
def test_mutant_detected_with_minimized_counterexample(mutant, tmp_path):
    ex = schedules.explore(f"mutant:{mutant}", 4, seed=0, max_runs=60)
    assert not ex.ok, f"explorer went blind to mutant {mutant}"
    div = ex.divergence
    # minimized: non-empty, no trailing default choices
    assert div["choices"], "empty counterexample cannot diverge"
    assert div["choices"][-1] != 0
    assert div["first_divergence"]["index"] is not None
    # the counterexample file replays in-process to the same divergence
    cx = tmp_path / f"{mutant}.json"
    schedules.write_counterexample(cx, ex)
    rep = schedules.replay_counterexample(cx)
    assert rep["diverged"]
    assert rep["reproduced"], rep


def test_counter_mutant_reports_racing_pair():
    # the vector-clock probe names the schedule-sensitive state: the
    # divergent run must expose at least one concurrent conflicting pair
    ex = schedules.explore("mutant:counter", 4, seed=0, max_runs=60)
    assert not ex.ok
    assert ex.divergence["racing"], "no racing pair reported"


def test_replay_reproduces_in_fresh_process(tmp_path):
    """The counterexample written by one process re-runs to the identical
    divergence (fingerprint pair + first divergent event) in another."""
    ex = schedules.explore("mutant:accum", 4, seed=0, max_runs=60)
    assert not ex.ok
    cx = tmp_path / "cx.json"
    schedules.write_counterexample(cx, ex)
    proc = _cli("--replay", str(cx), "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["reproduced"] is True
    assert rep["first_divergence"] == ex.divergence["first_divergence"]


def test_replay_detects_non_reproduction(tmp_path):
    ex = schedules.explore("mutant:accum", 4, seed=0, max_runs=60)
    cx = tmp_path / "cx.json"
    schedules.write_counterexample(cx, ex)
    doc = json.loads(cx.read_text())
    doc["choices"] = []  # tampered: the default schedule cannot diverge
    cx.write_text(json.dumps(doc))
    proc = _cli("--replay", str(cx))
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_cli_mutant_exit_code_and_counterexample(tmp_path):
    cx = tmp_path / "cx.json"
    proc = _cli(
        "--target", "mutant:listener", "--n", "4", "--max-runs", "60",
        "--counterexample", str(cx),
    )
    assert proc.returncode == 1
    assert cx.exists()
    doc = json.loads(cx.read_text())
    assert doc["target"] == "mutant:listener"
    assert doc["reference_parts"] != doc["divergent_parts"]


# ---------------------------------------------------------------------------
# Determinism of the machinery itself
# ---------------------------------------------------------------------------


def test_run_schedule_fingerprints_are_deterministic():
    a = schedules.run_schedule("pipeline", 4, 0, [])
    b = schedules.run_schedule("pipeline", 4, 0, [])
    assert a.parts == b.parts
    assert a.fingerprint == b.fingerprint
    assert a.canonical == b.canonical
    # a different seed is a different reference (the fingerprint is real)
    c = schedules.run_schedule("pipeline", 4, 1, [])
    assert a.parts != c.parts


def test_fingerprint_includes_the_contracted_parts():
    r = schedules.run_schedule("pipeline", 4, 0, [])
    assert set(r.parts) >= {
        "batches_sha", "faults", "counters", "device_dispatches", "error"
    }
    assert r.parts["error"] == ""
    assert r.parts["device_dispatches"] >= 0


# ---------------------------------------------------------------------------
# CI entry point: one command, deterministic, under budget
# ---------------------------------------------------------------------------


def test_ci_entry_point_runs_clean_and_under_budget():
    """``tools/ci.sh`` (lint --ci + explorer smoke) exits 0 on the
    current tree, prints deterministic stage output, and stays well
    inside the tier-1 budget (the smoke sweep alone must be ≤30 s)."""
    t0 = time.monotonic()
    proc = subprocess.run(
        ["bash", str(REPO_ROOT / "tools" / "ci.sh")],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "lint: 0 new finding(s)" in proc.stdout
    assert "ok=True" in proc.stdout
    assert proc.stdout.strip().endswith("ci: ok")
    assert wall < 60.0, f"ci.sh took {wall:.1f}s"
    # deterministic output: a second run prints the identical transcript
    proc2 = subprocess.run(
        ["bash", str(REPO_ROOT / "tools" / "ci.sh")],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc2.stdout == proc.stdout


def test_explorer_smoke_cli_under_30s():
    t0 = time.monotonic()
    proc = _cli("--smoke")
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert wall < 30.0, f"smoke sweep took {wall:.1f}s"


# ---------------------------------------------------------------------------
# Slow arm: the full sweep (the acceptance bar lives here)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_sweep_explores_1000_schedules_and_agrees():
    # the CLI's --full and this acceptance bar share schedules.FULL_PLAN
    t0 = time.monotonic()
    total_classes = 0
    for target, n, max_runs in schedules.FULL_PLAN:
        ex = schedules.explore(target, n, seed=0, max_runs=max_runs)
        assert ex.ok, f"{target} n={n}: {ex.divergence}"
        total_classes += ex.classes
    assert total_classes >= 1000
    assert time.monotonic() - t0 < 300
