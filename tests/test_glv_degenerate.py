"""Adversarial degenerate-case tests for the GLV/GLS joint-table ladders.

The classic ladders exclude the unequal-add degenerate case structurally
(safe_scalar's proof); the joint-table ladders cannot — the short lattice
vectors put decomposed coordinates inside the prefix ranges, so crafted
scalars reach acc = ±T mid-ladder.  These tests drive exactly those
collisions and assert the select-routed complete add returns the correct
point (an incomplete add would produce finite-residue garbage and a wrong
group element, so correctness here is a sharp probe of the route).

Constructions (verified arithmetically in-test before the ladder runs):

* G1 doubling route: halves (k1, k2) = (7, λ+1).  At the final window
  step the accumulator multiplier is 4·(1 + λ·(λ+1)/4) = 4 + λ(λ+1) =
  r + 3 ≡ 3, and the selected table entry is w1 = 3 — acc == T, the
  P = Q case.  (λ+1 ≡ 0 mod 4 for BLS12-381, so (λ+1)/4 is an integer
  prefix; λ+1 exceeds the 2^127 Babai bound, which is WHY an adversary
  must hand-craft the halves — and why the ladder must not trust bounds.)
* G1 infinity route: halves (1, λ+1) → final-step accumulator ≡ −1 with
  table entry w1 = 1 — acc == −T, the P = −Q case; the whole product is
  r·P = ∞, so the ladder must output the point at infinity.
* G2 doubling route: quarters (3, 0, 3, |u|) with signs (+, +, −, −).
  The final-step collision 2·M − T = 1 − u² + u·u³ = r holds exactly
  (asserted in-test); expected product (2 − 2u²)·P.

The non-default ``HBBFT_TPU_FQ_IMPL`` arm runs the same module in a
subprocess (the impl binds at import) — both field implementations must
route the degenerate cases identically.
"""

import os
import random
import subprocess
import sys

import pytest

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import R
from hbbft_tpu.ops import curve, fq

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not curve.glv_enabled(), reason="GLV disabled in this environment"
)


def _ladder_g1(halves, negs, pts):
    import jax
    import numpy as np

    bits = curve.scalars_to_bits(
        [h for pair in halves for h in pair], curve.GLV_HALF_BITS
    ).reshape(len(halves), 2, curve.GLV_HALF_BITS)
    negs = np.array(negs, dtype=bool).reshape(len(halves), 2)
    return curve.g1_from_device(
        jax.jit(curve.g1_scalar_mul_signed)(curve.g1_to_device(pts), bits, negs)
    )


def test_g1_doubling_and_infinity_routes():
    lam = curve._G1_LAM
    assert (lam + 1) % 4 == 0
    # meta-check the crafted collisions: accumulator vs table multiplier
    # at the final step, doubling case acc ≡ T, infinity case acc ≡ −T
    acc_dbl = 4 * (1 + lam * ((lam + 1) // 4)) % R
    assert acc_dbl == 3 % R  # selected entry w1 = 3
    acc_inf = 4 * (0 + lam * ((lam + 1) // 4)) % R
    assert acc_inf == (R - 1) % R  # selected entry w1 = 1 → acc == −T

    rng = random.Random(17)
    p = gold.ec_mul(gold.FQ, rng.randrange(1, R), gold.G1_GEN)
    got = _ladder_g1(
        [(7, lam + 1), (1, lam + 1)],
        [(False, False), (False, False)],
        [p, p],
    )
    want_dbl = gold.ec_mul(gold.FQ, (7 + lam * (lam + 1)) % R, p)
    assert (7 + lam * (lam + 1)) % R == 6
    assert got[0] == want_dbl  # doubling route returned 6·P
    assert (1 + lam * (lam + 1)) % R == 0
    assert got[1] is None  # infinity route: r·P = ∞


def test_g2_doubling_route():
    u = curve._G2_U  # signed, negative for BLS12-381
    au = abs(u)
    assert au % 2 == 0
    # final-step collision: 2·M − T = 1 − u² + u·u³ = r exactly
    assert 1 - u * u + u * (u**3) == R
    k = (3 - 3 * u * u + u**4) % R
    assert k == (2 - 2 * u * u) % R

    rng = random.Random(23)
    p = gold.ec_mul(gold.FQ2, rng.randrange(1, R), gold.G2_GEN)
    import jax
    import numpy as np

    quarters = [3, 0, 3, au]
    bits = curve.scalars_to_bits(quarters, curve.GLS_QUARTER_BITS).reshape(
        1, 4, curve.GLS_QUARTER_BITS
    )
    negs = np.array([[False, False, True, True]])
    got = curve.g2_from_device(
        jax.jit(curve.g2_scalar_mul_signed)(curve.g2_to_device([p]), bits, negs)
    )
    assert got == [gold.ec_mul(gold.FQ2, k, p)]


def _rerun_module(extra_env: dict, tag: str) -> None:
    env = dict(os.environ)
    env.update(extra_env)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "-x",
            "-q",
            "-m",
            "not slow",
            os.path.join(_REPO, "tests", "test_glv_degenerate.py"),
        ],
        env=env,
        capture_output=True,
        text=True,
        cwd=_REPO,
        timeout=1800,
    )
    assert proc.returncode == 0, (
        f"degenerate-route tests failed under {tag}:\n"
        f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}"
    )


@pytest.mark.slow
def test_degenerate_routes_under_other_fq_impl():
    """Re-run this module's in-process tests under the non-default field
    implementation (import-time binding → subprocess), so the complete
    add's zero-test routing is proven on BOTH representations."""
    other = "limb" if fq.IMPL == "rns" else "rns"
    _rerun_module({"HBBFT_TPU_FQ_IMPL": other}, other)


@pytest.mark.slow
def test_degenerate_routes_under_int32_limb_width():
    """The legacy 11-bit int32 limb representation must drive the same
    routes: the table gather and zero probes run in int32 there, and a
    dtype promotion anywhere in the joint-table ladder breaks the scan
    carry at trace time (regression: the one-hot gather einsum used to
    promote int32 planes to f32)."""
    _rerun_module(
        {"HBBFT_TPU_FQ_IMPL": "limb", "HBBFT_TPU_FQ_BITS": "11"},
        "limb/int32 (BITS=11)",
    )
