"""Golden tests: JAX limb Fq arithmetic vs Python-int arithmetic mod Q.

Every device field op is checked against exact big-int math, including
adversarial limb patterns (all-max, negatives from deep subtraction chains)
— SURVEY.md §7 hard part 1 prescribes golden-testing every layer from the
first commit.
"""

import numpy as np
import pytest

from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq_limb as fq  # limb arm, independent of the rns facade default


def rnd_ints(rng, n):
    return [rng.randrange(Q) for _ in range(n)]


@pytest.fixture(scope="module")
def rng():
    import random

    return random.Random(1234)


def test_roundtrip(rng):
    for x in [0, 1, Q - 1, Q // 2] + rnd_ints(rng, 20):
        assert fq.to_int(fq.from_int(x)) == x % Q


def test_add_sub_neg(rng):
    xs = rnd_ints(rng, 32)
    ys = rnd_ints(rng, 32)
    a = fq.from_ints(xs)
    b = fq.from_ints(ys)
    assert fq.to_ints(np.asarray(fq.add(a, b))) == [(x + y) % Q for x, y in zip(xs, ys)]
    assert fq.to_ints(np.asarray(fq.sub(a, b))) == [(x - y) % Q for x, y in zip(xs, ys)]
    assert fq.to_ints(np.asarray(fq.neg(a))) == [(-x) % Q for x in xs]


def test_mul_batch(rng):
    xs = rnd_ints(rng, 64) + [0, 1, Q - 1, Q - 1]
    ys = rnd_ints(rng, 64) + [Q - 1, Q - 1, Q - 1, 0]
    a = fq.from_ints(xs)
    b = fq.from_ints(ys)
    got = fq.to_ints(np.asarray(fq.mul(a, b)))
    assert got == [(x * y) % Q for x, y in zip(xs, ys)]


def test_mul_lazy_inputs(rng):
    """Products of un-carried sums/differences must still be exact."""
    xs, ys, zs = (rnd_ints(rng, 16) for _ in range(3))
    a, b, c = fq.from_ints(xs), fq.from_ints(ys), fq.from_ints(zs)
    lazy1 = fq.add(fq.add(a, b), c)  # limbs up to ~3·BASE
    lazy2 = fq.sub(fq.sub(a, b), c)  # negative limbs
    got = fq.to_ints(np.asarray(fq.mul(lazy1, lazy2)))
    want = [
        ((x + y + z) * (x - y - z)) % Q for x, y, z in zip(xs, ys, zs)
    ]
    assert got == want


def test_mul_worst_case_limbs():
    """Worst in-domain lazy limbs stay exact through mul.

    All-max limbs in positions 0..FOLD_FROM-1 put the value right at the
    fold boundary; the negated variant exercises the signed path.
    """
    worst = np.zeros((4, fq.NLIMBS), dtype=fq.NP_DTYPE)
    worst[:2, : fq.FOLD_FROM] = fq.MASK
    worst[2:, : fq.FOLD_FROM] = -fq.MASK
    vals = [fq.to_int(w) for w in worst]
    got = fq.to_ints(np.asarray(fq.mul(worst, worst[::-1].copy())))
    assert got == [(a * b) % Q for a, b in zip(vals, vals[::-1])]


def test_value_bound_invariant(rng):
    """Lazy residues stay within limb bounds through long op chains."""
    xs = rnd_ints(rng, 8)
    a = fq.from_ints(xs)
    acc = a
    for _ in range(12):
        acc = fq.mul(fq.add(acc, a), fq.sub(acc, a))
    arr = np.asarray(acc)
    assert np.all(np.abs(arr) <= fq.BASE + 1)
    # exactness after the chain
    vals = xs[:]
    accv = xs[:]
    for _ in range(12):
        accv = [((v + x) * (v - x)) % Q for v, x in zip(accv, vals)]
    assert fq.to_ints(arr) == accv


def test_mul_small(rng):
    xs = rnd_ints(rng, 16)
    a = fq.from_ints(xs)
    for k in (0, 1, 2, 3, 4, 12, 32767):
        got = fq.to_ints(np.asarray(fq.mul_small(a, k)))
        assert got == [(x * k) % Q for x in xs]


def test_pow_and_inv(rng):
    xs = rnd_ints(rng, 4)
    a = fq.from_ints(xs)
    got = fq.to_ints(np.asarray(fq.pow_fixed(a, 65537)))
    assert got == [pow(x, 65537, Q) for x in xs]
    inv = fq.to_ints(np.asarray(fq.inv(a)))
    assert inv == [pow(x, -1, Q) for x in xs]


def test_jit_and_vmap(rng):
    import jax
    import jax.numpy as jnp

    xs = rnd_ints(rng, 8)
    ys = rnd_ints(rng, 8)
    a = jnp.asarray(fq.from_ints(xs))
    b = jnp.asarray(fq.from_ints(ys))
    f = jax.jit(fq.mul)
    assert fq.to_ints(np.asarray(f(a, b))) == [(x * y) % Q for x, y in zip(xs, ys)]
    g = jax.jit(jax.vmap(fq.mul))
    assert fq.to_ints(np.asarray(g(a, b))) == [(x * y) % Q for x, y in zip(xs, ys)]
