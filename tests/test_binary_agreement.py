"""BinaryAgreement integration tests (reference `tests/binary_agreement.rs` §).

All correct nodes must decide the same bit; if all correct nodes propose the
same value, that value is decided (validity).  Exercised under reordering and
silent-fault adversaries, in eager and round-batched crypto modes.
"""

import pytest

from hbbft_tpu.net.adversary import NodeOrderAdversary, ReorderingAdversary, SilentAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement


def build(n, f=0, adversary=None, defer_mode="eager", seed=0):
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .defer_mode(defer_mode)
        .crank_limit(200_000)
        .using(lambda ni, be: BinaryAgreement(ni, be, session_id=b"test-ba"))
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


def decisions(net):
    return {node.id: node.outputs for node in net.correct_nodes()}


def assert_agreement(net, expected=None):
    ds = decisions(net)
    assert all(len(v) == 1 for v in ds.values()), f"outputs: {ds}"
    vals = {v[0] for v in ds.values()}
    assert len(vals) == 1, f"disagreement: {ds}"
    if expected is not None:
        assert vals == {expected}


@pytest.mark.parametrize("n", [1, 2, 4])
@pytest.mark.parametrize("value", [True, False])
def test_unanimous_input_decides_that_value(n, value):
    net = build(n)
    net.broadcast_input(value)
    net.crank_to_quiescence()
    assert_agreement(net, expected=value)


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("defer_mode", ["eager", "round"])
def test_mixed_inputs_agree(seed, defer_mode):
    net = build(4, f=1, defer_mode=defer_mode, seed=seed)
    for i in sorted(net.nodes):
        net.send_input(i, i % 2 == 0)
    if defer_mode == "round":
        while net.queue or net._pending_work:
            net.crank_round()
    else:
        net.crank_to_quiescence()
    assert_agreement(net)


@pytest.mark.parametrize("adversary_cls", [ReorderingAdversary, NodeOrderAdversary])
@pytest.mark.parametrize("seed", range(4))
def test_adversarial_scheduling(adversary_cls, seed):
    net = build(7, f=2, adversary=adversary_cls(), seed=seed)
    for i in sorted(net.nodes):
        net.send_input(i, i % 3 == 0)
    net.crank_to_quiescence()
    assert_agreement(net)


@pytest.mark.parametrize("seed", range(4))
def test_silent_faulty_minority(seed):
    net = build(7, f=2, adversary=SilentAdversary(), seed=seed)
    for i in sorted(net.nodes):
        net.send_input(i, i % 2 == 1)
    net.crank_to_quiescence()
    assert_agreement(net)


def test_larger_net():
    net = build(10, f=3, adversary=ReorderingAdversary(), seed=13)
    for i in sorted(net.nodes):
        net.send_input(i, i < 5)
    net.crank_to_quiescence()
    assert_agreement(net)
