"""Static phase-label registry guard: the critpath phase vocabulary is
CLOSED.  Every ``stamp(...)`` call site across the protocols, engine,
and net layers must pass a literal phase from ``critpath.PHASES``
(checked by AST walk, so a typo'd or drifted label fails here instead of
raising mid-soak), every phase bills exactly one tracer span category,
and the dependency-free inline twins in ``tools/trace_report.py`` (which
must not import the package) stay pinned to the registry."""

import ast
from pathlib import Path

from hbbft_tpu.obs import critpath, flight
from tools import trace_report

REPO = Path(__file__).resolve().parent.parent

#: every module that may stamp critpath phases (the AST sweep below
#: walks these whole directories, so a NEW stamp call site is guarded
#: automatically)
STAMP_SCOPES = ("hbbft_tpu/protocols", "hbbft_tpu/engine", "hbbft_tpu/net",
                "hbbft_tpu/obs")


def _stamp_literals():
    """(path, lineno, literal) for every ``stamp("...")``-shaped call —
    plain ``stamp(...)``, ``_critpath.stamp(...)``, ``rec.stamp(...)``,
    ``critpath.stamp(...)`` — with a string-literal first argument."""
    out = []
    for scope in STAMP_SCOPES:
        for path in sorted((REPO / scope).rglob("*.py")):
            tree = ast.parse(path.read_text(), filename=str(path))
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = (
                    fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None
                )
                if name != "stamp" or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    out.append((str(path.relative_to(REPO)), node.lineno, arg.value))
    return out


def test_every_stamp_call_site_uses_a_registered_phase():
    sites = _stamp_literals()
    # the protocol seams must actually be instrumented: RBC decode, BA
    # decision, coin reveal, decrypt combine, batch commit all stamp
    stamped = {phase for _, _, phase in sites}
    assert {
        "rbc.output", "ba.decide", "coin.reveal",
        "decrypt.combine", "epoch.commit",
    } <= stamped, sorted(stamped)
    bad = [s for s in sites if s[2] not in critpath.PHASES]
    assert not bad, f"unregistered phase literals: {bad}"


def test_phase_registry_is_closed_and_total():
    assert len(critpath.PHASES) == len(set(critpath.PHASES))
    # every phase bills exactly one tracer span category
    assert set(critpath.PHASE_SPAN_CATS) == set(critpath.PHASES)
    # the engine's phase-stamp keys resolve into the registry
    assert set(critpath._ENGINE_PHASES.values()) <= set(critpath.PHASES)


def test_trace_report_inline_twins_stay_pinned():
    # tools/trace_report.py is dependency-free by contract (its helpers
    # import into the test suite without hbbft_tpu), so it carries
    # COPIES of the registry — this is the cross-check that keeps them
    # from drifting
    assert trace_report.CRITPATH_PHASES == critpath.PHASES
    assert trace_report.SPAN_CAT_PHASES == {
        cat: phase for phase, cat in critpath.PHASE_SPAN_CATS.items()
    }
    assert trace_report.REQUIRED_FORENSICS_KEYS == flight.REQUIRED_BUNDLE_KEYS


def test_trace_report_imports_nothing_from_the_package():
    tree = ast.parse((REPO / "tools" / "trace_report.py").read_text())
    for node in ast.walk(tree):
        mods = []
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mods = [node.module or ""]
        for m in mods:
            assert not m.startswith("hbbft_tpu"), (
                f"trace_report.py imports {m}: it must stay dependency-free"
            )


def test_forensics_validators_agree():
    # the inline validator and obs/flight.validate_bundle must render
    # the same verdict on the same bundles
    fr = flight.FlightRecorder(epochs=2)
    fr.record(0, events=[
        {"phase": "rbc.output", "node": 0, "instance": 0, "round": None,
         "epoch": None, "crank": 1, "now": 1},
        {"phase": "epoch.commit", "node": 0, "instance": None, "round": None,
         "epoch": 0, "crank": 5, "now": 5},
    ])
    good = fr.bundle("verdict_failure")
    assert flight.validate_bundle(good) == []
    assert trace_report.validate_forensics(good) == []
    bad = dict(good)
    bad["critical_path"] = {
        "gate": None, "gating": {"rbc.echo": 1.0}, "paths": [],
    }
    assert bool(flight.validate_bundle(bad)) == bool(
        trace_report.validate_forensics(bad)
    )
    del bad["frames"]
    assert bool(flight.validate_bundle(bad)) == bool(
        trace_report.validate_forensics(bad)
    )
