"""Golden tests for the fused Pallas Fq-mul kernel (interpret mode on CPU).

The real-TPU path is exercised by bench.py and the driver; here the kernel
runs under the Pallas interpreter against Python-int golden values,
including lazy/negative inputs and vmap batching.
"""

import random

import numpy as np
import pytest

import jax

from hbbft_tpu.crypto.field import Q
from hbbft_tpu.ops import fq_pallas
from hbbft_tpu.ops import fq_limb as fq  # limb arm, independent of the rns facade default


@pytest.fixture(scope="module")
def rng():
    return random.Random(31)


def test_matches_golden(rng):
    xs = [rng.randrange(Q) for _ in range(6)] + [0, 1, Q - 1]
    ys = [rng.randrange(Q) for _ in range(6)] + [Q - 1, 0, Q - 1]
    a = fq.from_ints(xs)
    b = fq.from_ints(ys)
    got = fq.to_ints(np.asarray(fq_pallas.mul(a, b, interpret=True)))
    assert got == [(x * y) % Q for x, y in zip(xs, ys)]


def test_lazy_and_negative_inputs(rng):
    xs = [rng.randrange(Q) for _ in range(4)]
    ys = [rng.randrange(Q) for _ in range(4)]
    a, b = fq.from_ints(xs), fq.from_ints(ys)
    lazy = fq.add(fq.add(a, b), a)
    neg = fq.sub(b, fq.add(a, a))
    got = fq.to_ints(np.asarray(fq_pallas.mul(lazy, neg, interpret=True)))
    want = [((2 * x + y) * (y - 2 * x)) % Q for x, y in zip(xs, ys)]
    assert got == want


def test_vmap(rng):
    xs = [[rng.randrange(Q) for _ in range(3)] for _ in range(2)]
    ys = [[rng.randrange(Q) for _ in range(3)] for _ in range(2)]
    a = np.stack([fq.from_ints(r) for r in xs])
    b = np.stack([fq.from_ints(r) for r in ys])
    f = jax.vmap(lambda u, v: fq_pallas.mul(u, v, interpret=True))
    out = np.asarray(f(a, b))
    for i in range(2):
        for j in range(3):
            assert fq.to_int(out[i, j]) == (xs[i][j] * ys[i][j]) % Q


def test_pow_fixed_kernel(rng):
    """The in-kernel square-and-multiply chain (fori_loop over a
    scalar-prefetched bit schedule) matches Python pow, including the
    Fermat-inverse exponent that dominates final exponentiation."""
    xs = [rng.randrange(1, Q) for _ in range(5)] + [1, Q - 1]
    a = fq.from_ints(xs)
    for e in (1, 2, 3, 0b101101, Q - 2):
        got = fq.to_ints(np.asarray(fq_pallas.pow_fixed(a, e, interpret=True)))
        assert got == [pow(x, e, Q) for x in xs], hex(e)


def test_pow_fixed_kernel_lazy_input(rng):
    xs = [rng.randrange(Q) for _ in range(4)]
    ys = [rng.randrange(Q) for _ in range(4)]
    lazy = fq.add(fq.from_ints(xs), fq.from_ints(ys))
    got = fq.to_ints(np.asarray(fq_pallas.pow_fixed(lazy, 7, interpret=True)))
    assert got == [pow(x + y, 7, Q) for x, y in zip(xs, ys)]


def test_all_conv_modes_match_golden(rng, monkeypatch):
    """Every convolution strategy (concat / scratch / grouped) computes the
    same product — the modes exist only for on-chip A/B timing."""
    xs = [rng.randrange(fq.Q) for _ in range(8)]
    ys = [rng.randrange(fq.Q) for _ in range(8)]
    a, b = fq.from_ints(xs), fq.from_ints(ys)
    want = [(x * y) % fq.Q for x, y in zip(xs, ys)]
    for mode in ("concat", "scratch", "grouped"):
        monkeypatch.setattr(fq_pallas, "_CONV_MODE", mode)
        got = fq.to_ints(np.asarray(fq_pallas.mul(a, b, interpret=True)))
        assert got == want, mode
