"""ThresholdDecrypt integration tests (reference shape: SURVEY.md §4)."""

import random

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.net.adversary import ReorderingAdversary, SilentAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecrypt

MSG = b"the secret plaintext payload"


def build_with_ct(n, f=0, adversary=None, defer_mode="eager", seed=0):
    """Build a net of ThresholdDecrypt instances sharing one ciphertext."""
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .defer_mode(defer_mode)
        .using(lambda ni, be: ThresholdDecrypt(ni, be))
    )
    if adversary:
        b = b.adversary(adversary)
    net = b.build(seed=seed)
    pk_set = net.nodes[0].algorithm.netinfo.public_key_set
    ct = pk_set.encrypt(MSG, random.Random(seed + 1000))
    for nid in sorted(net.nodes):
        node = net.nodes[nid]
        step = node.algorithm.set_ciphertext(ct)
        net._process_step(node, step)
    return net, ct


@pytest.mark.parametrize("n,f", [(1, 0), (4, 1), (7, 2)])
@pytest.mark.parametrize("defer_mode", ["eager", "round"])
def test_all_decrypt_same(n, f, defer_mode):
    net, _ = build_with_ct(n, f, defer_mode=defer_mode)
    net.broadcast_input(None)
    if defer_mode == "round":
        while net.queue or net._pending_work:
            net.crank_round()
    else:
        net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [MSG]


@pytest.mark.parametrize("seed", range(4))
def test_silent_faulty(seed):
    net, _ = build_with_ct(7, 2, adversary=SilentAdversary(), seed=seed)
    net.broadcast_input(None)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [MSG]


def test_shares_before_ciphertext_are_buffered():
    """A node that learns the ciphertext late still decrypts."""
    b = (
        NetBuilder(range(4))
        .num_faulty(1)
        .using(lambda ni, be: ThresholdDecrypt(ni, be))
    )
    net = b.build(seed=3)
    pk_set = net.nodes[0].algorithm.netinfo.public_key_set
    ct = pk_set.encrypt(MSG, random.Random(7))
    # Only nodes 0-2 get the ciphertext now.
    for nid in [0, 1, 2]:
        node = net.nodes[nid]
        net._process_step(node, node.algorithm.set_ciphertext(ct))
        net._process_step(node, node.algorithm.start_decryption())
    # Deliver everything: node 3's shares buffer (no ct yet).
    net.crank_to_quiescence()
    assert net.nodes[3].outputs == []
    # Late ciphertext: buffered shares drain and it catches up.
    node3 = net.nodes[3]
    net._process_step(node3, node3.algorithm.set_ciphertext(ct))
    net._process_step(node3, node3.algorithm.start_decryption())
    net.crank_to_quiescence()
    assert node3.outputs == [MSG]


def test_invalid_ciphertext_rejected():
    from hbbft_tpu.crypto.keys import Ciphertext

    backend = MockBackend()
    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .backend(backend)
        .using(lambda ni, be: ThresholdDecrypt(ni, be))
        .build(seed=5)
    )
    pk_set = net.nodes[0].algorithm.netinfo.public_key_set
    good = pk_set.encrypt(MSG, random.Random(1))
    # Tamper W so the validity pairing fails.
    bad = Ciphertext(backend.group, good.u, good.v, backend.group.g2_mul(3, good.w))
    node = net.nodes[0]
    net._process_step(node, node.algorithm.set_ciphertext(bad))
    assert node.algorithm.terminated()
    assert node.outputs == []


def test_corrupted_share_flagged_and_tolerated():
    from hbbft_tpu.crypto.keys import DecryptionShare
    from hbbft_tpu.net.adversary import RandomAdversary
    from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecryptMessage

    def garbage(net, msg):
        el = net.backend.group.hash_to_g1(bytes([net.rng.randrange(256)]))
        return ThresholdDecryptMessage(DecryptionShare(net.backend.group, el))

    b = (
        NetBuilder(range(4))
        .num_faulty(1)
        .adversary(RandomAdversary(garbage, p_replace=1.0))
        .using(lambda ni, be: ThresholdDecrypt(ni, be))
    )
    net = b.build(seed=11)
    pk_set = net.nodes[0].algorithm.netinfo.public_key_set
    ct = pk_set.encrypt(MSG, random.Random(2))
    for nid in sorted(net.nodes):
        node = net.nodes[nid]
        net._process_step(node, node.algorithm.set_ciphertext(ct))
    net.broadcast_input(None)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert node.outputs == [MSG]
    faults = [f for n in net.correct_nodes() for f in n.faults_observed]
    assert any(f.kind == "threshold_decrypt:invalid_share" for f in faults)
