"""Golden tests for the opt-in HBBFT_TPU_FUSE2 whole-loop kernels
(single-launch Miller loop / x-chain pow, pairing_fused._miller_full_call
and _pow_chain_call).

The default tests drive the kernels with SMALL segment plans / exponents
against references composed from the already-golden-tested building
blocks (`_step_call` + `_miller_add_step`, `_cyclo_run_call` +
`_mul12_call`) — the kernel bodies are identical for any plan, so a small
plan validates the double-step, the mixed-addition step, and the segment
plumbing in minutes instead of hours (the full 63-bit schedule in CPU
interpret mode exceeded a 50-minute budget).

The full-width end-to-end golden (whole verification equation through the
FUSE2 path) is gated behind HBBFT_TPU_FUSE2_FULL_GOLDENS=1 — run it
one-off before flipping FUSE2 on by default."""

import os
import random

import pytest

import jax
import jax.numpy as jnp

from hbbft_tpu.crypto import bls381 as gold
from hbbft_tpu.crypto.field import R as SUBR
from hbbft_tpu.ops import pairing, pairing_fused, tower


@pytest.fixture(scope="module", autouse=True)
def small_tile():
    calls = (
        pairing_fused._step_call,
        pairing_fused._cyclo_run_call,
        pairing_fused._mul12_call,
        pairing_fused._miller_full_call,
        pairing_fused._pow_chain_call,
    )
    old = pairing_fused.TILE
    pairing_fused.TILE = 8
    for c in calls:
        c.cache_clear()
    yield
    pairing_fused.TILE = old
    for c in calls:
        c.cache_clear()


@pytest.fixture(scope="module")
def rng():
    return random.Random(31)


@pytest.fixture(scope="module")
def points(rng):
    quads = []
    for a in (rng.randrange(1, SUBR), 1):
        quads.append(
            (
                gold.ec_mul(gold.FQ, a, gold.G1_GEN),
                gold.ec_mul(gold.FQ2, (a * 5 + 2) % SUBR, gold.G2_GEN),
            )
        )
    P = pairing.g1_affine_to_device([q[0] for q in quads])
    Qa = pairing.g2_affine_to_device([q[1] for q in quads])
    return P, Qa


def _ref_miller_custom(segments, P, Qa):
    """Reference Miller value for an arbitrary segment plan, composed from
    the per-step scan primitives (golden-tested in test_pairing_fused.py /
    test_pairing_jax.py)."""
    xP, yP, _ = P
    xQ, yQ, _ = Qa
    batch_shape = jnp.asarray(xP).shape[:-1]
    one2 = tower.fq2_broadcast(tower.FQ2_ONE, batch_shape)
    Rj = (xQ, yQ, one2, jnp.zeros(batch_shape, dtype=bool))
    Qj = (xQ, yQ, one2, jnp.zeros(batch_shape, dtype=bool))
    f = tower.fq12_broadcast_one(batch_shape)
    for run, add_after in segments:
        for _ in range(run):
            f, Rj = pairing._miller_double_step(f, Rj, xP, yP)
        if add_after:
            f, Rj = pairing._miller_add_step(f, Rj, Qa, Qj, xP, yP)
    return f


# A plan that exercises every structural feature: multiple runs of
# different lengths, an addition between them, and a trailing no-add run.
_SMALL_PLAN = ((1, True), (2, True), (3, False))


def test_miller_full_kernel_small_plan(points):
    P, Qa = points
    want = _ref_miller_custom(_SMALL_PLAN, P, Qa)

    xP, yP, _ = P
    xQ, yQ, _ = Qa
    lanes = 2
    q = pairing_fused.pack_rows([xQ[0], xQ[1], yQ[0], yQ[1]], lanes)
    pq = pairing_fused.pack_rows([xP, yP], lanes)
    fold = jnp.asarray(pairing_fused._FOLD_T)
    out = pairing_fused._miller_full_call(_SMALL_PLAN, 1, True)(q, pq, fold)
    got = pairing_fused.unpack_f12(out, lanes)
    for i in range(lanes):
        assert tower.fq12_to_ints(got, i) == tower.fq12_to_ints(want, i)


def test_pow_chain_kernel_small_exponent(points):
    P, Qa = points
    mw = pairing_fused.miller_loop(P, Qa)
    # Easy part → a genuinely cyclotomic element.
    m = tower.fq12_mul(tower.fq12_conj(mw), tower.fq12_inv(mw))
    m = tower.fq12_mul(tower.fq12_frobenius_n(m, 2), m)
    pm = pairing_fused.pack_rows(pairing_fused._leaves_f12(m), 2)
    fold = jnp.asarray(pairing_fused._FOLD_T)

    # exponent 0b1001101: runs+multiplies in every combination.
    exp = 0b1001101
    want = pm
    for run, mult in pairing_fused._segments(exp):
        want = pairing_fused._cyclo_run_call(run, 1, True)(want, fold)
        if mult:
            want = pairing_fused._mul12_call(1, True)(want, pm, fold)
    got = pairing_fused._pow_chain_call(exp, 1, True)(pm, fold)
    wu = pairing_fused.unpack_f12(want, 2)
    gu = pairing_fused.unpack_f12(got, 2)
    for i in range(2):
        assert tower.fq12_to_ints(gu, i) == tower.fq12_to_ints(wu, i)


@pytest.mark.skipif(
    not os.environ.get("HBBFT_TPU_FUSE2_FULL_GOLDENS"),
    reason="full 63-bit FUSE2 goldens take >1h in CPU interpret mode; "
    "run with HBBFT_TPU_FUSE2_FULL_GOLDENS=1 before enabling FUSE2",
)
def test_fuse2_full_verification_end_to_end(monkeypatch):
    """FE(ML(−G1, aG2)·ML(aG1, G2)) == 1 composed on the FUSE2 kernels."""
    monkeypatch.setenv("HBBFT_TPU_FUSE2", "1")
    args = pairing.example_verify_batch(2, distinct=2)
    f = tower.fq12_mul(
        pairing_fused.miller_loop(args[0], args[1]),
        pairing_fused.miller_loop(args[2], args[3]),
    )
    out = pairing_fused.final_exp_fast(f)
    for i in range(2):
        assert pairing.is_one_host(out, i)
