"""Cross-check the two Fq limb representations.

The default build uses 8-bit limbs in float32 (MXU/VPU-rate path); the
11-bit int32 representation is kept as an independent implementation of the
same field (SURVEY.md §7 hard part 1: golden-test every layer).  The limb
width is fixed at import time by HBBFT_TPU_FQ_BITS, so the non-default
width runs in a subprocess.
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("bits", ["8", "11"])
def test_fq_suite_under_width(bits):
    env = dict(os.environ)
    env["HBBFT_TPU_FQ_BITS"] = bits
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "-x",
            "-q",
            os.path.join(_REPO, "tests", "test_fq_jax.py"),
            os.path.join(_REPO, "tests", "test_fq_pallas.py"),
        ],
        env=env,
        capture_output=True,
        text=True,
        cwd=_REPO,
        timeout=1200,
    )
    assert proc.returncode == 0, (
        f"fq suite failed under {bits}-bit limbs:\n{proc.stdout[-3000:]}"
        f"\n{proc.stderr[-2000:]}"
    )
