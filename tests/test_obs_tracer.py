"""Tracer golden tests: Chrome trace-event schema, span discipline,
zero-cost disabled mode, export round-trips."""

import json

import pytest

from hbbft_tpu.obs.tracer import Tracer
from tools.trace_report import (
    REQUIRED_KEYS,
    device_span_seconds,
    load_events,
    validate_chrome_trace,
)


def _fake_clock(start=100.0, step=0.001):
    t = [start]

    def clock():
        t[0] += step
        return t[0]

    return clock


def _sample_tracer():
    tr = Tracer(clock=_fake_clock())
    tr.begin("epoch:0", cat="epoch", epoch=0)
    tr.begin("subset", cat="subset")
    t0, t1 = tr.clock(), tr.clock()
    tr.complete("dispatch:pairing", t0, t1, cat="pairing", track="device",
                items=64, device=True)
    tr.end()  # subset
    tr.end()  # epoch
    tr.hist("dispatch_batch_items").record(64)
    return tr


def test_golden_chrome_trace_schema(tmp_path):
    tr = _sample_tracer()
    path = str(tmp_path / "trace.json")
    tr.write(path)
    doc = json.load(open(path))
    assert set(doc) >= {"traceEvents", "displayTimeUnit", "otherData"}
    events = doc["traceEvents"]
    # required keys on every event, monotonic ts, matched B/E pairs
    assert validate_chrome_trace(events) == []
    for ev in events:
        assert all(k in ev for k in REQUIRED_KEYS)
    # thread-name metadata labels every track
    names = {
        ev["args"]["name"]
        for ev in events
        if ev["ph"] == "M" and ev["name"] == "thread_name"
    }
    assert {"main", "device"} <= names
    # the device dispatch span round-trips with its duration intact
    assert device_span_seconds(load_events(path)) == pytest.approx(
        0.001, rel=1e-6
    )
    # histograms ride in otherData
    assert "dispatch_batch_items" in doc["otherData"]["histograms"]


def test_spans_nest_and_mismatch_raises():
    tr = Tracer()
    with pytest.raises(ValueError):
        tr.end()  # no open span
    tr.begin("a")
    with pytest.raises(ValueError):
        # retroactive complete may not interleave with an open stack
        tr.complete("x", 0.0, 1.0)
    tr.end()
    tr.complete("x", tr.clock(), tr.clock())  # fine once the stack is empty


def test_write_chrome_refuses_open_spans(tmp_path):
    tr = Tracer()
    tr.begin("open")
    with pytest.raises(ValueError):
        tr.write_chrome(str(tmp_path / "t.json"))
    tr.end()
    tr.write_chrome(str(tmp_path / "t.json"))  # closed: fine


def test_disabled_spans_are_noops_histograms_live():
    tr = Tracer(spans=False)
    tr.begin("a")
    tr.end()
    tr.complete("b", 0.0, 1.0)
    assert len(tr) == 0
    tr.hist("lat").record(5.0)
    assert tr.hist_summary()["lat"]["count"] == 1


def test_capacity_drops_whole_spans_and_stays_valid(tmp_path):
    tr = Tracer(capacity=4)
    for i in range(6):
        tr.begin(f"s{i}")
    for _ in range(6):
        tr.end()
    # 4 Bs fit; their Es close unconditionally (E of a recorded B is
    # never dropped — an unclosed span would fail the validator); the 2
    # overflow spans drop as whole B/E pairs
    assert len(tr) == 8
    assert tr.dropped == 4
    tr.complete("pair", tr.clock(), tr.clock())  # over capacity: drops both
    assert len(tr) == 8 and tr.dropped == 6
    path = str(tmp_path / "t.json")
    tr.write(path)
    assert validate_chrome_trace(load_events(path)) == []


def test_span_context_manager_and_jsonl(tmp_path):
    tr = Tracer()
    with tr.span("outer", cat="epoch"):
        with tr.span("inner"):
            pass
    path = str(tmp_path / "trace.jsonl")
    tr.write(path)
    lines = [json.loads(line) for line in open(path)]
    assert [e["ph"] for e in lines] == ["B", "B", "E", "E"]
    assert lines[0]["name"] == "outer" and lines[1]["name"] == "inner"
    assert validate_chrome_trace(load_events(path)) == []


def test_tracks_get_distinct_tids():
    tr = Tracer()
    tr.begin("a", track="main")
    tr.begin("b", track="ba/0")
    tr.end(track="ba/0")
    tr.end(track="main")
    tids = {ev["tid"] for ev in tr.events}
    assert len(tids) == 2
