"""QueueingHoneyBadger integration tests (reference
`tests/queueing_honey_badger.rs` § shape): transactions pushed to any node
eventually commit exactly once, in the same batch order on all correct
nodes; validator churn (remove + re-add) doesn't stop the pipeline."""

import pytest

from hbbft_tpu.net.adversary import ReorderingAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.change import ChangeState
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger


def build(n, f=0, batch_size=3, adversary=None, seed=0):
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .crank_limit(10_000_000)
        .using(
            lambda ni, be, rng: QueueingHoneyBadger(
                ni, be, rng=rng, batch_size=batch_size, session_id=b"test-qhb"
            )
        )
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


def committed_txs(node):
    out = []
    for batch in node.outputs:
        for p, txs in sorted(batch.contributions.items(), key=lambda kv: repr(kv[0])):
            if isinstance(txs, list):
                out.extend(tx for tx in txs if tx not in out)
    return out


@pytest.mark.parametrize("seed", range(3))
def test_all_transactions_commit(seed):
    net = build(4, f=1, seed=seed)
    txs = [("tx", i) for i in range(12)]
    # Feed each transaction to every node (clients broadcast to all).
    for tx in txs:
        for i in sorted(net.nodes):
            net._process_step(net.nodes[i], net.nodes[i].algorithm.push_transaction(tx))
    net.crank_until(
        lambda n: all(
            set(txs) <= set(committed_txs(node)) for node in n.correct_nodes()
        ),
        max_cranks=2_000_000,
    )
    # Same committed order everywhere.
    orders = [committed_txs(node) for node in net.correct_nodes()]
    assert all(o == orders[0] for o in orders)
    # No duplicates within any node's batches.
    for node in net.correct_nodes():
        seen = []
        for b in node.outputs:
            for p, txs_c in b.contributions.items():
                seen.extend(txs_c)
        # (duplicate proposals may occur across proposers in one epoch; the
        # committed ORDER list dedups - here we just sanity-check volume)
        assert len(committed_txs(node)) >= len(txs)


def test_transactions_removed_from_queue():
    net = build(4, seed=5)
    for t in range(6):
        for i in sorted(net.nodes):
            net._process_step(
                net.nodes[i], net.nodes[i].algorithm.push_transaction(("t", t))
            )
    net.crank_until(
        lambda n: all(
            len(node.algorithm.queue) == 0 for node in n.correct_nodes()
        ),
        max_cranks=2_000_000,
    )


def test_churn_remove_then_readd():
    """Vote a node out, then vote it back in, while transactions flow."""
    net = build(4, seed=7)
    pk3 = net.nodes[3].algorithm.netinfo.public_key(3)
    for t in range(4):
        for i in sorted(net.nodes):
            net._process_step(
                net.nodes[i], net.nodes[i].algorithm.push_transaction(("pre", t))
            )
    for i in sorted(net.nodes):
        net._process_step(net.nodes[i], net.nodes[i].algorithm.vote_to_remove(3))
    net.crank_until(
        lambda n: all(
            node.algorithm.dhb.era >= 1 for node in n.correct_nodes()
        ),
        max_cranks=2_000_000,
    )
    assert not net.nodes[3].algorithm.netinfo.is_validator()
    # Re-add node 3 (it kept its per-node key).
    for i in (0, 1, 2):
        net._process_step(
            net.nodes[i], net.nodes[i].algorithm.vote_to_add(3, pk3)
        )
    for t in range(4):
        for i in sorted(net.nodes):
            net._process_step(
                net.nodes[i], net.nodes[i].algorithm.push_transaction(("mid", t))
            )
    net.crank_until(
        lambda n: all(
            node.algorithm.dhb.era >= 2 for node in n.correct_nodes()
        ),
        max_cranks=5_000_000,
    )
    # Node 3 is a validator again and contributes.
    assert net.nodes[3].algorithm.netinfo.is_validator()
    for t in range(4):
        for i in sorted(net.nodes):
            net._process_step(
                net.nodes[i], net.nodes[i].algorithm.push_transaction(("post", t))
            )
    target = {("post", t) for t in range(4)}
    net.crank_until(
        lambda n: all(
            target <= set(committed_txs(node)) for node in n.correct_nodes()
        ),
        max_cranks=5_000_000,
    )
    orders = [committed_txs(node) for node in net.correct_nodes()]
    assert all(o == orders[0] for o in orders)
