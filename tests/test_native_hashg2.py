"""Native hash-to-G2 kernel vs the pure golden path.

The C kernel (native/hashg2_kernel.c) must be point-for-point identical
with crypto/bls381._hash_to_g2_pure — same try-and-increment schedule,
same deterministic sign choice, same Budroni-Pintore clearing — because
call sites treat the two as interchangeable (signatures hash-compare
across backends).  The loader's own golden self-test guards first use;
these tests pin the contract in CI and the env kill-switch.
"""

import os
import subprocess
import sys

import pytest

import hbbft_tpu.crypto.bls381 as B
from hbbft_tpu import native

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def kernel():
    if native.hashg2(b"probe", pure_fn=B._hash_to_g2_pure) is None:
        pytest.skip("no C toolchain / kernel unavailable")
    return True


def test_matches_pure_on_varied_docs(kernel):
    docs = [
        b"",
        b"a",
        b"doc-needing-retries-0",
        b"x" * 55,   # single-block boundary
        b"y" * 56,   # padding spills to a second block
        b"z" * 64,
        bytes(range(256)),
        b"coin" * 300,
    ]
    for d in docs:
        assert B.hash_to_g2(d) == B._hash_to_g2_pure(d), d[:16]


def test_results_are_in_subgroup(kernel):
    for i in range(4):
        p = B.hash_to_g2(b"subgroup-%d" % i)
        assert B.g2_on_curve(p) and B.g2_in_subgroup(p)


def test_env_kill_switch_forces_pure_path():
    """HBBFT_TPU_NO_NATIVE_HASHG2 must disable the kernel (and the pure
    path alone must still serve hash_to_g2) — checked in a subprocess
    because the loader caches its decision at first use."""
    code = (
        "import hbbft_tpu.crypto.bls381 as B\n"
        "from hbbft_tpu import native\n"
        "p = B.hash_to_g2(b'kill-switch')\n"
        "assert native._hg2_lib is None\n"
        "assert p == B._hash_to_g2_pure(b'kill-switch')\n"
        "print('pure-only OK')\n"
    )
    env = dict(os.environ)
    env["HBBFT_TPU_NO_NATIVE_HASHG2"] = "1"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, text=True, cwd=_REPO, timeout=300,
    )
    assert proc.returncode == 0 and "pure-only OK" in proc.stdout, (
        proc.stdout[-500:], proc.stderr[-1000:]
    )
