"""Epoch critical-path reconstruction (obs/critpath.py): the stamp
hook, per-epoch chain walking, crash:recovery attribution, engine
phase-stamp collapse, run-level gating histograms, and the run_cell
integration (fingerprint identity obs on/off, seeded-replay identity).
"""

import pytest

from hbbft_tpu.net.scenarios import Cell, run_cell
from hbbft_tpu.obs import critpath
from hbbft_tpu.obs.critpath import (
    PHASES,
    CritPathRecorder,
    EpochCritPath,
    diff_gating,
    gating_from_series,
    gating_histogram,
    path_from_phase_seconds,
    paths_from_events,
    phase_label,
)


# ---------------------------------------------------------------------------
# the module-level stamp hook
# ---------------------------------------------------------------------------


def test_stamp_is_noop_without_recorder():
    critpath.deactivate()
    critpath.stamp("rbc.output", node=0, instance=1)  # must not raise
    assert critpath.active() is None


def test_stamp_reaches_active_recorder_with_crank_context():
    rec = CritPathRecorder()
    critpath.activate(rec)
    try:
        rec.tick(crank=41, now=7)
        critpath.stamp("ba.decide", node=2, instance=3, rnd=1, value=True)
        (ev,) = rec.take()
        assert ev["phase"] == "ba.decide"
        assert ev["node"] == 2 and ev["instance"] == 3 and ev["round"] == 1
        assert ev["crank"] == 41 and ev["now"] == 7
        assert ev["value"] is True
    finally:
        critpath.deactivate()
    # deactivated again: further stamps don't land
    critpath.stamp("ba.decide", node=2)
    assert rec.take() == []


def test_unknown_phase_rejected():
    rec = CritPathRecorder()
    with pytest.raises(ValueError, match="unknown critpath phase"):
        rec.stamp("rbc.echo", node=0)


def test_ring_bound_counts_drops():
    rec = CritPathRecorder(capacity=4)
    for i in range(7):
        rec.stamp("crank", node=i)
    assert len(rec.events) == 4
    assert rec.dropped == 3
    assert [ev["node"] for ev in rec.take()] == [3, 4, 5, 6]


def test_recovery_scope_rebills_stamps():
    rec = CritPathRecorder()
    rec.begin_recovery(node=3)
    rec.stamp("rbc.output", node=3, instance=1)
    rec.end_recovery()
    rec.stamp("rbc.output", node=3, instance=2)
    marker, replayed, live = rec.take()
    assert marker["phase"] == "crash:recovery" and "via" not in marker
    assert replayed["phase"] == "crash:recovery"
    assert replayed["via"] == "rbc.output" and replayed["recovering"] == 3
    assert live["phase"] == "rbc.output" and "via" not in live


# ---------------------------------------------------------------------------
# chain reconstruction from completion events
# ---------------------------------------------------------------------------


def _ev(phase, crank, node=0, instance=None, epoch=None, rnd=None, **kw):
    ev = {
        "phase": phase,
        "node": node,
        "instance": instance,
        "round": rnd,
        "epoch": epoch,
        "crank": crank,
        "now": crank,
    }
    ev.update(kw)
    return ev


def test_window_closes_at_last_commit_and_gate_owns_longest_segment():
    # node 1 commits late: it is the gate node, and its BA decision sat
    # at crank 40 after an RBC output at crank 5 — BA owns the longest
    # stretch, so the epoch is gated by BA on node 1
    events = [
        _ev("rbc.output", 5, node=1, instance=0),
        _ev("rbc.output", 6, node=0, instance=0),
        _ev("ba.decide", 10, node=0, instance=0, rnd=0),
        _ev("decrypt.combine", 12, node=0, instance=0),
        _ev("epoch.commit", 14, node=0, epoch=0),
        _ev("ba.decide", 40, node=1, instance=0, rnd=0),
        _ev("decrypt.combine", 42, node=1, instance=0),
        _ev("epoch.commit", 44, node=1, epoch=0),
    ]
    (p,) = paths_from_events(events)
    assert p.epoch == 0
    assert p.gate_phase == "ba.decide"
    assert p.gate_node == repr(1)
    assert p.gate_instance == 0
    assert p.cranks == 44 - 5
    # chain reads commit-first
    assert p.chain[0]["phase"] == "epoch.commit"
    # contributors sort tightest-slack first: the gate node's last
    # completion (decrypt at crank 42, 2 cranks behind the commit) leads
    assert p.contributors[0]["node"] == repr(1)
    assert p.contributors[0]["slack"] == 2
    assert all(
        c["slack"] >= p.contributors[0]["slack"] for c in p.contributors
    )


def test_crash_recovery_overrides_gate_and_names_recovering_node():
    events = [
        _ev("rbc.output", 5, node=0, instance=0),
        _ev(
            "crash:recovery", 8, node=2,
            via="rbc.output", recovering=2, instance=0,
        ),
        _ev("ba.decide", 10, node=0, instance=0, rnd=0),
        _ev("epoch.commit", 14, node=0, epoch=3),
    ]
    (p,) = paths_from_events(events)
    assert p.gate_phase == "crash:recovery"
    assert p.gate_node == repr(2)
    assert "crash:recovery" in p.one_liner() and "node 2" in p.one_liner()


def test_multiple_epochs_partition_into_windows():
    events = []
    for ep in range(3):
        base = ep * 100
        events += [
            _ev("rbc.output", base + 1, node=0, instance=0),
            _ev("ba.decide", base + 4, node=0, instance=0, rnd=0),
            _ev("decrypt.combine", base + 6, node=0, instance=0),
            _ev("epoch.commit", base + 8, node=0, epoch=ep),
        ]
    paths = paths_from_events(events)
    assert [p.epoch for p in paths] == [0, 1, 2]


def test_path_roundtrips_through_dict():
    events = [
        _ev("rbc.output", 1, node=0, instance=0),
        _ev("epoch.commit", 9, node=0, epoch=0),
    ]
    (p,) = paths_from_events(events)
    q = EpochCritPath.from_dict(p.to_dict())
    assert q == p


# ---------------------------------------------------------------------------
# the array engine's phase-stamp collapse
# ---------------------------------------------------------------------------


def test_path_from_phase_seconds_gates_longest_phase():
    p = path_from_phase_seconds(
        5, {"rbc": 0.02, "ba": 0.05, "coin": 0.01, "decrypt": 0.03}, cranks=9
    )
    assert p.epoch == 5 and p.cranks == 9
    assert p.gate_phase == "ba.decide"
    assert [ln["phase"] for ln in p.chain] == [
        "ba.decide", "decrypt.combine", "rbc.output", "coin.reveal",
    ]
    assert p.wall_s == pytest.approx(0.11)


def test_path_from_phase_seconds_ignores_unknown_keys():
    p = path_from_phase_seconds(0, {"rbc": 0.1, "warmup": 9.9})
    assert p.gate_phase == "rbc.output"
    assert len(p.chain) == 1


# ---------------------------------------------------------------------------
# run-level aggregation
# ---------------------------------------------------------------------------


def test_gating_histogram_and_series_agree():
    paths = [
        EpochCritPath(epoch=0, gate_phase="ba.decide"),
        EpochCritPath(epoch=1, gate_phase="ba.decide"),
        EpochCritPath(epoch=2, gate_phase="rbc.output"),
        EpochCritPath(epoch=3, gate_phase="decrypt.combine"),
    ]
    hist = gating_histogram(paths)
    assert hist == {"ba.decide": 0.5, "decrypt.combine": 0.25, "rbc.output": 0.25}
    rows = [{"epoch": p.epoch, "gate": {"phase": p.gate_phase}} for p in paths]
    assert gating_from_series(rows) == hist
    assert gating_histogram([]) == {}


def test_diff_gating_flags_shifts_beyond_tol():
    old = {"ba.decide": 0.6, "rbc.output": 0.4}
    new = {"ba.decide": 0.35, "rbc.output": 0.45, "coin.reveal": 0.2}
    shifts = diff_gating(old, new, tol=0.10)
    assert {s["phase"] for s in shifts} == {"ba.decide", "coin.reveal"}
    assert diff_gating(old, dict(old)) == []


def test_phase_labels_are_human_vocabulary():
    assert phase_label("rbc.output", 3) == "RBC(3) output"
    assert phase_label("ba.decide", 7, rnd=2) == "BA(7) decision round 2"
    assert phase_label("coin.reveal", 1, rnd=0) == "BA(1) coin round 0"
    assert phase_label("crash:recovery") == "crash:recovery"


# ---------------------------------------------------------------------------
# run_cell integration: attribution + the acceptance identities
# ---------------------------------------------------------------------------

_CELL = dict(
    attack="passive", schedule="uniform", churn="none", traffic="none",
    n=4, epochs=6, seed=2,
)


def test_run_cell_attributes_gates_and_clears_hook():
    r = run_cell(Cell(crash="none", **_CELL))
    assert r.ok, r.error
    assert r.gating and abs(sum(r.gating.values()) - 1.0) < 0.01
    assert set(r.gating) <= set(PHASES)
    assert len(r.series) >= 6
    assert all("gate" in row for row in r.series if row["epoch"] < 6)
    # the module hook must not leak past the run
    assert critpath.active() is None


def test_restart_epoch_gated_by_crash_recovery():
    # crash-axis attribution: the epoch that replays a WAL is billed to
    # the crash:recovery pseudo-phase, naming the recovering node
    r = run_cell(
        Cell(crash="one_restart", **dict(_CELL, epochs=10, seed=4))
    )
    assert r.ok, r.error
    assert r.restarts == 1
    assert "crash:recovery" in r.gating, r.gating
    gates = [
        row["gate"] for row in r.series
        if row.get("gate", {}).get("phase") == "crash:recovery"
    ]
    assert gates and gates[0]["node"] is not None


def test_fingerprint_identical_with_obs_off():
    cell = Cell(crash="one_restart", **dict(_CELL, epochs=10, seed=4))
    on, off = run_cell(cell), run_cell(cell, obs=False)
    assert on.ok and off.ok
    assert on.fingerprint() == off.fingerprint()
    assert off.series == [] and off.gating == {}


def test_series_and_gating_replay_bit_identically():
    cell = Cell(crash="one_restart", **dict(_CELL, epochs=10, seed=4))
    a, b = run_cell(cell), run_cell(cell)
    assert a.series == b.series
    assert a.gating == b.gating


def test_why_stalled_leads_with_gate_line():
    from hbbft_tpu.obs.health import why_stalled

    class FakeNet:
        nodes = {}
        critpath = CritPathRecorder()

    FakeNet.critpath.last_path = EpochCritPath(
        epoch=9, gate_phase="coin.reveal", gate_instance=2,
        gate_node=repr(1), gate_round=3,
    )
    report = why_stalled(FakeNet())
    assert report["gate"] == "epoch 9 gated by BA(2) coin round 3 on node 1"
    assert report["summary"][0] == f"last {report['gate']}"
