"""ThresholdSign integration tests over VirtualNet.

Mirrors the reference `tests/threshold_sign.rs` § shape (SURVEY.md §4): N
nodes sign a common document; all correct nodes output the same valid
signature, under benign and adversarial scheduling, in both eager and
round-batched (deferred) crypto modes.
"""

import pytest

from hbbft_tpu.crypto.backend import MockBackend
from hbbft_tpu.net.adversary import ReorderingAdversary, SilentAdversary
from hbbft_tpu.net.virtual_net import NetBuilder
from hbbft_tpu.protocols.threshold_sign import ThresholdSign

DOC = b"sign me"


def build(n, f=0, adversary=None, defer_mode="eager", seed=0):
    b = (
        NetBuilder(range(n))
        .num_faulty(f)
        .defer_mode(defer_mode)
        .using(lambda ni, be: ThresholdSign(ni, be, doc=DOC))
    )
    if adversary:
        b = b.adversary(adversary)
    return b.build(seed=seed)


@pytest.mark.parametrize("n", [1, 2, 4, 7])
@pytest.mark.parametrize("defer_mode", ["eager", "round"])
def test_all_sign_same(n, defer_mode):
    net = build(n, defer_mode=defer_mode)
    net.broadcast_input(None)
    net.crank_to_quiescence()
    sigs = [node.outputs for node in net.correct_nodes()]
    assert all(len(s) == 1 for s in sigs)
    assert all(s == sigs[0] for s in sigs)
    # The combined signature verifies under the master key.
    sig = sigs[0][0]
    pk = net.nodes[0].algorithm.netinfo.public_key_set.public_key()
    assert pk.verify(sig, DOC)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_reordering_adversary(seed):
    net = build(4, f=1, adversary=ReorderingAdversary(), seed=seed)
    net.broadcast_input(None)
    net.crank_to_quiescence()
    sigs = [node.outputs for node in net.correct_nodes()]
    assert all(len(s) == 1 for s in sigs)
    assert all(s == sigs[0] for s in sigs)


def test_silent_faulty_nodes_tolerated():
    # f silent nodes: the other N-f ≥ f+1 shares still combine.
    net = build(4, f=1, adversary=SilentAdversary(), seed=5)
    net.broadcast_input(None)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert len(node.outputs) == 1


def test_eager_and_round_mode_agree():
    sig_by_mode = {}
    for mode in ("eager", "round"):
        net = build(4, defer_mode=mode, seed=9)
        net.broadcast_input(None)
        if mode == "round":
            while net.queue or net._pending_work:
                net.crank_round()
        else:
            net.crank_to_quiescence()
        sig_by_mode[mode] = net.nodes[0].outputs[0]
    assert sig_by_mode["eager"] == sig_by_mode["round"]


def test_corrupted_share_is_flagged():
    """A tampered share is detected by batched verification and logged."""
    from hbbft_tpu.crypto.keys import SignatureShare
    from hbbft_tpu.net.adversary import RandomAdversary
    from hbbft_tpu.net.virtual_net import NetBuilder

    def garbage(net, msg):
        from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage

        el = net.backend.group.hash_to_g2(b"garbage" + bytes([net.rng.randrange(256)]))
        return ThresholdSignMessage(SignatureShare(net.backend.group, el))

    net = (
        NetBuilder(range(4))
        .num_faulty(1)
        .adversary(RandomAdversary(garbage, p_replace=1.0))
        .using(lambda ni, be: ThresholdSign(ni, be, doc=DOC))
        .build(seed=11)
    )
    net.broadcast_input(None)
    net.crank_to_quiescence()
    for node in net.correct_nodes():
        assert len(node.outputs) == 1  # still terminates: 3 honest shares ≥ f+1
    faults = [f for node in net.correct_nodes() for f in node.faults_observed]
    assert any(f.kind == "threshold_sign:invalid_sig_share" for f in faults)
