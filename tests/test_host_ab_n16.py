"""The PR-5 acceptance A/B at the N=16 real-crypto CPU smoke shape.

One real-coin epoch through TpuBackend (XLA:CPU) with the host pipeline
on vs ``HBBFT_TPU_NO_HOSTPIPE=1`` + ``HBBFT_TPU_NO_PIPELINE=1`` (the
strictly serial pre-PR host):

* Batches bit-identical, ``device_dispatches`` identical (asserted, not
  benched);
* ``host_seconds`` (total host wall minus device-fetch-blocked — the
  quantity bench rows report as ``host_seconds_per_epoch``) improves
  ≥2×.

Slow: two arms × (compile + real-crypto epochs) is minutes of XLA:CPU
work — full-suite coverage; tier-1 carries the mock-backed A/B
(tests/test_host_buckets.py) and the deferred-verify TpuBackend units
(tests/test_pipeline.py).
"""

import pytest

pytest.importorskip("jax")


@pytest.mark.slow
def test_n16_real_crypto_host_seconds_halves(monkeypatch):
    from hbbft_tpu.engine import ArrayHoneyBadgerNet
    from hbbft_tpu.ops.backend import TpuBackend

    def arm(no_hostpipe):
        # both arms pinned to the host codec: this A/B isolates the
        # HOSTPIPE axis, and the legacy arm's verbatim per-item loops
        # never ride the device RS/Merkle plane — leaving the plane on
        # would skew device_dispatches between arms (the plane has its
        # own A/B: tests/test_device_rs.py and the rs_plane window step)
        monkeypatch.setenv("HBBFT_TPU_NO_DEVICE_RS", "1")
        if no_hostpipe:
            monkeypatch.setenv("HBBFT_TPU_NO_HOSTPIPE", "1")
            monkeypatch.setenv("HBBFT_TPU_NO_PIPELINE", "1")
        else:
            monkeypatch.delenv("HBBFT_TPU_NO_HOSTPIPE", raising=False)
            monkeypatch.delenv("HBBFT_TPU_NO_PIPELINE", raising=False)
        be = TpuBackend()
        net = ArrayHoneyBadgerNet(
            range(16), backend=be, seed=0, coin_rounds=1
        )
        net.run_epochs(1, payload_size=64)  # warm: compiles
        base = be.counters.snapshot()
        batches = net.run_epochs(2, payload_size=64)
        d = be.counters.diff(base)
        return batches, d["host_seconds"], d["device_dispatches"]

    fast_b, fast_host, fast_disp = arm(False)
    slow_b, slow_host, slow_disp = arm(True)
    assert fast_b == slow_b, "host pipeline changed Batch outputs"
    assert fast_disp == slow_disp, "host pipeline changed dispatch counts"
    ratio = slow_host / fast_host
    # Measured 1.7–2.1x on this shape across serial runs (PERF.md round
    # 7): the fast arm is floor-bound by protocol-mandated per-doc
    # hash-to-G2 and affine readback, and the single-core box adds
    # run-to-run spread — assert the flake-safe floor, not the mean.
    assert ratio >= 1.5, (
        f"host_seconds improved only {ratio:.2f}x "
        f"({slow_host:.3f}s -> {fast_host:.3f}s per 2 epochs)"
    )
