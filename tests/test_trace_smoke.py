"""Tier-1 smoke: examples/simulation.py with tracing on, both engines.

Runs the CLI in-process (same interpreter, mock backend) and asserts the
emitted trace parses and validates against the Chrome trace-event schema
— the fast guard that keeps `--trace` working for the real acceptance
run (`-n 10 -f 3 --epochs 2 --engine array` on hardware).
"""

import json

from examples.simulation import main as sim_main
from tools.trace_report import (
    device_span_seconds,
    kind_table,
    load_events,
    validate_chrome_trace,
)


def test_object_engine_trace_smoke(tmp_path):
    path = str(tmp_path / "trace.json")
    rc = sim_main(
        ["-n", "4", "-f", "1", "--epochs", "1", "--trace", path,
         "--heartbeat", "3600"]
    )
    assert rc == 0
    events = load_events(path)
    assert validate_chrome_trace(events) == []
    doc = json.load(open(path))
    hists = doc["otherData"]["histograms"]
    assert hists["crank_latency_us"]["count"] > 0
    assert "p99" in hists["crank_latency_us"]
    cats = {e["cat"] for e in events if e.get("ph") == "B"}
    assert "epoch" in cats
    # mock backend: every dispatch span is a host span, so traced device
    # time must agree with the (zero) device_seconds counter
    assert device_span_seconds(events) == 0.0


def test_array_engine_trace_has_every_span_level(tmp_path):
    path = str(tmp_path / "trace.json")
    rc = sim_main(
        ["-n", "4", "-f", "1", "--epochs", "1", "--engine", "array",
         "--trace", path]
    )
    assert rc == 0
    events = load_events(path)
    assert validate_chrome_trace(events) == []
    cats = {e["cat"] for e in events if e.get("ph") == "B"}
    # the span hierarchy the tentpole promises: epoch → subset →
    # per-proposer RBC/BA instances → coin round → dispatch
    assert {"epoch", "subset", "rbc", "ba", "coin"} <= cats
    names = {e["name"] for e in events if e.get("ph") == "B"}
    assert any(n.startswith("dispatch:") for n in names)
    assert any(n.startswith("ba:") for n in names)  # per-instance spans
    assert any(n.startswith("coin_round:") for n in names)
    table = {(r["cat"], r["device"]) for r in kind_table(events)}
    assert ("epoch", False) in table


def test_jsonl_trace_export(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    rc = sim_main(
        ["-n", "4", "-f", "1", "--epochs", "1", "--engine", "array",
         "--trace", path]
    )
    assert rc == 0
    events = load_events(path)
    assert events and validate_chrome_trace(events) == []
