"""GLV/GLS A/B bit-identity: every batched ladder path must produce
byte-identical outputs under ``HBBFT_TPU_NO_GLV=1`` (classic w2 ladders)
vs the default (endomorphism joint-table ladders).

The knob is read per batch (curve.glv_enabled), so both arms run in ONE
process against the same TpuBackend class — the bit-matrix shapes differ
per arm, so each arm jit-compiles its own graphs and the lru-cached
jitted callables cannot alias.

Module-scoped: both arms execute once (the XLA:CPU compiles dominate);
the per-path tests then assert over the recorded outputs.  The G2 combine
and DKG-mul paths ride identical group-generic code to their G1 twins and
carry the heaviest Fq2 compiles, so they sit behind ``slow`` (full-suite
coverage) while tier-1 keeps the G1 paths and the G2 sign ladder.
"""

import os
import random

import pytest

from hbbft_tpu.crypto.field import R
from hbbft_tpu.ops.backend import TpuBackend

pytest.importorskip("jax")


def _run_paths(g2_paths: bool) -> dict:
    rng = random.Random(5)
    be = TpuBackend()
    sks = be.generate_key_set(1, rng)
    pks = sks.public_keys()
    key_shares = [sks.secret_key_share(i) for i in range(8)]
    out = {}
    g1 = be.group.g1()
    scal = [rng.randrange(R) for _ in range(8)]

    # decrypt path: batched G1 ladders (x_i·U)
    cts = [pks.public_key().encrypt(b"msg%032d" % i, rng) for i in range(4)]
    pairs = [(key_shares[i % 8], cts[i % 4]) for i in range(8)]
    out["decrypt"] = [
        be.group.g1_to_bytes(d.el) for d in be.decrypt_shares_batch(pairs)
    ]
    # combine path: batched G1 Lagrange combines
    dec_items = []
    for ct in cts:
        dec_items.append(
            ({i: key_shares[i].decrypt_share_unchecked(ct) for i in range(2)}, ct)
        )
    out["combine"] = be.combine_dec_shares_batch(pks, dec_items)
    # mul_batch path (the DKG primitive)
    out["mul_batch"] = [
        be.group.g1_to_bytes(p) for p in be.g1_mul_batch(scal, [g1] * 8)
    ]
    # lincomb path: the device MSM
    pts = [be.group.g1_mul(rng.randrange(R), g1) for _ in range(9)]
    out["lincomb"] = be.group.g1_to_bytes(
        be.g1_lincomb([rng.randrange(R) for _ in range(9)], pts)
    )
    # sign path: batched G2 ladders (x_i·H2(doc))
    docs = [b"doc%d" % i for i in range(8)]
    out["sign"] = [
        be.group.g2_to_bytes(s.el)
        for s in be.sign_shares_batch(list(zip(key_shares, docs)))
    ]
    if g2_paths:
        share_maps = []
        for d in docs[:4]:
            share_maps.append(
                ({i: key_shares[i].sign_share(d) for i in range(2)}, d)
            )
        out["sig_combine"] = [
            be.group.g2_to_bytes(s.el)
            for s in be.combine_sig_shares_batch(pks, share_maps)
        ]
        g2 = be.group.g2()
        out["g2_mul_batch"] = [
            be.group.g2_to_bytes(p) for p in be.g2_mul_batch(scal, [g2] * 8)
        ]
    out["counters"] = be.counters
    return out


def _both_arms(g2_paths: bool):
    saved = os.environ.pop("HBBFT_TPU_NO_GLV", None)
    try:
        glv = _run_paths(g2_paths)
        os.environ["HBBFT_TPU_NO_GLV"] = "1"
        w2 = _run_paths(g2_paths)
        return glv, w2
    finally:
        if saved is None:
            os.environ.pop("HBBFT_TPU_NO_GLV", None)
        else:
            os.environ["HBBFT_TPU_NO_GLV"] = saved


@pytest.fixture(scope="module")
def arms():
    return _both_arms(g2_paths=False)


def test_g1_and_sign_paths_bit_identical(arms):
    glv, w2 = arms
    for path in ("decrypt", "combine", "mul_batch", "lincomb", "sign"):
        assert glv[path] == w2[path], f"GLV vs w2 mismatch on {path}"


def test_glv_arm_actually_decomposed(arms):
    """The A/B is vacuous if the default arm silently fell back to w2:
    pin the accounting — decompositions happened, the table cost is
    tracked, and the per-lane scan cost dropped ≥1.5× on the G1 ladder
    dispatches (2368 vs 3810 per lane; the mixed-path totals here also
    include the 2× G2 sign ladders)."""
    glv, w2 = arms
    assert glv["counters"].glv_decompositions > 0
    assert w2["counters"].glv_decompositions == 0
    assert glv["counters"].glv_table_field_muls > 0
    assert glv["counters"].glv_table_build_seconds > 0.0
    assert (
        w2["counters"].ladder_field_muls
        >= 1.5 * glv["counters"].ladder_field_muls
    )


@pytest.mark.slow
def test_g2_combine_and_mul_paths_bit_identical():
    glv, w2 = _both_arms(g2_paths=True)
    for path in ("sig_combine", "g2_mul_batch"):
        assert glv[path] == w2[path], f"GLV vs w2 mismatch on {path}"
